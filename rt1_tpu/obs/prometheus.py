"""Prometheus text-format exposition (and a tiny scrape listener).

The serve layer's `/metrics` spoke JSON only — fine for
`scripts/serve_loadgen.py`, invisible to every standard scraper. This
module renders the exposition format (version 0.0.4: `# HELP`/`# TYPE`
comments, cumulative `le` histogram buckets ending at `+Inf`, `_sum` and
`_count` series) from plain Python dicts, so:

* the serve `/metrics` endpoint can content-negotiate: JSON by default,
  text when the scraper asks (`Accept: text/plain` or openmetrics) —
  `rt1_tpu/serve/server.py`;
* the train loop can expose its own scrape target
  (`config.obs.prometheus_port`) without importing any serving code —
  `MetricsServer` below is a stdlib `ThreadingHTTPServer` on a daemon
  thread.

Everything renders FROM the JSON snapshot (`ServeMetrics.snapshot()` now
carries cumulative bucket counts), so the two formats cannot drift: same
numbers, two syntaxes.

No third-party dependencies — this module must stay importable in a
headless serve deployment with no clu/tensorboard installed (pinned by
`tests/test_obs_imports.py`).
"""

from __future__ import annotations

import math
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_name(name: str) -> str:
    """Coerce an arbitrary metric key into a legal Prometheus name."""
    name = _NAME_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def format_value(v: float) -> str:
    v = float(v)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


class TextExposition:
    """Accumulates metric families and renders the text format."""

    def __init__(self):
        self._lines: List[str] = []
        self._seen: set = set()

    def _header(self, name: str, mtype: str, help_text: Optional[str]):
        if name in self._seen:
            raise ValueError(f"metric family {name!r} already rendered")
        self._seen.add(name)
        if help_text:
            # Escape per the exposition spec: backslash and newline.
            escaped = help_text.replace("\\", "\\\\").replace("\n", "\\n")
            self._lines.append(f"# HELP {name} {escaped}")
        self._lines.append(f"# TYPE {name} {mtype}")

    def counter(self, name: str, value: float, help_text: str = ""):
        name = sanitize_name(name)
        self._header(name, "counter", help_text)
        self._lines.append(f"{name} {format_value(value)}")

    def gauge(self, name: str, value: float, help_text: str = ""):
        name = sanitize_name(name)
        self._header(name, "gauge", help_text)
        self._lines.append(f"{name} {format_value(value)}")

    def histogram(
        self,
        name: str,
        cumulative: Sequence[Tuple[Any, int]],
        sum_value: float,
        count: int,
        help_text: str = "",
    ):
        """`cumulative`: (upper_bound, cumulative_count) pairs in ascending
        bound order; the final bound may be inf / "+Inf" — if absent, an
        `+Inf` bucket equal to `count` is appended (the spec requires it)."""
        name = sanitize_name(name)
        self._header(name, "histogram", help_text)
        has_inf = False
        for le, c in cumulative:
            if isinstance(le, str):
                le_str = le
                has_inf = has_inf or le == "+Inf"
            else:
                le_f = float(le)
                has_inf = has_inf or math.isinf(le_f)
                le_str = format_value(le_f)
            self._lines.append(f'{name}_bucket{{le="{le_str}"}} {int(c)}')
        if not has_inf:
            self._lines.append(f'{name}_bucket{{le="+Inf"}} {int(count)}')
        self._lines.append(f"{name}_sum {format_value(sum_value)}")
        self._lines.append(f"{name}_count {int(count)}")

    def family(
        self,
        name: str,
        mtype: str,
        samples: Sequence[Tuple[Dict[str, str], float]],
        help_text: str = "",
    ):
        """One metric family with LABELED samples — the fleet-aggregation
        shape: one `# TYPE` header, one sample per replica
        (``{replica_id="0"} 42``). Label values are escaped per the
        exposition spec (backslash, quote, newline)."""
        name = sanitize_name(name)
        self._header(name, mtype, help_text)
        for labels, value in samples:
            rendered = ",".join(
                f'{sanitize_name(str(k))}="{self._escape_label(str(v))}"'
                for k, v in labels.items()
            )
            self._lines.append(
                f"{name}{{{rendered}}} {format_value(value)}"
                if rendered
                else f"{name} {format_value(value)}"
            )

    @staticmethod
    def _escape_label(value: str) -> str:
        return (
            value.replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
        )

    def render(self) -> str:
        return "\n".join(self._lines) + "\n"


# --------------------------------------------------------------- renderers

# snapshot() counter keys -> (family suffix, help). Everything else numeric
# in the snapshot becomes a gauge; *_buckets / *_count / *_sum_s triples
# become histograms.
_SERVE_COUNTERS = {
    "requests_total": "Requests accepted by /act (including failed).",
    "errors_total": "Requests answered with an error status.",
    "rejected_total": "Requests shed by queue backpressure (503 busy).",
    "resets_total": "Session resets via /reset.",
    "reloads_total": "Zero-downtime checkpoint hot-swaps served.",
    "sessions_restarted_total": (
        "Sessions re-homed to another replica after theirs died."
    ),
    "sessions_migrated_total": (
        "Sessions whose window was carried intact to another replica "
        "(live migration or snapshot-ring restore)."
    ),
    "batches_total": "Batched device steps executed.",
    "joined_mid_cycle_total": (
        "Requests that rode a batch formed while another batch was "
        "already in flight (continuous-batching occupancy signal)."
    ),
    # Data-flywheel capture sink (rt1_tpu/flywheel/capture.py) — present
    # only on replicas serving with --capture_dir.
    "capture_episodes_total": "Captured sessions written as episodes.",
    "capture_steps_total": "Steps written into captured episodes.",
    "capture_dropped_episodes_total": (
        "Sessions discarded (too short / no resolvable instruction)."
    ),
    "capture_dropped_steps_total": (
        "Steps dropped past the per-session capture bound."
    ),
    "capture_write_errors_total": "Episode writes that failed (kept serving).",
    "capture_pruned_total": "Old capture files pruned by the disk ring.",
    # KV-cached incremental decode (rt1_tpu/serve/engine.py
    # cached_inference=True): steps served from per-session caches vs
    # full-window recomputes (cache rebuilds after hot-swap).
    "cache_cached_steps_total": (
        "Session steps served through the incremental KV-cache decode "
        "path (one frame attended against cached keys)."
    ),
    "cache_rebuild_steps_total": (
        "Per-session full-window cache recomputes (rebuilds after "
        "checkpoint hot-swap invalidation)."
    ),
    # Durable sessions (rt1_tpu/serve/migrate.py): the replica-side
    # export/import/restore legs of live migration and snapshot-ring
    # crash recovery.
    "migration_exports_total": (
        "Session snapshots exported via POST /session/export."
    ),
    "migration_imports_total": (
        "Session snapshots imported via POST /session/import."
    ),
    "migration_import_failures_total": (
        "Session imports refused (compatibility) or failed (malformed)."
    ),
    "migration_restores_total": (
        "Sessions restored from the on-disk snapshot ring at /act time."
    ),
    "migration_restore_failures_total": (
        "Snapshot-ring restores that failed or were refused (stale, "
        "incompatible, injected fault) — the session restarted fresh."
    ),
}

_SERVE_HISTOGRAMS = {
    "latency": ("request_latency_seconds", "Full request wall time."),
    "step": ("step_latency_seconds", "Batched device step latency."),
}

def _numeric_label_key(kv):
    """Sort key for numeric label values (AOT bucket sizes)."""
    return int(kv[0])


def _lexical_label_key(kv):
    """Sort key for string label values (task slugs)."""
    return str(kv[0])


# snapshot dict keys -> (family, type, label, sort_key, help): snapshot
# entries that are {label_value: count} dicts, rendered as ONE labeled
# family each — the per-AOT-bucket occupancy histogram (`bucket` label,
# numeric order) and the per-task serve labels (`task` label, lexical
# order; task slugs like "unknown:<reward>" pass through label escaping).
_SERVE_LABELED_FAMILIES = (
    (
        "bucket_batches",
        "bucket_batches_total",
        "counter",
        "bucket",
        _numeric_label_key,
        "Batched steps executed per AOT batch-size bucket.",
    ),
    (
        "bucket_occupancy_sum",
        "bucket_occupancy_sum",
        "counter",
        "bucket",
        _numeric_label_key,
        "Summed active requests per AOT bucket (mean fill = sum/batches).",
    ),
    (
        "task_requests_total",
        "task_requests_total",
        "counter",
        "task",
        _lexical_label_key,
        "Served /act requests per client-declared task tag "
        "('unlabeled' = no tag).",
    ),
    (
        "task_sessions_total",
        "task_sessions_total",
        "counter",
        "task",
        _lexical_label_key,
        "Sessions started per client-declared task tag.",
    ),
    (
        "cache_invalidations",
        "cache_invalidations_total",
        "counter",
        "reason",
        _lexical_label_key,
        "KV-cache invalidations by cause ('swap' checkpoint hot-swap | "
        "'reset' session reset | 'evict' LRU slot reclaim).",
    ),
)


# Router-level labeled families (ISSUE 15 elastic fleet): rendered from the
# router's own snapshot like _SERVE_LABELED_FAMILIES, but deliberately NOT
# fanned out per replica — scale events, admission sheds, and tier counts
# are fleet-shape facts that only the router/supervisor process owns.
_ROUTER_LABELED_FAMILIES = (
    (
        "autoscale_scale_events_total",
        "autoscale_scale_events_total",
        "counter",
        "direction",
        _lexical_label_key,
        "Fleet scale events by direction (elastic autoscaler).",
    ),
    (
        "autoscale_shed_total",
        "autoscale_shed_total",
        "counter",
        "reason",
        _lexical_label_key,
        "Requests shed by router admission control, by reason "
        "('client_rate' token bucket | 'overload' global threshold).",
    ),
    (
        "autoscale_tier_replicas",
        "autoscale_tier_replicas",
        "gauge",
        "dtype",
        _lexical_label_key,
        "Live replicas per dtype capacity tier (base + surge).",
    ),
)


def render_serve_snapshot(
    snapshot: Dict[str, Any], prefix: str = "rt1_serve_"
) -> str:
    """ServeMetrics JSON snapshot -> Prometheus text, one source of truth."""
    exp = TextExposition()
    _render_serve_into(exp, snapshot, prefix)
    return exp.render()


def _render_serve_into(
    exp: TextExposition, snapshot: Dict[str, Any], prefix: str
) -> None:
    consumed = set()
    for key, help_text in _SERVE_COUNTERS.items():
        if key in snapshot:
            exp.counter(prefix + key, snapshot[key], help_text)
            consumed.add(key)
    # The engine's low-precision mode is a string, exposed info-style
    # (`rt1_serve_inference_dtype{dtype="int8"} 1`) so dashboards can
    # group latency by dtype without an enum-code mapping.
    if isinstance(snapshot.get("inference_dtype"), str):
        exp.family(
            prefix + "inference_dtype",
            "gauge",
            [({"dtype": snapshot["inference_dtype"]}, 1.0)],
            "Engine inference dtype (f32 | bf16 | int8), info-style.",
        )
        consumed.add("inference_dtype")
    for key, (family, help_text) in _SERVE_HISTOGRAMS.items():
        buckets = snapshot.get(f"{key}_buckets")
        if buckets is None:
            continue
        exp.histogram(
            prefix + family,
            buckets,
            sum_value=snapshot.get(f"{key}_sum_s", 0.0),
            count=snapshot.get(f"{key}_count", 0),
            help_text=help_text,
        )
        consumed.update({f"{key}_buckets", f"{key}_sum_s", f"{key}_count"})
    # Labeled-dict families: the per-AOT-bucket occupancy histogram
    # (`rt1_serve_bucket_batches_total{bucket="4"} 17`, ISSUE 12), the
    # per-task serve labels (`rt1_serve_task_requests_total{task="play"}`,
    # ISSUE 13), and the router's elastic-fleet families (ISSUE 15) —
    # each snapshot dict becomes one labeled family.
    for key, family, mtype, label, sort_key, help_text in (
        _SERVE_LABELED_FAMILIES + _ROUTER_LABELED_FAMILIES
    ):
        table = snapshot.get(key)
        if isinstance(table, dict):
            consumed.add(key)
            if table:
                exp.family(
                    prefix + family,
                    mtype,
                    [
                        ({label: str(b)}, v)
                        for b, v in sorted(table.items(), key=sort_key)
                    ],
                    help_text,
                )
    for key in sorted(snapshot.keys() - consumed):
        value = snapshot[key]
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            continue
        exp.gauge(prefix + _gauge_suffix(key), value)


def _gauge_suffix(key: str) -> str:
    """Snapshot key -> metric-name suffix (units spelled out per the
    Prometheus naming convention)."""
    return "uptime_seconds" if key == "uptime_s" else key


# Replica snapshot fields fanned into the router's aggregated /metrics,
# one labeled family each: rt1_serve_replica_<field>{replica_id="N"}.
# Curated (not "every numeric key") so the fleet scrape stays a stable
# contract — per-replica occupancy, compiles, reloads, queue depth, the
# request counters, and the latency quantiles.
_FLEET_REPLICA_FIELDS = {
    "requests_total": ("counter", "Requests served by this replica."),
    "errors_total": ("counter", "Error responses from this replica."),
    "rejected_total": ("counter", "Requests shed by this replica (503)."),
    "reloads_total": ("counter", "Checkpoint hot-swaps on this replica."),
    "batches_total": ("counter", "Batched device steps on this replica."),
    "active_sessions": ("gauge", "Live session slots in use."),
    "compile_count": (
        "gauge",
        "AOT compiles this replica lifetime (invariant: 1).",
    ),
    "queue_depth": ("gauge", "Micro-batcher queue depth at last batch."),
    "mean_batch_occupancy": ("gauge", "Mean batch fill."),
    "max_batch_occupancy": ("gauge", "Max batch fill."),
    "batches_in_flight": (
        "gauge",
        "Batches dispatched but not yet collected (double-buffer depth).",
    ),
    "max_batches_in_flight": (
        "gauge",
        "High-water mark of overlapping batches this lifetime.",
    ),
    "joined_mid_cycle_total": (
        "counter",
        "Requests that rode a batch formed while another was in flight.",
    ),
    "bucket_count": (
        "gauge",
        "Configured AOT batch-size buckets (compile_count invariant).",
    ),
    "latency_p50_ms": ("gauge", "Replica-local request p50 (ms)."),
    "latency_p99_ms": ("gauge", "Replica-local request p99 (ms)."),
    "requests_per_sec": ("gauge", "Replica-local request rate."),
    "ready": ("gauge", "1 when the replica reports ready."),
    "reloading": ("gauge", "1 while a hot-swap is in progress."),
    "draining": ("gauge", "1 while draining after SIGTERM."),
    "session_evictions": ("gauge", "LRU slot reclaims (oversubscription)."),
    "slow_exemplars": ("gauge", "Slow-request exemplars retained."),
    "uptime_s": ("gauge", "Replica process uptime (seconds)."),
    "param_bytes_device": (
        "gauge",
        "Device-resident serving-tree bytes (int8 quantized size counts).",
    ),
    "param_bytes_master": (
        "gauge",
        "f32 master checkpoint bytes this replica restores from.",
    ),
    "capture_enabled": ("gauge", "1 when the flywheel capture sink is on."),
    "capture_episodes_total": (
        "counter",
        "Captured sessions written as flywheel episodes.",
    ),
    "capture_open_sessions": (
        "gauge",
        "Capture buffers currently open on this replica.",
    ),
    "capture_write_errors_total": (
        "counter",
        "Episode writes that failed on this replica (kept serving).",
    ),
    "capture_pruned_total": (
        "counter",
        "Old capture files pruned by this replica's disk ring.",
    ),
    "cache_enabled": (
        "gauge",
        "1 when this replica serves with per-session KV caches.",
    ),
    "cache_bytes_per_slot": (
        "gauge",
        "Device bytes of transformer K/V cache per session slot.",
    ),
    "cache_cached_steps_total": (
        "counter",
        "Steps served through incremental KV-cache decode.",
    ),
    "cache_rebuild_steps_total": (
        "counter",
        "Per-session full-window cache recomputes after invalidation.",
    ),
    "migration_exports_total": (
        "counter",
        "Session snapshots this replica exported (live migration).",
    ),
    "migration_imports_total": (
        "counter",
        "Session snapshots this replica imported (live migration).",
    ),
    "migration_import_failures_total": (
        "counter",
        "Session imports this replica refused or failed.",
    ),
    "migration_restores_total": (
        "counter",
        "Sessions this replica restored from the snapshot ring.",
    ),
    "migration_restore_failures_total": (
        "counter",
        "Snapshot-ring restores that failed on this replica "
        "(session restarted fresh).",
    ),
}


# Router-attributed per-replica SLO families (deploy canary judgement):
# rendered from Router.replica_slo_snapshot(), NOT the replica /metrics
# fan-out — the router is the only process that sees every outcome,
# including the death that the dead replica itself could never report.
_REPLICA_SLO_FAMILIES = (
    (
        "outcome_total",
        "counter",
        "Router-attributed request outcomes per replica "
        "(ok | migrated | restarted | rejected | failed).",
    ),
    (
        "slo_availability_rolling",
        "gauge",
        "Rolling ok-fraction of requests this replica answered.",
    ),
    (
        "slo_error_budget_burn_rolling",
        "gauge",
        "Rolling error-budget burn attributed to this replica "
        "(the canary rollback signal).",
    ),
)


def fleet_metric_names(prefix: str = "rt1_serve_") -> List[str]:
    """Every family name the aggregated fleet exposition can emit (the
    naming-contract test iterates this)."""
    names = [prefix + "replica_up", prefix + "replica_inference_dtype"]
    for key in _FLEET_REPLICA_FIELDS:
        names.append(prefix + "replica_" + _gauge_suffix(key))
    for _, family, _, _, _, _ in _SERVE_LABELED_FAMILIES:
        names.append(prefix + "replica_" + family)
    for suffix, _, _ in _REPLICA_SLO_FAMILIES:
        names.append(prefix + "replica_" + suffix)
    return names


def render_fleet_snapshot(
    router_snapshot: Dict[str, Any],
    replicas: Dict[Any, Optional[Dict[str, Any]]],
    prefix: str = "rt1_serve_",
    replica_slo: Optional[Dict[Any, Dict[str, Any]]] = None,
) -> str:
    """Router snapshot + per-replica snapshots -> ONE exposition body.

    The router's own families render exactly as `render_serve_snapshot`
    (same names, so a single-replica dashboard keeps working against a
    fleet); each replica's curated fields follow as labeled families with
    a ``replica_id`` label. A replica whose `/metrics` probe failed
    (value None) appears only in ``replica_up`` as 0 — absence of data is
    itself a scraped fact, not a silent gap. ``replica_slo``
    (`Router.replica_slo_snapshot()`) adds the router-attributed
    per-replica outcome families — the canary burn signal.
    """
    exp = TextExposition()
    _render_serve_into(exp, router_snapshot, prefix)
    up = [
        ({"replica_id": str(rid)}, 0.0 if snap is None else 1.0)
        for rid, snap in sorted(replicas.items(), key=lambda kv: str(kv[0]))
    ]
    if up:
        exp.family(
            prefix + "replica_up",
            "gauge",
            up,
            "1 when the replica's /metrics answered the fan-out probe.",
        )
    # Mixed-dtype fleets: each replica's inference dtype as one labeled
    # info family — `{replica_id="1",dtype="int8"} 1` — so a per-dtype
    # latency dashboard needs no enum mapping.
    dtype_samples = [
        (
            {"replica_id": str(rid), "dtype": snap["inference_dtype"]},
            1.0,
        )
        for rid, snap in sorted(replicas.items(), key=lambda kv: str(kv[0]))
        if snap is not None and isinstance(snap.get("inference_dtype"), str)
    ]
    if dtype_samples:
        exp.family(
            prefix + "replica_inference_dtype",
            "gauge",
            dtype_samples,
            "Replica inference dtype (f32 | bf16 | int8), info-style.",
        )
    for key, (mtype, help_text) in _FLEET_REPLICA_FIELDS.items():
        samples = [
            ({"replica_id": str(rid)}, snap[key])
            for rid, snap in sorted(
                replicas.items(), key=lambda kv: str(kv[0])
            )
            if snap is not None and isinstance(snap.get(key), (int, float))
            and not isinstance(snap.get(key), bool)
        ]
        if not samples:
            continue
        exp.family(
            prefix + "replica_" + _gauge_suffix(key), mtype, samples,
            help_text,
        )
    # Per-replica labeled-dict families: AOT-bucket occupancy
    # ({replica_id, bucket}) and per-task serve labels ({replica_id,
    # task}) — a fleet dashboard reads each replica's fill profile and
    # task mix without scraping replicas individually.
    for key, family, mtype, label, sort_key, help_text in (
        _SERVE_LABELED_FAMILIES
    ):
        samples = [
            ({"replica_id": str(rid), label: str(b)}, v)
            for rid, snap in sorted(
                replicas.items(), key=lambda kv: str(kv[0])
            )
            if snap is not None and isinstance(snap.get(key), dict)
            for b, v in sorted(snap[key].items(), key=sort_key)
        ]
        if not samples:
            continue
        exp.family(
            prefix + "replica_" + family, mtype, samples, help_text
        )
    # Router-attributed per-replica SLO families (the canary judgement
    # view): outcome-class counters double-labeled {replica_id, outcome}
    # plus the rolling availability/burn gauge pair per replica.
    if replica_slo:
        ordered = sorted(replica_slo.items(), key=lambda kv: str(kv[0]))
        outcome_samples = [
            ({"replica_id": str(rid), "outcome": str(o)}, count)
            for rid, entry in ordered
            for o, count in entry.get("outcomes", {}).items()
        ]
        families = {
            key: [
                ({"replica_id": str(rid)}, entry[field])
                for rid, entry in ordered
                if isinstance(entry.get(field), (int, float))
            ]
            for key, field in (
                ("slo_availability_rolling", "availability_rolling"),
                (
                    "slo_error_budget_burn_rolling",
                    "error_budget_burn_rolling",
                ),
            )
        }
        for suffix, mtype, help_text in _REPLICA_SLO_FAMILIES:
            samples = (
                outcome_samples
                if suffix == "outcome_total"
                else families[suffix]
            )
            if samples:
                exp.family(
                    prefix + "replica_" + suffix, mtype, samples, help_text
                )
    return exp.render()


def render_scalar_gauges(
    scalars: Dict[str, Any], prefix: str = "rt1_train_"
) -> str:
    """Flat {name: number} -> all-gauge text (the train-side scrape body).

    Names pass through `sanitize_name` ('timing/wait_data_ms' ->
    'timing_wait_data_ms'); non-numeric values are skipped.
    """
    exp = TextExposition()
    for key in sorted(scalars):
        value = scalars[key]
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            continue
        exp.gauge(sanitize_name(prefix + key), value)
    return exp.render()


def deploy_metric_names(
    snapshot: Dict[str, Any], prefix: str = "rt1_deploy_"
) -> List[str]:
    """Family names `render_deploy_snapshot` emits for `snapshot` (the
    naming-contract test iterates this against a full gauges payload)."""
    return [
        sanitize_name(prefix + key)
        for key in sorted(snapshot)
        if isinstance(snapshot[key], str)
        or (
            isinstance(snapshot[key], (int, float))
            and not isinstance(snapshot[key], bool)
        )
    ]


def render_deploy_snapshot(
    snapshot: Dict[str, Any], prefix: str = "rt1_deploy_"
) -> str:
    """PromotionController.deploy_gauges() -> ``rt1_deploy_*`` text.

    Same typing convention as the serve families: ``*_total`` keys are
    counters, string values render info-style
    (``rt1_deploy_state{state="canary"} 1``), everything else numeric is
    a gauge. Concatenates cleanly after a fleet exposition body (distinct
    prefix, no family collisions) — the supervisor serves both from one
    scrape.
    """
    exp = TextExposition()
    for key in sorted(snapshot):
        value = snapshot[key]
        name = prefix + key
        if isinstance(value, str):
            exp.family(
                name,
                "gauge",
                [({key: value}, 1.0)],
                f"Deploy controller {key} (info-style).",
            )
        elif isinstance(value, bool) or not isinstance(value, (int, float)):
            continue
        elif key.endswith("_total"):
            exp.counter(name, value)
        else:
            exp.gauge(name, value)
    return exp.render()


# ------------------------------------------------------------------ parsing


def parse_value(text: str) -> float:
    """Inverse of `format_value`: the three special spellings, then float."""
    if text == "+Inf":
        return math.inf
    if text == "-Inf":
        return -math.inf
    if text == "NaN":
        return math.nan
    return float(text)


def _unescape(value: str) -> str:
    """Inverse of `TextExposition._escape_label` (and the HELP escaping):
    ``\\\\`` -> backslash, ``\\"`` -> quote, ``\\n`` -> newline. An unknown
    escape keeps its backslash verbatim, per the exposition spec."""
    out: List[str] = []
    i = 0
    while i < len(value):
        ch = value[i]
        if ch == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            if nxt == "\\":
                out.append("\\")
            elif nxt == '"':
                out.append('"')
            elif nxt == "n":
                out.append("\n")
            else:
                out.append(ch)
                out.append(nxt)
            i += 2
            continue
        out.append(ch)
        i += 1
    return "".join(out)


def _parse_labels(line: str, start: int) -> Tuple[Dict[str, str], int]:
    """Parse ``{k="v",...}`` starting at the ``{``; returns (labels, index
    just past the ``}``). Escapes inside quoted values are honoured — a
    label value may contain braces, commas, spaces, escaped quotes."""
    labels: Dict[str, str] = {}
    i = start + 1
    while i < len(line) and line[i] != "}":
        eq = line.find("=", i)
        if eq < 0 or eq + 1 >= len(line) or line[eq + 1] != '"':
            raise ValueError(f"malformed labels in sample line: {line!r}")
        key = line[i:eq].lstrip(",").strip()
        j = eq + 2  # first char inside the quotes
        raw: List[str] = []
        while j < len(line):
            ch = line[j]
            if ch == "\\" and j + 1 < len(line):
                raw.append(line[j : j + 2])
                j += 2
                continue
            if ch == '"':
                break
            raw.append(ch)
            j += 1
        if j >= len(line) or line[j] != '"':
            raise ValueError(f"unterminated label value: {line!r}")
        labels[key] = _unescape("".join(raw))
        i = j + 1
        if i < len(line) and line[i] == ",":
            i += 1
    if i >= len(line) or line[i] != "}":
        raise ValueError(f"unterminated label set: {line!r}")
    return labels, i + 1


class Exposition:
    """A parsed text exposition: {family: type}, {family: help}, and the
    flat (name, labels, value) sample list. What `parse_exposition`
    returns; the collector iterates `samples`, the round-trip tests
    compare values against the source snapshot."""

    def __init__(
        self,
        types: Dict[str, str],
        help_texts: Dict[str, str],
        samples: List[Tuple[str, Dict[str, str], float]],
    ):
        self.types = types
        self.help = help_texts
        self.samples = samples

    def value(self, name: str, **labels: str) -> float:
        """The single sample with exactly these labels; KeyError if absent
        (or ambiguous — duplicates indicate a renderer bug)."""
        hits = [
            v for n, lb, v in self.samples if n == name and lb == labels
        ]
        if len(hits) != 1:
            raise KeyError(
                f"{name}{labels}: {len(hits)} matching samples"
            )
        return hits[0]

    def labeled(self, name: str) -> List[Tuple[Dict[str, str], float]]:
        return [(lb, v) for n, lb, v in self.samples if n == name]

    def histogram(self, family: str) -> Dict[str, Any]:
        """Reassemble one histogram family back into the snapshot shape:
        cumulative ``buckets`` as (le, count) pairs with le in JSON form
        (float, or "+Inf" for the overflow — matching
        `ServeMetrics._bucket_json`), plus ``sum`` and ``count``."""
        if self.types.get(family) != "histogram":
            raise KeyError(f"{family!r} is not a parsed histogram family")
        buckets: List[Tuple[Any, int]] = []
        for labels, value in self.labeled(family + "_bucket"):
            le = labels.get("le", "")
            buckets.append(
                (le if le == "+Inf" else float(le), int(value))
            )
        return {
            "buckets": buckets,
            "sum": self.value(family + "_sum"),
            "count": int(self.value(family + "_count")),
        }


_HISTOGRAM_SUFFIXES = ("_bucket", "_sum", "_count")


def parse_exposition(text: str) -> Exposition:
    """Parse Prometheus text exposition 0.0.4 — the provable inverse of
    `TextExposition.render` (and so of every ``render_*`` in this module).

    Strict by design: a sample before its ``# TYPE`` header, a duplicate
    family header, an unknown comment, or an unparsable value raises
    ``ValueError``. If the renderer ever drifts from the format the
    collector ingests, the round-trip tests fail loudly instead of the
    history silently dropping families.
    """
    types: Dict[str, str] = {}
    help_texts: Dict[str, str] = {}
    samples: List[Tuple[str, Dict[str, str], float]] = []
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP ") :]
            name, _, help_text = rest.partition(" ")
            help_texts[name] = _unescape(help_text)
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) != 4:
                raise ValueError(f"malformed TYPE line: {line!r}")
            _, _, name, mtype = parts
            if name in types:
                raise ValueError(f"duplicate family header: {name!r}")
            types[name] = mtype
            continue
        if line.startswith("#"):
            raise ValueError(f"unknown comment line: {line!r}")
        # Sample: name[{labels}] value — label values may contain spaces.
        brace = line.find("{")
        space = line.find(" ")
        if brace >= 0 and (space < 0 or brace < space):
            name = line[:brace]
            labels, end = _parse_labels(line, brace)
            value_text = line[end:].strip()
        else:
            name, _, value_text = line.partition(" ")
            labels = {}
        base = name
        for suffix in _HISTOGRAM_SUFFIXES:
            stripped = name[: -len(suffix)] if name.endswith(suffix) else ""
            if stripped and types.get(stripped) == "histogram":
                base = stripped
        if base not in types:
            raise ValueError(f"sample {name!r} precedes its # TYPE header")
        samples.append((name, labels, parse_value(value_text.strip())))
    return Exposition(types, help_texts, samples)


CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def accepts_text(accept_header: Optional[str]) -> bool:
    """Content negotiation for a dual JSON/text /metrics endpoint: JSON
    stays the default (existing loadgen/automation), text is returned when
    the client asks the way Prometheus does.

    Listed order breaks ties (a full q-value parse is overkill here): a
    client sending ``application/json, text/plain, */*`` — the stock
    axios/fetch Accept — wants JSON first and gets JSON.
    """
    if not accept_header:
        return False
    for entry in accept_header.lower().split(","):
        media = entry.split(";", 1)[0].strip()
        if media == "application/json":
            return False
        if media == "text/plain" or "openmetrics" in media:
            return True
    return False


# ----------------------------------------------------------------- listener


class MetricsServer:
    """Opt-in scrape listener: GET /metrics -> `render_fn()` as text.

    Stdlib-only, daemon-threaded, ephemeral-port-friendly (port=0). The
    train loop hands it a closure over its StepTimeline / ThroughputMeter /
    feeder stats; rendering cost is paid by the scraper's request, never by
    the train step.
    """

    def __init__(
        self,
        render_fn: Callable[[], str],
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: D102 - stdlib hook
                pass

            def do_GET(self):  # noqa: N802 - stdlib casing
                if self.path == "/metrics":
                    try:
                        body = outer._render_fn().encode("utf-8")
                    except Exception as exc:  # noqa: BLE001 - scrape-safe
                        body = f"# render error: {exc}\n".encode("utf-8")
                        self._send(500, body)
                        return
                    self._send(200, body)
                elif self.path == "/healthz":
                    self._send(200, b"ok\n", content_type="text/plain")
                else:
                    self._send(404, b"not found\n", content_type="text/plain")

            def _send(self, code, body, content_type=CONTENT_TYPE):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._render_fn = render_fn
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="rt1-obs-metrics",
            daemon=True,
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}/metrics"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)
