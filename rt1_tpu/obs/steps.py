"""Per-step wall-time attribution for the train loop.

PR 2's input-stall number (`bench.py --mode e2e`: `1 - dt_compute /
dt_e2e`) needed a second, batch-resident timing loop — nothing a real
training run can afford. `StepTimeline` gets the same attribution from the
production loop itself by bucketing each step's host wall time:

* ``wait_data``   — blocked pulling the next host batch (feeder queue or
                    tf.data); accrued by wrapping the host iterator with
                    :meth:`StepTimeline.timed`.
* ``h2d``         — laying the batch out on device (`jax.device_put`
                    enqueue inside `device_feeder`), i.e. time in
                    ``next(dev_iter)`` *minus* the inner ``wait_data``.
* ``device_step`` — the jitted step call. Dispatch is asynchronous, so by
                    default this is host dispatch time and the device's
                    actual execution hides inside the *next* step's
                    ``wait_data``/``h2d`` (the queues only back up when the
                    device is the bottleneck). With ``sync=True`` the
                    timeline blocks on a step output and the bucket is the
                    true device latency — exact attribution for ~one extra
                    sync per step (use for diagnosis, not for the headline
                    run).
* ``host``        — the residual: logging, checkpoint scheduling, Python.

The rolling window turns these into the production `stall_pct` gauge —
``(wait_data + h2d) / total`` over the last N steps, the same quantity the
bench's lab A/B estimates — written through the ordinary clu metric writer
(`scalars()`), so the PR 2 metric is observable on every run, not just in
`bench.py`.

Single-consumer by design: all methods are called from the train loop's
thread (the timed iterator is pulled from inside ``next(dev_iter)`` on
that same thread). Feeder workers report through `obs.trace` spans and the
feeder's own stats, not through this object.
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Any, Dict, Iterator, Optional

from rt1_tpu.obs import trace

BUCKETS = ("wait_data", "h2d", "device_step", "host")


class StepTimeline:
    """Attributes each step's wall time into `BUCKETS` + rolling stall%."""

    def __init__(self, window: int = 50, sync: bool = False):
        if window <= 0:
            raise ValueError(f"window must be positive, got {window}")
        self.window = window
        self.sync = sync
        self._records: collections.deque = collections.deque(maxlen=window)
        # Recording is single-consumer, but the rolling window is READ from
        # other threads (the train-side Prometheus listener renders
        # scalars() on the scraper's thread) — guard the deque, or a scrape
        # landing mid-append raises "deque mutated during iteration".
        self._records_lock = threading.Lock()
        self._steps_seen = 0
        # Bucket time accrued while no step is open (prefetch warm-up pulls
        # before the loop's first start_step) is credited to the next step.
        self._orphan: Dict[str, float] = {}
        self._cur: Optional[Dict[str, float]] = None
        self._cur_step = -1
        self._t0 = 0.0
        self._step_span = None

    # ------------------------------------------------------------ recording

    def timed(self, iterator: Iterator, bucket: str = "wait_data") -> Iterator:
        """Wrap a host iterator so time blocked in ``next()`` accrues to
        `bucket` (of the step open at the moment of the pull)."""

        def _gen():
            while True:
                t0 = time.perf_counter()
                try:
                    item = next(iterator)
                except StopIteration:
                    return
                self._add(bucket, time.perf_counter() - t0)
                yield item

        return _gen()

    def _add(self, bucket: str, seconds: float) -> None:
        target = self._cur if self._cur is not None else self._orphan
        target[bucket] = target.get(bucket, 0.0) + seconds

    def start_step(self, step: int) -> None:
        self._cur = dict(self._orphan)
        self._orphan = {}
        self._cur_step = step
        self._t0 = time.perf_counter()
        self._step_span = trace.span("train_step", step=step)
        self._step_span.__enter__()

    @contextlib.contextmanager
    def phase(self, bucket: str, exclusive_of: Optional[str] = None):
        """Time a block into `bucket`; with `exclusive_of`, time accrued to
        that other bucket during the block is subtracted (e.g. the `h2d`
        phase wraps ``next(dev_iter)``, whose inner host-iterator pull
        already accrued to ``wait_data``). Outside an open step (e.g. a
        checkpoint save between steps) the time folds into the next step's
        bucket via the orphan dict."""
        cur = self._cur if self._cur is not None else self._orphan
        inner0 = cur.get(exclusive_of, 0.0) if exclusive_of else 0.0
        t0 = time.perf_counter()
        with trace.span(bucket):
            yield
        dt = time.perf_counter() - t0
        if exclusive_of:
            dt -= cur.get(exclusive_of, 0.0) - inner0
        cur[bucket] = cur.get(bucket, 0.0) + max(dt, 0.0)

    def end_step(self, sync_on: Any = None) -> Dict[str, float]:
        """Close the open step; returns its record (ms buckets + stall).

        `sync_on`: a step output (e.g. the loss array) to block on when
        `sync=True`, charging true device latency to ``device_step``.
        """
        if self._cur is None:
            raise RuntimeError("end_step without start_step")
        if self.sync and sync_on is not None:
            import jax

            t0 = time.perf_counter()
            with trace.span("device_sync"):
                jax.block_until_ready(sync_on)
            self._add("device_step", time.perf_counter() - t0)
        total = time.perf_counter() - self._t0
        cur, self._cur = self._cur, None
        if self._step_span is not None:
            self._step_span.__exit__(None, None, None)
            self._step_span = None
        buckets = {b: cur.get(b, 0.0) for b in BUCKETS}
        buckets["host"] += max(
            0.0, total - sum(cur.get(b, 0.0) for b in BUCKETS)
        )
        input_s = buckets["wait_data"] + buckets["h2d"]
        record = {
            "step": self._cur_step,
            "total_ms": total * 1e3,
            "stall_pct": (input_s / total * 100.0) if total > 0 else 0.0,
        }
        for b in BUCKETS:
            record[f"{b}_ms"] = buckets[b] * 1e3
        with self._records_lock:
            self._records.append(record)
            self._steps_seen += 1
        trace.counter("stall_pct", record["stall_pct"])
        return record

    # ------------------------------------------------------------ reporting

    @staticmethod
    def _stall(records) -> float:
        total = sum(r["total_ms"] for r in records)
        if total <= 0:
            return 0.0
        stalled = sum(r["wait_data_ms"] + r["h2d_ms"] for r in records)
        return stalled / total * 100.0

    @property
    def stall_pct(self) -> float:
        """Rolling input-stall%: input-bound time over total, last N steps."""
        with self._records_lock:
            return self._stall(list(self._records))

    def last(self) -> Optional[Dict[str, float]]:
        with self._records_lock:
            return self._records[-1] if self._records else None

    def scalars(self, prefix: str = "timing/") -> Dict[str, float]:
        """Rolling means for the metric writer (clu `write_scalars`).
        Thread-safe: also rendered by the scrape listener's handler."""
        with self._records_lock:
            records = list(self._records)
        n = len(records)
        if n == 0:
            return {}
        out = {"stall_pct": self._stall(records)}
        for key in ("total_ms", *(f"{b}_ms" for b in BUCKETS)):
            out[f"{prefix}{key}"] = sum(r[key] for r in records) / n
        return out
