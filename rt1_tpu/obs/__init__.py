"""rt1_tpu.obs — unified observability across train, data, and serve.

One subsystem, nine pieces, all optional and all cheap when off:

* :mod:`rt1_tpu.obs.trace`      — host-side Chrome-trace span recorder
  (Perfetto-loadable); train loop, feeder workers, and serve batcher emit
  into one timeline.
* :mod:`rt1_tpu.obs.steps`      — `StepTimeline`: per-step wall-time
  attribution (wait_data / h2d / device_step / host) + the rolling
  `stall_pct` gauge.
* :mod:`rt1_tpu.obs.prometheus` — exposition text format + the opt-in
  scrape listener (`MetricsServer`).
* :mod:`rt1_tpu.obs.recorder`   — `FlightRecorder`: ring buffer of recent
  step records, dumped to JSONL on crash/SIGTERM.
* :mod:`rt1_tpu.obs.health`     — on-device model-health pack (per-layer
  gradient/update norms, logit entropy, token accuracy) computed inside
  the jitted step, fetched only at log steps.
* :mod:`rt1_tpu.obs.goodput`    — `GoodputLedger`: run-level wall-time
  partition (init/compile/step/stall/ckpt/rollback/preempt) + live MFU.
* :mod:`rt1_tpu.obs.flops`      — XLA cost-analysis FLOPs + MFU math,
  shared by `bench.py --mode mfu` and the goodput ledger.
* :mod:`rt1_tpu.obs.slo`        — serving SLO ledger: request outcome
  buckets, availability, error-budget burn, `slo_summary.json`.
* :mod:`rt1_tpu.obs.quantiles`  — the one percentile implementation
  (exact-from-samples + histogram upper bound) every reporter shares.

Import hygiene is part of the contract: this package (and everything it
imports at module scope) must not require clu, tensorboard, or tensorflow
— headless serve deployments scrape `/metrics` without dragging in the
training stack. `tests/test_obs_imports.py` pins this.

See `docs/observability.md` for the operator guide.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

from rt1_tpu.obs import (
    flops,
    goodput,
    health,
    prometheus,
    quantiles,
    recorder,
    slo,
    steps,
    trace,
)
from rt1_tpu.obs.goodput import GoodputLedger
from rt1_tpu.obs.prometheus import MetricsServer
from rt1_tpu.obs.recorder import ExemplarRing, FlightRecorder
from rt1_tpu.obs.slo import SLOLedger, SLOObjectives
from rt1_tpu.obs.steps import StepTimeline
from rt1_tpu.obs.trace import TraceRecorder

__all__ = [
    "ExemplarRing",
    "FlightRecorder",
    "GoodputLedger",
    "MetricsServer",
    "ObsOptions",
    "SLOLedger",
    "SLOObjectives",
    "StepTimeline",
    "TraceRecorder",
    "flops",
    "goodput",
    "health",
    "prometheus",
    "quantiles",
    "recorder",
    "slo",
    "steps",
    "trace",
]


@dataclasses.dataclass
class ObsOptions:
    """Resolved `config.obs` with defaults for configs that predate it.

    The train loop consumes this instead of poking `config.obs.*` directly
    so pre-obs configs (proof configs, pinned sweep artifacts) keep running
    unmodified, and so defaults live in exactly one place.
    """

    trace: bool = False
    trace_path: Optional[str] = None  # None -> <workdir>/trace.json
    trace_max_events: int = 200_000
    stall_window: int = 50
    sync_timing: bool = False
    prometheus_port: int = -1  # < 0: no train-side listener; 0: ephemeral
    prometheus_host: str = "127.0.0.1"
    flight_recorder: bool = True
    flight_recorder_size: int = 256
    flight_recorder_path: Optional[str] = None  # None -> <workdir>/...jsonl
    # Model-health pack (obs/health.py): computed inside the jitted step,
    # fetched at log steps. Off by default so configs predating it keep a
    # bit-identical step program.
    model_health: bool = False
    health_group_depth: int = 2
    # Goodput ledger (obs/goodput.py): host-side run wall-time partition +
    # final JSON summary. Pure host arithmetic — safe to default on.
    goodput: bool = True
    goodput_summary_path: Optional[str] = None  # None -> <workdir>/goodput...
    # Live MFU gauge: estimate step FLOPs via XLA cost analysis of the
    # *lowered* step (no extra compile). Off by default: lowering costs a
    # second trace of the step at startup.
    goodput_mfu: bool = False

    @classmethod
    def from_config(cls, config, workdir: Optional[str] = None) -> "ObsOptions":
        """Read `config.obs` if present (ml_collections or plain mapping);
        absent keys fall back to the dataclass defaults."""
        node = None
        if config is not None:
            get = getattr(config, "get", None)
            node = get("obs") if callable(get) else getattr(config, "obs", None)
        kwargs = {}
        if node is not None:
            for field in dataclasses.fields(cls):
                getter = getattr(node, "get", None)
                value = (
                    getter(field.name)
                    if callable(getter)
                    else getattr(node, field.name, None)
                )
                if value is not None:
                    kwargs[field.name] = value
        opts = cls(**kwargs)
        if workdir:
            if opts.trace_path is None:
                opts.trace_path = os.path.join(workdir, "trace.json")
            if opts.flight_recorder_path is None:
                opts.flight_recorder_path = os.path.join(
                    workdir, "flight_record.jsonl"
                )
            if opts.goodput_summary_path is None:
                opts.goodput_summary_path = os.path.join(
                    workdir, goodput.SUMMARY_BASENAME
                )
        return opts
