"""rt1_tpu.obs — unified observability across train, data, and serve.

One subsystem, four pieces, all optional and all cheap when off:

* :mod:`rt1_tpu.obs.trace`      — host-side Chrome-trace span recorder
  (Perfetto-loadable); train loop, feeder workers, and serve batcher emit
  into one timeline.
* :mod:`rt1_tpu.obs.steps`      — `StepTimeline`: per-step wall-time
  attribution (wait_data / h2d / device_step / host) + the rolling
  `stall_pct` gauge.
* :mod:`rt1_tpu.obs.prometheus` — exposition text format + the opt-in
  scrape listener (`MetricsServer`).
* :mod:`rt1_tpu.obs.recorder`   — `FlightRecorder`: ring buffer of recent
  step records, dumped to JSONL on crash/SIGTERM.

Import hygiene is part of the contract: this package (and everything it
imports at module scope) must not require clu, tensorboard, or tensorflow
— headless serve deployments scrape `/metrics` without dragging in the
training stack. `tests/test_obs_imports.py` pins this.

See `docs/observability.md` for the operator guide.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

from rt1_tpu.obs import prometheus, recorder, steps, trace
from rt1_tpu.obs.prometheus import MetricsServer
from rt1_tpu.obs.recorder import FlightRecorder
from rt1_tpu.obs.steps import StepTimeline
from rt1_tpu.obs.trace import TraceRecorder

__all__ = [
    "FlightRecorder",
    "MetricsServer",
    "ObsOptions",
    "StepTimeline",
    "TraceRecorder",
    "prometheus",
    "recorder",
    "steps",
    "trace",
]


@dataclasses.dataclass
class ObsOptions:
    """Resolved `config.obs` with defaults for configs that predate it.

    The train loop consumes this instead of poking `config.obs.*` directly
    so pre-obs configs (proof configs, pinned sweep artifacts) keep running
    unmodified, and so defaults live in exactly one place.
    """

    trace: bool = False
    trace_path: Optional[str] = None  # None -> <workdir>/trace.json
    trace_max_events: int = 200_000
    stall_window: int = 50
    sync_timing: bool = False
    prometheus_port: int = -1  # < 0: no train-side listener; 0: ephemeral
    prometheus_host: str = "127.0.0.1"
    flight_recorder: bool = True
    flight_recorder_size: int = 256
    flight_recorder_path: Optional[str] = None  # None -> <workdir>/...jsonl

    @classmethod
    def from_config(cls, config, workdir: Optional[str] = None) -> "ObsOptions":
        """Read `config.obs` if present (ml_collections or plain mapping);
        absent keys fall back to the dataclass defaults."""
        node = None
        if config is not None:
            get = getattr(config, "get", None)
            node = get("obs") if callable(get) else getattr(config, "obs", None)
        kwargs = {}
        if node is not None:
            for field in dataclasses.fields(cls):
                getter = getattr(node, "get", None)
                value = (
                    getter(field.name)
                    if callable(getter)
                    else getattr(node, field.name, None)
                )
                if value is not None:
                    kwargs[field.name] = value
        opts = cls(**kwargs)
        if workdir:
            if opts.trace_path is None:
                opts.trace_path = os.path.join(workdir, "trace.json")
            if opts.flight_recorder_path is None:
                opts.flight_recorder_path = os.path.join(
                    workdir, "flight_record.jsonl"
                )
        return opts
