"""On-device model-health pack: is the model *learning*, not just stepping.

`obs/steps.py` answers "where does the step's wall time go"; nothing
answered "is the optimization healthy" — per-layer-group gradient norms,
update/param ratios, logit entropy — the signals that show divergence,
dead layers, or a collapsing policy long before the loss curve admits it.

The pack is computed *inside* the jitted train step (`trainer/train.py`,
behind ``config.obs.model_health``) so it inherits the step's contracts:

* **Zero host sync.** Every statistic is packed into ONE small replicated
  float32 vector returned alongside the step metrics; like `loss`, it is
  only fetched at log steps. No per-step D2H, no dispatch stall.
* **Donation-safe.** The pack never reads the *pre-update* params — that
  would keep every donated input buffer alive past the optimizer write
  and break the in-place-update aliasing. It consumes the optimizer's
  update tree instead (``TrainState.apply_gradients(return_updates=True)``;
  ``new = old + updates`` exactly, so nothing is lost).
* **Bit-identical when off.** The gate is a Python-level ``if`` in the
  step builder (the same discipline as the resilience guard): with
  ``model_health=False`` the traced program is exactly the pre-change one.

Layout is static per (param tree, depth, action_dims): :func:`pack_names`
computed on the host template and :func:`compute_pack` traced in the step
derive the same ordering from the same pure function, so the host can
unpack the fetched vector by position. Entries:

* ``health/grad_norm/<group>``     — L2 norm of the (averaged) gradients
  per layer group (param-tree path truncated to `depth` segments).
* ``health/update_ratio/<group>``  — ||params_new - params_old|| /
  (||params_new|| + eps), *post-optimizer* (LR schedule, Adam precond,
  and clipping included). The classic healthy band is ~1e-4..1e-2.
  The denominator is the post-update norm — within ~ratio² of the
  pre-update one, and it saves a whole extra param-tree reduction pass
  (the pack's cost budget is 2% of a *tiny* CPU step, bench --health).
* ``health/param_norm_global``     — global L2 of the updated params.
* ``health/update_norm_global``    — global L2 of the applied update.
* ``health/logit_entropy``         — mean action-token softmax entropy in
  nats (0 = deterministic collapse, log(vocab) = uniform; the copycat
  collapse diagnosed in RESULTS.md shows up here first).
* ``health/token_acc/dim<k>``      — per-action-dimension token accuracy
  of the argmax prediction against the label, one entry per action token.
* ``health/task_loss/<task>`` / ``health/task_acc/<task>`` /
  ``health/task_frac/<task>`` — per-task mean loss, token accuracy, and
  batch share, present only when the feeder emits per-example task ids
  (:data:`TASK_ID_KEY`; ``SampleAheadFeeder(emit_task_ids=True)``).
  Computed by a one-hot segment reduction inside the step — the
  multi-task quality signal (which reward families the policy is
  actually learning) at zero extra host syncs. A task absent from a
  batch reports loss/acc 0 with frac 0; read frac first.

Import-light by contract: jax only inside functions (pinned by
tests/test_obs_imports.py).
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Sequence, Tuple

#: Key under which the packed vector rides in the step metrics dict. The
#: train loop pops it before `scalars_from_metrics` (a vector has no
#: meaningful scalar mean) and unpacks it against `TrainStepFns.health_names`.
PACK_KEY = "health_pack"

#: Observation key carrying the per-example int32 task ids the feeder
#: emits (`SampleAheadFeeder(emit_task_ids=True)`). The step builder
#: strips it from the observations BEFORE the model forward and threads
#: it to `compute_pack` for the per-task one-hot segment reduction — the
#: model never sees it.
TASK_ID_KEY = "task_id"

#: Guard against division by a zero param norm (fresh zeros-init leaves).
_EPS = 1e-12

#: Default group depth: 2 path segments gives per-layer granularity on the
#: RT-1 tree (``transformer/layer_3``) without per-kernel explosion.
DEFAULT_GROUP_DEPTH = 2


def _path_str(path: Sequence[Any]) -> str:
    parts = []
    for k in path:
        parts.append(str(getattr(k, "key", getattr(k, "idx", k))))
    return "/".join(parts)


def param_groups(params: Any, depth: int = DEFAULT_GROUP_DEPTH) -> List[str]:
    """Sorted group names: param-tree paths truncated to `depth` segments.

    Pure function of the tree *structure* — callable on the host template
    state and inside a trace with identical results, which is what keeps
    the packed layout and the host-side names in lockstep.
    """
    import jax

    if depth < 1:
        raise ValueError(f"depth must be >= 1, got {depth}")
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    return sorted({_path_str(path[:depth]) for path, _ in leaves})


def pack_names(
    params: Any,
    depth: int = DEFAULT_GROUP_DEPTH,
    action_dims: int = 0,
    prefix: str = "health/",
    task_names: Sequence[str] = (),
) -> Tuple[str, ...]:
    """The pack's entry names, in pack order (host-side contract).

    `task_names` (non-empty only when the data stream carries per-example
    task ids AND the step produces action statistics) appends the
    per-task telemetry block: ``task_loss/<t>``, ``task_acc/<t>``,
    ``task_frac/<t>`` per task, in `task_names` order — the model-quality
    signals the eval matrix reads live as ``rt1_train_health_task_*``.
    """
    groups = param_groups(params, depth)
    names = [f"{prefix}grad_norm/{g}" for g in groups]
    names += [f"{prefix}update_ratio/{g}" for g in groups]
    names += [f"{prefix}param_norm_global", f"{prefix}update_norm_global"]
    if action_dims > 0:
        names.append(f"{prefix}logit_entropy")
        names += [f"{prefix}token_acc/dim{k}" for k in range(action_dims)]
        names += [f"{prefix}task_loss/{t}" for t in task_names]
        names += [f"{prefix}task_acc/{t}" for t in task_names]
        names += [f"{prefix}task_frac/{t}" for t in task_names]
    return tuple(names)


def _grouped_sumsq(tree: Any, depth: int) -> Dict[str, Any]:
    """{group: sum of squares} over the tree's leaves (traced).

    Per-leaf reductions, deliberately in the same form as
    `trainer.train.optax_global_norm` — when both run over the SAME tree
    (the gradients) XLA's CSE merges the subcomputations and this pass is
    free next to the ``grad_norm`` metric the step already emits.
    """
    import jax
    import jax.numpy as jnp

    out: Dict[str, Any] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        group = _path_str(path[:depth])
        sq = jnp.sum(jnp.square(jnp.asarray(leaf, jnp.float32)))
        out[group] = out.get(group, 0.0) + sq
    return out


def _grouped_sumsq_concat(tree: Any, depth: int) -> Dict[str, Any]:
    """Like :func:`_grouped_sumsq`, via one concat + one vdot per group.

    ~8 ops per tree instead of ~|leaves|: on XLA:CPU each un-fused
    reduction pays a dispatch, and the pack's budget is 2% of a *tiny*
    step (bench.py --health). The transient per-group flat copies are
    noise next to activations at RT-1 scale.
    """
    import jax
    import jax.numpy as jnp

    grouped: Dict[str, list] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        grouped.setdefault(_path_str(path[:depth]), []).append(
            jnp.ravel(jnp.asarray(leaf, jnp.float32))
        )
    out: Dict[str, Any] = {}
    for group, flats in grouped.items():
        v = flats[0] if len(flats) == 1 else jnp.concatenate(flats)
        out[group] = jnp.vdot(v, v)
    return out


def compute_pack(
    updates: Any,
    new_params: Any,
    grads: Any,
    out: Mapping[str, Any],
    depth: int = DEFAULT_GROUP_DEPTH,
    action_dims: int = 0,
    task_names: Sequence[str] = (),
):
    """Build the packed health vector inside the traced train step.

    `updates` is the optimizer's applied update tree (``new = old +
    updates``) — taking it instead of (old, new) params matters beyond
    convenience: a pack that reads the *pre-update* params would force
    XLA to keep every donated input param buffer alive past the optimizer
    write, breaking the in-place-update aliasing the donated-state
    contract exists for.

    `out` is the loss closure's aux dict; action-logit statistics are read
    from it only when ``action_dims > 0`` (the builder decides that
    statically — RT-1 loss with accum_steps == 1). Returns a float32
    vector whose entries line up with :func:`pack_names` called with the
    same (tree, depth, action_dims).
    """
    import jax
    import jax.numpy as jnp

    groups = param_groups(new_params, depth)
    # Grads per-leaf (CSE-merges with the step's grad_norm metric, ~free);
    # updates/new-params via concat+vdot (few ops — no metric to CSE with).
    grad_sq = _grouped_sumsq(grads, depth)
    upd_sq = _grouped_sumsq_concat(updates, depth)
    new_sq = _grouped_sumsq_concat(new_params, depth)

    parts = [
        jnp.stack([jnp.sqrt(grad_sq[g]) for g in groups]),
        jnp.stack(
            [
                jnp.sqrt(upd_sq[g]) / (jnp.sqrt(new_sq[g]) + _EPS)
                for g in groups
            ]
        ),
        jnp.sqrt(sum(new_sq[g] for g in groups))[None],
        jnp.sqrt(sum(upd_sq[g] for g in groups))[None],
    ]

    if action_dims > 0:
        logits = jnp.asarray(out["action_logits"], jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        parts.append(-jnp.mean(jnp.sum(jnp.exp(logp) * logp, axis=-1))[None])
        correct = (out["action_predictions"] == out["action_labels"]).astype(
            jnp.float32
        )  # (b, t, A)
        per_dim = jnp.mean(correct, axis=(0, 1))  # (A,)
        if per_dim.shape[0] != action_dims:
            raise ValueError(
                f"action_dims={action_dims} but the step produced "
                f"{per_dim.shape[0]} action token dims"
            )
        parts.append(per_dim)
        if task_names:
            # Per-task loss / token accuracy / batch share via ONE one-hot
            # segment reduction (K = len(task_names) matmuls fused by XLA):
            # the multi-task training signal, still zero host sync — it
            # rides the same replicated pack vector. Tasks absent from
            # this batch report 0 with frac 0 (readable as "no data", not
            # "perfectly learned": dashboards gate on task_frac).
            task_ids = jnp.asarray(out["task_ids"], jnp.int32)  # (b,)
            per_ex_loss = jnp.mean(
                jnp.asarray(out["action_loss"], jnp.float32), axis=-1
            )  # (b,)
            per_ex_acc = jnp.mean(correct, axis=(1, 2))  # (b,)
            onehot = jax.nn.one_hot(
                task_ids, len(task_names), dtype=jnp.float32
            )  # (b, K)
            counts = jnp.sum(onehot, axis=0)  # (K,)
            denom = jnp.maximum(counts, 1.0)
            parts.append(onehot.T @ per_ex_loss / denom)
            parts.append(onehot.T @ per_ex_acc / denom)
            parts.append(counts / task_ids.shape[0])
    return jnp.concatenate(parts).astype(jnp.float32)


def unpack(names: Sequence[str], vector: Any) -> Dict[str, float]:
    """Fetched pack vector -> {name: float} for the scalar stream.

    The names come out as e.g. ``health/grad_norm/transformer/layer_0`` —
    the clu writer takes them as-is, and the train Prometheus listener's
    sanitizer renders them as ``rt1_train_health_grad_norm_...`` gauges.
    """
    import numpy as np

    values = np.asarray(vector, dtype=np.float64).reshape(-1)
    if values.shape[0] != len(names):
        raise ValueError(
            f"health pack length {values.shape[0]} != {len(names)} names — "
            f"the step builder and the host disagree on the layout"
        )
    return {name: float(v) for name, v in zip(names, values)}
