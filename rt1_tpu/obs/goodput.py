"""Run-level goodput ledger: where did the run's *hours* go.

`StepTimeline` attributes one step's milliseconds; nothing attributed the
run's wall clock — a 97k-step job that spent 40 minutes compiling, lost an
epoch to a rollback replay, and stalled 20% on input looks identical to a
clean one in the step-level view. `GoodputLedger` partitions the whole
run's wall time into named buckets and keeps the arithmetic honest: the
fractions ALWAYS sum to 100% (an explicit ``unattributed`` bucket absorbs
whatever no instrument claimed, so a hole in coverage is visible instead
of silently inflating another bucket).

Buckets:

* ``init``            — process start to the first loop step: model build,
  dataset open, state init, sharding (checkpoint restore time is carved
  out into ``ckpt_restore`` even when it happens inside init).
* ``compile``         — the first executed step's whole wall time (XLA
  compilation dominates it; subsequent steps hit the executable cache).
* ``step``            — productive step time: everything in a non-replay
  step except its input-stall share. This is the GOODPUT bucket.
* ``data_stall``      — the ``wait_data + h2d`` share of productive steps
  (from the StepTimeline records the loop already produces).
* ``ckpt_save`` / ``ckpt_restore`` — checkpoint I/O, reported by the
  `trainer/checkpoints.py` retry wrappers via ``on_io``.
* ``rollback_replay`` — steps re-run after a guard rollback (the whole
  step, stall included: replayed time is badput regardless of why it was
  slow), plus nothing else — the triggering restore lands in
  ``ckpt_restore``.
* ``preempt_drain``   — from acting on the preemption signal to exit:
  force-save (carved out into ``ckpt_save``) + feeder drain.
* ``unattributed``    — wall minus everything above: logging, eval,
  Python between steps. Large values are a finding, not an error.

A live MFU gauge rides along when the loop hands the ledger a
FLOPs-per-step estimate (:mod:`rt1_tpu.obs.flops`): achieved FLOP/s over
*productive step time* against the chip's peak.

Everything is host-side stdlib arithmetic on numbers the loop already has;
the clock is injectable so tests pin the bucket algebra exactly. Scalars
flow through the ordinary writer at log steps (``goodput/*`` →
TensorBoard and ``rt1_train_goodput_*`` on the Prometheus listener), and
`write_summary` drops the final JSON next to the checkpoints —
`scripts/run_report.py` merges it with the flight-recorder dump and TB
events into the post-mortem report.

Import-light by contract: stdlib only (pinned by tests/test_obs_imports.py).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Callable, Dict, Mapping, Optional

#: Reporting order; ``unattributed`` is always computed, never accrued.
BUCKETS = (
    "init",
    "compile",
    "step",
    "data_stall",
    "ckpt_save",
    "ckpt_restore",
    "rollback_replay",
    "preempt_drain",
    "unattributed",
)

_IO_BUCKETS = ("ckpt_save", "ckpt_restore")

#: Default filename for the end-of-run summary (under the workdir).
SUMMARY_BASENAME = "goodput_summary.json"


class GoodputLedger:
    """Accrues run wall time into `BUCKETS`; fractions sum to 100%."""

    def __init__(self, clock: Callable[[], float] = time.perf_counter):
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.Lock()
        self._buckets: Dict[str, float] = {b: 0.0 for b in BUCKETS[:-1]}
        self._steps_productive = 0
        self._steps_replayed = 0
        self._rollbacks = 0
        self._preempted = False
        self._flops_per_step: Optional[float] = None
        self._peak_flops: Optional[float] = None
        self._n_chips = 1
        # One open phase at a time (the loop is single-threaded); I/O
        # reported while a phase is open is "stolen" from it so a restore
        # inside init is not double-counted.
        self._phase_name: Optional[str] = None
        self._phase_t0 = 0.0
        self._phase_stolen = 0.0

    # ------------------------------------------------------------- phases

    def open_phase(self, name: str) -> None:
        if name not in self._buckets:
            raise ValueError(f"unknown bucket {name!r}")
        with self._lock:
            if self._phase_name is not None:
                raise RuntimeError(
                    f"phase {self._phase_name!r} still open"
                )
            self._phase_name = name
            self._phase_t0 = self._clock()
            self._phase_stolen = 0.0

    def close_phase(self) -> None:
        with self._lock:
            if self._phase_name is None:
                raise RuntimeError("no open phase")
            dt = self._clock() - self._phase_t0 - self._phase_stolen
            self._buckets[self._phase_name] += max(dt, 0.0)
            self._phase_name = None

    @contextlib.contextmanager
    def phase(self, name: str):
        """Accrue the block's wall time to bucket `name`."""
        self.open_phase(name)
        try:
            yield
        finally:
            self.close_phase()

    # -------------------------------------------------------------- events

    def note_io(self, kind: str, seconds: float) -> None:
        """Checkpoint I/O time from the CheckpointManager's ``on_io`` hook.

        `kind` is "ckpt_save" or "ckpt_restore" (unknown kinds are folded
        into ckpt_save rather than dropped — I/O time must not vanish).
        Steals from the currently open phase so a restore during ``init``
        or a force-save during ``preempt_drain`` is counted once.
        """
        seconds = max(float(seconds), 0.0)
        bucket = kind if kind in _IO_BUCKETS else "ckpt_save"
        with self._lock:
            self._buckets[bucket] += seconds
            if self._phase_name is not None:
                self._phase_stolen += seconds

    def note_step(self, record: Mapping[str, Any], replay: bool = False) -> None:
        """Consume one StepTimeline record (ms buckets, see obs/steps.py).

        The first record of the run goes wholesale to ``compile``; replayed
        steps (post-rollback re-runs) go wholesale to ``rollback_replay``;
        everything else splits into ``data_stall`` (wait_data + h2d) and
        ``step`` (the productive remainder).
        """
        total = float(record.get("total_ms", 0.0)) / 1e3
        stall = (
            float(record.get("wait_data_ms", 0.0))
            + float(record.get("h2d_ms", 0.0))
        ) / 1e3
        stall = min(max(stall, 0.0), max(total, 0.0))
        with self._lock:
            first = self._steps_productive == 0 and self._steps_replayed == 0
            if first and self._buckets["compile"] == 0.0:
                self._buckets["compile"] += total
            elif replay:
                self._buckets["rollback_replay"] += total
                self._steps_replayed += 1
            else:
                self._buckets["data_stall"] += stall
                self._buckets["step"] += total - stall
                self._steps_productive += 1

    def mark_rollback(self) -> None:
        with self._lock:
            self._rollbacks += 1

    def mark_preempted(self) -> None:
        with self._lock:
            self._preempted = True

    def set_flops_per_step(
        self,
        flops: Optional[float],
        peak_flops: Optional[float] = None,
        n_chips: int = 1,
    ) -> None:
        """Arm the MFU gauge (flops=None leaves it disarmed)."""
        with self._lock:
            self._flops_per_step = float(flops) if flops else None
            self._peak_flops = peak_flops
            self._n_chips = max(int(n_chips), 1)

    # ----------------------------------------------------------- reporting

    def _snapshot(self) -> Dict[str, float]:
        """Buckets incl. live partial of an open phase (scrape-safe)."""
        with self._lock:
            out = dict(self._buckets)
            if self._phase_name is not None:
                live = self._clock() - self._phase_t0 - self._phase_stolen
                out[self._phase_name] += max(live, 0.0)
            return out

    def wall_s(self) -> float:
        return max(self._clock() - self._t0, 0.0)

    def mfu_pct(self) -> Optional[float]:
        """Live MFU over productive step time, or None when disarmed."""
        with self._lock:
            flops, steps = self._flops_per_step, self._steps_productive
            step_s = self._buckets["step"]
            peak, n_chips = self._peak_flops, self._n_chips
        if not flops or steps <= 0 or step_s <= 0:
            return None
        from rt1_tpu.obs import flops as flops_lib

        return flops_lib.mfu_pct(
            flops, step_s / steps, n_chips=n_chips, peak_flops=peak
        )

    def summary(self) -> Dict[str, Any]:
        """Final (or live) ledger: seconds, fractions summing to 1.0."""
        buckets = self._snapshot()
        attributed = sum(buckets.values())
        wall = self.wall_s()
        # The denominator is whichever is larger: clock skew between the
        # run timer and the per-bucket timers must never produce a
        # negative bucket or fractions past 1.
        denom = max(wall, attributed)
        buckets["unattributed"] = denom - attributed
        fractions = {
            b: (buckets[b] / denom if denom > 0 else 0.0) for b in BUCKETS
        }
        goodput_s = buckets["step"]
        out: Dict[str, Any] = {
            "wall_s": wall,
            "buckets_s": {b: buckets[b] for b in BUCKETS},
            "fractions": fractions,
            "goodput_pct": fractions["step"] * 100.0,
            "badput_pct": (1.0 - fractions["step"]) * 100.0,
            "steps_productive": self._steps_productive,
            "steps_replayed": self._steps_replayed,
            "rollbacks": self._rollbacks,
            "preempted": self._preempted,
        }
        if self._steps_productive > 0 and goodput_s > 0:
            out["sec_per_productive_step"] = (
                goodput_s / self._steps_productive
            )
        mfu = self.mfu_pct()
        if mfu is not None:
            out["mfu_pct"] = mfu
            out["flops_per_step"] = self._flops_per_step
        return out

    def scalars(self, prefix: str = "goodput/") -> Dict[str, float]:
        """Flat gauges for the writer/Prometheus (``rt1_train_goodput_*``)."""
        s = self.summary()
        out = {f"{prefix}wall_s": s["wall_s"]}
        for b in BUCKETS:
            out[f"{prefix}{b}_s"] = s["buckets_s"][b]
            out[f"{prefix}{b}_pct"] = s["fractions"][b] * 100.0
        out[f"{prefix}goodput_pct"] = s["goodput_pct"]
        out[f"{prefix}badput_pct"] = s["badput_pct"]
        out[f"{prefix}steps_replayed"] = float(s["steps_replayed"])
        out[f"{prefix}rollbacks_total"] = float(s["rollbacks"])
        out[f"{prefix}preempted"] = 1.0 if s["preempted"] else 0.0
        if "mfu_pct" in s:
            out[f"{prefix}mfu_pct"] = s["mfu_pct"]
        return out

    def write_summary(self, path: str) -> str:
        """Write the JSON summary (the run_report/post-mortem artifact)."""
        summary = self.summary()
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        with open(path, "w") as f:
            json.dump(summary, f, indent=2, sort_keys=True)
            f.write("\n")
        return path


def read_summary(path: str) -> Dict[str, Any]:
    """Load a written summary (run_report's side of the contract)."""
    with open(path) as f:
        return json.load(f)
