"""XLA cost-analysis FLOPs + MFU arithmetic, shared by bench and the ledger.

Extracted from ``bench.py --mode mfu`` (which remains the lab A/B
entrypoint) so the *same* estimator can feed the run-level goodput ledger
(:mod:`rt1_tpu.obs.goodput`) as a live ``goodput/mfu_pct`` gauge: FLOPs per
train step come from XLA's own cost analysis of the step program — the
whole fwd+bwd+update graph, not a hand-derived 6·N·D guess — and MFU is
``measured FLOP/s / peak FLOP/s``.

Two analysis paths, deliberately distinct:

* :func:`train_step_flops` with ``compile=False`` (default) analyzes the
  *lowered* (pre-compile) program. No executable is built, so the train
  loop can estimate FLOPs from ``ShapeDtypeStruct`` avals without paying a
  second multi-minute compile or touching device memory.
* ``compile=True`` analyzes the *compiled* executable — post-fusion, the
  numbers ``bench.py --mode mfu`` has always published. Bench keeps this
  path so its baselines stay comparable.

Peak FLOP/s defaults to a v5e chip's bf16 197 TFLOP/s; override with the
``RT1_TPU_PEAK_FLOPS`` env var for other generations (same knob bench has
always honored).

Import-light by contract: stdlib at module scope, jax only inside the
functions that analyze a program (pinned by tests/test_obs_imports.py).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

#: Default peak FLOP/s assumed for MFU: one v5e chip's bf16 peak.
DEFAULT_PEAK_FLOPS = 197e12

PEAK_FLOPS_ENV = "RT1_TPU_PEAK_FLOPS"


def default_peak_flops() -> float:
    """Peak FLOP/s per chip: ``RT1_TPU_PEAK_FLOPS`` env or the v5e default."""
    return float(os.environ.get(PEAK_FLOPS_ENV, DEFAULT_PEAK_FLOPS))


def cost_analysis_flops(cost: Any) -> float:
    """Pull the 'flops' entry out of a jax cost-analysis result.

    Handles both shapes jax has returned over versions: a plain dict, or a
    one-element list/tuple of dicts (one per XLA computation).
    """
    if cost is None:
        return 0.0
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return float(cost.get("flops", 0.0))


def train_step_flops(
    jitted_fn: Any, *args: Any, compile: bool = False
) -> Optional[float]:
    """FLOPs of one call of `jitted_fn(*args)` per XLA cost analysis.

    `args` may be concrete arrays or ``jax.ShapeDtypeStruct`` avals (the
    train loop passes avals so no device transfer happens). Returns None
    when the analysis is unavailable or reports zero — callers treat that
    as "no MFU gauge", never as a real measurement.
    """
    try:
        lowered = jitted_fn.lower(*args)
        target = lowered.compile() if compile else lowered
        flops = cost_analysis_flops(target.cost_analysis())
    except Exception:  # noqa: BLE001 - an estimator must never kill a run
        return None
    return flops if flops > 0 else None


def mfu_pct(
    flops_per_step: float,
    sec_per_step: float,
    n_chips: int = 1,
    peak_flops: Optional[float] = None,
) -> float:
    """Model-FLOPs-utilization in percent: achieved / peak FLOP/s."""
    if sec_per_step <= 0 or flops_per_step <= 0:
        return 0.0
    peak = default_peak_flops() if peak_flops is None else float(peak_flops)
    n = max(int(n_chips), 1)
    return flops_per_step / sec_per_step / (peak * n) * 100.0


def mfu_detail(
    flops_per_step: float,
    sec_per_step: float,
    n_chips: int = 1,
    peak_flops: Optional[float] = None,
) -> Dict[str, float]:
    """The stderr detail dict bench has always printed next to the metric."""
    peak = default_peak_flops() if peak_flops is None else float(peak_flops)
    return {
        "flops_per_step": float(flops_per_step),
        "sec_per_step": round(float(sec_per_step), 6),
        "peak_flops_assumed": peak,
        "mfu_pct": round(
            mfu_pct(flops_per_step, sec_per_step, n_chips, peak), 3
        ),
    }
