"""Scrape loop: poll exposition/status targets into the TSDB, evaluate.

The one writer the metrics plane needs: every ``interval_s`` the
collector fetches each target — the train process's Prometheus listener,
the router's fleet fan-out ``/metrics``, the supervisor's
``/deploy/status`` JSON — parses it (`parse_exposition`, the provable
inverse of the renderers in ``obs/prometheus.py``), and appends every
sample into the TSDB under ONE shared timestamp per cycle, so windowed
queries across families line up. When an `AlertManager` is attached,
each cycle ends with one evaluation pass — scrape cadence IS alert
cadence, exactly like a Prometheus rule group.

Targets are declarative (`Target(name, url, kind)`): ``metrics`` targets
speak exposition text; ``json`` targets are flattened — numeric leaves
become families named ``<prefix><dotted_path>`` (bools as 0/1, strings
and lists skipped), which is how ``/deploy/status`` history lands
without a second renderer.

A target that fails to answer is a *counted* fact
(``rt1_obs_collector_scrape_errors_total{target=...}``), never an
exception out of the loop: the collector is the component that must
outlive the incident it is recording.

Runs as a daemon thread inside the fleet supervisor (``--collector``)
or standalone (`scripts/obs_collector.py`). Stdlib-only — urllib, no
requests — same import-light contract as the rest of ``obs/``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from rt1_tpu.obs.alerts import AlertManager
from rt1_tpu.obs.prometheus import TextExposition, parse_exposition
from rt1_tpu.obs.tsdb import TSDB

KINDS = ("metrics", "json")


@dataclasses.dataclass(frozen=True)
class Target:
    """One thing to poll. ``prefix`` applies to json targets only: the
    family namespace flattened leaves land under."""

    name: str
    url: str
    kind: str = "metrics"
    prefix: str = ""

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"kind {self.kind!r} not in {KINDS}")


def _default_fetch(url: str, timeout_s: float) -> str:
    # The router's /metrics content-negotiates (JSON by default, text
    # when asked); a scraper without an Accept header would get JSON and
    # fail exposition parsing. The train listener always answers text,
    # so the header is harmless there.
    req = urllib.request.Request(url, headers={"Accept": "text/plain"})
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return resp.read().decode("utf-8")


def flatten_json(
    obj: Any, prefix: str = ""
) -> List[Tuple[str, Optional[Dict[str, str]], float]]:
    """Numeric leaves of a JSON document as (family, labels, value)
    samples: nested keys join with ``_``, bools coerce to 0/1, strings
    and lists are skipped (history stores numbers; the info-style state
    strings already ride the exposition targets)."""
    out: List[Tuple[str, Optional[Dict[str, str]], float]] = []
    if isinstance(obj, dict):
        for key, value in obj.items():
            path = f"{prefix}_{key}" if prefix else str(key)
            out.extend(flatten_json(value, path))
    elif isinstance(obj, bool):
        out.append((prefix, None, 1.0 if obj else 0.0))
    elif isinstance(obj, (int, float)):
        out.append((prefix, None, float(obj)))
    return out


class Collector:
    """The scrape loop. `scrape_once()` is the unit of work (and the unit
    the tests drive with an injected clock + fetch_fn); `start()` runs it
    on a daemon thread every `interval_s`."""

    def __init__(
        self,
        tsdb: TSDB,
        targets: Sequence[Target],
        interval_s: float = 5.0,
        alert_manager: Optional[AlertManager] = None,
        clock=time.time,
        fetch_fn: Optional[Callable[[str, float], str]] = None,
        timeout_s: float = 2.0,
    ):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be positive, got {interval_s}")
        names = [t.name for t in targets]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate target names in {names}")
        self.tsdb = tsdb
        self.targets = list(targets)
        self.interval_s = float(interval_s)
        self.alert_manager = alert_manager
        self._clock = clock
        self._fetch = fetch_fn or _default_fetch
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        self._per_target: Dict[str, Dict[str, float]] = {
            t.name: {
                "scrapes_total": 0.0,
                "scrape_errors_total": 0.0,
                "samples_ingested_total": 0.0,
                "last_scrape_duration_s": 0.0,
                "up": 0.0,
            }
            for t in self.targets
        }
        self.cycles_total = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -------------------------------------------------------------- scraping

    def scrape_once(self, now: Optional[float] = None) -> Dict[str, int]:
        """One full cycle: every target, one shared sample timestamp,
        then one alert evaluation. Returns {target: samples_ingested}
        (-1 marks a failed scrape)."""
        if now is None:
            now = self._clock()
        ingested: Dict[str, int] = {}
        for target in self.targets:
            t0 = time.perf_counter()
            try:
                body = self._fetch(target.url, self.timeout_s)
                samples = self._parse(target, body)
                self.tsdb.append_many(samples, t=now)
            except Exception:  # noqa: BLE001 - a dead target is a
                # counted fact, not a loop exit.
                with self._lock:
                    stats = self._per_target[target.name]
                    stats["scrapes_total"] += 1
                    stats["scrape_errors_total"] += 1
                    stats["last_scrape_duration_s"] = (
                        time.perf_counter() - t0
                    )
                    stats["up"] = 0.0
                ingested[target.name] = -1
                continue
            with self._lock:
                stats = self._per_target[target.name]
                stats["scrapes_total"] += 1
                stats["samples_ingested_total"] += len(samples)
                stats["last_scrape_duration_s"] = time.perf_counter() - t0
                stats["up"] = 1.0
            ingested[target.name] = len(samples)
        with self._lock:
            self.cycles_total += 1
        if self.alert_manager is not None:
            self.alert_manager.evaluate(now=now)
        return ingested

    @staticmethod
    def _parse(
        target: Target, body: str
    ) -> List[Tuple[str, Optional[Dict[str, str]], float]]:
        if target.kind == "json":
            import json

            return flatten_json(json.loads(body), target.prefix)
        parsed = parse_exposition(body)
        return [
            (name, labels or None, value)
            for name, labels, value in parsed.samples
        ]

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("collector already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="rt1-obs-collector", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            self.scrape_once()
            self._stop.wait(self.interval_s)

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            self._thread = None

    # ------------------------------------------------------------- reporting

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "cycles_total": self.cycles_total,
                "interval_s": self.interval_s,
                "targets": {
                    name: dict(stats)
                    for name, stats in self._per_target.items()
                },
            }

    def prometheus_text(self, prefix: str = "rt1_obs_collector_") -> str:
        """``rt1_obs_collector_*``: per-target scrape bookkeeping as
        labeled families, appended to the ops scrape when armed."""
        stats = self.stats()
        exp = TextExposition()
        exp.counter(
            prefix + "cycles_total",
            float(stats["cycles_total"]),
            "Completed scrape cycles.",
        )
        per_target = stats["targets"]
        ordered = sorted(per_target)
        for key, mtype, help_text in (
            ("up", "gauge", "1 when the target's last scrape succeeded."),
            ("scrapes_total", "counter", "Scrape attempts per target."),
            (
                "scrape_errors_total",
                "counter",
                "Scrape attempts that failed per target.",
            ),
            (
                "samples_ingested_total",
                "counter",
                "Samples appended into the TSDB per target.",
            ),
            (
                "last_scrape_duration_s",
                "gauge",
                "Wall seconds the last scrape of this target took.",
            ),
        ):
            samples = [
                ({"target": name}, per_target[name][key])
                for name in ordered
            ]
            if samples:
                exp.family(prefix + key, mtype, samples, help_text)
        return exp.render()
