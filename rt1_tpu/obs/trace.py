"""Chrome-trace-event span recorder: one host-side timeline across threads.

The XPlane traces from `jax.profiler` show device ops but are blind to the
host threads that feed them — the train loop, the `SampleAheadFeeder`
workers, the serve micro-batcher all spend wall time the device profiler
cannot attribute. This module records *host* spans from any thread into one
in-memory ring and serializes them as Chrome trace events (the
`{"traceEvents": [...]}` JSON that `chrome://tracing` and Perfetto load
directly), so a single file shows the feeder assembling batch N+2 while
the train loop blocks on batch N's H2D.

Design constraints, in order:

1. ~zero cost when disabled. Instrumented hot paths (`feeder._worker`
   assembles a batch in under a millisecond) call `span(...)` per
   iteration; when no recorder is installed that must be one global read
   and one shared no-op context manager — no allocation, no lock.
2. Thread-safe when enabled. Events land on a `collections.deque`, whose
   `append` is atomic under the GIL; the only lock guards the
   first-event-per-thread name registration.
3. Bounded. The deque is a ring (`max_events`): a week-long run with
   tracing left on keeps the most recent window instead of eating the
   host's RAM. Dropped-event count is reported in the dump's metadata.

Usage:

    from rt1_tpu.obs import trace
    trace.enable("/tmp/run/trace.json")   # or enable(None) + dump(path)
    with trace.span("assemble", ticket=7):
        ...
    trace.counter("feeder_queue_depth", depth)
    trace.dump()                          # writes the JSON, keeps recording

`enable()` is idempotent and returns the live recorder; `disable()`
uninstalls (a final `dump()` happens automatically if a path was given).
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

# Perf-counter origin shared by every event so spans from different threads
# line up on one clock. Chrome trace timestamps are microseconds.
_EPOCH = time.perf_counter()


def _now_us() -> float:
    return (time.perf_counter() - _EPOCH) * 1e6


def now_us() -> float:
    """Current time on the trace clock (µs since the process epoch) —
    capture one of these per phase boundary, then emit with `complete`.
    Valid whether or not a recorder is installed, so phase stamping can
    be unconditional while emission stays gated."""
    return _now_us()


class _NullSpan:
    """Shared no-op context manager: the disabled-tracer fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """One live span; records a complete ("X") event on exit."""

    __slots__ = ("_recorder", "_name", "_args", "_t0")

    def __init__(self, recorder: "TraceRecorder", name: str, args):
        self._recorder = recorder
        self._name = name
        self._args = args
        self._t0 = _now_us()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._recorder._complete(self._name, self._t0, _now_us() - self._t0,
                                 self._args)
        return False


class TraceRecorder:
    """Thread-safe in-memory trace-event ring."""

    def __init__(self, path: Optional[str] = None, max_events: int = 200_000):
        self.path = path
        self._events: collections.deque = collections.deque(
            maxlen=max(int(max_events), 1)
        )
        self._pid = os.getpid()
        self._meta_lock = threading.Lock()
        self._named_tids: set = set()
        self._meta_events: List[Dict[str, Any]] = []
        self._appended = 0

    # ------------------------------------------------------------ recording

    def _tid(self) -> int:
        t = threading.current_thread()
        tid = t.ident or 0
        if tid not in self._named_tids:
            with self._meta_lock:
                if tid not in self._named_tids:
                    self._named_tids.add(tid)
                    # Thread-name metadata events make Perfetto label the
                    # track "rt1-feeder-0" instead of a bare ident.
                    self._meta_events.append(
                        {
                            "ph": "M",
                            "name": "thread_name",
                            "pid": self._pid,
                            "tid": tid,
                            "args": {"name": t.name},
                        }
                    )
        return tid

    def _append(self, event: Dict[str, Any]) -> None:
        self._appended += 1
        self._events.append(event)

    def _complete(self, name: str, ts_us: float, dur_us: float, args) -> None:
        event = {
            "ph": "X",
            "name": name,
            "pid": self._pid,
            "tid": self._tid(),
            "ts": ts_us,
            "dur": dur_us,
        }
        if args:
            event["args"] = args
        self._append(event)

    def span(self, name: str, **args) -> _Span:
        return _Span(self, name, args or None)

    def complete(self, name: str, ts_us: float, dur_us: float, **args) -> None:
        """Record a complete event from explicit timestamps (`now_us()`
        clock). This is how cross-thread phases become spans: `span()`
        times the current thread's with-block, but a request's queue wait
        starts on an HTTP handler thread and ends on the batcher loop —
        the waiter stamps both ends and emits the span after the fact."""
        self._complete(name, ts_us, max(dur_us, 0.0), args or None)

    def instant(self, name: str, **args) -> None:
        """Point-in-time marker (thread-scoped)."""
        event = {
            "ph": "i",
            "s": "t",
            "name": name,
            "pid": self._pid,
            "tid": self._tid(),
            "ts": _now_us(),
        }
        if args:
            event["args"] = args
        self._append(event)

    def counter(self, name: str, value: float, **series) -> None:
        """Counter track (queue depths, gauge time-series)."""
        self._append(
            {
                "ph": "C",
                "name": name,
                "pid": self._pid,
                "tid": 0,
                "ts": _now_us(),
                "args": series if series else {"value": value},
            }
        )

    # ------------------------------------------------------------ reporting

    @property
    def dropped(self) -> int:
        return self._appended - len(self._events)

    def to_dict(self) -> Dict[str, Any]:
        """Chrome trace JSON object (snapshot; recording may continue)."""
        with self._meta_lock:
            meta = list(self._meta_events)
        return {
            "traceEvents": meta + list(self._events),
            "displayTimeUnit": "ms",
            "otherData": {
                "producer": "rt1_tpu.obs.trace",
                "dropped_events": self.dropped,
            },
        }

    def dump(self, path: Optional[str] = None) -> str:
        """Write the trace JSON; returns the path written."""
        path = path or self.path
        if not path:
            raise ValueError("no dump path: pass one or construct with path=")
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f)
        os.replace(tmp, path)
        return path


# ---------------------------------------------------------------- module API
#
# One process-wide recorder keeps the call sites dependency-free: the feeder
# and batcher just call `trace.span(...)` and stay no-ops until something
# (train loop, bench --trace, a test) installs a recorder.

_tracer: Optional[TraceRecorder] = None


def enable(
    path: Optional[str] = None, max_events: Optional[int] = None
) -> TraceRecorder:
    """Install (or return the already-installed) process-wide recorder.

    Explicit arguments win even when a recorder already exists (a stale
    recorder from an aborted run must not silently hijack the new run's
    dump path or ring size); existing events are preserved across a
    resize. Omitted arguments keep whatever is installed (new recorders
    default to 200k events).
    """
    global _tracer
    if _tracer is None:
        _tracer = TraceRecorder(
            path=path,
            max_events=200_000 if max_events is None else max_events,
        )
        return _tracer
    if path:
        _tracer.path = path
    if max_events is not None and max_events != _tracer._events.maxlen:
        _tracer._events = collections.deque(
            _tracer._events, maxlen=max(int(max_events), 1)
        )
    return _tracer


def disable() -> None:
    """Uninstall; dumps first when the recorder was given a path."""
    global _tracer
    t, _tracer = _tracer, None
    if t is not None and t.path:
        t.dump()


def active() -> Optional[TraceRecorder]:
    return _tracer


def enabled() -> bool:
    return _tracer is not None


def span(name: str, **args):
    """Context manager timing one span on the current thread.

    The disabled path is one global load + returning a shared no-op object;
    keyword construction is the only per-call cost left to the caller.
    """
    t = _tracer
    if t is None:
        return _NULL_SPAN
    return t.span(name, **args)


def instant(name: str, **args) -> None:
    t = _tracer
    if t is not None:
        t.instant(name, **args)


def complete(name: str, ts_us: float, dur_us: float, **args) -> None:
    """Record a complete event from explicit `now_us()` timestamps
    (no-op when disabled) — the cross-thread span path; see
    `TraceRecorder.complete`."""
    t = _tracer
    if t is not None:
        t.complete(name, ts_us, dur_us, **args)


def counter(name: str, value: float = 0.0, **series) -> None:
    t = _tracer
    if t is not None:
        t.counter(name, value, **series)


def dump(path: Optional[str] = None) -> Optional[str]:
    """Dump the active recorder (no-op when disabled); returns the path."""
    t = _tracer
    if t is None:
        return None
    return t.dump(path)
