"""Ops console rendering: sparklines + alert table, HTML and terminal.

One render path, two skins. ``render_dashboard_html`` produces a single
self-contained page — inline CSS, inline SVG sparklines, zero external
assets, a meta-refresh tag instead of JavaScript — served by the
router's ``/dashboard`` endpoint; ``render_console`` produces the same
story as terminal text (unicode block sparklines) for
`scripts/obs_console.py`. Both read the TSDB/AlertManager/Collector
objects directly when in-process, or the snapshot/status JSON when
remote, so the dashboard can never disagree with the store it renders.

Stdlib-only (``html.escape`` is the only import beyond typing) — this
must stay importable in the clu/TF/jax-free router process.
"""

from __future__ import annotations

import html
from typing import Any, Dict, List, Optional, Sequence, Tuple

_BLOCKS = "▁▂▃▄▅▆▇█"


def spark_line(values: Sequence[float], width: int = 40) -> str:
    """Unicode block sparkline, newest right. Downsamples by striding
    when more values than columns; flat series render mid-height."""
    vals = [float(v) for v in values]
    if not vals:
        return ""
    if len(vals) > width:
        stride = len(vals) / width
        vals = [vals[int(i * stride)] for i in range(width - 1)] + [vals[-1]]
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _BLOCKS[3] * len(vals)
    return "".join(
        _BLOCKS[
            min(len(_BLOCKS) - 1, int((v - lo) / span * (len(_BLOCKS) - 1)))
        ]
        for v in vals
    )


def spark_svg(
    points: Sequence[Tuple[float, float]],
    width: int = 240,
    height: int = 36,
) -> str:
    """Inline SVG polyline over (t, value) points — the HTML dashboard's
    sparkline. Degenerate inputs (no points, zero span) render a flat
    midline so every series row keeps its shape."""
    if not points:
        return f'<svg width="{width}" height="{height}"></svg>'
    ts = [p[0] for p in points]
    vs = [p[1] for p in points]
    t_lo, t_hi = min(ts), max(ts)
    v_lo, v_hi = min(vs), max(vs)
    t_span = (t_hi - t_lo) or 1.0
    v_span = (v_hi - v_lo) or 1.0
    pad = 2
    coords = " ".join(
        f"{pad + (t - t_lo) / t_span * (width - 2 * pad):.1f},"
        f"{height - pad - (v - v_lo) / v_span * (height - 2 * pad):.1f}"
        for t, v in points
    )
    return (
        f'<svg width="{width}" height="{height}" '
        f'viewBox="0 0 {width} {height}">'
        f'<polyline fill="none" stroke="#4a90d9" stroke-width="1.5" '
        f'points="{coords}"/></svg>'
    )


def _series_rows(
    tsdb, window_s: float, max_series: int
) -> List[Dict[str, Any]]:
    """The flattened per-series view both skins iterate: family, labels,
    latest value, and the windowed points for the sparkline."""
    rows: List[Dict[str, Any]] = []
    for entry in tsdb.series_index():
        if len(rows) >= max_series:
            break
        pts = tsdb.points(
            entry["family"], labels=entry["labels"] or None,
            window_s=window_s,
        )
        if not pts:
            continue
        rows.append(
            {
                "family": entry["family"],
                "labels": entry["labels"],
                "latest": pts[-1][1],
                "points": pts,
            }
        )
    return rows


def _label_text(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


_SEVERITY_COLORS = {"page": "#d9534a", "warn": "#e8a33d", "info": "#4a90d9"}

_CSS = """
body{font-family:ui-monospace,Menlo,Consolas,monospace;background:#11151a;
color:#cdd6e0;margin:1.5em;font-size:13px}
h1{font-size:16px;color:#e8edf2}h2{font-size:14px;color:#9fb3c8;
border-bottom:1px solid #2a3440;padding-bottom:4px}
table{border-collapse:collapse;width:100%}
td,th{padding:3px 10px;text-align:left;border-bottom:1px solid #1d242c;
vertical-align:middle}th{color:#7d8fa3}
.num{text-align:right;font-variant-numeric:tabular-nums}
.state-firing{color:#d9534a;font-weight:bold}
.state-pending{color:#e8a33d}
.ok{color:#5cb85c}.muted{color:#5d6b7a}
"""


def render_dashboard_html(
    tsdb,
    alert_manager=None,
    collector=None,
    fleet_status: Optional[Dict[str, Any]] = None,
    deploy_status: Optional[Dict[str, Any]] = None,
    title: str = "rt1 ops",
    window_s: float = 900.0,
    max_series: int = 120,
    refresh_s: int = 5,
) -> str:
    """The whole ops story as one self-contained HTML document."""
    e = html.escape
    parts: List[str] = [
        "<!doctype html><html><head>",
        f"<meta charset='utf-8'><title>{e(title)}</title>",
        f"<meta http-equiv='refresh' content='{int(refresh_s)}'>",
        f"<style>{_CSS}</style></head><body>",
        f"<h1>{e(title)}</h1>",
    ]
    # --- alerts -----------------------------------------------------------
    if alert_manager is not None:
        active = alert_manager.active()
        parts.append("<h2>Alerts</h2>")
        if not active:
            parts.append("<p class='ok'>no active alerts</p>")
        else:
            parts.append(
                "<table><tr><th>alert</th><th>severity</th><th>state</th>"
                "<th>labels</th><th class='num'>value</th>"
                "<th>summary</th></tr>"
            )
            for a in active:
                color = _SEVERITY_COLORS.get(a["severity"], "#cdd6e0")
                parts.append(
                    f"<tr><td>{e(a['alert'])}</td>"
                    f"<td style='color:{color}'>{e(a['severity'])}</td>"
                    f"<td class='state-{e(a['state'])}'>{e(a['state'])}"
                    f"</td><td>{e(_label_text(a['labels']))}</td>"
                    f"<td class='num'>{a['value']:.4g}</td>"
                    f"<td class='muted'>"
                    f"{e(a['annotations'].get('summary', ''))}</td></tr>"
                )
            parts.append("</table>")
        history = alert_manager.history()
        if history:
            parts.append("<h2>Alert history</h2><table>")
            parts.append(
                "<tr><th>t</th><th>event</th><th>alert</th>"
                "<th>labels</th><th class='num'>value</th></tr>"
            )
            for ev in reversed(history[-20:]):
                parts.append(
                    f"<tr><td class='muted'>{ev['t']:.1f}</td>"
                    f"<td class='state-{e(ev['event'])}'>"
                    f"{e(ev['event'])}</td><td>{e(ev['alert'])}</td>"
                    f"<td>{e(_label_text(ev['labels']))}</td>"
                    f"<td class='num'>{ev['value']:.4g}</td></tr>"
                )
            parts.append("</table>")
    # --- fleet / deploy state --------------------------------------------
    for name, status in (("Fleet", fleet_status), ("Deploy", deploy_status)):
        if not status:
            continue
        parts.append(f"<h2>{name}</h2><table>")
        for key in sorted(status):
            value = status[key]
            if isinstance(value, (dict, list)):
                continue
            parts.append(
                f"<tr><td>{e(str(key))}</td>"
                f"<td class='num'>{e(str(value))}</td></tr>"
            )
        parts.append("</table>")
    # --- collector --------------------------------------------------------
    if collector is not None:
        stats = collector.stats()
        parts.append("<h2>Collector</h2><table>")
        parts.append(
            "<tr><th>target</th><th class='num'>up</th>"
            "<th class='num'>scrapes</th><th class='num'>errors</th>"
            "<th class='num'>samples</th><th class='num'>last (ms)</th>"
            "</tr>"
        )
        for tname in sorted(stats["targets"]):
            t = stats["targets"][tname]
            up = "<span class='ok'>1</span>" if t["up"] else (
                "<span class='state-firing'>0</span>"
            )
            parts.append(
                f"<tr><td>{e(tname)}</td><td class='num'>{up}</td>"
                f"<td class='num'>{int(t['scrapes_total'])}</td>"
                f"<td class='num'>{int(t['scrape_errors_total'])}</td>"
                f"<td class='num'>{int(t['samples_ingested_total'])}</td>"
                f"<td class='num'>"
                f"{t['last_scrape_duration_s'] * 1e3:.1f}</td></tr>"
            )
        parts.append("</table>")
    # --- history sparklines ----------------------------------------------
    rows = _series_rows(tsdb, window_s, max_series)
    parts.append(
        f"<h2>History ({len(rows)} series, last {window_s:g}s)</h2>"
    )
    if rows:
        parts.append("<table>")
        for row in rows:
            parts.append(
                f"<tr><td>{e(row['family'])}"
                f"<span class='muted'>"
                f"{e(_label_text(row['labels']))}</span></td>"
                f"<td>{spark_svg(row['points'])}</td>"
                f"<td class='num'>{row['latest']:.6g}</td></tr>"
            )
        parts.append("</table>")
    else:
        parts.append("<p class='muted'>no samples yet</p>")
    parts.append("</body></html>")
    return "".join(parts)


def render_console(
    tsdb,
    alert_manager=None,
    collector=None,
    window_s: float = 900.0,
    max_series: int = 40,
    width: int = 40,
) -> str:
    """The terminal skin: same sections as the HTML, block sparklines."""
    lines: List[str] = []
    if alert_manager is not None:
        active = alert_manager.active()
        lines.append(f"ALERTS ({len(active)} active)")
        if not active:
            lines.append("  none")
        for a in active:
            lines.append(
                f"  [{a['severity']:>4}] {a['state']:<7} {a['alert']}"
                f"{_label_text(a['labels'])} = {a['value']:.4g}"
            )
        history = alert_manager.history()
        if history:
            lines.append("RECENT EVENTS")
            for ev in history[-8:]:
                lines.append(
                    f"  t={ev['t']:.1f} {ev['event']:<8} {ev['alert']}"
                    f"{_label_text(ev['labels'])}"
                )
    if collector is not None:
        stats = collector.stats()
        lines.append(f"COLLECTOR (cycles={stats['cycles_total']})")
        for tname in sorted(stats["targets"]):
            t = stats["targets"][tname]
            state = "up" if t["up"] else "DOWN"
            lines.append(
                f"  {tname:<16} {state:<4} scrapes="
                f"{int(t['scrapes_total'])} errors="
                f"{int(t['scrape_errors_total'])} samples="
                f"{int(t['samples_ingested_total'])}"
            )
    rows = _series_rows(tsdb, window_s, max_series)
    lines.append(f"HISTORY ({len(rows)} series, last {window_s:g}s)")
    name_w = max(
        [len(r["family"] + _label_text(r["labels"])) for r in rows],
        default=0,
    )
    for row in rows:
        name = row["family"] + _label_text(row["labels"])
        spark = spark_line([v for _, v in row["points"]], width=width)
        lines.append(
            f"  {name:<{name_w}} {spark:<{width}} {row['latest']:.6g}"
        )
    return "\n".join(lines) + "\n"
