"""Shared percentile math for every latency reporter.

Before this module, three places computed p50/p99 independently:
`scripts/serve_loadgen.py` had an index-into-sorted-list `_pct`, the
serve `LatencyHistogram` had its own cumulative-bucket walk, and the SLO
ledger would have added a third. Two different estimators for "p99" in
one report is how dashboards end up disagreeing with benches, so both
estimators live here — exact from samples, conservative upper bound from
histogram buckets — and everything (loadgen, `serve/metrics.py`,
`obs/slo.py`) calls these.

Stdlib-only (pinned by `tests/test_obs_imports.py`).
"""

from __future__ import annotations

from typing import Sequence, Tuple


def percentile(sorted_values: Sequence[float], q: float) -> float:
    """Exact sample percentile: the value at rank ``q`` of an ascending
    sorted sequence (nearest-rank, the loadgen convention). Returns 0.0
    on an empty sequence — latency reports treat "no samples" as zero
    rather than raising mid-summary.
    """
    if not sorted_values:
        return 0.0
    index = min(int(q * len(sorted_values)), len(sorted_values) - 1)
    return sorted_values[index]


def bucket_quantile(
    buckets: Sequence[float],
    counts: Sequence[int],
    total_count: int,
    observed_max: float,
    q: float,
) -> float:
    """Conservative (upper-bound) quantile from fixed histogram buckets.

    ``buckets`` are ascending upper bounds; ``counts[i]`` is the number
    of observations at or below ``buckets[i]`` (non-cumulative,
    per-bucket). The quantile is the upper bound of the bucket containing
    the q-rank; the overflow bucket reports ``observed_max``. 0.0 when
    empty. This is the `LatencyHistogram.quantile` semantics, hoisted so
    the histogram and the SLO ledger agree by construction.
    """
    if total_count <= 0:
        return 0.0
    rank = q * total_count
    cumulative = 0
    for upper, c in zip(buckets, counts):
        cumulative += c
        if cumulative >= rank:
            return upper
    return observed_max


def percentiles_ms(
    sorted_seconds: Sequence[float], qs: Sequence[float] = (0.50, 0.99)
) -> Tuple[float, ...]:
    """Convenience: exact percentiles of sorted second-latencies, in ms."""
    return tuple(percentile(sorted_seconds, q) * 1e3 for q in qs)
