"""Bounded ring time-series store: scrape history with memory.

Every ``rt1_*`` family in the repo is scrape-time-only — ``/metrics``
answers "what is the value NOW" and forgets it. The TSDB is the missing
memory: the collector (``obs/collector.py``) appends each scraped sample
here, keyed by ``(family, labels)``, and the alert engine
(``obs/alerts.py``), the ``/history`` + ``/dashboard`` ops surface, and
the ``run_report.py`` post-mortem all read windows back out.

Deliberately small and stdlib-only (the same import-light contract as
``serve/router.py``): one lock, one ``deque`` ring per series, bounded
two ways — ``max_points`` per series AND ``retention_s`` by sample age —
plus a ``max_series`` cap so an unbounded label set (a buggy exporter
minting a fresh label per request) evicts least-recently-written series
instead of eating the host. Windowed queries reuse the one shared
quantile estimator (``obs/quantiles.py``); ``rate``/``increase`` are
counter-reset tolerant (negative steps contribute zero, the Prometheus
convention).

Snapshots are JSONL — header line first, one series per line — written
atomically (tmp + ``os.replace``, the ``SLOLedger.write_summary``
pattern) so a post-mortem reader never sees a half-written file, and
``read_snapshot``/``restore`` tolerate a torn final line (disk full,
SIGKILL mid-write) exactly like the flight recorder's ``read_dump``.

The clock is injectable (``clock=``) so retention and window math are
unit-testable without sleeping.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from rt1_tpu.obs.quantiles import percentile

#: Default snapshot filename inside a workdir — what the fleet writes on
#: stop and what `run_report.py` looks for.
SNAPSHOT_BASENAME = "tsdb_snapshot.jsonl"

#: Canonical label identity: sorted (key, value) string pairs. Dict
#: ordering must never mint a second series for the same labels.
LabelKey = Tuple[Tuple[str, str], ...]

_AGGS = (
    "latest", "avg", "min", "max", "sum", "count",
    "delta", "increase", "rate", "quantile",
)


def _label_key(labels: Optional[Dict[str, Any]]) -> LabelKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class TSDB:
    """Thread-safe bounded ring store of (family, labels) -> [(t, value)]."""

    def __init__(
        self,
        max_points: int = 2048,
        retention_s: float = 3600.0,
        max_series: int = 4096,
        clock=time.time,
    ):
        if max_points <= 0:
            raise ValueError(f"max_points must be positive, got {max_points}")
        if retention_s <= 0:
            raise ValueError(
                f"retention_s must be positive, got {retention_s}"
            )
        if max_series <= 0:
            raise ValueError(f"max_series must be positive, got {max_series}")
        self.max_points = int(max_points)
        self.retention_s = float(retention_s)
        self.max_series = int(max_series)
        self._clock = clock
        self._lock = threading.Lock()
        # OrderedDict in least-recently-APPENDED order: the max_series cap
        # evicts the series that has gone quietest, not the oldest-created.
        self._series: "collections.OrderedDict[Tuple[str, LabelKey], collections.deque]" = (  # noqa: E501
            collections.OrderedDict()
        )
        self._labels: Dict[Tuple[str, LabelKey], Dict[str, str]] = {}
        self.appends_total = 0
        self.points_evicted_total = 0
        self.series_dropped_total = 0

    # ------------------------------------------------------------- writing

    def append(
        self,
        family: str,
        value: float,
        labels: Optional[Dict[str, Any]] = None,
        t: Optional[float] = None,
    ) -> None:
        """Record one sample. `t` defaults to the injected clock — the
        collector passes one shared timestamp per scrape cycle so every
        family in a cycle windows identically."""
        if t is None:
            t = self._clock()
        key = (str(family), _label_key(labels))
        v = float(value)
        with self._lock:
            dq = self._series.get(key)
            if dq is None:
                if len(self._series) >= self.max_series:
                    dropped_key, dropped = self._series.popitem(last=False)
                    self._labels.pop(dropped_key, None)
                    self.series_dropped_total += 1
                    self.points_evicted_total += len(dropped)
                dq = collections.deque(maxlen=self.max_points)
                self._series[key] = dq
                self._labels[key] = dict(_label_key(labels))
            if len(dq) == dq.maxlen:
                self.points_evicted_total += 1  # ring overwrite
            dq.append((float(t), v))
            self._series.move_to_end(key)
            self._evict_old_locked(dq, float(t))
            self.appends_total += 1

    def append_many(
        self,
        samples: Iterable[Tuple[str, Optional[Dict[str, Any]], float]],
        t: Optional[float] = None,
    ) -> int:
        """Append (family, labels, value) triples under ONE timestamp
        (default: now). Returns the number appended."""
        if t is None:
            t = self._clock()
        n = 0
        for family, labels, value in samples:
            self.append(family, value, labels=labels, t=t)
            n += 1
        return n

    def _evict_old_locked(self, dq, now: float) -> None:
        cutoff = now - self.retention_s
        while dq and dq[0][0] < cutoff:
            dq.popleft()
            self.points_evicted_total += 1

    # ------------------------------------------------------------- reading

    def families(self) -> List[str]:
        with self._lock:
            return sorted({family for family, _ in self._series})

    def instances(self, family: str) -> List[Dict[str, str]]:
        """Every label set currently stored for `family` (the per-instance
        fan-out an alert rule iterates)."""
        with self._lock:
            return [
                dict(self._labels[key])
                for key in self._series
                if key[0] == family
            ]

    def series_index(self) -> List[Dict[str, Any]]:
        """[{family, labels, points}] — the /history listing payload."""
        with self._lock:
            return [
                {
                    "family": family,
                    "labels": dict(self._labels[(family, lk)]),
                    "points": len(dq),
                }
                for (family, lk), dq in self._series.items()
            ]

    def points(
        self,
        family: str,
        labels: Optional[Dict[str, Any]] = None,
        window_s: Optional[float] = None,
        now: Optional[float] = None,
    ) -> List[Tuple[float, float]]:
        """The stored (t, value) points for one series, oldest first,
        optionally restricted to the trailing `window_s`. Retention is
        enforced at read time too, so a quiet series cannot serve samples
        older than `retention_s`."""
        if now is None:
            now = self._clock()
        key = (str(family), _label_key(labels))
        with self._lock:
            dq = self._series.get(key)
            if dq is None:
                return []
            self._evict_old_locked(dq, float(now))
            pts = list(dq)
        if window_s is not None:
            cutoff = float(now) - float(window_s)
            pts = [p for p in pts if p[0] >= cutoff]
        return pts

    def latest(
        self, family: str, labels: Optional[Dict[str, Any]] = None
    ) -> Optional[Tuple[float, float]]:
        pts = self.points(family, labels=labels)
        return pts[-1] if pts else None

    def query(
        self,
        family: str,
        agg: str,
        window_s: float,
        labels: Optional[Dict[str, Any]] = None,
        q: Optional[float] = None,
        now: Optional[float] = None,
    ) -> Optional[float]:
        """One windowed aggregate over a series, or None when the window
        holds no data (rate/delta/increase need >= 2 points: a single
        sample carries no change information).

        * ``latest/avg/min/max/sum/count`` — over the values in window.
        * ``delta`` — last - first (signed).
        * ``increase`` — counter-reset-tolerant rise: sum of positive
          steps (a restart's drop to zero contributes nothing).
        * ``rate`` — increase / observed span, per second.
        * ``quantile`` — nearest-rank percentile at ``q`` via the shared
          estimator in ``obs/quantiles.py``.
        """
        if agg not in _AGGS:
            raise ValueError(f"unknown agg {agg!r}; known: {_AGGS}")
        pts = self.points(family, labels=labels, window_s=window_s, now=now)
        if not pts:
            return None
        values = [v for _, v in pts]
        if agg == "latest":
            return values[-1]
        if agg == "avg":
            return sum(values) / len(values)
        if agg == "min":
            return min(values)
        if agg == "max":
            return max(values)
        if agg == "sum":
            return sum(values)
        if agg == "count":
            return float(len(values))
        if agg == "quantile":
            if q is None:
                raise ValueError("agg='quantile' requires q=")
            return percentile(sorted(values), q)
        # Change aggregates: need two points to say anything.
        if len(pts) < 2:
            return None
        if agg == "delta":
            return values[-1] - values[0]
        rise = sum(
            max(0.0, b - a) for a, b in zip(values, values[1:])
        )
        if agg == "increase":
            return rise
        span = pts[-1][0] - pts[0][0]
        if span <= 0:
            return None
        return rise / span  # rate

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "series": len(self._series),
                "points": sum(len(dq) for dq in self._series.values()),
                "max_points": self.max_points,
                "retention_s": self.retention_s,
                "max_series": self.max_series,
                "appends_total": self.appends_total,
                "points_evicted_total": self.points_evicted_total,
                "series_dropped_total": self.series_dropped_total,
            }

    # ----------------------------------------------------------- snapshots

    def write_snapshot(self, path: str) -> str:
        """Atomic JSONL dump: header line + one line per series. tmp +
        os.replace so a reader never sees a partial file from US — the
        torn-file tolerance in `read_snapshot` covers everything else."""
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with self._lock:
            header = {
                "tsdb": {
                    "written_at": self._clock(),
                    "series": len(self._series),
                    "points": sum(len(dq) for dq in self._series.values()),
                    "max_points": self.max_points,
                    "retention_s": self.retention_s,
                    "appends_total": self.appends_total,
                }
            }
            rows = [
                {
                    "family": family,
                    "labels": dict(self._labels[(family, lk)]),
                    "points": [[t, v] for t, v in dq],
                }
                for (family, lk), dq in self._series.items()
            ]
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps(header) + "\n")
            for row in rows:
                f.write(json.dumps(row) + "\n")
        os.replace(tmp, path)
        return path

    def restore(self, path: str) -> int:
        """Load a snapshot's points back in (bounds and retention apply as
        usual). Tolerates a torn final line; returns points restored."""
        loaded = read_snapshot(path)
        n = 0
        for row in loaded["series"]:
            family = row.get("family")
            labels = row.get("labels") or None
            for point in row.get("points", []):
                try:
                    t, v = float(point[0]), float(point[1])
                except (TypeError, ValueError, IndexError):
                    continue
                self.append(family, v, labels=labels, t=t)
                n += 1
        return n


def read_snapshot(path: str) -> Dict[str, Any]:
    """Parse a TSDB JSONL snapshot -> {"header": ..., "series": [...]}.
    A torn final line (hard kill mid-write of a foreign snapshot) ends the
    parse instead of raising — same contract as `recorder.read_dump`."""
    header: Dict[str, Any] = {}
    series: List[Dict[str, Any]] = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                break
            if i == 0 and "tsdb" in obj:
                header = obj["tsdb"]
            elif isinstance(obj, dict) and "family" in obj:
                series.append(obj)
    return {"header": header, "series": series}
