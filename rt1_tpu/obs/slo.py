"""Serving SLO ledger: outcome buckets, availability, error-budget burn.

The serving fleet already *measures* (latency histograms, restart
counters) but nothing *judges*: after a chaos run, "was the fleet within
its SLO" took a human squinting at four counters. This module is the
serve-side analogue of the train-side `GoodputLedger` — every request
lands in exactly one outcome class, and the ledger turns the stream into
an availability / latency / error-budget story:

* ``ok``         — answered 200, full-fidelity.
* ``migrated``   — answered 200 after the session's window was *live-
                   migrated* to another replica (scale-down drain,
                   rolling reload, rebalance, or a snapshot-ring crash
                   restore). The context window survived intact — the
                   client got token-identical continuity — so this class
                   counts as *good* for the availability SLO and burns
                   no error budget. It stays a separate class (not
                   folded into ``ok``) so post-mortems can see how much
                   traffic rode the durability layer.
* ``restarted``  — answered 200 but the session's context window was
                   reset by a replica death. Honest degradation: the
                   client got an action, not the one a surviving replica
                   would have produced — it burns error budget without
                   counting as an outage.
* ``rejected``   — shed with a retryable 503 (backpressure or a
                   no-ready-replicas window).
* ``failed``     — transport death or any unexpected 4xx/5xx; the class
                   a fleet run's acceptance bar pins at zero.

Definitions (classic SRE error-budget arithmetic):

* availability            = ok / total          (cumulative)
* error budget            = 1 - objective availability (e.g. 0.99 -> 1%)
* error-budget burn       = (1 - availability) / budget; 1.0 means the
  run spent its budget exactly, >1 means burning faster than allowed.
* rolling variants over the last ``window`` requests, so a long healthy
  run does not hide a current incident.
* time-windowed variants (``windowed_burn`` / ``windowed_availability``)
  over the last ``window_s`` SECONDS. The request-indexed rolling view
  freezes at its peak when traffic stops — after a shed burst with no
  follow-on requests, nothing ages the bad outcomes out of the deque,
  which is exactly the pathology the PR 15 autoscaler had to patch with
  an activity gate. The time-windowed view decays on the wall clock
  instead: a quiet minute after an incident reads as burn -> 0, not
  burn-frozen-at-peak. The clock is injectable for tests.

Latency objectives are judged on *answered* requests (ok + migrated +
restarted):
a shed request has no meaningful latency, and a fleet must not be able
to "fix" its p99 by rejecting slow traffic into the rejected bucket.

Consumed by the fleet router (live ``rt1_serve_slo_*`` gauges on
`/metrics`) and by `scripts/serve_loadgen.py` (client-side ledger +
``slo_summary.json`` artifact merged into the post-mortem by
`scripts/run_report.py`). Stdlib-only — the router process stays
clu/TF-free (`tests/test_obs_imports.py`).
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import threading
from typing import Any, Deque, Dict, Optional

from rt1_tpu.obs.quantiles import percentile

OUTCOMES = ("ok", "migrated", "restarted", "rejected", "failed")

#: Classes that count as *good* for the availability SLO: a migrated
#: session answered with its window intact — nothing was lost, so it
#: spends no error budget (unlike ``restarted``, which did lose context).
GOOD_OUTCOMES = ("ok", "migrated")

#: Classes with a meaningful latency sample (answered 200s) — the set
#: latency objectives are judged on.
ANSWERED_OUTCOMES = ("ok", "migrated", "restarted")

SUMMARY_BASENAME = "slo_summary.json"


@dataclasses.dataclass(frozen=True)
class SLOObjectives:
    """The contract a serving fleet is judged against.

    ``availability`` is the fraction of requests that must be ``ok``;
    everything else (restarted/rejected/failed) spends the complementary
    error budget. Latency objectives bound the answered-request p50/p99.
    ``window`` sizes the rolling availability/burn view (requests, not
    seconds — request-indexed windows stay meaningful across load
    levels).
    """

    availability: float = 0.99
    latency_p50_ms: float = 250.0
    latency_p99_ms: float = 2500.0
    window: int = 1024

    def __post_init__(self):
        # 1.0 ("every request must be ok") is a legal, if brutal,
        # objective: the budget is zero and any non-ok burns it
        # infinitely-fast — `_burn` reports 0.0 on a clean run and the
        # availability verdict still judges correctly.
        if not 0.0 < self.availability <= 1.0:
            raise ValueError(
                f"availability objective must be in (0, 1], got "
                f"{self.availability}"
            )
        if self.window < 1:
            raise ValueError(f"window must be >= 1, got {self.window}")

    @property
    def error_budget(self) -> float:
        return 1.0 - self.availability

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class SLOLedger:
    """Thread-safe request-outcome ledger with rolling burn-rate view.

    ``observe(outcome, latency_s)`` from any handler thread; ``gauges()``
    for the flat `/metrics` merge; ``summary()`` / ``write_summary()``
    for the post-mortem artifact.
    """

    #: Retention cap for the timestamped outcome deque: the widest window
    #: `windowed_burn` can be asked about. 15 minutes covers every
    #: fast/slow multi-burn-rate pair the alert plane ships by default.
    MAX_WINDOW_S = 900.0

    def __init__(
        self,
        objectives: Optional[SLOObjectives] = None,
        clock=None,
        max_window_s: Optional[float] = None,
    ):
        import time as _time

        self.objectives = objectives or SLOObjectives()
        self._clock = clock if clock is not None else _time.monotonic
        self.max_window_s = float(
            max_window_s if max_window_s is not None else self.MAX_WINDOW_S
        )
        if self.max_window_s <= 0:
            raise ValueError(
                f"max_window_s must be positive, got {self.max_window_s}"
            )
        self._lock = threading.Lock()
        self._counts = {k: 0 for k in OUTCOMES}
        # Rolling good/bad flags (1 = ok) for the burn-rate window.
        self._rolling_good: Deque[int] = collections.deque(
            maxlen=self.objectives.window
        )
        # Timestamped (t, good) outcomes for the TIME-windowed burn view.
        # Evicted by age (> max_window_s) on observe and on read, and by
        # point count as a backstop, so a traffic spike cannot grow the
        # deque without bound.
        self._timed_good: Deque[tuple] = collections.deque(
            maxlen=max(self.objectives.window * 8, 4096)
        )
        # Bounded per-class latency reservoirs (most recent `window`
        # samples): percentiles over the recent past, not a week-old mix.
        self._latencies: Dict[str, Deque[float]] = {
            k: collections.deque(maxlen=self.objectives.window)
            for k in OUTCOMES
        }

    # ------------------------------------------------------------ recording

    def observe(self, outcome: str, latency_s: float = 0.0) -> None:
        if outcome not in self._counts:
            raise ValueError(
                f"unknown outcome {outcome!r}; expected one of {OUTCOMES}"
            )
        now = self._clock()
        with self._lock:
            good = 1 if outcome in GOOD_OUTCOMES else 0
            self._counts[outcome] += 1
            self._rolling_good.append(good)
            self._timed_good.append((now, good))
            self._evict_timed_locked(now)
            self._latencies[outcome].append(float(latency_s))

    def _evict_timed_locked(self, now: float) -> None:
        cutoff = now - self.max_window_s
        while self._timed_good and self._timed_good[0][0] < cutoff:
            self._timed_good.popleft()

    # ------------------------------------------------------------ reporting

    @staticmethod
    def _burn(availability: float, budget: float) -> float:
        return (1.0 - availability) / budget if budget > 0 else 0.0

    def _answered_sorted(self) -> list:
        return sorted(
            sample
            for klass in ANSWERED_OUTCOMES
            for sample in self._latencies[klass]
        )

    # ------------------------------------------------- time-windowed view

    def windowed_counts(
        self, window_s: float, now: Optional[float] = None
    ) -> Dict[str, int]:
        """{"total": n, "good": n} over the trailing `window_s` seconds."""
        if window_s <= 0:
            raise ValueError(f"window_s must be positive, got {window_s}")
        if now is None:
            now = self._clock()
        cutoff = now - min(window_s, self.max_window_s)
        with self._lock:
            self._evict_timed_locked(now)
            total = good = 0
            for t, g in reversed(self._timed_good):
                if t < cutoff:
                    break
                total += 1
                good += g
        return {"total": total, "good": good}

    def windowed_availability(
        self, window_s: float, now: Optional[float] = None
    ) -> float:
        """good-fraction (ok + migrated) over the trailing `window_s`
        seconds; 1.0 when the window holds no requests (no traffic
        spends no budget)."""
        counts = self.windowed_counts(window_s, now=now)
        if not counts["total"]:
            return 1.0
        return counts["good"] / counts["total"]

    def windowed_burn(
        self, window_s: float, now: Optional[float] = None
    ) -> float:
        """Error-budget burn over the trailing `window_s` SECONDS — the
        signal the autoscaler and the multi-window burn alerts consume.
        Unlike ``slo_error_budget_burn_rolling`` (request-indexed), this
        decays on the wall clock: a post-incident quiet period ages the
        bad outcomes out of the window and the burn falls back to 0
        instead of freezing at its peak."""
        return self._burn(
            self.windowed_availability(window_s, now=now),
            self.objectives.error_budget,
        )

    def gauges(self) -> Dict[str, float]:
        """Flat ``slo_*`` gauges for the `/metrics` merge (the serve
        snapshot prefixes them to ``rt1_serve_slo_*`` in exposition)."""
        with self._lock:
            return self._gauges_locked()

    def _gauges_locked(self) -> Dict[str, float]:
        """Gauge computation proper; caller holds ``self._lock``."""
        obj = self.objectives
        total = sum(self._counts.values())
        ok = self._counts["ok"]
        good = sum(self._counts[k] for k in GOOD_OUTCOMES)
        availability = good / total if total else 1.0
        rolling = (
            sum(self._rolling_good) / len(self._rolling_good)
            if self._rolling_good
            else 1.0
        )
        answered = self._answered_sorted()
        p50_ms = percentile(answered, 0.50) * 1e3
        p99_ms = percentile(answered, 0.99) * 1e3
        return {
            "slo_requests_total": float(total),
            "slo_requests_ok": float(ok),
            "slo_requests_migrated": float(self._counts["migrated"]),
            "slo_requests_restarted": float(self._counts["restarted"]),
            "slo_requests_rejected": float(self._counts["rejected"]),
            "slo_requests_failed": float(self._counts["failed"]),
            "slo_availability": availability,
            "slo_availability_rolling": rolling,
            "slo_error_budget_burn": self._burn(
                availability, obj.error_budget
            ),
            "slo_error_budget_burn_rolling": self._burn(
                rolling, obj.error_budget
            ),
            "slo_latency_p50_ms": p50_ms,
            "slo_latency_p99_ms": p99_ms,
            "slo_objective_availability": obj.availability,
            "slo_objective_latency_p99_ms": obj.latency_p99_ms,
            "slo_availability_ok": float(availability >= obj.availability),
            "slo_latency_ok": float(
                p50_ms <= obj.latency_p50_ms
                and p99_ms <= obj.latency_p99_ms
            ),
        }

    def summary(self) -> Dict[str, Any]:
        """The full judgement: objectives, per-class counts + latency
        percentiles, availability, burn, and the met/violated verdicts —
        the ``slo_summary.json`` payload. One lock hold end to end, so
        the gauge half and the by-class half are cut from the same
        request count (the per-class burns must sum to the total burn
        even while traffic races this call)."""
        obj = self.objectives
        with self._lock:
            gauges = self._gauges_locked()
            total = sum(self._counts.values())
            by_class = {}
            for klass in OUTCOMES:
                lats = sorted(self._latencies[klass])
                entry = {
                    "count": self._counts[klass],
                    "p50_ms": round(percentile(lats, 0.50) * 1e3, 3),
                    "p99_ms": round(percentile(lats, 0.99) * 1e3, 3),
                }
                if klass not in GOOD_OUTCOMES:
                    # This class's share of the error budget: its bad
                    # fraction over the budget. The non-good entries sum
                    # to the total burn, so "who spent the budget" is
                    # read straight off the summary. Good classes (ok,
                    # migrated) carry no burn key at all.
                    entry["error_budget_burn"] = self._burn(
                        1.0 - (self._counts[klass] / total if total else 0.0),
                        obj.error_budget,
                    )
                by_class[klass] = entry
        availability_ok = bool(gauges["slo_availability_ok"])
        latency_ok = bool(gauges["slo_latency_ok"])
        return {
            "objectives": self.objectives.as_dict(),
            "requests_total": int(gauges["slo_requests_total"]),
            "by_class": by_class,
            "availability": gauges["slo_availability"],
            "availability_rolling": gauges["slo_availability_rolling"],
            "error_budget_burn": gauges["slo_error_budget_burn"],
            "error_budget_burn_rolling": gauges[
                "slo_error_budget_burn_rolling"
            ],
            "latency_p50_ms": round(gauges["slo_latency_p50_ms"], 3),
            "latency_p99_ms": round(gauges["slo_latency_p99_ms"], 3),
            "availability_within_objective": availability_ok,
            "latency_within_objective": latency_ok,
            "slo_met": availability_ok and latency_ok,
        }

    def write_summary(self, path: str) -> str:
        """Write ``summary()`` as JSON (atomic rename); returns the path."""
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.summary(), f, indent=2)
        os.replace(tmp, path)
        return path


def read_summary(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)
