"""Declarative alerting over TSDB windows: pending -> firing -> resolved.

The TSDB (`obs/tsdb.py`) remembers; this module judges. An `AlertRule`
is a named condition evaluated against the store each collector cycle —
the condition returns the *violating instances* (label-set, value pairs),
and the `AlertManager` runs the standard alerting state machine over
them:

* **pending** — the condition is true but has not yet held for
  ``for_duration_s``. A blip that clears while pending is dropped
  silently (no event, the instance re-arms) — exactly the debounce
  `for:` provides in Prometheus Alertmanager rules.
* **firing** — the condition held for the full duration. One ``firing``
  event is recorded into history and ``on_fire`` is called (the fleet
  wires this into the flight-recorder/exemplar stream).
* **resolved** — a firing instance whose condition cleared. One
  ``resolved`` event, ``on_resolve`` fires, and the instance re-arms
  from scratch (a relapse must re-earn its ``for_duration_s``).

``default_ruleset()`` ships the signals this repo already knows matter,
headlined by **multi-window multi-burn-rate** SLO alerting (the
Google-SRE-workbook shape): burn is recomputed from TSDB *counter
deltas* of ``rt1_serve_slo_requests_total`` / ``_ok`` over two window
pairs — a fast pair that pages on a cliff within seconds and a slow
pair that warns on a simmer — so the signal is time-indexed end to end
and decays by itself when traffic stops (the request-indexed rolling
gauge froze at its peak, which is why the autoscaler needed an activity
gate until `SLOLedger.windowed_burn` landed).

A rule whose condition raises is *skipped for that pass* — its
instances keep their state (a broken rule must not mass-resolve real
incidents) and ``rule_errors_total`` counts the failure.

Stdlib-only, same import-light contract as the rest of ``obs/``
(`tests/test_obs_imports.py` pins tsdb/collector/alerts clu/TF/jax-free).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import (
    Any,
    Callable,
    Dict,
    List,
    Optional,
    Sequence,
    Tuple,
)

from rt1_tpu.obs.prometheus import TextExposition
from rt1_tpu.obs.tsdb import TSDB

SEVERITIES = ("page", "warn", "info")

#: A condition inspects the TSDB at `now` and returns the violating
#: instances as (labels, observed_value) pairs — empty list = healthy.
Condition = Callable[[TSDB, float], List[Tuple[Dict[str, str], float]]]


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One named judgement over TSDB history.

    ``labels`` are attached to every instance this rule raises (routing
    metadata: team, layer); ``annotations`` carry the human story
    (summary, runbook hint) and ride into history events verbatim.
    """

    name: str
    condition: Condition
    severity: str = "warn"
    for_duration_s: float = 0.0
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if not self.name:
            raise ValueError("alert rule needs a name")
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"severity {self.severity!r} not in {SEVERITIES}"
            )
        if self.for_duration_s < 0:
            raise ValueError(
                f"for_duration_s must be >= 0, got {self.for_duration_s}"
            )


def _instance_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class AlertManager:
    """The state machine: `evaluate()` once per collector cycle.

    Thread-safe (the router's `/alerts` handler reads while the
    collector thread evaluates). History is a bounded deque of
    firing/resolved events, oldest first on read — the post-mortem
    timeline `run_report.py` renders.
    """

    def __init__(
        self,
        tsdb: TSDB,
        rules: Sequence[AlertRule],
        clock=time.time,
        on_fire: Optional[Callable[[Dict[str, Any]], None]] = None,
        on_resolve: Optional[Callable[[Dict[str, Any]], None]] = None,
        history_capacity: int = 512,
    ):
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names in {names}")
        self.tsdb = tsdb
        self.rules = list(rules)
        self._clock = clock
        self._on_fire = on_fire
        self._on_resolve = on_resolve
        self._lock = threading.Lock()
        # (rule_name, instance_key) -> {"state", "since", "fired_at",
        # "value", "labels"}
        self._instances: Dict[Tuple[str, Tuple], Dict[str, Any]] = {}
        self._history: collections.deque = collections.deque(
            maxlen=int(history_capacity)
        )
        self.evaluations_total = 0
        self.fired_total = 0
        self.resolved_total = 0
        self.rule_errors_total = 0
        self.callback_errors_total = 0

    # ----------------------------------------------------------- evaluation

    def evaluate(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One pass over every rule; returns the transition events
        (firing/resolved) this pass produced, oldest first."""
        if now is None:
            now = self._clock()
        events: List[Dict[str, Any]] = []
        for rule in self.rules:
            try:
                violations = rule.condition(self.tsdb, now)
            except Exception:  # noqa: BLE001 - a broken rule must not
                # resolve (or fire) anything: freeze its instances.
                with self._lock:
                    self.rule_errors_total += 1
                continue
            events.extend(self._advance(rule, violations, now))
        with self._lock:
            self.evaluations_total += 1
        for event in events:
            cb = (
                self._on_fire
                if event["event"] == "firing"
                else self._on_resolve
            )
            if cb is None:
                continue
            try:
                cb(event)
            except Exception:  # noqa: BLE001 - observability callbacks
                # must never kill the evaluation loop.
                with self._lock:
                    self.callback_errors_total += 1
        return events

    def _advance(
        self,
        rule: AlertRule,
        violations: List[Tuple[Dict[str, str], float]],
        now: float,
    ) -> List[Dict[str, Any]]:
        events: List[Dict[str, Any]] = []
        with self._lock:
            seen = set()
            for labels, value in violations:
                merged = dict(rule.labels)
                merged.update({str(k): str(v) for k, v in labels.items()})
                key = (rule.name, _instance_key(merged))
                seen.add(key)
                inst = self._instances.get(key)
                if inst is None:
                    inst = {
                        "state": "pending",
                        "since": now,
                        "fired_at": None,
                        "labels": merged,
                    }
                    self._instances[key] = inst
                inst["value"] = float(value)
                if (
                    inst["state"] == "pending"
                    and now - inst["since"] >= rule.for_duration_s
                ):
                    inst["state"] = "firing"
                    inst["fired_at"] = now
                    self.fired_total += 1
                    events.append(
                        self._event_locked(rule, inst, "firing", now)
                    )
            # Cleared instances: firing -> resolved (event), pending ->
            # dropped silently (re-arm).
            for key in [
                k
                for k in self._instances
                if k[0] == rule.name and k not in seen
            ]:
                inst = self._instances.pop(key)
                if inst["state"] == "firing":
                    self.resolved_total += 1
                    events.append(
                        self._event_locked(rule, inst, "resolved", now)
                    )
            for event in events:
                self._history.append(event)
        return events

    def _event_locked(
        self, rule: AlertRule, inst: Dict[str, Any], kind: str, now: float
    ) -> Dict[str, Any]:
        event = {
            "t": now,
            "event": kind,
            "alert": rule.name,
            "severity": rule.severity,
            "labels": dict(inst["labels"]),
            "value": inst["value"],
            "annotations": dict(rule.annotations),
        }
        if kind == "resolved" and inst["fired_at"] is not None:
            event["fired_at"] = inst["fired_at"]
            event["duration_s"] = max(0.0, now - inst["fired_at"])
        return event

    # ------------------------------------------------------------ reporting

    def active(self) -> List[Dict[str, Any]]:
        """Every pending/firing instance, firing first, then by name."""
        by_rule = {r.name: r for r in self.rules}
        with self._lock:
            out = [
                {
                    "alert": name,
                    "severity": by_rule[name].severity,
                    "state": inst["state"],
                    "since": inst["since"],
                    "fired_at": inst["fired_at"],
                    "value": inst["value"],
                    "labels": dict(inst["labels"]),
                    "annotations": dict(by_rule[name].annotations),
                }
                for (name, _), inst in self._instances.items()
                if name in by_rule
            ]
        out.sort(
            key=lambda a: (
                a["state"] != "firing",
                a["alert"],
                sorted(a["labels"].items()),
            )
        )
        return out

    def history(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._history]

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return {
                "evaluations_total": self.evaluations_total,
                "fired_total": self.fired_total,
                "resolved_total": self.resolved_total,
                "rule_errors_total": self.rule_errors_total,
                "callback_errors_total": self.callback_errors_total,
            }

    def status(self) -> Dict[str, Any]:
        """The `/alerts` endpoint payload."""
        return {
            "rules": [
                {
                    "name": r.name,
                    "severity": r.severity,
                    "for_duration_s": r.for_duration_s,
                }
                for r in self.rules
            ],
            "active": self.active(),
            "history": self.history(),
            "counters": self.counters(),
        }

    def prometheus_text(self, prefix: str = "rt1_alert_") -> str:
        """``rt1_alert_*`` families: one labeled sample per active
        instance plus the manager's own lifecycle counters. Appended to
        the fleet exposition when the collector arm is on."""
        active = self.active()
        counters = self.counters()
        exp = TextExposition()
        for state in ("firing", "pending"):
            samples = [
                (
                    dict(
                        {"alert": a["alert"], "severity": a["severity"]},
                        **a["labels"],
                    ),
                    1.0,
                )
                for a in active
                if a["state"] == state
            ]
            if samples:
                exp.family(
                    prefix + state,
                    "gauge",
                    samples,
                    f"Alert instances currently {state}.",
                )
            exp.gauge(
                f"{prefix}{state}_count",
                float(len(samples)),
                f"Number of alert instances currently {state}.",
            )
        exp.gauge(
            prefix + "rules",
            float(len(self.rules)),
            "Alert rules loaded.",
        )
        for key, help_text in (
            ("evaluations_total", "Alert evaluation passes."),
            ("fired_total", "pending->firing transitions."),
            ("resolved_total", "firing->resolved transitions."),
            ("rule_errors_total", "Rule conditions that raised (skipped)."),
            (
                "callback_errors_total",
                "on_fire/on_resolve callbacks that raised.",
            ),
        ):
            exp.counter(prefix + key, float(counters[key]), help_text)
        return exp.render()


# -------------------------------------------------------------- conditions

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
    "==": lambda v, t: v == t,
    "!=": lambda v, t: v != t,
}


def threshold_condition(
    family: str,
    agg: str,
    window_s: float,
    op: str,
    threshold: float,
    q: Optional[float] = None,
) -> Condition:
    """Per-instance windowed threshold: every label set stored under
    `family` is judged independently (`replica_up{replica_id="2"}` can
    fire while replica 0 stays green). A series with no data in the
    window is healthy — absence is the collector's problem
    (`rt1_obs_collector_scrape_errors_total`), not a threshold breach."""
    if op not in _OPS:
        raise ValueError(f"unknown op {op!r}; known: {tuple(_OPS)}")
    cmp = _OPS[op]

    def cond(tsdb: TSDB, now: float) -> List[Tuple[Dict[str, str], float]]:
        out = []
        for labels in tsdb.instances(family):
            value = tsdb.query(
                family, agg, window_s, labels=labels, q=q, now=now
            )
            if value is not None and cmp(value, threshold):
                out.append((labels, value))
        return out

    return cond


def _counter_burn(
    tsdb: TSDB,
    window_s: float,
    now: float,
    total_family: str,
    ok_family: str,
    objective_family: str,
    default_objective: float,
) -> Optional[float]:
    """Error-budget burn over `window_s` from TSDB counter deltas:
    ((total_delta - ok_delta) / total_delta) / budget. None when the
    counters have no history yet; 0.0 when the window saw no traffic
    (no requests spend no budget — the time-indexed decay property)."""
    total = tsdb.query(total_family, "increase", window_s, now=now)
    ok = tsdb.query(ok_family, "increase", window_s, now=now)
    if total is None or ok is None:
        return None
    if total <= 0:
        return 0.0
    latest = tsdb.latest(objective_family)
    objective = latest[1] if latest else default_objective
    budget = 1.0 - objective
    if budget <= 0:
        return None
    bad = max(0.0, total - ok)
    return (bad / total) / budget


def slo_burn_condition(
    short_window_s: float,
    long_window_s: float,
    threshold: float,
    total_family: str = "rt1_serve_slo_requests_total",
    ok_family: str = "rt1_serve_slo_requests_ok",
    objective_family: str = "rt1_serve_slo_objective_availability",
    default_objective: float = 0.99,
) -> Condition:
    """Multi-window burn: fires only when the burn computed over BOTH the
    short and the long window is at/above `threshold`. The short window
    gives detection latency (a cliff shows up within one scrape); the
    long window gives persistence (a single bad scrape inside an
    otherwise-healthy hour does not page). The reported value is the
    short-window burn — the current severity."""

    def cond(tsdb: TSDB, now: float) -> List[Tuple[Dict[str, str], float]]:
        burns = [
            _counter_burn(
                tsdb, w, now, total_family, ok_family,
                objective_family, default_objective,
            )
            for w in (short_window_s, long_window_s)
        ]
        if any(b is None or b < threshold for b in burns):
            return []
        return [
            (
                {
                    "window": (
                        f"{short_window_s:g}s/{long_window_s:g}s"
                    )
                },
                burns[0],
            )
        ]

    return cond


def compile_drift_condition(
    compile_family: str = "rt1_serve_replica_compile_count",
    bucket_family: str = "rt1_serve_replica_bucket_count",
) -> Condition:
    """Any replica whose lifetime compile count exceeds its configured
    AOT bucket count — the one-compile-per-bucket pin every serve test
    asserts; a recompile in production means a shape leak."""

    def cond(tsdb: TSDB, now: float) -> List[Tuple[Dict[str, str], float]]:
        out = []
        for labels in tsdb.instances(compile_family):
            compiled = tsdb.latest(compile_family, labels)
            buckets = tsdb.latest(bucket_family, labels)
            if compiled is None or buckets is None or buckets[1] <= 0:
                continue
            if compiled[1] > buckets[1]:
                out.append((labels, compiled[1]))
        return out

    return cond


def flapping_condition(
    window_s: float,
    min_events: float,
    family: str = "rt1_serve_autoscale_scale_events_total",
) -> Condition:
    """Autoscaler thrash: BOTH an up and a down scale event inside the
    window, and at least `min_events` total — one direction alone is the
    autoscaler doing its job; alternation is oscillation."""

    def cond(tsdb: TSDB, now: float) -> List[Tuple[Dict[str, str], float]]:
        per_direction: Dict[str, float] = {}
        for labels in tsdb.instances(family):
            rise = tsdb.query(
                family, "increase", window_s, labels=labels, now=now
            )
            if rise:
                direction = labels.get("direction", "?")
                per_direction[direction] = (
                    per_direction.get(direction, 0.0) + rise
                )
        total = sum(per_direction.values())
        if (
            per_direction.get("up", 0.0) > 0
            and per_direction.get("down", 0.0) > 0
            and total >= min_events
        ):
            return [({}, total)]
        return []

    return cond


def capture_pressure_condition(
    window_s: float,
    pruned_threshold: float,
    errors_family: str = "rt1_serve_replica_capture_write_errors_total",
    pruned_family: str = "rt1_serve_replica_capture_pruned_total",
) -> Condition:
    """Flywheel capture sink distress, per replica: any episode write
    error in the window (disk full / permission loss), or the disk ring
    pruning faster than `pruned_threshold` episodes per window (capture
    outrunning its budget — history is being eaten as fast as it is
    written)."""

    def cond(tsdb: TSDB, now: float) -> List[Tuple[Dict[str, str], float]]:
        out = []
        for labels in tsdb.instances(errors_family):
            rise = tsdb.query(
                errors_family, "increase", window_s, labels=labels, now=now
            )
            if rise:
                out.append((labels, rise))
        flagged = {_instance_key(lb) for lb, _ in out}
        for labels in tsdb.instances(pruned_family):
            if _instance_key(labels) in flagged:
                continue
            rise = tsdb.query(
                pruned_family, "increase", window_s, labels=labels, now=now
            )
            if rise is not None and rise >= pruned_threshold:
                out.append((labels, rise))
        return out

    return cond


# ---------------------------------------------------------- default rules


def default_ruleset(
    burn_fast_windows: Tuple[float, float] = (60.0, 300.0),
    burn_fast_threshold: float = 8.0,
    burn_slow_windows: Tuple[float, float] = (300.0, 900.0),
    burn_slow_threshold: float = 2.0,
    stall_pct_threshold: float = 50.0,
    stall_window_s: float = 300.0,
    flap_window_s: float = 600.0,
    flap_events: float = 4.0,
    rebuild_window_s: float = 120.0,
    rebuild_steps: float = 50.0,
    capture_window_s: float = 300.0,
    capture_pruned_threshold: float = 20.0,
    canary_burn_threshold: float = 1.0,
    migration_window_s: float = 300.0,
    migration_failures: float = 3.0,
    for_duration_s: float = 0.0,
) -> List[AlertRule]:
    """The signals this repo already knows matter, as rules.

    Window/threshold defaults are production-shaped (minutes); the chaos
    proof and the stub-fleet tests pass seconds-scale values instead —
    the state machine is identical, only the clock arithmetic scales.
    ``for_duration_s`` applies to the non-burn rules (the burn pair's
    long window already provides persistence).
    """
    return [
        AlertRule(
            name="SLOBurnRateFast",
            severity="page",
            condition=slo_burn_condition(
                burn_fast_windows[0],
                burn_fast_windows[1],
                burn_fast_threshold,
            ),
            annotations={
                "summary": (
                    "Error budget burning at >= "
                    f"{burn_fast_threshold:g}x over both fast windows "
                    "— at this rate the budget is gone within hours."
                ),
            },
        ),
        AlertRule(
            name="SLOBurnRateSlow",
            severity="warn",
            condition=slo_burn_condition(
                burn_slow_windows[0],
                burn_slow_windows[1],
                burn_slow_threshold,
            ),
            annotations={
                "summary": (
                    "Sustained error-budget burn >= "
                    f"{burn_slow_threshold:g}x over both slow windows."
                ),
            },
        ),
        AlertRule(
            name="ReplicaDown",
            severity="page",
            for_duration_s=for_duration_s,
            condition=threshold_condition(
                "rt1_serve_replica_up", "latest", 60.0, "==", 0.0
            ),
            annotations={
                "summary": (
                    "Replica /metrics stopped answering the router "
                    "fan-out probe."
                ),
            },
        ),
        AlertRule(
            name="CompileCountDrift",
            severity="page",
            condition=compile_drift_condition(),
            annotations={
                "summary": (
                    "Replica recompiled past its AOT bucket pin — a "
                    "shape leaked through the bucketing contract."
                ),
            },
        ),
        AlertRule(
            name="FeederStall",
            severity="warn",
            for_duration_s=for_duration_s,
            condition=threshold_condition(
                "rt1_train_stall_pct",
                "avg",
                stall_window_s,
                ">=",
                stall_pct_threshold,
            ),
            annotations={
                "summary": (
                    "Train step input-stall share over "
                    f"{stall_pct_threshold:g}% — the feeder is not "
                    "keeping the device fed."
                ),
            },
        ),
        AlertRule(
            name="AutoscalerFlapping",
            severity="warn",
            condition=flapping_condition(flap_window_s, flap_events),
            annotations={
                "summary": (
                    "Fleet scaled both up and down inside the window — "
                    "hysteresis band too narrow for this traffic."
                ),
            },
        ),
        AlertRule(
            name="CacheRebuildStorm",
            severity="warn",
            condition=threshold_condition(
                "rt1_serve_replica_cache_rebuild_steps_total",
                "increase",
                rebuild_window_s,
                ">=",
                rebuild_steps,
            ),
            annotations={
                "summary": (
                    "KV-cache full-window rebuilds spiking — sessions "
                    "are paying recompute instead of incremental decode."
                ),
            },
        ),
        AlertRule(
            name="CaptureDiskPressure",
            severity="warn",
            condition=capture_pressure_condition(
                capture_window_s, capture_pruned_threshold
            ),
            annotations={
                "summary": (
                    "Flywheel capture sink under disk pressure: write "
                    "errors or runaway ring pruning."
                ),
            },
        ),
        AlertRule(
            name="MigrationFailureStorm",
            severity="warn",
            condition=threshold_condition(
                "rt1_serve_replica_migration_import_failures_total",
                "increase",
                migration_window_s,
                ">=",
                migration_failures,
            ),
            annotations={
                "summary": (
                    "Session-snapshot imports repeatedly refused or "
                    "failing — live migration is degrading to window "
                    "resets (check checkpoint-generation / engine-mode "
                    "skew across the fleet)."
                ),
            },
        ),
        AlertRule(
            name="CanarySLOBreach",
            severity="page",
            condition=threshold_condition(
                "rt1_deploy_canary_burn",
                "latest",
                60.0,
                ">=",
                canary_burn_threshold,
            ),
            annotations={
                "summary": (
                    "Canary replica burning error budget past the "
                    "rollback threshold — expect the promotion "
                    "controller to demote it."
                ),
            },
        ),
    ]
