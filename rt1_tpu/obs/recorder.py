"""Flight recorder: a ring of recent step records, dumped on the way down.

When a 97k-step run dies at step 61_344 — OOM, a truncated episode file, a
SIGTERM from the scheduler — the log shows the last `log_every_steps`
scalar line and nothing else. The flight recorder keeps the last N *per
step* records (loss when cheaply available, timing buckets from
`StepTimeline`, feeder queue depths, `device.memory_stats()`) in a bounded
deque and writes them as JSONL only when something goes wrong (unhandled
exception in the guarded block, or SIGTERM), so the post-mortem has the
seconds *before* the failure at per-step resolution, for the cost of one
dict append per step.

The dump is JSONL (one record per line, header line first) rather than a
JSON array so a truncated dump — the disk was full, the kill was -9 after
all — still parses line by line.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import signal
import threading
import time
from typing import Any, Dict, List, Optional


def device_memory_stats() -> Dict[str, Any]:
    """`memory_stats()` of each addressable device, or {} where the backend
    does not implement it (CPU). Keys are short device labels."""
    try:
        import jax

        out = {}
        for d in jax.local_devices():
            stats = d.memory_stats()
            if stats:
                out[f"{d.platform}:{d.id}"] = {
                    k: int(v) for k, v in stats.items()
                }
        return out
    except Exception:  # noqa: BLE001 - observability must not take down train
        return {}


def _jsonable(value: Any) -> Any:
    """Coerce numpy/jax scalars so records never poison the dump."""
    if isinstance(value, (str, bool, int, float)) or value is None:
        return value
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    try:
        return float(value)
    except (TypeError, ValueError):
        return repr(value)


class FlightRecorder:
    """Bounded ring of step records + crash/SIGTERM dump hooks."""

    def __init__(self, capacity: int = 256, path: Optional[str] = None):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = capacity
        self.path = path
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        # RLock, not Lock: the SIGTERM handler runs on the main thread
        # BETWEEN bytecodes — possibly inside record()'s critical section —
        # and dump() -> snapshot() re-acquires; a plain Lock self-deadlocks
        # exactly on the dump the handler exists to produce.
        self._lock = threading.RLock()
        self._recorded = 0
        self._dumped = False
        self._prev_sigterm = None

    # ------------------------------------------------------------ recording

    def record(self, step: int, **fields: Any) -> None:
        rec = {"step": int(step), "t": time.time()}
        for k, v in fields.items():
            rec[k] = _jsonable(v)
        with self._lock:
            self._recorded += 1
            self._ring.append(rec)

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # ------------------------------------------------------------- dumping

    def dump(self, path: Optional[str] = None, reason: str = "manual") -> str:
        """Write header + ring as JSONL; returns the path written."""
        path = path or self.path
        if not path:
            raise ValueError("no dump path: pass one or construct with path=")
        records = self.snapshot()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            f.write(
                json.dumps(
                    {
                        "flight_recorder": {
                            "reason": reason,
                            "dumped_at": time.time(),
                            "capacity": self.capacity,
                            "records": len(records),
                            "recorded_total": self._recorded,
                            "memory_stats": device_memory_stats(),
                        }
                    }
                )
                + "\n"
            )
            for rec in records:
                f.write(json.dumps(rec) + "\n")
        self._dumped = True
        return path

    @contextlib.contextmanager
    def dump_on_exception(self, path: Optional[str] = None):
        """Re-raises after dumping; KeyboardInterrupt/SystemExit included
        (they are exactly the post-mortems a long run cares about)."""
        try:
            yield self
        except BaseException as exc:
            try:
                self.dump(path, reason=f"exception:{type(exc).__name__}")
            except Exception:  # noqa: BLE001 - never mask the real failure
                pass
            raise

    # -------------------------------------------------------------- signals

    def install_sigterm(self, extra: Optional[Any] = None) -> bool:
        """Dump on SIGTERM, then chain to the previous handler (or re-raise
        the default so the exit code stays honest). Main-thread only —
        returns False (no-op) elsewhere, e.g. under pytest workers.

        `extra`: optional callable run (exception-guarded) after the dump
        and before chaining — the train loop passes the host tracer's dump
        here, because chaining to SIG_DFL kills the process before any
        normal-exit teardown could write the trace.
        """
        if threading.current_thread() is not threading.main_thread():
            return False

        def _handler(signum, frame):
            try:
                self.dump(reason="SIGTERM")
            except Exception:  # noqa: BLE001 - exit path
                pass
            if extra is not None:
                try:
                    extra()
                except Exception:  # noqa: BLE001 - exit path
                    pass
            prev = self._prev_sigterm
            if callable(prev):
                prev(signum, frame)
            elif prev is signal.SIG_IGN:
                # SIGTERM was deliberately ignored before we installed;
                # dumping must not turn an ignored signal into an exit.
                pass
            else:
                # SIG_DFL (or an unknown non-Python handler): keep the
                # default die-on-SIGTERM semantics and the honest exit code.
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                signal.raise_signal(signal.SIGTERM)

        self._prev_sigterm = signal.signal(signal.SIGTERM, _handler)
        return True

    def uninstall_sigterm(self) -> None:
        if self._prev_sigterm is not None:
            signal.signal(signal.SIGTERM, self._prev_sigterm)
            self._prev_sigterm = None


class ExemplarRing:
    """Bounded ring of slow-request exemplars — the serve-side flight
    recorder.

    The serving latency histogram says *that* p99 spiked; it cannot say
    *which* requests and *where inside the server* their time went. The
    ring keeps the most recent N requests whose total latency crossed
    ``threshold_ms`` (0 = keep everything, still bounded), each with its
    request id and per-phase breakdown, so a post-mortem names offenders
    instead of quantiles. Dumped as JSONL next to the flight record on
    replica drain/crash, served live on ``GET /slow_requests``, and
    aggregated fleet-wide by the router.
    """

    def __init__(self, capacity: int = 128, threshold_ms: float = 0.0):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = capacity
        self.threshold_ms = float(threshold_ms)
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._offered = 0
        self._kept = 0

    def offer(self, total_ms: float, **fields: Any) -> bool:
        """Record one finished request; kept past the threshold, and
        ALWAYS kept when ``outcome`` is present and not ``"ok"`` — a 1 ms
        503 storm is exactly the exemplar a post-mortem wants, and the
        threshold must not filter it. Returns whether it was kept (the
        caller's cost when not: one float compare)."""
        degraded = fields.get("outcome") not in (None, "ok")
        with self._lock:
            self._offered += 1
            if total_ms < self.threshold_ms and not degraded:
                return False
            self._kept += 1
            rec = {"total_ms": round(float(total_ms), 3), "t": time.time()}
            for k, v in fields.items():
                rec[k] = _jsonable(v)
            self._ring.append(rec)
            return True

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "capacity": self.capacity,
                "threshold_ms": self.threshold_ms,
                "offered": self._offered,
                "kept": self._kept,
                "retained": len(self._ring),
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def dump(self, path: str, reason: str = "manual") -> str:
        """Header + exemplars as JSONL (same truncation-tolerant shape as
        the flight recorder; `read_dump` parses both)."""
        records = self.snapshot()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            f.write(
                json.dumps(
                    {
                        "slow_requests": {
                            "reason": reason,
                            "dumped_at": time.time(),
                            **self.stats(),
                        }
                    }
                )
                + "\n"
            )
            for rec in records:
                f.write(json.dumps(rec) + "\n")
        return path


def read_exemplars(path: str) -> Dict[str, Any]:
    """Parse an ExemplarRing JSONL dump -> {"header": ..., "records": [...]}.
    Tolerates a truncated final line, same as `read_dump`."""
    header: Dict[str, Any] = {}
    records: List[Dict[str, Any]] = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                break
            if i == 0 and "slow_requests" in obj:
                header = obj["slow_requests"]
            else:
                records.append(obj)
    return {"header": header, "records": records}


def read_dump(path: str) -> Dict[str, Any]:
    """Parse a flight-recorder JSONL dump -> {"header": ..., "records": [...]}.
    Tolerates a truncated final line (partial write before hard kill)."""
    header: Dict[str, Any] = {}
    records: List[Dict[str, Any]] = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                break
            if i == 0 and "flight_recorder" in obj:
                header = obj["flight_recorder"]
            else:
                records.append(obj)
    return {"header": header, "records": records}
