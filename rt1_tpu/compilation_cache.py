"""Persistent XLA compilation-cache setup shared by the repo entry points.

First compile of the full B3+transformer train step costs minutes (CPU
backend for the multichip dry-run, remote tunnel for the TPU bench); the
on-disk cache makes every later process start in seconds. Used by
`bench.py`, `__graft_entry__.py`, and available to user scripts.
"""

from __future__ import annotations

import os

DEFAULT_CACHE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"
)


def enable_persistent_cache(cache_dir: str = DEFAULT_CACHE_DIR) -> None:
    """Point JAX's compilation cache at `cache_dir` (created on demand)."""
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
