"""rt1_tpu — a TPU-native (JAX/XLA/Flax/pjit) robotics-transformer framework.

Brand-new implementation of everything `tanhuajie/Pytorch-RT1-for-Distributed-Training`
provides — the RT-1 policy network (FiLM-EfficientNet-B3 image tokenizer, TokenLearner,
discretized action tokenizer, causal decoder transformer), an SPMD data-parallel /
FSDP / tensor-parallel trainer for Language-Table `blocktoblock_sim`, the RLDS→numpy
data path, and a closed-loop evaluation harness on the Language-Table simulator —
re-designed TPU-first:

* one `jax.sharding.Mesh`, `jit`-with-shardings everywhere; gradient reduction is an
  XLA `psum` over ICI instead of NCCL allreduce (reference: Lightning DDPStrategy,
  `distribute_train.py:235`).
* static shapes + `lax.scan`/`lax.cond` control flow so every hot path lives in one
  compiled XLA program (reference runs a Python loop of 3 transformer calls per
  control step, `transformer_network.py:246-268`; we compute all action tokens in a
  single pass — provably equivalent because action tokens are zeroed at input
  assembly, `transformer_network.py:383`).
* NHWC image layouts, bfloat16 matmul compute with fp32 params, fused XLA image
  preprocessing on device.

Package map (subpackage → reference counterpart):
  models/    ← pytorch_robotics_transformer/ (transformer_network.py, transformer.py,
               tokenizers/, film_efficientnet/)
  ops/       ← film_efficientnet/preprocessors.py + attention primitives
  parallel/  ← Lightning DDP / NCCL layer (distribute_train.py:235) → Mesh + shardings
  train/     ← distribute_train.py + language_table/train/{train,bc}.py
  data/      ← rlds_np_convert.py + load_np_dataset.py + input_pipeline_rlds.py
  envs/      ← language_table/environments/
  eval/      ← language_table/eval/ + language_table/train/policy.py
"""

__version__ = "0.1.0"

# Chip-claim guard (mechanism, not documentation): an axon-enabled process
# importing the framework either becomes the single allowed TPU claimant or
# is refused loudly while another live claimant exists — BEFORE any jax
# backend init can dial the relay and collide with the in-flight claim.
# CPU-pinned processes (PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu) pass
# through untouched. See rt1_tpu/chip_claim.py for the failure history.
from rt1_tpu import chip_claim as _chip_claim

_chip_claim.guard()
