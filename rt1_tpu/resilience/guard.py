"""Step guard: divergence detection with a bounded escalation ladder.

The device-side half lives in `rt1_tpu/trainer/train.py` (the
``guard_nonfinite`` train step drops any update whose loss or grad-norm is
non-finite — a per-step `jnp.where` select, no host sync, with a cumulative
skip counter carried as a device scalar). This module is the host-side
half: `StepGuard.observe` inspects the scalars the loop *already* fetched
at log steps and walks a configurable escalation ladder:

    OK ──bad──▶ SKIP (tolerate; the device already dropped the update)
         │
         └─ `skip_budget` consecutive bad checks ──▶ ROLLBACK
               (restore the last good checkpoint + a fresh data-stream
                seed, performed by the train loop)
         │
         └─ `rollback_budget` rollbacks spent ──▶ ABORT (GuardAbortError)

"Bad" means: non-finite loss or grad-norm; grad-norm above
``grad_norm_max`` (when set); or loss above ``loss_spike_factor`` × a
rolling EMA of recent healthy losses (when set, after ``warmup_checks``
healthy observations arm the detector). A rollback resets the EMA — the
restored stream starts a fresh baseline.

Everything the guard does is visible: `counters()` feeds the loop's scalar
stream, so `rt1_train_guard_*` series land in TensorBoard, the Prometheus
listener, and the flight recorder.
"""

from __future__ import annotations

import dataclasses
import enum
import math
from typing import Dict, Optional


class GuardVerdict(enum.Enum):
    OK = "ok"
    SKIP = "skip"
    ROLLBACK = "rollback"
    ABORT = "abort"


class GuardAbortError(RuntimeError):
    """Raised by the train loop when the rollback budget is exhausted."""


@dataclasses.dataclass(frozen=True)
class GuardOptions:
    enabled: bool = False
    # 0 disables the threshold; finiteness is always checked when enabled.
    grad_norm_max: float = 0.0
    # 0 disables spike detection; > 0 flags loss > factor * EMA(healthy).
    loss_spike_factor: float = 0.0
    spike_ema_beta: float = 0.9
    warmup_checks: int = 3
    # Consecutive bad host checks tolerated before proposing a rollback.
    skip_budget: int = 3
    # Rollbacks allowed before the run aborts (bounded self-healing).
    rollback_budget: int = 2


class StepGuard:
    """Host-side escalation ladder over per-log-step scalars."""

    def __init__(self, options: GuardOptions):
        self.options = options
        self._ema: Optional[float] = None
        self._healthy_checks = 0
        self._consecutive_bad = 0
        self._last_good_step: Optional[int] = None
        self._checks = 0
        self._bad_checks = 0
        self._nonfinite = 0
        self._spikes = 0
        self._grad_norm_trips = 0
        self._rollbacks = 0
        self._device_skips = 0.0
        self._last_reason = ""

    # ------------------------------------------------------------- checking

    def _classify(self, loss: Optional[float], grad_norm: Optional[float]) -> str:
        """'' when healthy, else a short reason string."""
        for name, v in (("loss", loss), ("grad_norm", grad_norm)):
            if v is not None and not math.isfinite(v):
                self._nonfinite += 1
                return f"non-finite {name} ({v})"
        gmax = self.options.grad_norm_max
        if gmax > 0 and grad_norm is not None and grad_norm > gmax:
            self._grad_norm_trips += 1
            return f"grad_norm {grad_norm:.4g} > max {gmax:.4g}"
        factor = self.options.loss_spike_factor
        if (
            factor > 0
            and loss is not None
            and self._ema is not None
            and self._healthy_checks >= self.options.warmup_checks
            and loss > factor * self._ema
        ):
            self._spikes += 1
            return f"loss spike {loss:.4g} > {factor:g} x EMA {self._ema:.4g}"
        return ""

    def observe(self, step: int, scalars: Dict[str, float]) -> GuardVerdict:
        """Judge one log step's already-fetched scalars; never raises —
        the loop acts on the verdict (ABORT -> raise GuardAbortError)."""
        if not self.options.enabled:
            return GuardVerdict.OK
        self._checks += 1
        loss = scalars.get("loss")
        grad_norm = scalars.get("grad_norm")
        # The device-side cumulative skip counter rides in as a metric.
        if "guard_skips_cum" in scalars:
            self._device_skips = float(scalars["guard_skips_cum"])
        reason = self._classify(loss, grad_norm)
        if not reason:
            self._consecutive_bad = 0
            self._healthy_checks += 1
            self._last_good_step = step
            if loss is not None and math.isfinite(loss):
                beta = self.options.spike_ema_beta
                self._ema = (
                    loss
                    if self._ema is None
                    else beta * self._ema + (1.0 - beta) * loss
                )
            return GuardVerdict.OK
        self._bad_checks += 1
        self._consecutive_bad += 1
        self._last_reason = reason
        if self._consecutive_bad <= self.options.skip_budget:
            return GuardVerdict.SKIP
        if self._rollbacks >= self.options.rollback_budget:
            return GuardVerdict.ABORT
        return GuardVerdict.ROLLBACK

    def notify_rollback(self, restored_step: int) -> None:
        """The loop performed a rollback: reset the ladder for the fresh
        stream (the EMA baseline no longer describes the restored regime)."""
        self._rollbacks += 1
        self._consecutive_bad = 0
        self._healthy_checks = 0
        self._ema = None
        self._last_good_step = restored_step

    # ------------------------------------------------------------ reporting

    @property
    def rollbacks(self) -> int:
        return self._rollbacks

    @property
    def last_reason(self) -> str:
        return self._last_reason

    @property
    def last_good_step(self) -> Optional[int]:
        return self._last_good_step

    def counters(self, prefix: str = "guard/") -> Dict[str, float]:
        """Flat scalars for the metric writer / Prometheus / recorder —
        rendered as ``rt1_train_guard_*`` by the train scrape listener."""
        return {
            f"{prefix}checks_total": float(self._checks),
            f"{prefix}bad_checks_total": float(self._bad_checks),
            f"{prefix}nonfinite_total": float(self._nonfinite),
            f"{prefix}spikes_total": float(self._spikes),
            f"{prefix}grad_norm_trips_total": float(self._grad_norm_trips),
            f"{prefix}rollbacks_total": float(self._rollbacks),
            f"{prefix}device_skips_total": float(self._device_skips),
            f"{prefix}consecutive_bad": float(self._consecutive_bad),
        }
