"""Exponential-backoff retry for the I/O seams of a long training run.

A 97k-step run touches remote storage thousands of times (checkpoint saves,
manifest opens, feeder construction over a network filesystem); any one of
those calls can hit a transient error that would kill the run outright even
though the same call succeeds 200 ms later. `retry_call` turns those into
logged warnings: exponential backoff with decorrelating jitter, a deadline
cap so a *persistent* failure still surfaces within bounded time, and an
exception filter so programming errors (TypeError, ValueError) never get
retried into oblivion.

Observability: every retry and every exhaustion bumps a process-wide
counter (``retry/<name>_retries_total`` / ``retry/<name>_exhausted_total``)
exposed via :func:`counters` — the train loop merges these into its scalar
stream, so they reach TensorBoard, the Prometheus listener
(``rt1_train_retry_*``), and the flight recorder. A counter event is also
emitted on the obs host trace when tracing is live.

Import-light by contract: stdlib + `rt1_tpu.obs.trace` only (the checkpoint
layer and the data feeder both import this module).
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Callable, Dict, Optional, Tuple, Type

from rt1_tpu.obs import trace as obs_trace


@dataclasses.dataclass(frozen=True)
class RetryOptions:
    """Shape of the backoff schedule; `retry_on` filters what is transient."""

    attempts: int = 3
    backoff_s: float = 0.5
    max_backoff_s: float = 8.0
    multiplier: float = 2.0
    # Fraction of each delay randomized away (full-jitter style): delay_k in
    # [(1-jitter)*d_k, d_k]. 0 = deterministic schedule (tests pin this).
    jitter: float = 0.25
    # Wall-clock cap over ALL attempts; None = attempts alone bound it.
    deadline_s: Optional[float] = 120.0
    retry_on: Tuple[Type[BaseException], ...] = (OSError, IOError)


# ------------------------------------------------------------------ counters

_counters_lock = threading.Lock()
_counters: Dict[str, int] = {}


def _bump(key: str, n: int = 1) -> None:
    with _counters_lock:
        _counters[key] = _counters.get(key, 0) + n


def counters(prefix: str = "retry/") -> Dict[str, float]:
    """Snapshot of process-wide retry counters for the obs scalar stream."""
    with _counters_lock:
        return {f"{prefix}{k}": float(v) for k, v in _counters.items()}


def reset_counters() -> None:
    """Test hook: zero the process-wide counters."""
    with _counters_lock:
        _counters.clear()


# ------------------------------------------------------------------- retry


def retry_call(
    fn: Callable,
    *args,
    options: Optional[RetryOptions] = None,
    name: str = "io",
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    rng: Optional[random.Random] = None,
    **kwargs,
):
    """Call ``fn(*args, **kwargs)``, retrying filtered exceptions.

    Re-raises the last exception when attempts or the deadline run out
    (with the exhaustion counted and logged loudly); anything outside
    ``options.retry_on`` propagates immediately — a bug is not transient.
    `sleep`/`clock`/`rng` are injectable for deterministic tests.
    """
    options = options or RetryOptions()
    if options.attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {options.attempts}")
    rng = rng or random
    t0 = clock()
    delay = options.backoff_s
    for attempt in range(1, options.attempts + 1):
        try:
            return fn(*args, **kwargs)
        except options.retry_on as exc:
            from absl import logging

            if attempt >= options.attempts:
                _bump(f"{name}_exhausted_total")
                logging.error(
                    "resilience: %s failed %d/%d attempts, giving up: %s",
                    name, attempt, options.attempts, exc,
                )
                raise
            pause = min(delay, options.max_backoff_s)
            if options.jitter > 0:
                pause *= 1.0 - options.jitter * rng.random()
            if (
                options.deadline_s is not None
                and clock() - t0 + pause > options.deadline_s
            ):
                _bump(f"{name}_exhausted_total")
                logging.error(
                    "resilience: %s retry deadline (%.1fs) exceeded after "
                    "attempt %d: %s",
                    name, options.deadline_s, attempt, exc,
                )
                raise
            _bump(f"{name}_retries_total")
            if obs_trace.enabled():
                obs_trace.counter(f"retry_{name}", attempt)
            logging.warning(
                "resilience: %s attempt %d/%d failed (%s); retrying in "
                "%.2fs", name, attempt, options.attempts, exc, pause,
            )
            sleep(pause)
            delay *= options.multiplier
    raise AssertionError("unreachable")  # pragma: no cover


def retriable(options: Optional[RetryOptions] = None, name: str = "io"):
    """Decorator form of :func:`retry_call`."""

    def deco(fn: Callable) -> Callable:
        def wrapped(*args, **kwargs):
            return retry_call(fn, *args, options=options, name=name, **kwargs)

        wrapped.__name__ = getattr(fn, "__name__", name)
        wrapped.__doc__ = fn.__doc__
        return wrapped

    return deco
