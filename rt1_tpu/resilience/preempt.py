"""Preemption coordinator: SIGTERM/SIGINT becomes "save, drain, exit 0".

Preemptible TPU slices *will* be reclaimed mid-run; the scheduler's SIGTERM
is a routine event, not a crash. Before this module, the train loop's
SIGTERM story was the flight recorder's handler (rt1_tpu/obs/recorder.py):
dump the ring, chain to SIG_DFL, die — a good post-mortem, a wasted epoch.

`PreemptionCoordinator` converts the first signal into a *cooperative*
shutdown request: the handler runs its callbacks (the train loop passes the
flight-recorder dump here, so the post-mortem artifact survives without the
recorder needing its own competing handler), sets a flag, and returns. The
train loop polls `triggered` once per step and performs the orderly exit
itself — force-save a checkpoint at the current step, drain the feeder,
return normally (exit 0) — which makes `restore_or_initialize` a true
preemption-resume path.

Chaining is explicit and escalation-safe: the previous handlers are saved
at install; a SECOND signal restores them and re-raises, so a wedged drain
(or an impatient operator's double Ctrl-C) still gets the pre-existing
behavior — including the flight recorder's die-with-dump handler if one was
installed before this coordinator.

Main-thread only (CPython delivers signals there); `install` returns False
and no-ops elsewhere, mirroring `FlightRecorder.install_sigterm`.
"""

from __future__ import annotations

import signal
import threading
import time
from typing import Callable, Dict, Iterable, Optional, Tuple


class PreemptionCoordinator:
    """First signal -> cooperative save-and-exit; second -> previous handler."""

    def __init__(
        self,
        callbacks: Iterable[Callable[[], None]] = (),
        signals: Tuple[int, ...] = (signal.SIGTERM, signal.SIGINT),
    ):
        self._callbacks = list(callbacks)
        self._signals = tuple(signals)
        self._event = threading.Event()
        self._prev: Dict[int, object] = {}
        self._signum: Optional[int] = None
        self._triggered_at: Optional[float] = None
        self._installed = False

    # -------------------------------------------------------------- handler

    def _handler(self, signum, frame):
        if self._event.is_set():
            # Second signal: the cooperative drain is not fast enough for
            # whoever is sending these — restore the previous handlers and
            # re-deliver, so the pre-coordinator semantics (flight-recorder
            # dump + die, or plain SIG_DFL) take over with an honest exit.
            self.uninstall()
            signal.raise_signal(signum)
            return
        self._signum = signum
        self._triggered_at = time.time()
        for cb in self._callbacks:
            try:
                cb()
            except Exception:  # noqa: BLE001 - exit path must not mask itself
                pass
        self._event.set()

    # ------------------------------------------------------------ lifecycle

    def install(self) -> bool:
        """Install handlers; False (no-op) off the main thread."""
        if threading.current_thread() is not threading.main_thread():
            return False
        if self._installed:
            return True
        for signum in self._signals:
            self._prev[signum] = signal.signal(signum, self._handler)
        self._installed = True
        return True

    def uninstall(self) -> None:
        if not self._installed:
            return
        for signum, prev in self._prev.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, TypeError):  # non-main thread / exotic prev
                pass
        self._prev.clear()
        self._installed = False

    # ------------------------------------------------------------ inspection

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    @property
    def signum(self) -> Optional[int]:
        return self._signum

    @property
    def triggered_at(self) -> Optional[float]:
        return self._triggered_at

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def counters(self, prefix: str = "preempt/") -> Dict[str, float]:
        """Gauge for the obs scalar stream (1 once a signal arrived)."""
        return {f"{prefix}triggered": 1.0 if self.triggered else 0.0}
