"""Deterministic fault injection: the failure paths become testable.

Every recovery path in this package (guard skip/rollback, checkpoint-save
retry, preemption save-and-exit, feeder stall diagnosis) exists because a
specific failure happens on real runs — and none of those failures can be
*scheduled* on demand without help. This registry injects them
deterministically: a fault plan is a list of ``site@occurrence`` specs,
matched by pure counting (no clocks, no randomness), so a test or a chaos
run reproduces the exact same failure at the exact same step every time.

Spec grammar (comma-separated)::

    nan_batch@7          poison host batch 7 (floats -> NaN) -> NaN loss
    ckpt_save@2          raise OSError on the 2nd CheckpointManager.save
                         (the 2nd LOGICAL save — retry attempts of one save
                         re-consult with the same ordinal, so an `x<K>`
                         budget fails K consecutive attempts of that save
                         rather than consuming later saves' occurrences)
    ckpt_restore@1       raise OSError on the 1st restore
    feeder_kill@12       the worker assembling ticket 12 raises
    feeder_hang@12       the worker assembling ticket 12 dies silently
                         (simulated deadlock; pairs with the feeder's
                         stall-timeout diagnosis)
    sigterm@5            deliver SIGTERM to this process at train step 5
    replica_kill@2       serve fleet: SIGKILL a serving replica at chaos
                         tick 2 (ticks count supervision cycles after the
                         fleet first reports all-ready; see serve/fleet.py)
    replica_hang@3       serve fleet: SIGSTOP a replica at chaos tick 3 —
                         alive to the OS, black-holes requests until the
                         supervisor's hang detector kills and respawns it
    serve_reload@4       serve fleet: start a rolling checkpoint reload
                         (one replica at a time) at chaos tick 4
    capture_write@2      flywheel: raise OSError on the 2nd episode the
                         serve-side capture sink tries to write (the sink
                         must drop the episode and keep serving)
    pack_append@1        flywheel: raise OSError on the 1st pack append,
                         AFTER the shard files land but BEFORE the
                         manifest rename — the torn-append window readers
                         must be immune to (rt1_tpu/data/pack.py)
    promote@1            deploy: raise OSError on the 1st fleet-wide
                         promote the PromotionController attempts — the
                         controller must roll the canary back and leave
                         the incumbent serving (rt1_tpu/deploy/)
    canary_slo_breach@3  deploy: force the canary burn signal over the
                         rollback threshold starting at canary-watch
                         tick 3 (synthetic breach: client traffic stays
                         clean, the decision path is what's under test)
    migrate_export@1     serve: raise OSError before the 1st session-
                         export leg of a live migration — the victim
                         session must degrade to the legacy
                         orphan+restart path, never a client 5xx
    migrate_import@2     serve: raise OSError before the 2nd session-
                         import leg of a live migration (export
                         succeeded; the snapshot is dropped and the
                         session restarts on its new replica)
    session_restore@1    serve: raise OSError on the 1st snapshot-ring
                         restore a replica attempts for an unknown
                         session — /act must fall back to a fresh
                         window (legacy restart), not fail the request
    <site>@<n>x<k>       fire on k consecutive occurrences starting at n
                         (e.g. nan_batch@3x4 poisons batches 3,4,5,6)

Two matching modes, chosen by the call site:

* count-based — ``should_fire(site)``: the injector counts calls to the
  site; the spec fires on occurrences ``at .. at+times-1`` (1-based). Only
  for call sites that are never retried — inside a retry loop every
  attempt would advance the count, silently consuming later occurrences.
* index-based — ``should_fire(site, index=i)``: the caller supplies the
  ordinal (batch index, feeder ticket, train step, logical save number);
  the spec fires while ``at <= i < at+times`` and the spec's own fire
  budget lasts. Every in-tree site uses this mode: the budget keeps a
  rolled-back run (whose batch indices restart at 0) from re-firing an
  exhausted fault, and the checkpoint layer passes its logical-operation
  ordinal so retry attempts don't advance the schedule.

Install a plan process-wide with :func:`install` /
:func:`install_from` (config string, with the ``RT1_FAULTS`` env var
appended — the subprocess-friendly channel chaos drivers use). Call sites
pay one module-global read when no plan is installed.

This module must stay import-light (stdlib + numpy only): the feeder's
worker threads and the checkpoint layer both consult it.
"""

from __future__ import annotations

import dataclasses
import os
import re
import signal
import threading
from typing import Dict, List, Optional

ENV_VAR = "RT1_FAULTS"

_SPEC_RE = re.compile(r"^(?P<site>[a-z0-9_]+)@(?P<at>\d+)(x(?P<times>\d+))?$")

KNOWN_SITES = (
    "nan_batch",
    "ckpt_save",
    "ckpt_restore",
    "feeder_kill",
    "feeder_hang",
    "sigterm",
    "replica_kill",
    "replica_hang",
    "serve_reload",
    "capture_write",
    "pack_append",
    "promote",
    "canary_slo_breach",
    "migrate_export",
    "migrate_import",
    "session_restore",
)


@dataclasses.dataclass
class FaultSpec:
    """One scheduled fault: fire `times` occurrences starting at `at`."""

    site: str
    at: int
    times: int = 1
    fired: int = 0

    def spec_str(self) -> str:
        return f"{self.site}@{self.at}" + (
            f"x{self.times}" if self.times != 1 else ""
        )


class FaultPlan:
    """A deterministic schedule of faults, matched by counting only."""

    def __init__(self, specs: List[FaultSpec]):
        self._specs = list(specs)
        self._lock = threading.Lock()
        self._site_calls: Dict[str, int] = {}

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        specs = []
        for part in (text or "").split(","):
            part = part.strip()
            if not part:
                continue
            m = _SPEC_RE.match(part)
            if not m:
                raise ValueError(
                    f"bad fault spec {part!r}; expected <site>@<n> or "
                    f"<site>@<n>x<times> (e.g. 'nan_batch@7,ckpt_save@2')"
                )
            site = m.group("site")
            if site not in KNOWN_SITES:
                raise ValueError(
                    f"unknown fault site {site!r}; known: {KNOWN_SITES}"
                )
            specs.append(
                FaultSpec(
                    site=site,
                    at=int(m.group("at")),
                    times=int(m.group("times") or 1),
                )
            )
        return cls(specs)

    def __len__(self) -> int:
        return len(self._specs)

    def should_fire(self, site: str, index: Optional[int] = None) -> bool:
        """True when a spec for `site` fires on this call.

        `index=None` counts calls to the site (1-based occurrence match);
        an explicit `index` matches the caller's own ordinal. Either way a
        spec fires at most `times` total — deterministic and replay-safe.
        """
        with self._lock:
            if index is None:
                self._site_calls[site] = self._site_calls.get(site, 0) + 1
                index = self._site_calls[site]
            for spec in self._specs:
                if (
                    spec.site == site
                    and spec.fired < spec.times
                    and spec.at <= index < spec.at + spec.times
                ):
                    spec.fired += 1
                    return True
        return False

    def fired_counts(self) -> Dict[str, int]:
        """{spec-string: times fired} — for logs and chaos-run summaries."""
        with self._lock:
            return {s.spec_str(): s.fired for s in self._specs}

    def counters(self, prefix: str = "faults/") -> Dict[str, float]:
        """Flat per-site fired totals for the obs scalar stream."""
        with self._lock:
            out: Dict[str, float] = {}
            for s in self._specs:
                key = f"{prefix}{s.site}_fired"
                out[key] = out.get(key, 0.0) + float(s.fired)
        return out


# ------------------------------------------------------------- process-wide

_active: Optional[FaultPlan] = None


def install(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Set (or with None, clear) the process-wide fault plan."""
    global _active
    _active = plan
    return plan


def clear() -> None:
    install(None)


def active() -> Optional[FaultPlan]:
    """The installed plan, or None (the zero-cost common case)."""
    return _active


def install_from(config_spec: str = "") -> Optional[FaultPlan]:
    """Build + install a plan from a config string, appending ``RT1_FAULTS``.

    Returns None (and installs nothing) when both sources are empty, so
    production runs never pay a per-call plan lookup beyond one global read.
    """
    parts = [p for p in (config_spec or "", os.environ.get(ENV_VAR, "")) if p]
    text = ",".join(parts)
    if not text:
        install(None)
        return None
    return install(FaultPlan.parse(text))


# ---------------------------------------------------------------- injectors


def maybe_fail(site: str, index: Optional[int] = None, what: str = "") -> None:
    """Raise an injected OSError when the active plan fires for `site`."""
    plan = _active
    if plan is not None and plan.should_fire(site, index=index):
        raise OSError(
            f"injected fault [{site}]" + (f": {what}" if what else "")
        )


def maybe_signal(site: str, index: Optional[int], signum=signal.SIGTERM) -> bool:
    """Deliver `signum` to this process when the plan fires; returns True."""
    plan = _active
    if plan is not None and plan.should_fire(site, index=index):
        os.kill(os.getpid(), signum)
        return True
    return False


def poison_batch(batch):
    """Return a copy of a nested host batch with every float leaf set to NaN.

    Integer/uint8 leaves (token ids, packed images) pass through untouched —
    NaN has no integer encoding, and poisoning the float leaves (embeddings,
    actions) is already sufficient to drive the loss non-finite.
    """
    import numpy as np

    def _poison(value):
        if isinstance(value, dict):
            return {k: _poison(v) for k, v in value.items()}
        arr = np.asarray(value)
        if np.issubdtype(arr.dtype, np.floating):
            return np.full_like(arr, np.nan)
        return arr

    return _poison(batch)
