"""rt1_tpu.resilience — self-healing training for long preemptible runs.

The obs subsystem (PR 3) made failures *visible*; this package makes the
train loop *survive* them. Four pieces, all config-gated and all cheap (or
free) when off:

* :mod:`rt1_tpu.resilience.guard`   — NaN/spike step guard with a bounded
  escalation ladder: device-side update skip -> checkpoint rollback with a
  fresh data-stream seed -> abort. (`rt1_train_guard_*` counters.)
* :mod:`rt1_tpu.resilience.retry`   — exponential-backoff-with-jitter retry
  wrapped around the I/O seams (checkpoint save/restore, packed-cache open,
  feeder construction). (`rt1_train_retry_*` counters.)
* :mod:`rt1_tpu.resilience.preempt` — SIGTERM/SIGINT coordinator turning
  preemption into "force-save at the current step, drain the feeder,
  exit 0" — `restore_or_initialize` then resumes exactly.
* :mod:`rt1_tpu.resilience.faults`  — deterministic fault injection
  ("NaN loss at batch 7", "IOError on the 2nd checkpoint save") so every
  recovery path above is provable in tier-1 tests and chaos runs
  (`scripts/chaos_train.py`).

Import hygiene matches `rt1_tpu.obs`: stdlib + numpy + obs.trace only at
module scope — the feeder workers and checkpoint layer import from here.

See `docs/resilience.md` for the operator guide (failure modes -> knobs ->
recovery semantics, and the fault-injection cookbook).
"""

from __future__ import annotations

import dataclasses

from rt1_tpu.resilience import faults, guard, preempt, retry
from rt1_tpu.resilience.guard import (
    GuardAbortError,
    GuardOptions,
    GuardVerdict,
    StepGuard,
)
from rt1_tpu.resilience.preempt import PreemptionCoordinator
from rt1_tpu.resilience.retry import RetryOptions, retry_call

__all__ = [
    "GuardAbortError",
    "GuardOptions",
    "GuardVerdict",
    "PreemptionCoordinator",
    "ResilienceOptions",
    "RetryOptions",
    "StepGuard",
    "faults",
    "guard",
    "preempt",
    "retry",
    "retry_call",
]


@dataclasses.dataclass
class ResilienceOptions:
    """Resolved `config.resilience` with defaults for configs that predate it.

    Mirrors `obs.ObsOptions`: the train loop consumes this instead of poking
    `config.resilience.*`, so pre-resilience configs (pinned proof configs,
    sweep artifacts) keep running with the exact old loop semantics —
    every default below is "off"/parity.
    """

    # Step guard (guard.py + the guarded train step in trainer/train.py).
    guard: bool = False
    guard_grad_norm_max: float = 0.0
    guard_loss_spike_factor: float = 0.0
    guard_spike_ema_beta: float = 0.9
    guard_warmup_checks: int = 3
    guard_skip_budget: int = 3
    guard_rollback_budget: int = 2
    # Retry on the I/O seams (checkpoint save/restore, packed-cache open,
    # feeder construction).
    io_retry: bool = False
    retry_attempts: int = 3
    retry_backoff_s: float = 0.5
    retry_max_backoff_s: float = 8.0
    retry_deadline_s: float = 120.0
    # SIGTERM/SIGINT -> save-and-exit-0 instead of die-with-dump.
    preempt_save: bool = False
    # Deterministic fault schedule (faults.py grammar); RT1_FAULTS appends.
    faults: str = ""

    @classmethod
    def from_config(cls, config) -> "ResilienceOptions":
        """Read `config.resilience` if present (ml_collections or mapping);
        absent keys fall back to the dataclass defaults."""
        node = None
        if config is not None:
            get = getattr(config, "get", None)
            node = (
                get("resilience")
                if callable(get)
                else getattr(config, "resilience", None)
            )
        kwargs = {}
        if node is not None:
            for field in dataclasses.fields(cls):
                getter = getattr(node, "get", None)
                value = (
                    getter(field.name)
                    if callable(getter)
                    else getattr(node, field.name, None)
                )
                if value is not None:
                    kwargs[field.name] = value
        return cls(**kwargs)

    def guard_options(self) -> GuardOptions:
        return GuardOptions(
            enabled=self.guard,
            grad_norm_max=self.guard_grad_norm_max,
            loss_spike_factor=self.guard_loss_spike_factor,
            spike_ema_beta=self.guard_spike_ema_beta,
            warmup_checks=self.guard_warmup_checks,
            skip_budget=self.guard_skip_budget,
            rollback_budget=self.guard_rollback_budget,
        )

    def retry_options(self) -> "RetryOptions | None":
        if not self.io_retry:
            return None
        return RetryOptions(
            attempts=self.retry_attempts,
            backoff_s=self.retry_backoff_s,
            max_backoff_s=self.retry_max_backoff_s,
            deadline_s=self.retry_deadline_s,
        )
