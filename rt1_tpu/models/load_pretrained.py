"""Torch -> Flax pretrained weight porting for EfficientNet-B3.

Parity source: reference `film_efficientnet/film_efficientnet_encoder.py:
376-425` — it loads torchvision's `efficientnet_b3` checkpoint by a blind
*ordered zip* of state-dict keys (`load_official_pytorch_param:411-425`,
"differs from the official pytorch implementation only in parameter names"),
then copies the non-FiLM subset into the FiLM variant (FiLM layers stay
zero-initialized, so pretrained behavior is preserved, `:400-407`).

We do the same ordered alignment, made explicit and checked:

1. group the torch state dict into per-module bundles (conv / batchnorm /
   linear) in key order;
2. group our Flax EfficientNet params (+ batch_stats) into bundles in
   construction order, skipping FiLM layers (zero-init by design);
3. zip per-kind and copy with layout conversion: conv OIHW -> HWIO
   (depthwise OIHW -> HWIO with the channel-multiplier layout flax expects),
   linear (out,in) -> (in,out), BN gamma/beta/mean/var straight through.

Every copy shape-checks after conversion, so any architecture or ordering
drift fails loudly instead of silently loading garbage (the blobs are
missing from the reference checkout too, `.MISSING_LARGE_BLOBS`; with no
torchvision in this image the entry point accepts any torch-format
state_dict file).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

import flax


def _to_numpy(t) -> np.ndarray:
    if hasattr(t, "detach"):
        return t.detach().cpu().numpy()
    return np.asarray(t)


def _group_torch(state_dict) -> List[Tuple[str, str, Dict[str, np.ndarray]]]:
    """[(kind, module_name, tensors)] in state-dict order.

    kind in {conv, bn, linear}; tensors keyed weight/bias/mean/var.
    """
    groups: List[Tuple[str, str, Dict[str, np.ndarray]]] = []
    by_module: Dict[str, Dict[str, np.ndarray]] = {}
    order: List[str] = []
    for key, value in state_dict.items():
        if key.endswith("num_batches_tracked"):
            continue
        module, leaf = key.rsplit(".", 1)
        if module not in by_module:
            by_module[module] = {}
            order.append(module)
        by_module[module][leaf] = _to_numpy(value)

    for module in order:
        tensors = by_module[module]
        if "running_mean" in tensors:
            kind = "bn"
        elif tensors["weight"].ndim == 4:
            kind = "conv"
        elif tensors["weight"].ndim == 2:
            kind = "linear"
        else:
            raise ValueError(
                f"Unrecognized torch module {module!r} with "
                f"weight shape {tensors['weight'].shape}"
            )
        groups.append((kind, module, tensors))
    return groups


def _structural_key(path: Tuple) -> Tuple:
    """Sort key putting EfficientNet modules in ARCHITECTURAL order.

    Dict iteration order is not trustworthy here: a fresh `model.init`
    yields construction order, but any pytree round-trip — `jax.eval_shape`,
    jit output reconstruction, an **Orbax checkpoint restore** — returns
    keys string-sorted ('block_10' before 'block_2'). The ordered-zip
    alignment must therefore be derived from the architecture, not from
    whatever order the dict happens to carry.
    """
    order_top = {"stem": 0, "top": 2, "classifier": 3}
    order_in_block = {"expand": 0, "depthwise": 1, "se": 2, "project": 3}
    order_se = {"fc1": 0, "fc2": 1}
    order_cna = {"conv": 0, "bn": 1}  # within a ConvNormAct
    key: List = []
    for part in path:
        name = str(part)
        if name.startswith("block_") and name[6:].isdigit():
            key.append((1, int(name[6:]), ""))
        elif name in order_top:
            key.append((order_top[name], -1, ""))
        elif name in order_in_block:
            key.append((order_in_block[name], -1, ""))
        elif name in order_se:
            key.append((order_se[name], -1, ""))
        elif name in order_cna:
            key.append((order_cna[name], -1, ""))
        else:
            key.append((9, -1, name))  # unknown: stable alphabetical tail
    return tuple(key)


def _group_flax(params, batch_stats) -> List[Tuple[str, Tuple, Dict]]:
    """[(kind, path, leaves)] in ARCHITECTURAL order, FiLM layers skipped."""
    flat_params = flax.traverse_util.flatten_dict(params)
    flat_stats = flax.traverse_util.flatten_dict(batch_stats or {})

    groups: List[Tuple[str, Tuple, Dict]] = []
    seen = set()
    for path in flat_params:
        parent = path[:-1]
        if parent in seen:
            continue
        seen.add(parent)
        if any("film" in str(p).lower() for p in parent):
            continue
        leaves = {
            p[-1]: v
            for p, v in flat_params.items()
            if p[:-1] == parent
        }
        stats = {
            p[-1]: v for p, v in flat_stats.items() if p[:-1] == parent
        }
        if stats:
            groups.append(("bn", parent, {**leaves, **stats}))
        elif "kernel" in leaves and leaves["kernel"].ndim == 4:
            groups.append(("conv", parent, leaves))
        elif "kernel" in leaves and leaves["kernel"].ndim == 2:
            groups.append(("linear", parent, leaves))
        else:
            raise ValueError(f"Unrecognized flax module at {parent}")
    groups.sort(key=lambda g: _structural_key(g[1]))
    return groups


def _convert_conv(torch_w: np.ndarray, flax_kernel: np.ndarray) -> np.ndarray:
    """OIHW -> HWIO, handling depthwise (torch groups=C: weight (C,1,kh,kw),
    flax feature_group_count=C: kernel (kh, kw, 1, C))."""
    o, i, kh, kw = torch_w.shape
    # One transpose covers both cases: regular convs (O,I,kh,kw)->(kh,kw,I,O)
    # and depthwise (C,1,kh,kw)->(kh,kw,1,C), which is exactly flax's
    # feature_group_count layout.
    hwio = np.transpose(torch_w, (2, 3, 1, 0))
    if hwio.shape != flax_kernel.shape:
        raise ValueError(
            f"conv shape mismatch: torch {torch_w.shape} -> {hwio.shape}, "
            f"flax {flax_kernel.shape}"
        )
    return hwio


def port_torch_efficientnet(
    state_dict: Any,
    variables: Dict[str, Any],
    submodule_path: Tuple[str, ...] = (),
) -> Dict[str, Any]:
    """Copy a torch EfficientNet state dict into our Flax variables.

    Args:
      state_dict: torch state dict (torchvision efficientnet_b3 layout, or
        the reference's renamed equivalent — only ordering matters).
      variables: our model's {'params': ..., 'batch_stats': ...}.
      submodule_path: path of the EfficientNet submodule inside `variables`
        (e.g. ("image_tokenizer", "encoder", "net")), empty = whole tree.
    Returns:
      New variables dict with ported weights (input unmodified).
    """

    def descend(tree):
        node = tree
        for p in submodule_path:
            node = node[p]
        return node

    params = flax.core.unfreeze(variables["params"])
    batch_stats = flax.core.unfreeze(variables.get("batch_stats", {}))
    sub_params = descend(params)
    sub_stats = descend(batch_stats) if batch_stats else {}

    torch_groups = _group_torch(state_dict)
    flax_groups = _group_flax(sub_params, sub_stats)

    by_kind_torch: Dict[str, list] = {"conv": [], "bn": [], "linear": []}
    for kind, name, tensors in torch_groups:
        by_kind_torch[kind].append((name, tensors))
    by_kind_flax: Dict[str, list] = {"conv": [], "bn": [], "linear": []}
    for kind, path, leaves in flax_groups:
        by_kind_flax[kind].append((path, leaves))

    for kind in ("conv", "bn", "linear"):
        n_torch = len(by_kind_torch[kind])
        n_flax = len(by_kind_flax[kind])
        if n_torch != n_flax:
            raise ValueError(
                f"{kind} count mismatch: torch has {n_torch}, "
                f"flax (non-FiLM) has {n_flax}"
            )

    flat_params = flax.traverse_util.flatten_dict(sub_params)
    flat_stats = flax.traverse_util.flatten_dict(sub_stats)

    def assign(path, leaf, value, target_flat):
        current = target_flat[path + (leaf,)]
        if current.shape != value.shape:
            raise ValueError(
                f"shape mismatch at {path + (leaf,)}: "
                f"{current.shape} vs {value.shape}"
            )
        target_flat[path + (leaf,)] = value.astype(current.dtype)

    for (name, tensors), (path, leaves) in zip(
        by_kind_torch["conv"], by_kind_flax["conv"]
    ):
        assign(
            path, "kernel",
            _convert_conv(tensors["weight"], np.asarray(leaves["kernel"])),
            flat_params,
        )
        if "bias" in tensors and "bias" in leaves:
            assign(path, "bias", tensors["bias"], flat_params)

    for (name, tensors), (path, leaves) in zip(
        by_kind_torch["bn"], by_kind_flax["bn"]
    ):
        assign(path, "scale", tensors["weight"], flat_params)
        assign(path, "bias", tensors["bias"], flat_params)
        assign(path, "mean", tensors["running_mean"], flat_stats)
        assign(path, "var", tensors["running_var"], flat_stats)

    for (name, tensors), (path, leaves) in zip(
        by_kind_torch["linear"], by_kind_flax["linear"]
    ):
        assign(path, "kernel", tensors["weight"].T, flat_params)
        if "bias" in tensors and "bias" in leaves:
            assign(path, "bias", tensors["bias"], flat_params)

    new_sub_params = flax.traverse_util.unflatten_dict(flat_params)
    new_sub_stats = flax.traverse_util.unflatten_dict(flat_stats)

    def replace(tree, new_sub):
        if not submodule_path:
            return new_sub
        node = tree
        for p in submodule_path[:-1]:
            node = node[p]
        node[submodule_path[-1]] = new_sub
        return tree

    params = replace(params, new_sub_params)
    if batch_stats:
        batch_stats = replace(batch_stats, new_sub_stats)
    out = dict(variables)
    out["params"] = params
    if batch_stats:
        out["batch_stats"] = batch_stats
    return out


def load_torch_checkpoint(path: str):
    """Load a .pth state dict (torch is CPU-only in this image)."""
    import torch

    obj = torch.load(path, map_location="cpu", weights_only=True)
    if hasattr(obj, "state_dict"):
        obj = obj.state_dict()
    return obj
