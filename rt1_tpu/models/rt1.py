"""RT-1 policy network: tokenizers + causal transformer, train & inference paths.

Re-design of `pytorch_robotics_transformer/transformer_network.py` (`TransformerNetwork`,
`:35-532`). Same semantics, TPU-native structure:

* **Masks** (`rt1_attention_mask`, reference `_generate_masks:156-192`): causal tril
  minus an action mask — an action-token query may never attend to action-token keys
  of the same or earlier timestep (including itself); image-token queries are only
  causally masked. Action tokens are additionally **zeroed at input assembly**
  (reference `:378-390`, comment at `:383`), so logits never depend on action values.
* **Training** (`__call__`): ONE transformer pass over the T·(I+A) sequence; CE loss
  on the logits at position (action position − 1) (the transformer's shift-by-one,
  reference `:237,304-322`), with the reference's `/ (b·t·(I+A))` scaling reproduced
  under `loss_scale='reference'` (`:314-319` — the LR schedule was tuned against it).
* **Inference** (`infer_step`): the reference runs `tokens_per_action` FULL transformer
  passes per control step, argmaxing one token at a time (`:246-268`). Because action
  inputs are zeroed and masked out, those passes are *identical*, so all action tokens
  can be read from a SINGLE pass — a ~`tokens_per_action`× inference speedup with
  bit-identical results (proved in tests/test_rt1.py::test_single_pass_equals_autoregressive).
  The rolling `network_state` window (context_image_tokens, action_tokens, seq_idx;
  reference `:105-123,462-492`) becomes a static-shape pytree updated with
  `dynamic_update_slice` + `jnp.where`-gated rolls, fully jittable.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from rt1_tpu.models import action_tokenizer
from rt1_tpu.models.image_tokenizer import RT1ImageTokenizer
from rt1_tpu.models.transformer import CausalTransformer
from rt1_tpu.ops import image as image_ops


def rt1_attention_mask(
    time_sequence_length: int, tokens_per_image: int, tokens_per_action: int
) -> np.ndarray:
    """The RT-1 custom attention mask (reference `_generate_masks:156-192`).

    Returns (S, S) uint8, S = T·(I+A); 1 = may attend, 0 = blocked. Row = query
    position, column = key position.
    """
    step = tokens_per_image + tokens_per_action
    size = time_sequence_length * step

    def action_time(k: int) -> int:
        # Timestep index if k is an action token, else -1 (reference :131-150).
        return k // step if (k % step) >= tokens_per_image else -1

    mask = np.tril(np.ones((size, size), np.uint8))
    for i in range(size):
        ti = action_time(i)
        if ti < 0:
            continue
        for j in range(i + 1):
            tj = action_time(j)
            if tj < 0:
                continue
            if tj < ti or (tj == ti and j <= i):
                mask[i, j] = 0
    return mask


def action_token_positions(
    time_sequence_length: int, tokens_per_image: int, tokens_per_action: int
) -> np.ndarray:
    """Sequence indices of the action tokens (reference `_action_tokens_mask:166-169`)."""
    step = tokens_per_image + tokens_per_action
    return np.array(
        [
            t * step + tokens_per_image + x
            for t in range(time_sequence_length)
            for x in range(tokens_per_action)
        ],
        np.int32,
    )


class RT1Policy(nn.Module):
    """The RT-1 actor network (reference `TransformerNetwork:35-123`)."""

    action_space: Any                 # Mapping[str, Spec] — static metadata
    vocab_size: int = 256
    token_embedding_size: int = 512
    num_layers: int = 8
    layer_size: int = 128             # per-head attention width (key_dim)
    num_heads: int = 8
    feed_forward_size: int = 512      # d_model
    dropout_rate: float = 0.1
    time_sequence_length: int = 6
    use_token_learner: bool = True
    num_image_tokens: int = 8
    crop_ratio: float = 0.07          # pad-and-random-shift ratio (preprocessors.py:37)
    photometric_augmentation: bool = False  # on-device color jitter (train only)
    loss_scale: str = "reference"     # 'reference' (:314-319) or 'mean'
    # Focal modulation of the action-token CE (Lin et al. 2017): ce *=
    # (1 - p_label)^gamma. 0 disables (reference parity). BC on smooth
    # scripted demos concentrates labels on a few near-center buckets, so a
    # near-constant policy already scores low CE (the "copycat" collapse
    # diagnosed in RESULTS.md round 2); gamma > 0 down-weights those easy
    # marginal tokens and shifts gradient onto the rare directional ones.
    focal_gamma: float = 0.0
    # Soft-argmax auxiliary regression: loss += w * MSE(E[a], a_true) where
    # E[a] = sum_v softmax(logits)[v] * bin_value[v] over the Box action
    # tokens (action_tokenizer.box_bin_values). Parameter-free (no new
    # weights — checkpoints unaffected) and differentiable, it supplies a
    # dense regression gradient through the whole network while the token
    # CE sits on its marginal-entropy plateau — the round-3 diagnosis: CE
    # alone spends its first many epochs fitting the marginal (measured
    # 2.508 nats on the oracle corpus) with ~zero input-dependence.
    # 0 disables (reference parity).
    aux_mse_weight: float = 0.0
    # Inference action decode: "argmax" (reference parity,
    # transformer_network.py:262) or "expected" — E[a] under the token
    # softmax for Box dims (action_tokenizer.detokenize_expected), smoother
    # when distribution mass straddles a bin edge and consistent with the
    # aux_mse training objective. The rolling state always stores argmax
    # tokens either way (the reference's state semantics).
    action_decode: str = "argmax"
    return_attention_scores: bool = False
    dtype: jnp.dtype = jnp.float32
    # "dense" (default), "ring", or "pallas". "ring" shards the token
    # sequence over the mesh's ``seq`` axis (sequence/context parallelism
    # for long-horizon variants; requires `mesh` with a >1 seq axis).
    # "pallas" fuses inference attention into one VMEM kernel on TPU
    # (training and non-TPU backends fall back to dense).
    attention_impl: str = "dense"
    mesh: Optional[Any] = None
    pallas_interpret: bool = False  # test-only: run the kernel off-TPU
    # FFN choice for the decoder blocks: "dense" (reference parity) or "moe"
    # (Switch-routed expert FFN, rt1_tpu/models/moe.py — expert-parallel when
    # the stacked expert weights are sharded over 'model'). The Switch
    # load-balancing aux loss is sown into intermediates and added to the
    # training loss by the trainer with weight `moe_aux_weight`.
    ffn_impl: str = "dense"
    num_experts: int = 4
    moe_capacity_factor: float = 2.0
    moe_ff_dim: Optional[int] = None
    moe_aux_weight: float = 0.01
    # Pipeline parallelism: when `mesh` has a >1 "stage" axis, the decoder's
    # layer stack runs GPipe-pipelined over it (parallel/pipeline.py) with
    # this many microbatches per step; per-(layer, microbatch) dropout rngs
    # are folded from the "dropout" stream. Param layout is unchanged
    # (checkpoints are stage-count-portable); parameters stay replicated —
    # PP here scales *compute* across chips, which at RT-1 size (decoder
    # ~17M params) is the binding constraint, not parameter memory.
    pipeline_microbatches: int = 4
    # Rematerialize transformer blocks AND MBConv blocks in the backward
    # pass (jax.checkpoint): O(depth)→O(1) activation memory for ~1/3 extra
    # FLOPs — batch-size headroom on HBM-bound flagship configs.
    # Semantics-preserving (loss/grads unchanged; pinned in tests).
    remat: bool = False
    # Optional custom image tokenizer module (must map (b,t,H,W,3), (b,t,D) →
    # (b,t,num_image_tokens,token_embedding_size)); used by tests to swap the
    # EfficientNet-B3 backbone for a tiny one.
    image_tokenizer_def: Optional[Any] = None

    @property
    def tokens_per_action(self) -> int:
        return action_tokenizer.tokens_per_action(self.action_space)

    @property
    def tokens_per_image(self) -> int:
        if not self.use_token_learner and self.image_tokenizer_def is None:
            raise ValueError("token count is input-resolution-dependent without TokenLearner")
        return self.num_image_tokens

    @property
    def single_step_tokens(self) -> int:
        return self.tokens_per_image + self.tokens_per_action

    @property
    def sequence_tokens(self) -> int:
        return self.time_sequence_length * self.single_step_tokens

    def setup(self):
        if self.action_decode not in ("argmax", "expected"):
            raise ValueError(
                f"action_decode must be 'argmax' or 'expected', got "
                f"{self.action_decode!r}"
            )
        if self.action_decode == "expected" and not any(
            isinstance(s, action_tokenizer.BoxSpec)
            for s in self.action_space.values()
        ):
            # box_bin_values (the E[a] bin table) would raise at trace time
            # with a message about the aux-MSE objective; fail at
            # construction with the real reason instead.
            raise ValueError(
                "action_decode='expected' needs at least one Box action "
                "entry (soft decode only differs from argmax for Box); "
                "this action space is all-Discrete — use 'argmax'"
            )
        if self.image_tokenizer_def is not None:
            self.image_tokenizer = self.image_tokenizer_def
        else:
            self.image_tokenizer = RT1ImageTokenizer(
                embedding_output_dim=self.token_embedding_size,
                use_token_learner=self.use_token_learner,
                num_tokens=self.num_image_tokens,
                dtype=self.dtype,
                remat=self.remat,
            )
        self.transformer = CausalTransformer(
            num_layers=self.num_layers,
            key_dim=self.layer_size,
            num_heads=self.num_heads,
            d_model=self.feed_forward_size,
            dropout_rate=self.dropout_rate,
            vocab_size=self.vocab_size,
            # Reference fixes 256 (transformer.py:156); grow if the configured
            # window needs more so positions never clamp silently.
            max_seq_len=max(256, self.sequence_tokens),
            return_attention_scores=self.return_attention_scores,
            dtype=self.dtype,
            attention_impl=self.attention_impl,
            mesh=self.mesh,
            pallas_interpret=self.pallas_interpret,
            ffn_impl=self.ffn_impl,
            num_experts=self.num_experts,
            moe_capacity_factor=self.moe_capacity_factor,
            moe_ff_dim=self.moe_ff_dim,
            remat=self.remat,
        )
        self._mask = rt1_attention_mask(
            self.time_sequence_length, self.tokens_per_image, self.tokens_per_action
        )
        self._action_positions = action_token_positions(
            self.time_sequence_length, self.tokens_per_image, self.tokens_per_action
        )

    # ------------------------------------------------------------------ helpers

    def _preprocess_images(self, image: jnp.ndarray, train: bool) -> jnp.ndarray:
        """uint8→[0,1] plus train-time pad/random-shift crop (preprocessors.py:37-56).

        Deviation from the reference (documented): the reference random-crops in
        *every* forward, inference included (`transformer_network.py:445` has no
        train gate). We crop only when `train=True` — deterministic eval.
        """
        do_crop = train and self.crop_ratio > 0
        image = image_ops.convert_dtype_and_crop_images(
            image,
            rng=self.make_rng("crop") if do_crop else None,
            ratio=self.crop_ratio,
            train=do_crop,
        )
        if train and self.photometric_augmentation:
            # On-device color jitter (Stack B's PhotometricDistortions,
            # `input_pipeline_rlds.py:391-457`), fused into the forward so
            # the host pipeline stays augmentation-free. Dedicated "augment"
            # stream so color randomness is independent of the crop offsets
            # ("crop" fallback keeps old callers working).
            from rt1_tpu.ops.augment import photometric_distortions

            aug_rng = (
                self.make_rng("augment")
                if self.has_rng("augment")
                else self.make_rng("crop")
            )
            image = photometric_distortions(image, aug_rng)
        return image

    def _tokenize_images(
        self, image: jnp.ndarray, context: Optional[jnp.ndarray], train: bool
    ) -> jnp.ndarray:
        """image (b, t, H, W, 3), context (b, t, D) or (b, D) → tokens (b, t, I, E)."""
        if context is not None and context.ndim == 2:
            context = jnp.tile(context[:, None, :], (1, image.shape[1], 1))
        image = self._preprocess_images(image, train)
        return self.image_tokenizer(image, context=context, train=train)

    def _assemble(self, context_image_tokens: jnp.ndarray) -> jnp.ndarray:
        """(b, t, I, E) → (b, t·(I+A), E) with zeroed action slots (reference :378-390)."""
        b, t, _, e = context_image_tokens.shape
        action_slots = jnp.zeros((b, t, self.tokens_per_action, e), context_image_tokens.dtype)
        seq = jnp.concatenate([context_image_tokens, action_slots], axis=2)
        return seq.reshape(b, t * self.single_step_tokens, e)

    def _pipeline_enabled(self) -> bool:
        return (
            self.mesh is not None
            and getattr(self.mesh, "shape", {}).get("stage", 1) > 1
        )

    def _transformer_logits(self, context_image_tokens: jnp.ndarray, train: bool):
        seq = self._assemble(context_image_tokens)
        mask = jnp.asarray(self._mask)
        if self._pipeline_enabled() and not self.is_initializing():
            # GPipe path: same params, layer stack pipelined over the mesh's
            # "stage" axis. Init still runs the sequential module (below) so
            # the param tree is identical either way.
            if self.return_attention_scores:
                raise ValueError(
                    "attention scores are not materialized under pipeline "
                    "parallelism; use a stage=1 mesh for score visualization"
                )
            from rt1_tpu.parallel.pipeline import pp_causal_transformer_apply

            use_dropout = train and self.dropout_rate > 0
            logits = pp_causal_transformer_apply(
                self.transformer,
                {"params": self.transformer.variables["params"]},
                seq,
                mesh=self.mesh,
                num_microbatches=self.pipeline_microbatches,
                attention_mask=mask,
                train=train,
                dropout_rng=self.make_rng("dropout") if use_dropout else None,
            )
            return logits, None
        out = self.transformer(seq, attention_mask=mask, train=train)
        if self.return_attention_scores:
            return out  # (logits, scores)
        return out, None

    # ------------------------------------------------------------------ training

    def __call__(
        self,
        observations: Dict[str, jnp.ndarray],
        actions: Dict[str, jnp.ndarray],
        train: bool = False,
    ) -> Dict[str, jnp.ndarray]:
        """Training forward (reference `forward` else-branch `:294-332`).

        observations: {'image': (b, t, H, W, 3), 'natural_language_embedding':
        (b, t, D) or (b, D)}; actions: per-key (b, t, ...) labels.

        Returns aux dict mirroring the reference's `get_aux_info` (`:531`):
        loss (scalar), action_loss (b, t), action_predictions (b, t, A),
        action_labels (b, t, A), action_logits (b, t, A, vocab).
        """
        image = observations["image"]
        context = observations.get("natural_language_embedding")
        b, t = image.shape[0], image.shape[1]
        assert t == self.time_sequence_length, (t, self.time_sequence_length)

        context_image_tokens = self._tokenize_images(image, context, train)
        logits, scores = self._transformer_logits(context_image_tokens, train)

        labels = action_tokenizer.tokenize(self.action_space, actions, self.vocab_size)

        # Transformer predicts next token: read logits one position early (:237,304).
        pred_positions = jnp.asarray(self._action_positions - 1)
        action_logits = jnp.take(logits, pred_positions, axis=1)
        action_logits = action_logits.reshape(b, t, self.tokens_per_action, self.vocab_size)

        ce = _softmax_ce_int(action_logits.astype(jnp.float32), labels)  # (b, t, A)
        loss_terms = ce
        if self.focal_gamma > 0:
            # ce = -log p_label, so 1 - p_label = -expm1(-ce); gradients flow
            # through the modulating factor too (the standard focal-loss
            # form). The floor keeps the power branch differentiable at
            # ce == 0 for fractional gamma (x**g has an infinite slope at 0
            # when g < 1, and saturated easy tokens do reach ce == 0 in fp32).
            # Only the optimized loss is modulated; the "cross_entropy" aux
            # output stays raw CE so it remains comparable across gammas.
            base = jnp.maximum(-jnp.expm1(-ce), 1e-12)
            loss_terms = base ** self.focal_gamma * ce
        if self.loss_scale == "reference":
            num_items = float(b * t) * self.single_step_tokens
            action_loss = jnp.mean(loss_terms, axis=-1) / num_items  # (b, t), reference :314-320
        else:
            action_loss = jnp.mean(loss_terms, axis=-1)
        loss = jnp.mean(action_loss)  # harness loss_fn (distribute_train.py:112-118)

        out = {
            "loss": loss,
            "action_loss": action_loss,
            "cross_entropy": ce,
            "action_labels": labels,
            "action_logits": action_logits,
            "action_predictions": jnp.argmax(action_logits, axis=-1),
        }
        if self.aux_mse_weight > 0:
            bins, box_mask = action_tokenizer.box_bin_values(
                self.action_space, self.vocab_size
            )
            probs = jax.nn.softmax(
                action_logits.astype(jnp.float32), axis=-1
            )  # (b, t, A, V)
            expected = jnp.einsum("btav,av->bta", probs, jnp.asarray(bins))
            target = action_tokenizer.continuous_targets(
                self.action_space, actions
            )  # (b, t, A)
            mask = jnp.asarray(box_mask)  # (A,)
            mse = jnp.sum(
                jnp.square(expected - target) * mask
            ) / (jnp.sum(mask) * b * t)
            # Under 'reference' scaling the CE part is ∝ 1/(b·t·(I+A));
            # giving the aux term the same normalizer keeps (a) gradient
            # accumulation exact (the trainer's extra /accum correction
            # assumes the WHOLE loss is inversely proportional to runtime
            # batch) and (b) the CE/aux balance independent of batch size
            # and sequence length. The reported "aux_mse" metric stays the
            # raw, unit-interpretable mean-squared error.
            if self.loss_scale == "reference":
                loss = loss + self.aux_mse_weight * mse / num_items
            else:
                loss = loss + self.aux_mse_weight * mse
            out["loss"] = loss
            out["aux_mse"] = mse
        if scores is not None:
            out["attention_scores"] = scores
        return out

    # ------------------------------------------------------------------ inference

    def initial_state(
        self, batch_size: int, cached: bool = False
    ) -> Dict[str, jnp.ndarray]:
        """Zeroed rolling window state (reference `_state_space:105-123`).

        ``cached=True`` adds the per-layer transformer K/V cache consumed by
        `infer_step_cached` — one (b, layers, 2, sequence_tokens, heads,
        key_dim) leaf at the compute dtype. Default off: the state schema
        (and therefore every existing serving/eval program) is byte-
        identical to the pre-cache layout.
        """
        state = {
            "context_image_tokens": jnp.zeros(
                (batch_size, self.time_sequence_length, self.tokens_per_image,
                 self.token_embedding_size),
                jnp.float32,
            ),
            "action_tokens": jnp.zeros(
                (batch_size, self.time_sequence_length, self.tokens_per_action), jnp.int32
            ),
            "seq_idx": jnp.zeros((), jnp.int32),
        }
        if cached:
            state["kv_cache"] = jnp.zeros(
                (batch_size, self.num_layers, 2, self.sequence_tokens,
                 self.num_heads, self.layer_size),
                self.dtype,
            )
        return state

    def _advance_window(self, observation, state):
        """Shared inference prologue: roll-if-full, tokenize frame, insert (reference
        `_tokenize_images:462-482` / `_tokenize_actions:487-492`)."""
        seq_idx = state["seq_idx"]
        t_max = self.time_sequence_length
        time_step = jnp.minimum(seq_idx, t_max - 1)

        img_state = state["context_image_tokens"]
        act_state = state["action_tokens"]
        full = seq_idx == t_max
        img_state = jnp.where(full, jnp.roll(img_state, -1, axis=1), img_state)
        act_state = jnp.where(full, jnp.roll(act_state, -1, axis=1), act_state)

        image = observation["image"][:, None]  # (b, 1, H, W, 3)
        context = observation.get("natural_language_embedding")
        new_tokens = self._tokenize_images(image, context, train=False)  # (b, 1, I, E)
        img_state = jax.lax.dynamic_update_slice_in_dim(
            img_state, new_tokens.astype(img_state.dtype), time_step, axis=1
        )
        return img_state, act_state, time_step, seq_idx

    def infer_step(
        self, observation: Dict[str, jnp.ndarray], state: Dict[str, jnp.ndarray]
    ) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
        """One control step, SINGLE transformer pass (vs reference's A passes :246-268).

        observation: {'image': (b, H, W, 3), 'natural_language_embedding': (b, D)}.
        Returns ({'action_tokens', 'action_logits', <detokenized action>}, new_state).
        """
        img_state, act_state, time_step, seq_idx = self._advance_window(observation, state)

        logits, _ = self._transformer_logits(img_state, train=False)
        start = time_step * self.single_step_tokens + self.tokens_per_image - 1
        step_logits = jax.lax.dynamic_slice_in_dim(
            logits, start, self.tokens_per_action, axis=1
        )  # (b, A, vocab)
        tokens = jnp.argmax(step_logits, axis=-1).astype(jnp.int32)  # (b, A)

        act_state = jax.lax.dynamic_update_slice_in_dim(
            act_state, tokens[:, None, :], time_step, axis=1
        )
        new_state = {
            "context_image_tokens": img_state,
            "action_tokens": act_state,
            "seq_idx": jnp.minimum(seq_idx + 1, self.time_sequence_length),
        }
        output = {"action_tokens": tokens, "action_logits": step_logits}
        output.update(self._decode_action(tokens, step_logits))
        return output, new_state

    def infer_step_cached(
        self, observation: Dict[str, jnp.ndarray], state: Dict[str, jnp.ndarray]
    ) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
        """One control step against the per-session K/V cache: tokenize the
        incoming frame, run the transformer over ONLY its
        `single_step_tokens` new positions, attend them against the cached
        prefix, and roll the cache in place.

        Same observation/state/output contract as `infer_step`, plus a
        `kv_cache` state leaf (`initial_state(..., cached=True)`). While the
        window is filling the cached prefix is position-exact, so the step
        logits equal the full-window pass to float tolerance (pinned in
        tests/test_rt1_cache.py). Once the window is full, each step shifts
        the cache down by `single_step_tokens` (the ISSUE's shift layout):
        surviving entries keep the K/V they were computed with — their
        learned absolute position rows and their insertion-time context go
        stale by one frame per roll — while the new frame's queries stay
        position-exact. That staleness is the cached path's only deviation
        from `infer_step`; `serve/parity.check_cached_parity` gates it at
        the same ≥0.99 action-token-agreement contract as the quant gate,
        and `PolicyEngine` bounds it by rebuilding caches (`rebuild_cache`)
        on every invalidation event.
        """
        seq_idx = state["seq_idx"]
        t_max = self.time_sequence_length
        step = self.single_step_tokens
        time_step = jnp.minimum(seq_idx, t_max - 1)

        img_state = state["context_image_tokens"]
        act_state = state["action_tokens"]
        kv = state["kv_cache"]
        full = seq_idx == t_max
        img_state = jnp.where(full, jnp.roll(img_state, -1, axis=1), img_state)
        act_state = jnp.where(full, jnp.roll(act_state, -1, axis=1), act_state)
        kv = jnp.where(full, jnp.roll(kv, -step, axis=3), kv)

        image = observation["image"][:, None]  # (b, 1, H, W, 3)
        context = observation.get("natural_language_embedding")
        new_tokens = self._tokenize_images(image, context, train=False)  # (b, 1, I, E)
        img_state = jax.lax.dynamic_update_slice_in_dim(
            img_state, new_tokens.astype(img_state.dtype), time_step, axis=1
        )

        # The new frame's step block: image tokens + zeroed action slots,
        # exactly one row of `_assemble`'s layout (f32 like the stored
        # window so the transformer's input cast matches the full pass).
        frame = new_tokens[:, 0].astype(img_state.dtype)  # (b, I, E)
        b = frame.shape[0]
        step_inputs = jnp.concatenate(
            [frame, jnp.zeros((b, self.tokens_per_action, frame.shape[-1]), frame.dtype)],
            axis=1,
        )  # (b, I+A, E)
        q_start = time_step * step
        # Decode mask = this step block's rows of the full (S, S) RT-1 mask;
        # causal zeros past q_start+len already exclude the unwritten tail
        # of a filling cache.
        dec_mask = jax.lax.dynamic_slice_in_dim(
            jnp.asarray(self._mask), q_start, step, axis=0
        )  # (I+A, S)
        logits, new_kv = self.transformer(
            step_inputs,
            attention_mask=dec_mask,
            train=False,
            kv_cache=kv,
            cache_index=q_start,
        )  # (b, I+A, vocab)

        # Within the block, action logits sit one position early
        # (the shift-by-one read, same as infer_step's `start`).
        i0 = self.tokens_per_image - 1
        step_logits = jax.lax.slice_in_dim(
            logits, i0, i0 + self.tokens_per_action, axis=1
        )  # (b, A, vocab)
        tokens = jnp.argmax(step_logits, axis=-1).astype(jnp.int32)

        act_state = jax.lax.dynamic_update_slice_in_dim(
            act_state, tokens[:, None, :], time_step, axis=1
        )
        new_state = {
            "context_image_tokens": img_state,
            "action_tokens": act_state,
            "seq_idx": jnp.minimum(seq_idx + 1, self.time_sequence_length),
            "kv_cache": new_kv,
        }
        output = {"action_tokens": tokens, "action_logits": step_logits}
        output.update(self._decode_action(tokens, step_logits))
        return output, new_state

    def rebuild_cache(self, state: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
        """Recompute every K/V cache row from the stored per-frame image
        tokens — one full-window transformer pass, identical math to
        `infer_step`'s `_transformer_logits`.

        This is the cache invalidation primitive: after a params hot-swap
        (or any event that makes cached K/V stale relative to the window's
        image tokens) the serving engine runs this once per slot instead of
        serving poisoned caches. The rebuilt rows are position-exact AND
        context-exact for the current window, so the next cached step
        matches the full-window pass bit-for-bit-close again.
        """
        seq = self._assemble(state["context_image_tokens"])  # (b, S, E)
        mask = jnp.asarray(self._mask)  # (S, S)
        _, new_kv = self.transformer(
            seq,
            attention_mask=mask,
            train=False,
            kv_cache=jnp.zeros_like(state["kv_cache"]),
            cache_index=jnp.zeros((), jnp.int32),
        )
        return dict(state, kv_cache=new_kv)

    def _decode_action(self, tokens, step_logits):
        """Token→action decode shared by both inference paths
        (`action_decode`: hard argmax detokenize vs soft E[a])."""
        if self.action_decode == "expected":
            return action_tokenizer.detokenize_expected(
                self.action_space, step_logits, self.vocab_size
            )
        return action_tokenizer.detokenize(
            self.action_space, tokens, self.vocab_size
        )

    def infer_step_autoregressive(
        self, observation: Dict[str, jnp.ndarray], state: Dict[str, jnp.ndarray]
    ) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
        """Literal port of the reference's token-by-token loop (`:246-268`): A full
        transformer passes, argmaxing one position each. Exists to prove equivalence
        with `infer_step` (action inputs are zeroed, so the passes are identical) and
        for benchmark comparison; not used in production."""
        img_state, act_state, time_step, seq_idx = self._advance_window(observation, state)

        start = time_step * self.single_step_tokens + self.tokens_per_image - 1
        toks = []
        logit_slices = []
        for k in range(self.tokens_per_action):
            logits, _ = self._transformer_logits(img_state, train=False)
            sl = jax.lax.dynamic_slice_in_dim(logits, start + k, 1, axis=1)  # (b, 1, V)
            tok = jnp.argmax(sl, axis=-1).astype(jnp.int32)  # (b, 1)
            toks.append(tok)
            logit_slices.append(sl)
            # The reference writes the predicted token back into action_tokens
            # (:261-268); it cannot affect later passes (inputs zeroed) but we
            # mirror the state update.
            act_state = jax.lax.dynamic_update_slice(
                act_state, tok[:, None, :], (0, time_step, k)
            )
        tokens = jnp.concatenate(toks, axis=1)
        step_logits = jnp.concatenate(logit_slices, axis=1)

        new_state = {
            "context_image_tokens": img_state,
            "action_tokens": act_state,
            "seq_idx": jnp.minimum(seq_idx + 1, self.time_sequence_length),
        }
        output = {"action_tokens": tokens, "action_logits": step_logits}
        output.update(self._decode_action(tokens, step_logits))
        return output, new_state


def _softmax_ce_int(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Cross-entropy with integer labels (optax-equivalent, kept dependency-light)."""
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return logz - label_logits
