"""Tiny image tokenizer: a drop-in EfficientNet-B3 replacement.

Used by smoke configs (`rt1_tpu/train/configs/tiny.py`) and tests to drive
the full RT-1 policy/trainer/eval stack in seconds on one CPU core. A conv
stem pools the frame and projects (with optional language context) straight
to `num_tokens` embedding tokens.
"""

import flax.linen as nn
import jax.numpy as jnp

from rt1_tpu.models.quant import QuantConv, QuantDense


class TinyImageTokenizer(nn.Module):
    num_tokens: int = 2
    emb: int = 16
    # Compute dtype, threaded from config.model.dtype like the B3 tower's —
    # the bf16 serving mode needs the tiny tokenizer to honor it so tier-1
    # can pin bf16-restore ≡ bf16-compute on the smoke config.
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, image, context=None, train=False):
        b, t, h, w, c = image.shape
        x = image.reshape(b * t, h, w, c)
        # Quant layers == stock flax until an int8 serving tree arrives
        # (models/quant.py) — keeps the tiny config exercising the same
        # quantized-serving path as the flagship in tier-1.
        x = QuantConv(8, (3, 3), strides=(2, 2), dtype=self.dtype, name="conv")(x)
        x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))  # (b*t, 8)
        if context is not None:
            ctx = context.reshape(b * t, -1)
            x = jnp.concatenate(
                [x, QuantDense(8, dtype=self.dtype, name="ctx_proj")(ctx)], axis=-1
            )
        tokens = QuantDense(self.num_tokens * self.emb, dtype=self.dtype, name="tok")(x)
        return tokens.reshape(b, t, self.num_tokens, self.emb)
