"""Tiny image tokenizer: a drop-in EfficientNet-B3 replacement.

Used by smoke configs (`rt1_tpu/train/configs/tiny.py`) and tests to drive
the full RT-1 policy/trainer/eval stack in seconds on one CPU core. A conv
stem pools the frame and projects (with optional language context) straight
to `num_tokens` embedding tokens.
"""

import flax.linen as nn
import jax.numpy as jnp


class TinyImageTokenizer(nn.Module):
    num_tokens: int = 2
    emb: int = 16

    @nn.compact
    def __call__(self, image, context=None, train=False):
        b, t, h, w, c = image.shape
        x = image.reshape(b * t, h, w, c)
        x = nn.Conv(8, (3, 3), strides=(2, 2), name="conv")(x)
        x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))  # (b*t, 8)
        if context is not None:
            ctx = context.reshape(b * t, -1)
            x = jnp.concatenate(
                [x, nn.Dense(8, name="ctx_proj")(ctx)], axis=-1
            )
        tokens = nn.Dense(self.num_tokens * self.emb, name="tok")(x)
        return tokens.reshape(b, t, self.num_tokens, self.emb)
