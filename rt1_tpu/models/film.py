"""FiLM conditioning layer (Perez et al. 2018).

Re-design of `pytorch_robotics_transformer/film_efficientnet/film_conditioning_layer.py:23-50`:
two zero-initialized projections of the conditioning vector produce per-channel
(γ, β); output is `(1 + γ) · F + β`. Zero init keeps a pretrained backbone's function
unchanged at initialization (reference comment at `:29-34`).

NHWC: features are (..., H, W, C); conditioning is (..., D) with matching leading dims.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from rt1_tpu.models.quant import QuantDense


class FilmConditioning(nn.Module):
    num_channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, conv_filters: jnp.ndarray, conditioning: jnp.ndarray) -> jnp.ndarray:
        # QuantDense == nn.Dense until an int8 serving tree arrives; the
        # zero-init projections round-trip exactly (quantize_per_channel
        # maps an all-zero channel to scale 1.0).
        proj_add = QuantDense(
            self.num_channels,
            kernel_init=nn.initializers.zeros,
            bias_init=nn.initializers.zeros,
            dtype=self.dtype,
            name="projection_add",
        )(conditioning)
        proj_mult = QuantDense(
            self.num_channels,
            kernel_init=nn.initializers.zeros,
            bias_init=nn.initializers.zeros,
            dtype=self.dtype,
            name="projection_mult",
        )(conditioning)
        # Broadcast (B, C) → (B, 1, 1, C) over spatial dims (NHWC).
        proj_add = proj_add[..., None, None, :]
        proj_mult = proj_mult[..., None, None, :]
        return (1.0 + proj_mult) * conv_filters + proj_add
