"""Mixture-of-Experts feed-forward with expert parallelism.

Beyond reference parity (SURVEY.md §2.6: "Expert parallelism (EP/MoE): No").
The reference's block FFN is a single square Dense (`transformer.py:126,140`);
this module is the opt-in MoE replacement: Switch-style top-1 routing
(Fedus et al. 2021) with a fixed per-expert capacity so every shape is static
under jit.

TPU-first formulation — dense dispatch, no gather/scatter:

  gates    = softmax(x @ w_gate)                  (tokens, E)
  dispatch = one_hot(top1) · within-capacity mask  (tokens, E, C)
  buffers  = einsum('te c, td -> e c d')           (E, C, d)  ← all-to-all
  expert   = gelu(buffers @ wi) @ wo               batched over E on the MXU
  out      = einsum('tec, ecd -> td')              combine, gate-weighted

Expert parallelism is pure sharding: the stacked expert weights (E, d, ff)
are partitioned over the mesh's ``model`` axis (rt1_tpu/parallel/sharding.py
`moe_parameter_rules`), and GSPMD lowers the dispatch/combine einsums to
all-to-alls over ICI. With a size-1 axis everything runs locally — same
program, no collectives. 8-device ≡ 1-device parity is pinned by
tests/test_moe.py.

Dropped-token semantics: tokens over an expert's capacity fall through the
residual connection untouched (combine weight 0) — standard Switch behavior.
An auxiliary load-balancing loss (`aux_loss`, Switch eq. 4) is returned for
the trainer to add.
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoEFeedForward(nn.Module):
    """Top-1 routed expert FFN: d_model → ff_dim (gelu) → d_model."""

    d_model: int
    num_experts: int = 4
    ff_dim: Optional[int] = None           # default: d_model (reference shape)
    capacity_factor: float = 2.0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """x: (b, s, d) → (out (b, s, d), aux_loss scalar)."""
        b, s, d = x.shape
        e = self.num_experts
        ff = self.ff_dim or self.d_model
        t = b * s
        # Router in fp32: tiny, and routing decisions shouldn't flip under
        # bf16 rounding between two near-equal gate logits.
        tokens = x.reshape(t, d)
        gate_logits = nn.Dense(
            e, use_bias=False, dtype=jnp.float32, name="gate"
        )(tokens.astype(jnp.float32))
        gates = jax.nn.softmax(gate_logits, axis=-1)          # (t, e)
        expert_idx = jnp.argmax(gates, axis=-1)               # (t,)
        expert_gate = jnp.max(gates, axis=-1)                 # (t,)

        # Switch aux loss: E * Σ_e (fraction routed to e) · (mean gate to e).
        one_hot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # (t, e)
        density = one_hot.mean(axis=0)
        density_proxy = gates.mean(axis=0)
        aux_loss = (density * density_proxy).sum() * e

        # Position of each token within its expert's queue. NOTE: `t` is the
        # *call's* token count — under data parallelism this is the global
        # batch, so per-device expert buffers (E, C, d) grow with DP width
        # (they are sharded over 'model', not 'data'). For very large global
        # batches, lower capacity_factor or wrap the MoE in a shard_map over
        # 'data' so capacity binds per data shard.
        capacity = int(self.capacity_factor * t / e) or 1
        position_in_expert = (jnp.cumsum(one_hot, axis=0) - 1.0) * one_hot
        pos_one_hot = jax.nn.one_hot(   # (t, c); out-of-range (≥ capacity)
            position_in_expert.sum(axis=-1), capacity, dtype=jnp.float32
        )                               # rows are all-zero → token dropped
        dispatch = one_hot[:, :, None] * pos_one_hot[:, None, :]  # (t, e, c)

        # batch_axis=0: the leading expert axis is independent replicas, not
        # a receptive-field dim — plain lecun_normal would count fan_in as
        # E·d and under-scale every expert by ~sqrt(E) (Switch init recipe).
        expert_init = nn.initializers.variance_scaling(
            1.0, "fan_in", "truncated_normal", batch_axis=(0,)
        )
        # maybe_dequantize: the int8 serving path (models/quant.py) — a
        # no-op on f32/bf16 trees; the fp32 router above is never
        # quantized (parallel/plan.py rt1_quant_rules).
        from rt1_tpu.models.quant import maybe_dequantize

        wi = maybe_dequantize(
            self, self.param("wi", expert_init, (e, d, ff), jnp.float32),
            "wi_scale",
        ).astype(self.dtype)
        wo = maybe_dequantize(
            self, self.param("wo", expert_init, (e, ff, d), jnp.float32),
            "wo_scale",
        ).astype(self.dtype)

        dispatch = dispatch.astype(self.dtype)
        buffers = jnp.einsum("tec,td->ecd", dispatch, tokens.astype(self.dtype))
        h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", buffers, wi))
        expert_out = jnp.einsum("ecf,efd->ecd", h, wo)         # (e, c, d)

        combine = dispatch * expert_gate.astype(self.dtype)[:, None, None]
        out = jnp.einsum("tec,ecd->td", combine, expert_out)
        return out.reshape(b, s, d), aux_loss.astype(jnp.float32)
