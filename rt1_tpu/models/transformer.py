"""Causal decoder-only transformer for RT-1.

Re-design of `pytorch_robotics_transformer/transformer.py`. Architectural parity
(verified by tests/test_transformer.py):

* token embedding: Dense(input_emb → d_model) (`transformer.py:171,181`);
* learned positional embedding over `max_seq_len=256` positions (`:172,183-186`);
* N pre-norm blocks (`_TransformerLayer:112-144`): LN → TF-Keras-style MHA where the
  per-head width `key_dim` is decoupled from `d_model` (`TF_MultiHeadAttention:29-79`)
  → residual; LN → a *single* Dense(d_model → d_model) with NO activation (a quirk of
  the reference, `:126,140-141` — kept for parity) → dropout → residual;
* output head: Dense(d_model → vocab) (`:173,197`).

Naming note carried over from the reference: `layer_size` is the per-head attention
width (key_dim) and `feed_forward_size` is d_model (`transformer.py:115-117`).

TPU-first details: attention is two einsums (MXU-shaped), the additive mask is
prepared once outside jit, softmax in fp32 even under bf16 compute, and dropout on
attention probabilities matches the reference's placement (`transformer.py:94-98`).

Incremental decode (docs/serving.md "Incremental inference"): every module
below also accepts ``kv_cache``/``cache_index`` kwargs. With a cache, the
input carries only the NEW sequence positions; each attention layer projects
their q/k/v, writes the new k/v into the cache at ``cache_index``, and
attends the new queries against the full cached key/value prefix under a
``(new_len, cache_len)`` mask. Position embeddings are looked up at the
absolute positions ``cache_index + arange(new_len)``, so a cached step is
numerically the same computation the full pass would do for those rows.
The cache pytree is a single ``(b, layers, 2, cache_len, heads, key_dim)``
array (k at index 0, v at index 1 of axis 2) so it can ride a serving
engine's donated state chain as one leaf. The default (``kv_cache=None``)
path is untouched — byte-identical to the pre-cache program.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp

from rt1_tpu.models.quant import QuantDense

NEG_INF = -1e9


class TFMultiHeadAttention(nn.Module):
    """tf.keras-style MHA: qkv project d_model → heads·key_dim, out back to d_model.

    `attention_impl="ring"` + a mesh with a >1 ``seq`` axis computes the same
    attention ring-parallel over sequence shards (rt1_tpu/parallel/
    ring_attention.py) — exact, but attention probabilities are never
    materialized, so prob-dropout is skipped and no scores are returned.
    """

    num_heads: int
    key_dim: int
    d_model: int
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.float32
    # "dense" | "ring" | "pallas". "ring" needs `mesh` with a >1 seq axis;
    # "pallas" is the fused inference kernel — used only when train=False on
    # a TPU backend (gradients and non-TPU backends fall back to dense).
    attention_impl: str = "dense"
    mesh: Optional[Any] = None
    # Test escape hatch: run the pallas kernel in interpreter mode off-TPU
    # (orders of magnitude slower than dense; never set in production).
    pallas_interpret: bool = False

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
        train: bool = False,
        kv_cache: Optional[jnp.ndarray] = None,
        cache_index: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
        b, s, _ = x.shape
        h, k = self.num_heads, self.key_dim
        # QuantDense == nn.Dense until an int8 serving tree arrives
        # (models/quant.py); qkv/out/ff are the int8 group in the quant
        # plan (parallel/plan.py rt1_quant_rules).
        q = QuantDense(h * k, dtype=self.dtype, name="query")(x).reshape(b, s, h, k)
        kk = QuantDense(h * k, dtype=self.dtype, name="key")(x).reshape(b, s, h, k)
        v = QuantDense(h * k, dtype=self.dtype, name="value")(x).reshape(b, s, h, k)

        import jax as _jax

        if kv_cache is not None:
            # Incremental decode: x holds only the NEW positions; write
            # their k/v into the cache at cache_index and attend the new
            # queries against the whole cached prefix. `mask` must be
            # (new_len, cache_len). Same dense einsum/fp32-softmax math as
            # the full pass (no prob dropout: decode is inference-only), so
            # while the cache holds position-correct entries the outputs
            # match the full pass row-for-row. Returns the updated
            # (b, 2, cache_len, h, k) cache in place of the scores.
            k_cache = _jax.lax.dynamic_update_slice_in_dim(
                kv_cache[:, 0], kk, cache_index, axis=1
            )
            v_cache = _jax.lax.dynamic_update_slice_in_dim(
                kv_cache[:, 1], v, cache_index, axis=1
            )
            logits = jnp.einsum(
                "bqhd,bshd->bhqs", q, k_cache,
                preferred_element_type=jnp.float32,
            )
            logits = logits / jnp.sqrt(jnp.asarray(k, jnp.float32))
            if mask is not None:
                logits = jnp.where(mask[None, None].astype(bool), logits, NEG_INF)
            probs = nn.softmax(logits.astype(jnp.float32), axis=-1)
            out = jnp.einsum(
                "bhqs,bshd->bqhd", probs.astype(self.dtype), v_cache
            )
            out = out.reshape(b, s, h * k)
            new_cache = jnp.stack([k_cache, v_cache], axis=1)
            return QuantDense(self.d_model, dtype=self.dtype, name="out")(out), new_cache

        use_pallas = (
            self.attention_impl == "pallas"
            and not train  # forward-only kernel: no autodiff rule
            and (
                _jax.default_backend() == "tpu" or self.pallas_interpret
            )
        )
        if use_pallas:
            # Fused VMEM kernel (rt1_tpu/parallel/flash_attention.py).
            from rt1_tpu.parallel.flash_attention import fused_attention

            if mask is not None and mask.ndim != 2:
                raise ValueError("pallas attention supports (s, s) masks only")
            out = fused_attention(
                q,
                kk,
                v,
                mask=mask,
                scale=1.0 / float(k) ** 0.5,
                interpret=_jax.default_backend() != "tpu",
            )
            out = out.reshape(b, s, h * k)
            return QuantDense(self.d_model, dtype=self.dtype, name="out")(out), None

        use_ring = (
            self.attention_impl == "ring"
            and self.mesh is not None
            and self.mesh.shape.get("seq", 1) > 1
        )
        if use_ring:
            from rt1_tpu.parallel.ring_attention import ring_attention

            if mask is not None and mask.ndim != 2:
                raise ValueError("ring attention supports (s, s) masks only")
            out = ring_attention(
                q,
                kk,
                v,
                self.mesh,
                mask=mask,
                scale=1.0 / float(k) ** 0.5,
            )
            out = out.reshape(b, s, h * k)
            return QuantDense(self.d_model, dtype=self.dtype, name="out")(out), None

        # (b, h, sq, sk) attention logits; fp32 softmax for stability under bf16.
        logits = jnp.einsum("bshd,bthd->bhst", q, kk, preferred_element_type=jnp.float32)
        logits = logits / jnp.sqrt(jnp.asarray(k, jnp.float32))
        if mask is not None:
            # mask: (s, s) or (b, s, s); nonzero = attend, 0 = blocked (reference :89-92).
            if mask.ndim == 2:
                mask = mask[None, None]
            elif mask.ndim == 3:
                mask = mask[:, None]  # add head axis
            logits = jnp.where(mask.astype(bool), logits, NEG_INF)
        probs = nn.softmax(logits.astype(jnp.float32), axis=-1)
        probs = nn.Dropout(self.dropout_rate, deterministic=not train)(probs)
        out = jnp.einsum("bhst,bthd->bshd", probs.astype(self.dtype), v)
        out = out.reshape(b, s, h * k)
        return QuantDense(self.d_model, dtype=self.dtype, name="out")(out), probs


class TransformerLayer(nn.Module):
    """Pre-norm block: x + MHA(LN(x)); x + Dropout(FFN(LN(x))) (reference :130-144).

    ``ffn_impl="dense"`` is the reference-parity single square Dense;
    ``ffn_impl="moe"`` swaps in the Switch-routed expert FFN
    (rt1_tpu/models/moe.py) — its load-balancing aux loss is sown into the
    "intermediates" collection under "moe_aux_loss".
    """

    key_dim: int
    num_heads: int
    d_model: int
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.float32
    attention_impl: str = "dense"
    mesh: Optional[Any] = None
    pallas_interpret: bool = False
    ffn_impl: str = "dense"          # "dense" | "moe"
    num_experts: int = 4
    moe_capacity_factor: float = 2.0
    moe_ff_dim: Optional[int] = None  # expert hidden width; None → d_model

    @nn.compact
    def __call__(
        self, x, mask=None, train: bool = False, kv_cache=None, cache_index=None
    ):
        y = nn.LayerNorm(dtype=self.dtype, name="norm_1")(x)
        # In decode mode (kv_cache given) the second element is the layer's
        # updated (b, 2, cache_len, h, k) cache instead of attention scores.
        attn_out, scores = TFMultiHeadAttention(
            num_heads=self.num_heads,
            key_dim=self.key_dim,
            d_model=self.d_model,
            dropout_rate=self.dropout_rate,
            dtype=self.dtype,
            attention_impl=self.attention_impl,
            mesh=self.mesh,
            pallas_interpret=self.pallas_interpret,
            name="attn",
        )(y, mask=mask, train=train, kv_cache=kv_cache, cache_index=cache_index)
        x = x + attn_out
        y = nn.LayerNorm(dtype=self.dtype, name="norm_2")(x)
        if self.ffn_impl == "moe":
            from rt1_tpu.models.moe import MoEFeedForward

            y, aux = MoEFeedForward(
                d_model=self.d_model,
                num_experts=self.num_experts,
                ff_dim=self.moe_ff_dim,
                capacity_factor=self.moe_capacity_factor,
                dtype=self.dtype,
                name="moe",
            )(y)
            self.sow("intermediates", "moe_aux_loss", aux)
        else:
            y = QuantDense(self.d_model, dtype=self.dtype, name="ff")(y)
        y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        return x + y, scores


class CausalTransformer(nn.Module):
    """Token-in, vocab-logits-out decoder (reference `Transformer:146-198`)."""

    num_layers: int = 8
    key_dim: int = 128          # "layer_size" in the reference
    num_heads: int = 8
    d_model: int = 512          # "feed_forward_size" in the reference
    dropout_rate: float = 0.1
    vocab_size: int = 256
    max_seq_len: int = 256
    return_attention_scores: bool = False
    dtype: jnp.dtype = jnp.float32
    attention_impl: str = "dense"
    mesh: Optional[Any] = None
    pallas_interpret: bool = False
    ffn_impl: str = "dense"          # "dense" | "moe" (expert-parallel FFN)
    num_experts: int = 4
    moe_capacity_factor: float = 2.0
    moe_ff_dim: Optional[int] = None
    # jax.checkpoint each block: recompute activations in the backward pass
    # instead of storing them (O(layers)→O(1) activation memory, ~1/3 extra
    # FLOPs). Semantics-preserving; exactness pinned in tests/test_rt1.py.
    remat: bool = False

    @nn.compact
    def __call__(
        self,
        inputs: jnp.ndarray,
        attention_mask=None,
        train: bool = False,
        kv_cache=None,
        cache_index=None,
    ):
        """inputs: (b, s, input_emb) → logits (b, s, vocab_size).

        With ``kv_cache`` (b, num_layers, 2, cache_len, heads, key_dim) and
        a ``cache_index`` start position, `inputs` carries only the NEW
        positions: they are embedded at absolute positions
        ``cache_index + arange(s)``, each layer attends them against its
        cached prefix under the (s, cache_len) ``attention_mask``, and the
        call returns ``(logits, updated_kv_cache)``. Passing the full
        sequence with ``cache_index=0`` and the full square mask recomputes
        every cache row from scratch (the serving engine's invalidation
        rebuild) — identical math to the cache-free pass.
        """
        b, s, _ = inputs.shape
        if s > self.max_seq_len:
            raise ValueError(
                f"sequence length {s} exceeds max_seq_len={self.max_seq_len}"
            )
        if kv_cache is not None:
            x = nn.Dense(self.d_model, dtype=self.dtype, name="token_emb")(inputs)
            positions = cache_index + jnp.arange(s)
            pos_emb = nn.Embed(
                self.max_seq_len, self.d_model, dtype=self.dtype,
                name="position_emb",
            )(positions)
            x = x + pos_emb[None, :, :]
            new_caches = []
            for i in range(self.num_layers):
                x, layer_cache = TransformerLayer(
                    key_dim=self.key_dim,
                    num_heads=self.num_heads,
                    d_model=self.d_model,
                    dropout_rate=self.dropout_rate,
                    dtype=self.dtype,
                    # Decode always uses the dense einsum math: the
                    # ring/pallas kernels are full-sequence (square-mask)
                    # implementations and decode's prefix attention is a
                    # (s × cache_len) sliver that doesn't need them.
                    attention_impl="dense",
                    ffn_impl=self.ffn_impl,
                    num_experts=self.num_experts,
                    moe_capacity_factor=self.moe_capacity_factor,
                    moe_ff_dim=self.moe_ff_dim,
                    name=f"layer_{i}",
                )(x, attention_mask, False, kv_cache[:, i], cache_index)
                new_caches.append(layer_cache)
            logits = nn.Dense(
                self.vocab_size, dtype=self.dtype, name="output_tokens"
            )(x)
            return logits, jnp.stack(new_caches, axis=1)
        if self.return_attention_scores and self.attention_impl in (
            "ring",
            "pallas",
        ):
            raise ValueError(
                "attention scores are not materialized under ring/pallas "
                "attention; use attention_impl='dense' for score "
                "visualization"
            )
        x = nn.Dense(self.d_model, dtype=self.dtype, name="token_emb")(inputs)
        pos_emb = nn.Embed(self.max_seq_len, self.d_model, dtype=self.dtype, name="position_emb")(
            jnp.arange(s)
        )
        x = x + pos_emb[None, :, :]
        scores = []
        # static_argnums counts `self` as 0: (self, x, mask, train) → train=3.
        layer_cls = (
            nn.remat(TransformerLayer, static_argnums=(3,))
            if self.remat
            else TransformerLayer
        )
        for i in range(self.num_layers):
            x, sc = layer_cls(
                key_dim=self.key_dim,
                num_heads=self.num_heads,
                d_model=self.d_model,
                dropout_rate=self.dropout_rate,
                dtype=self.dtype,
                attention_impl=self.attention_impl,
                mesh=self.mesh,
                pallas_interpret=self.pallas_interpret,
                ffn_impl=self.ffn_impl,
                num_experts=self.num_experts,
                moe_capacity_factor=self.moe_capacity_factor,
                moe_ff_dim=self.moe_ff_dim,
                name=f"layer_{i}",
            )(x, attention_mask, train)
            if self.return_attention_scores:
                scores.append(sc)
        logits = nn.Dense(self.vocab_size, dtype=self.dtype, name="output_tokens")(x)
        if self.return_attention_scores:
            return logits, scores
        return logits
