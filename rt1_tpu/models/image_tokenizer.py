"""RT-1 contextual image tokenizer.

Re-design of `pytorch_robotics_transformer/tokenizers/image_tokenizer.py:31-85`
(`RT1ImageTokenizer`): fold time into batch, run the FiLM-EfficientNet encoder to a
spatial feature map, then either TokenLearner → `num_tokens` tokens per frame or
flatten the spatial map (h·w tokens, 100 at the B3-native 300×300 input;
`tokens_per_context_image` at `:44-50`).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from rt1_tpu.models.encoder import EfficientNetEncoder
from rt1_tpu.models.token_learner import TokenLearner


def tokens_per_context_image(
    use_token_learner: bool, num_tokens: int, feature_hw: int = 100
) -> int:
    """Static token count per frame (image_tokenizer.py:44-50)."""
    return num_tokens if use_token_learner else feature_hw


class RT1ImageTokenizer(nn.Module):
    embedding_output_dim: int = 512
    use_token_learner: bool = True
    num_tokens: int = 8
    dtype: jnp.dtype = jnp.float32
    width_coefficient: float = 1.2   # B3 default
    depth_coefficient: float = 1.4
    remat: bool = False  # jax.checkpoint the conv trunk (see EfficientNet)

    @nn.compact
    def __call__(
        self,
        image: jnp.ndarray,
        context: Optional[jnp.ndarray] = None,
        train: bool = False,
    ) -> jnp.ndarray:
        """image: (B, T, H, W, 3); context: (B, T, D) (constant along T).

        Returns (B, T, num_tokens_per_frame, embedding_output_dim).
        """
        b, t, h, w, c = image.shape
        image = image.reshape(b * t, h, w, c)
        if context is not None:
            context = context.reshape(b * t, -1)
        feats = EfficientNetEncoder(
            token_embedding_size=self.embedding_output_dim,
            early_film=True,
            pooling=False,
            dtype=self.dtype,
            width_coefficient=self.width_coefficient,
            depth_coefficient=self.depth_coefficient,
            remat=self.remat,
            name="encoder",
        )(image, context=context, train=train)  # (B*T, h', w', E)
        if self.use_token_learner:
            tokens = TokenLearner(
                num_tokens=self.num_tokens, dtype=self.dtype, name="token_learner"
            )(feats, train=train)  # (B*T, num_tokens, E)
            return tokens.reshape(b, t, self.num_tokens, self.embedding_output_dim)
        fh, fw = feats.shape[1], feats.shape[2]
        return feats.reshape(b, t, fh * fw, self.embedding_output_dim)
