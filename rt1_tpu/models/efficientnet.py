"""FiLM-conditioned EfficientNet in Flax, NHWC, bfloat16-friendly.

Re-design of `pytorch_robotics_transformer/film_efficientnet/film_efficientnet_encoder.py`
(EfficientNet `:246-373`, MBConvBlock `:164-244`, SeModule `:142-161`,
round_filters/round_repeats `:123-140`, B3 scaling `:429-442`). Architecture parity:

* stem: 3×3 stride-2 conv → BN → SiLU (`:271-279`);
* 7 stages of MBConv (expand 1×1 → depthwise k×k → SE(0.25 of *block input*) →
  project 1×1, no activation on the projection), stochastic depth rate increasing
  linearly over blocks (`:297-318`), identity skip when stride 1 and in==out;
* optional FiLM layer after **every** MBConv block when `include_film` (`:314-317`),
  zero-initialized so the unconditioned function is preserved;
* top: 1×1 conv → BN → SiLU to round_filters(1280) (`:326-336`); optional
  global-pool + dropout + classifier head (`:339-344`).

B3 = width 1.2 / depth 1.4 / dropout 0.3 → stem 40ch, 26 blocks, top 1536ch.

TPU-first deltas from the reference (behavior-preserving):
* NHWC layout throughout (XLA TPU native; reference is NCHW);
* depthwise convs expressed with `feature_group_count` so XLA lowers them to the
  native TPU depthwise path;
* a `dtype` knob runs all conv/matmul compute in bfloat16 with fp32 params & BN
  statistics (MXU-friendly);
* BatchNorm under SPMD: flax BN computes batch stats with plain `jnp.mean` — when
  the batch axis is sharded over the mesh, XLA inserts the cross-device reduction
  automatically, so global-batch statistics come for free (the reference's pmap
  stack needed explicit cross-replica merging, `language_table/train/train.py:258-266`).
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from rt1_tpu.models.film import FilmConditioning
from rt1_tpu.models.quant import QuantConv

# Table-1 base (B0) config; film_efficientnet_encoder.py:36-99.
BLOCKS_ARGS: Tuple[Dict[str, Any], ...] = (
    dict(kernel_size=3, repeats=1, in_size=32, out_size=16, expand_ratio=1, strides=1, se_ratio=0.25),
    dict(kernel_size=3, repeats=2, in_size=16, out_size=24, expand_ratio=6, strides=2, se_ratio=0.25),
    dict(kernel_size=5, repeats=2, in_size=24, out_size=40, expand_ratio=6, strides=2, se_ratio=0.25),
    dict(kernel_size=3, repeats=3, in_size=40, out_size=80, expand_ratio=6, strides=2, se_ratio=0.25),
    dict(kernel_size=5, repeats=3, in_size=80, out_size=112, expand_ratio=6, strides=1, se_ratio=0.25),
    dict(kernel_size=5, repeats=4, in_size=112, out_size=192, expand_ratio=6, strides=2, se_ratio=0.25),
    dict(kernel_size=3, repeats=1, in_size=192, out_size=320, expand_ratio=6, strides=1, se_ratio=0.25),
)


def round_filters(filters: float, divisor: int, width_coefficient: float) -> int:
    """Width scaling with snap-to-multiple-of-divisor (reference `:123-135`)."""
    filters *= width_coefficient
    new_filters = max(divisor, int(filters + divisor / 2) // divisor * divisor)
    if new_filters < 0.9 * filters:
        new_filters += divisor
    return int(new_filters)


def round_repeats(repeats: int, depth_coefficient: float) -> int:
    return int(math.ceil(depth_coefficient * repeats))


def stochastic_depth(x: jnp.ndarray, rate: float, deterministic: bool, rng) -> jnp.ndarray:
    """Row-mode stochastic depth (torchvision `StochasticDepth(p, "row")` parity)."""
    if deterministic or rate == 0.0:
        return x
    keep = 1.0 - rate
    mask_shape = (x.shape[0],) + (1,) * (x.ndim - 1)
    mask = jax.random.bernoulli(rng, keep, mask_shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


class ConvNormAct(nn.Module):
    """Conv → BatchNorm → optional SiLU (torchvision `Conv2dNormActivation` parity)."""

    features: int
    kernel_size: int
    strides: int = 1
    groups: int = 1
    use_act: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool) -> jnp.ndarray:
        pad = (self.kernel_size - 1) // 2
        # QuantConv == nn.Conv until an int8 serving tree arrives
        # (models/quant.py; conv kernels are the int8 group in
        # parallel/plan.py rt1_quant_rules — BN stays full precision).
        x = QuantConv(
            self.features,
            (self.kernel_size, self.kernel_size),
            strides=(self.strides, self.strides),
            padding=[(pad, pad), (pad, pad)],
            feature_group_count=self.groups,
            use_bias=False,
            dtype=self.dtype,
            name="conv",
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            name="bn",
        )(x)
        if self.use_act:
            x = nn.silu(x)
        return x


class SqueezeExcite(nn.Module):
    """SE with reduction computed from the *block input* width (reference `:142-161`)."""

    expand_size: int
    block_in_size: int
    se_ratio: float = 0.25
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        se_size = max(1, int(self.block_in_size * self.se_ratio))
        s = jnp.mean(x, axis=(-3, -2), keepdims=True)
        s = QuantConv(se_size, (1, 1), use_bias=True, dtype=self.dtype, name="fc1")(s)
        s = nn.silu(s)
        s = QuantConv(self.expand_size, (1, 1), use_bias=True, dtype=self.dtype, name="fc2")(s)
        s = nn.sigmoid(s)
        return x * s


class MBConvBlock(nn.Module):
    """Inverted residual block with SE and stochastic depth (reference `:164-244`)."""

    kernel_size: int
    in_size: int
    out_size: int
    expand_ratio: int
    strides: int
    se_ratio: float
    drop_rate: float
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jnp.ndarray, train: bool) -> jnp.ndarray:
        expand_size = self.in_size * self.expand_ratio
        x = inputs
        if self.expand_ratio != 1:
            x = ConvNormAct(expand_size, 1, dtype=self.dtype, name="expand")(x, train)
        x = ConvNormAct(
            expand_size,
            self.kernel_size,
            strides=self.strides,
            groups=expand_size,
            dtype=self.dtype,
            name="depthwise",
        )(x, train)
        if 0.0 < self.se_ratio <= 1.0:
            x = SqueezeExcite(expand_size, self.in_size, self.se_ratio, dtype=self.dtype, name="se")(x)
        x = ConvNormAct(self.out_size, 1, use_act=False, dtype=self.dtype, name="project")(x, train)
        if self.strides == 1 and self.in_size == self.out_size:
            if self.drop_rate > 0 and train:
                x = stochastic_depth(x, self.drop_rate, deterministic=not train, rng=self.make_rng("dropout"))
            x = inputs + x
        return x


class EfficientNet(nn.Module):
    """EfficientNet with optional per-block FiLM conditioning (reference `:246-373`)."""

    width_coefficient: float
    depth_coefficient: float
    dropout_rate: float = 0.2
    drop_connect_rate: float = 0.2
    depth_divisor: int = 8
    include_top: bool = True
    classes: int = 1000
    include_film: bool = False
    dtype: jnp.dtype = jnp.float32
    # Rematerialize each MBConv block's activations in the backward pass
    # (jax.checkpoint). The conv trunk dominates the train step's HBM
    # footprint (b·t images deep in the tokenizer); remat trades ~1/3 more
    # FLOPs for O(depth)→O(1) activation memory, buying batch headroom at
    # 256×456. Semantics-preserving (loss/grads numerically identical;
    # pinned by tests/test_vision.py::test_efficientnet_remat_grad_parity).
    remat: bool = False

    def block_configs(self) -> Sequence[Dict[str, Any]]:
        """Flattened per-block args after width/depth scaling (reference `:283-318`)."""
        configs = []
        total_repeats = float(
            sum(round_repeats(a["repeats"], self.depth_coefficient) for a in BLOCKS_ARGS)
        )
        b = 0
        for args in BLOCKS_ARGS:
            in_size = round_filters(args["in_size"], self.depth_divisor, self.width_coefficient)
            out_size = round_filters(args["out_size"], self.depth_divisor, self.width_coefficient)
            for j in range(round_repeats(args["repeats"], self.depth_coefficient)):
                configs.append(
                    dict(
                        kernel_size=args["kernel_size"],
                        in_size=in_size if j == 0 else out_size,
                        out_size=out_size,
                        expand_ratio=args["expand_ratio"],
                        strides=args["strides"] if j == 0 else 1,
                        se_ratio=args["se_ratio"],
                        drop_rate=self.drop_connect_rate * b / total_repeats,
                    )
                )
                b += 1
        return configs

    @nn.compact
    def __call__(
        self,
        inputs: jnp.ndarray,
        context: Optional[jnp.ndarray] = None,
        train: bool = False,
    ) -> jnp.ndarray:
        """inputs: (B, H, W, 3) float; context: (B, D) text embedding when FiLM."""
        stem_ch = round_filters(32, self.depth_divisor, self.width_coefficient)
        x = ConvNormAct(stem_ch, 3, strides=2, dtype=self.dtype, name="stem")(inputs, train)

        # static_argnums counts `self` as 0: (self, inputs, train) → train=2.
        block_cls = (
            nn.remat(MBConvBlock, static_argnums=(2,))
            if self.remat
            else MBConvBlock
        )
        for i, cfg in enumerate(self.block_configs()):
            x = block_cls(**cfg, dtype=self.dtype, name=f"block_{i}")(x, train)
            if self.include_film:
                x = FilmConditioning(cfg["out_size"], dtype=self.dtype, name=f"film_{i}")(x, context)

        top_ch = round_filters(1280, self.depth_divisor, self.width_coefficient)
        x = ConvNormAct(top_ch, 1, dtype=self.dtype, name="top")(x, train)

        if self.include_top:
            x = jnp.mean(x, axis=(-3, -2))
            if self.dropout_rate > 0 and train:
                x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
            x = nn.Dense(self.classes, dtype=self.dtype, name="classifier")(x)
        return x


def EfficientNetB3(
    include_top: bool = True,
    classes: int = 1000,
    include_film: bool = False,
    dtype: jnp.dtype = jnp.float32,
) -> EfficientNet:
    """B3 scaling: width 1.2, depth 1.4, dropout 0.3 (reference `:429-442`).

    Trained natively on 300×300 (→ 10×10 feature map); Language-Table feeds
    256×456 (→ 8×15).
    """
    return EfficientNet(
        width_coefficient=1.2,
        depth_coefficient=1.4,
        dropout_rate=0.3,
        include_top=include_top,
        classes=classes,
        include_film=include_film,
        dtype=dtype,
    )
