"""LAVA model family (Stack B of the reference).

Parity source: reference `language_table/train/networks/` — the vendored
Google JAX BC stack's architectures: `SequenceLAVMSE` (language-conditioned
cross-attention over a visual feature pyramid + temporal transformer) and
`PixelLangMSE` (conv-maxpool with multiplicative language fusion), both
regressing continuous actions with MSE (`bc.py:206-234`).
"""

from rt1_tpu.models.lava.blocks import (
    Add1DPositionEmbedding,
    DenseResnet,
    PrenormEncoderLayer,
    PrenormPixelLangEncoder,
    TemporalTransformer,
    positional_encoding_2d,
)
from rt1_tpu.models.lava.lava import SequenceLAVAEncoder, SequenceLAVMSE
from rt1_tpu.models.lava.pixel import PixelLangMSE
from rt1_tpu.models.lava.resnet import MultiscaleResNet

__all__ = [
    "Add1DPositionEmbedding",
    "DenseResnet",
    "PrenormEncoderLayer",
    "PrenormPixelLangEncoder",
    "TemporalTransformer",
    "positional_encoding_2d",
    "SequenceLAVAEncoder",
    "SequenceLAVMSE",
    "PixelLangMSE",
    "MultiscaleResNet",
]
