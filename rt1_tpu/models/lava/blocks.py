"""Shared LAVA building blocks.

Parity sources: reference `networks/dense_resnet.py` (residual MLP),
`networks/lava.py:101-218` (sinusoidal 1-D/2-D position encodings),
`:268-371` (prenorm cross/self attention layers + temporal transformer).
All dense layers use the reference's normal(0.05) init for both kernel and
bias.
"""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

_INIT = jax.nn.initializers.normal(stddev=0.05)


def _dense(features, name=None):
    return nn.Dense(features, kernel_init=_INIT, bias_init=_INIT, name=name)


class ResnetDenseBlock(nn.Module):
    """relu -> Dense(w/4) -> relu -> Dense(w/4) -> relu -> Dense(w) + skip."""

    width: int

    @nn.compact
    def __call__(self, x, *, train=False):
        y = nn.relu(x)
        y = _dense(self.width // 4)(y)
        y = nn.relu(y)
        y = _dense(self.width // 4)(y)
        y = nn.relu(y)
        y = _dense(self.width)(y)
        return x + y


class DenseResnet(nn.Module):
    """Dense projection + N residual MLP blocks (+ optional value head)."""

    width: int
    num_blocks: int
    value_net: bool = False

    @nn.compact
    def __call__(self, x, *, train=False):
        x = _dense(self.width)(x)
        for _ in range(self.num_blocks):
            x = ResnetDenseBlock(self.width)(x, train=train)
        if self.value_net:
            x = _dense(1)(x)
        return x


def sinusoidal_position_encoding(max_len, d_feature, max_timescale=1.0e4):
    """(1, max_len, d_feature) fixed sin/cos table."""
    pe = np.zeros((max_len, d_feature), dtype=np.float32)
    position = np.arange(0, max_len)[:, None]
    div_term = np.exp(
        np.arange(0, d_feature, 2) * -(np.log(max_timescale) / d_feature)
    )
    pe[:, 0::2] = np.sin(position * div_term)
    pe[:, 1::2] = np.cos(position * div_term)
    return jnp.asarray(pe[None])


class Add1DPositionEmbedding(nn.Module):
    """Adds the fixed sinusoidal table to (b, t, d) inputs."""

    max_len: Optional[int] = None

    @nn.compact
    def __call__(self, inputs):
        assert inputs.ndim == 3, f"expected (b, t, d), got {inputs.shape}"
        length = inputs.shape[1]
        max_len = self.max_len or length
        pe = sinusoidal_position_encoding(max_len, inputs.shape[-1])
        return inputs + pe[:, :length, :]


def positional_encoding_2d(d_model, height, width, flatten=True):
    """(1, h*w, d) fixed 2-D sin/cos table: half the channels encode width
    position, half encode height (reference `positional_encoding2d:189-218`)."""
    if d_model % 4 != 0:
        raise ValueError(f"2d sincos needs d_model % 4 == 0, got {d_model}")
    pe = np.zeros([d_model, height, width], dtype=np.float32)
    half = d_model // 2
    div_term = np.exp(np.arange(0.0, half, 2) * -(np.log(10000.0) / half))
    pos_w = np.arange(0.0, width)[:, None]
    pos_h = np.arange(0.0, height)[:, None]
    pe[0:half:2] = np.tile(
        np.transpose(np.sin(pos_w * div_term))[:, None, :], [1, height, 1]
    )
    pe[1:half:2] = np.tile(
        np.transpose(np.cos(pos_w * div_term))[:, None, :], [1, height, 1]
    )
    pe[half::2] = np.tile(
        np.transpose(np.sin(pos_h * div_term))[:, :, None], [1, 1, width]
    )
    pe[half + 1::2] = np.tile(
        np.transpose(np.cos(pos_h * div_term))[:, :, None], [1, 1, width]
    )
    if flatten:
        pe = np.reshape(pe, [height * width, d_model])
    else:
        pe = np.reshape(pe, [height, width, d_model])
    return jnp.asarray(pe[None])


class PrenormPixelLangEncoder(nn.Module):
    """Cross-attention: language queries attend over the visual sentence."""

    num_heads: int
    dropout_rate: float
    mha_dropout_rate: float
    dff: int

    @nn.compact
    def __call__(self, pixel_x, lang_x, *, train=False):
        residual_lang = lang_x
        pixel_x = nn.LayerNorm()(pixel_x)
        lang_x = nn.LayerNorm()(lang_x)
        attended = nn.MultiHeadDotProductAttention(
            self.num_heads, dropout_rate=self.mha_dropout_rate
        )(lang_x, pixel_x, deterministic=not train)
        attended = nn.Dropout(self.dropout_rate)(
            attended, deterministic=not train
        )
        x = residual_lang + attended  # residual only on the language path
        y = nn.LayerNorm()(x)
        y = _dense(self.dff)(y)
        y = nn.relu(y)
        y = _dense(self.dff)(y)
        y = nn.Dropout(self.dropout_rate)(y, deterministic=not train)
        return x + y


class PrenormEncoderLayer(nn.Module):
    """Standard prenorm self-attention block."""

    num_heads: int
    dropout_rate: float
    mha_dropout_rate: float
    dff: int

    @nn.compact
    def __call__(self, x, *, train=False):
        y = nn.LayerNorm()(x)
        y = nn.MultiHeadDotProductAttention(
            self.num_heads, dropout_rate=self.mha_dropout_rate
        )(y, y, deterministic=not train)
        y = nn.Dropout(self.dropout_rate)(y, deterministic=not train)
        x = x + y
        y = nn.LayerNorm()(x)
        y = _dense(self.dff)(y)
        y = nn.relu(y)
        y = _dense(self.dff)(y)
        y = nn.Dropout(self.dropout_rate)(y, deterministic=not train)
        return x + y


class TemporalTransformer(nn.Module):
    """Self-attention over frames, mean-pooled (reference `:336-371`)."""

    num_layers: int
    d_model: int
    num_heads: int
    dff: int
    sequence_length: int

    @nn.compact
    def __call__(self, x, *, train=False):
        x = _dense(self.d_model)(x)
        x = x * jnp.sqrt(self.d_model)
        x = Add1DPositionEmbedding(max_len=self.sequence_length)(x)
        x = nn.Dropout(0.1)(x, deterministic=not train)
        for _ in range(self.num_layers):
            x = PrenormEncoderLayer(
                num_heads=self.num_heads,
                dropout_rate=0.1,
                mha_dropout_rate=0.0,
                dff=self.dff,
            )(x, train=train)
        x = jnp.mean(x, axis=1)
        return nn.LayerNorm()(x)
