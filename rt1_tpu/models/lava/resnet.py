"""Flax ResNet-V1 with multiscale (pyramid) outputs.

Parity source: reference `language_table/train/networks/resnet_v1.py:37-259`
(itself derived from the public flax examples ResNet). `MultiscaleResNet`
returns the per-stage feature maps instead of a classification head, feeding
the LAVA visual pyramid.
"""

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    """Basic two-conv residual block."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckResNetBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck residual block."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class MultiscaleResNet(nn.Module):
    """ResNet stem + stages, returning [stem_features, stage_0, stage_1, ...]."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef = BottleneckResNetBlock
    num_filters: int = 64
    dtype: jnp.dtype = jnp.float32
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x, *, train=False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )

        x = conv(
            self.num_filters, (7, 7), (2, 2),
            padding=[(3, 3), (3, 3)],
            name="conv_init",
        )(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        outputs = [x]
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=self.act,
                )(x)
            outputs.append(x)
        return outputs
