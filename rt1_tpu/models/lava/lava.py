"""SequenceLAVA encoder + MSE policy head.

Parity source: reference `language_table/train/networks/lava.py:32-518`.
Language encoders supported:
  * "embedding_in_obs" — a precomputed language embedding is provided in the
    observation under `lang_key` (covers the reference's "clip_in_obs", and
    our USE/hash-embedding path).
  * "clip" — an in-graph CLIP text tower consuming `instruction_tokenized_clip`
    BPE tokens. Defaults to `clip_text.CLIPTextEncoder` (the architecture the
    reference pulls from scenic, `lava.py:29,425-435`); override with any
    module via `text_encoder_def`. Freeze it with
    `make_bc_optimizer(frozen_prefixes=(clip_text.FROZEN_PREFIX,))` and load
    public OpenAI weights via `clip_text.convert_clip_text_state_dict` +
    `remap_pretrained_params`.
"""

from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from rt1_tpu.models.lava.blocks import (
    DenseResnet,
    PrenormPixelLangEncoder,
    TemporalTransformer,
    positional_encoding_2d,
)
from rt1_tpu.models.lava.resnet import BottleneckResNetBlock, MultiscaleResNet

_INIT = jax.nn.initializers.normal(stddev=0.05)


class ConvMaxpoolCNNEncoder(nn.Module):
    """4x (conv3x3 -> relu -> maxpool) + final maxpool => 5-level pyramid."""

    @nn.compact
    def __call__(self, rgb, *, train=False):
        x = rgb
        pyramid = []
        for features in (32, 64, 128, 256):
            x = nn.Conv(features, (3, 3), padding="SAME")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2), padding="VALID")
            pyramid.append(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2), padding="VALID")
        pyramid.append(x)
        return pyramid


def normalize_image_resnet(images):
    """ImageNet-normalize + resize to 224 (reference `lava.py:90-98`)."""
    bs = images.shape[0]
    mean_rgb = jnp.array([0.485, 0.456, 0.406]).reshape((1, 1, 1, 3))
    stddev_rgb = jnp.array([0.229, 0.224, 0.225]).reshape((1, 1, 1, 3))
    x = (images - mean_rgb) / stddev_rgb
    return jax.image.resize(
        x, (bs, 224, 224, 3), method="bilinear", antialias=False
    )


class ResNetVisualEncoder(nn.Module):
    """Frozen ResNet stages + conv-maxpool tail => 5-level pyramid."""

    @nn.compact
    def __call__(self, rgb, *, train=False):
        rgb = normalize_image_resnet(rgb)
        # train=False always: the tower is frozen (reference `lava.py:62`).
        features = MultiscaleResNet(
            stage_sizes=(3, 4), block_cls=BottleneckResNetBlock
        )(rgb, train=False)
        pyramid = [features[0], features[1]]
        x = features[1]
        for conv_size in (128, 256):
            x = nn.Conv(conv_size, (3, 3), padding="SAME")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2), padding="VALID")
            pyramid.append(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2), padding="VALID")
        pyramid.append(x)
        return pyramid


class VisualDescriptorsNet(nn.Module):
    """Pyramid levels -> flattened, 2d-posembedded 'visual sentence'."""

    pyramid_fuse_layers: Sequence[int]
    d_model: int

    @nn.compact
    def __call__(self, pyramid, *, train=False):
        pieces = []
        for idx in self.pyramid_fuse_layers:
            x = pyramid[idx]
            h, w = x.shape[1], x.shape[2]
            x = nn.Dense(self.d_model, kernel_init=_INIT, bias_init=_INIT)(x)
            x = x.reshape(x.shape[0], h * w, self.d_model)
            x = x * jnp.sqrt(float(self.d_model))
            x = x + positional_encoding_2d(self.d_model, h, w)
            pieces.append(x)
        return jnp.concatenate(pieces, axis=1)


class SequenceLAVAEncoder(nn.Module):
    """Pyramid -> visual sentence -> language cross-attn -> temporal pool."""

    image_encoder: str                       # "resnet" | "conv_maxpool"
    lang_encoder: str                        # "embedding_in_obs" | "clip"
    num_layers: int = 2
    sequence_length: int = 4
    temporal_transformer_num_layers: int = 2
    d_model: int = 128
    num_heads: int = 2
    pyramid_fuse_layers: Tuple[int, ...] = (2, 3, 4)
    lang_key: str = "natural_language_embedding"
    text_encoder_def: Optional[Any] = None   # custom in-graph text tower

    @nn.compact
    def __call__(self, obs, *, train=False):
        rgb = obs["rgb"]
        bs, seqlen, h, w, c = rgb.shape
        rgb = rgb.reshape(bs * seqlen, h, w, c)

        if self.image_encoder == "resnet":
            pyramid = ResNetVisualEncoder()(rgb, train=train)
        elif self.image_encoder == "conv_maxpool":
            pyramid = ConvMaxpoolCNNEncoder()(rgb, train=train)
        else:
            raise NotImplementedError(self.image_encoder)

        visual_sentence = VisualDescriptorsNet(
            d_model=self.d_model,
            pyramid_fuse_layers=self.pyramid_fuse_layers,
        )(pyramid, train=train)
        visual_sentence = nn.Dropout(0.1)(
            visual_sentence, deterministic=not train
        )

        if self.lang_encoder == "embedding_in_obs":
            lang = obs[self.lang_key].reshape(bs * seqlen, -1)
        elif self.lang_encoder == "clip":
            from rt1_tpu.models.lava.clip_text import CLIPTextEncoder

            # Stable name "text_encoder" so the freeze prefix
            # (clip_text.FROZEN_PREFIX) and pretrained remap targets don't
            # depend on flax auto-numbering. Re-construct inline (clone()
            # would stay unbound inside compact) with the same fields.
            if self.text_encoder_def is None:
                tower = CLIPTextEncoder(name="text_encoder")
            else:
                import dataclasses

                fields = {
                    f.name: getattr(self.text_encoder_def, f.name)
                    for f in dataclasses.fields(self.text_encoder_def)
                    if f.name not in ("parent", "name")
                }
                tower = type(self.text_encoder_def)(
                    **fields, name="text_encoder"
                )
            tokens = obs["instruction_tokenized_clip"].astype(jnp.int32)[:, 0]
            lang = tower(tokens)
            lang = jnp.tile(lang[:, None, :], [1, seqlen, 1]).reshape(
                bs * seqlen, -1
            )
            lang = lang / jnp.linalg.norm(lang, axis=-1, keepdims=True)
        else:
            raise NotImplementedError(self.lang_encoder)

        lang = nn.Dense(self.d_model, kernel_init=_INIT, bias_init=_INIT)(lang)
        lang = lang * jnp.sqrt(self.d_model)
        lang = nn.Dropout(0.1)(lang, deterministic=not train)

        fused = lang[:, None, :]
        for _ in range(self.num_layers):
            fused = PrenormPixelLangEncoder(
                num_heads=2,
                dropout_rate=0.1,
                mha_dropout_rate=0.0,
                dff=self.d_model,
            )(visual_sentence, fused, train=train)
        fused = jnp.squeeze(fused, axis=1)
        fused = nn.LayerNorm()(fused)

        seq_encoding = fused.reshape(bs, seqlen, -1)
        return TemporalTransformer(
            num_layers=self.temporal_transformer_num_layers,
            d_model=self.d_model,
            num_heads=self.num_heads,
            dff=self.d_model,
            sequence_length=self.sequence_length,
        )(seq_encoding, train=train)


class SequenceLAVMSE(nn.Module):
    """LAVA encoder -> DenseResnet -> action regression head."""

    action_size: int
    dense_resnet_width: int
    dense_resnet_num_blocks: int
    lava_num_layers: int = 2
    lava_sequence_length: int = 4
    lava_temporal_transformer_num_layers: int = 2
    lava_d_model: int = 128
    lava_num_heads: int = 2
    lava_pyramid_fuse_layers: Tuple[int, ...] = (2, 3, 4)
    lava_image_encoder: str = "conv_maxpool"
    lava_lang_encoder: str = "embedding_in_obs"
    lang_key: str = "natural_language_embedding"
    text_encoder_def: Optional[Any] = None

    def setup(self):
        self.encoder = SequenceLAVAEncoder(
            num_layers=self.lava_num_layers,
            sequence_length=self.lava_sequence_length,
            temporal_transformer_num_layers=(
                self.lava_temporal_transformer_num_layers
            ),
            d_model=self.lava_d_model,
            num_heads=self.lava_num_heads,
            pyramid_fuse_layers=self.lava_pyramid_fuse_layers,
            image_encoder=self.lava_image_encoder,
            lang_encoder=self.lava_lang_encoder,
            lang_key=self.lang_key,
            text_encoder_def=self.text_encoder_def,
        )
        self.dense_resnet = DenseResnet(
            width=self.dense_resnet_width,
            num_blocks=self.dense_resnet_num_blocks,
            value_net=False,
        )
        self.action_projection = nn.Dense(
            self.action_size, kernel_init=_INIT, bias_init=_INIT
        )

    def __call__(self, obs, *, train=False):
        x = self.encoder(obs, train=train)
        x = self.dense_resnet(x, train=train)
        return self.action_projection(x)
