"""In-graph CLIP text tower for the LAVA "clip" language encoder.

Parity source: the reference pulls scenic's frozen CLIP-B/16 text encoder into
LAVA (`language_table/train/networks/lava.py:29,425-435`) and freezes it via
the optimizer (`language_table/train/bc.py:94-140`). This is the same
architecture (OpenAI CLIP text transformer: token embedding + learned
positional embedding, pre-LN causal transformer with QuickGELU MLPs, final
LayerNorm, EOT-token pooling, linear text projection) written as a Flax
module whose parameter tree mirrors the public CLIP checkpoint layout, so
`convert_clip_text_state_dict` can load real OpenAI weights when a checkpoint
is available and `make_bc_optimizer(frozen_prefixes=...)` can freeze it.

Token input comes from `rt1_tpu.text.clip_bpe.ClipTokenizer` (77-token
framing with SOT/EOT), under the observation key the reference uses:
`instruction_tokenized_clip`.
"""

from __future__ import annotations

from typing import Any, Dict

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

# OpenAI CLIP text-encoder constants (ViT-B checkpoints).
VOCAB_SIZE = 49408
CONTEXT_LENGTH = 77
WIDTH = 512
NUM_LAYERS = 12
NUM_HEADS = 8
EMBED_DIM = 512

# The param-tree prefix to freeze when the tower is used inside
# SequenceLAVAEncoder (make_bc_optimizer(frozen_prefixes=...)).
FROZEN_PREFIX = "encoder/text_encoder"


def quick_gelu(x):
    """CLIP's GELU approximation: x * sigmoid(1.702 x)."""
    return x * nn.sigmoid(1.702 * x)


class ResidualAttentionBlock(nn.Module):
    """Pre-LN block: LN -> causal MHA -> +res; LN -> QuickGELU MLP -> +res."""

    width: int
    num_heads: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, mask):
        y = nn.LayerNorm(epsilon=1e-5, name="ln_1")(x)
        y = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads,
            dtype=self.dtype,
            deterministic=True,
            name="attn",
        )(y, y, mask=mask)
        x = x + y
        y = nn.LayerNorm(epsilon=1e-5, name="ln_2")(x)
        y = nn.Dense(4 * self.width, dtype=self.dtype, name="c_fc")(y)
        y = quick_gelu(y)
        y = nn.Dense(self.width, dtype=self.dtype, name="c_proj")(y)
        return x + y


class CLIPTextEncoder(nn.Module):
    """tokens (B, context) int32 -> pooled text features (B, embed_dim).

    Pooling takes the sequence position of the highest token id — the EOT
    token (id vocab_size-1) in CLIP's BPE framing — then applies the linear
    text projection, exactly like the OpenAI / scenic implementations.
    """

    vocab_size: int = VOCAB_SIZE
    context_length: int = CONTEXT_LENGTH
    width: int = WIDTH
    num_layers: int = NUM_LAYERS
    num_heads: int = NUM_HEADS
    embed_dim: int = EMBED_DIM
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens):
        if tokens.ndim != 2:
            raise ValueError(f"tokens must be (batch, context), got {tokens.shape}")
        x = nn.Embed(
            self.vocab_size, self.width, dtype=self.dtype,
            name="token_embedding",
        )(tokens)
        posemb = self.param(
            "positional_embedding",
            nn.initializers.normal(stddev=0.01),
            (self.context_length, self.width),
        )
        x = x + posemb[: x.shape[1]].astype(self.dtype)

        # Static causal mask — no padding mask: CLIP attends causally over
        # the full 77-token frame; the EOT pooling ignores the padded tail.
        mask = nn.make_causal_mask(tokens)
        for i in range(self.num_layers):
            x = ResidualAttentionBlock(
                width=self.width,
                num_heads=self.num_heads,
                dtype=self.dtype,
                name=f"resblocks_{i}",
            )(x, mask)

        x = nn.LayerNorm(epsilon=1e-5, name="ln_final")(x)
        eot = jnp.argmax(tokens, axis=-1)
        pooled = jnp.take_along_axis(x, eot[:, None, None], axis=1)[:, 0]
        projection = self.param(
            "text_projection",
            nn.initializers.normal(stddev=self.width ** -0.5),
            (self.width, self.embed_dim),
        )
        return pooled @ projection.astype(self.dtype)


def convert_clip_text_state_dict(
    state_dict: Dict[str, np.ndarray],
    num_heads: int = NUM_HEADS,
) -> Dict[str, Any]:
    """Public OpenAI-CLIP torch state dict (text side) -> this module's params.

    Expected torch keys (possibly under a leading "transformer." scope for
    the text transformer blocks):
      token_embedding.weight, positional_embedding,
      transformer.resblocks.N.ln_1.{weight,bias},
      transformer.resblocks.N.attn.{in_proj_weight,in_proj_bias},
      transformer.resblocks.N.attn.out_proj.{weight,bias},
      transformer.resblocks.N.mlp.c_fc.{weight,bias},
      transformer.resblocks.N.mlp.c_proj.{weight,bias},
      ln_final.{weight,bias}, text_projection

    The packed qkv `in_proj_weight` (3W, W) is split and reshaped to flax
    MultiHeadDotProductAttention's (W, heads, head_dim) kernels.
    """
    sd = {k: np.asarray(v) for k, v in state_dict.items()}
    width = sd["token_embedding.weight"].shape[1]
    head_dim = width // num_heads

    params: Dict[str, Any] = {
        "token_embedding": {"embedding": sd["token_embedding.weight"]},
        "positional_embedding": sd["positional_embedding"],
        "ln_final": {
            "scale": sd["ln_final.weight"],
            "bias": sd["ln_final.bias"],
        },
        "text_projection": sd["text_projection"],
    }

    n_layers = 0
    while f"transformer.resblocks.{n_layers}.ln_1.weight" in sd:
        n_layers += 1
    if n_layers == 0:
        raise KeyError("No transformer.resblocks.* keys in state dict")

    for i in range(n_layers):
        p = f"transformer.resblocks.{i}"
        in_w = sd[f"{p}.attn.in_proj_weight"]  # (3W, W), rows are out dims
        in_b = sd[f"{p}.attn.in_proj_bias"]  # (3W,)
        out_w = sd[f"{p}.attn.out_proj.weight"]  # (W, W)
        qw, kw, vw = np.split(in_w, 3, axis=0)
        qb, kb, vb = np.split(in_b, 3, axis=0)

        def head_kernel(w):
            # torch Linear stores (out, in); flax wants (in, heads, head_dim).
            return w.T.reshape(width, num_heads, head_dim)

        params[f"resblocks_{i}"] = {
            "ln_1": {
                "scale": sd[f"{p}.ln_1.weight"],
                "bias": sd[f"{p}.ln_1.bias"],
            },
            "ln_2": {
                "scale": sd[f"{p}.ln_2.weight"],
                "bias": sd[f"{p}.ln_2.bias"],
            },
            "attn": {
                "query": {
                    "kernel": head_kernel(qw),
                    "bias": qb.reshape(num_heads, head_dim),
                },
                "key": {
                    "kernel": head_kernel(kw),
                    "bias": kb.reshape(num_heads, head_dim),
                },
                "value": {
                    "kernel": head_kernel(vw),
                    "bias": vb.reshape(num_heads, head_dim),
                },
                "out": {
                    "kernel": out_w.T.reshape(num_heads, head_dim, width),
                    "bias": sd[f"{p}.attn.out_proj.bias"],
                },
            },
            "c_fc": {
                "kernel": sd[f"{p}.mlp.c_fc.weight"].T,
                "bias": sd[f"{p}.mlp.c_fc.bias"],
            },
            "c_proj": {
                "kernel": sd[f"{p}.mlp.c_proj.weight"].T,
                "bias": sd[f"{p}.mlp.c_proj.bias"],
            },
        }
    return params
