"""PixelLang baseline: channel-stacked frames + multiplicative language fusion.

Parity source: reference `language_table/train/networks/pixel.py:25-111`.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp

from rt1_tpu.models.lava.blocks import DenseResnet

_INIT = jax.nn.initializers.normal(stddev=0.05)


class LanguageFusion(nn.Module):
    """Project language to the image channel dim and multiply per-pixel."""

    @nn.compact
    def __call__(self, lang, image):
        lang = nn.Dense(
            image.shape[-1], kernel_init=_INIT, bias_init=_INIT
        )(lang)
        h, w = image.shape[1], image.shape[2]
        lang = jnp.tile(lang[:, None, None, :], [1, h, w, 1])
        return image * lang


class ConvMaxpoolLanguageEncoder(nn.Module):
    """Conv stack with multiplicative language fusion from layer 2 on."""

    @nn.compact
    def __call__(self, rgb, lang_embedding, *, train=False):
        x = rgb
        fuse_from = 2
        conv_channels = (32, 64, 128, 256)
        for idx, ch in enumerate(conv_channels):
            x = nn.Conv(ch, (3, 3), padding="SAME")(x)
            if fuse_from <= idx + 1:
                x = LanguageFusion()(lang_embedding, x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2), padding="VALID")
        x = jnp.mean(x, axis=(1, 2))
        # Final multiplicative gate on the pooled features.
        lang_info = nn.Dense(
            conv_channels[-1], kernel_init=_INIT, bias_init=_INIT
        )(lang_embedding)
        x = x * lang_info
        x = nn.relu(x)
        return nn.LayerNorm()(x)


class PixelLangMSE(nn.Module):
    """Channel-stack frames, fuse language, regress actions with MSE."""

    action_size: int
    dense_resnet_width: int
    dense_resnet_num_blocks: int
    lang_key: str = "natural_language_embedding"

    def setup(self):
        self.encoder = ConvMaxpoolLanguageEncoder()
        self.dense_resnet = DenseResnet(
            width=self.dense_resnet_width,
            num_blocks=self.dense_resnet_num_blocks,
            value_net=False,
        )
        self.action_projection = nn.Dense(
            self.action_size, kernel_init=_INIT, bias_init=_INIT
        )

    def __call__(self, obs, *, train=False):
        rgb = obs["rgb"]
        b, n, h, w, c = rgb.shape
        # Stack history channelwise. Deviation (documented): the reference
        # does a raw reshape (b,n,w,h,c)->(b,w,h,c*n) (pixel.py:100-103),
        # which interleaves frames across spatial rows; we transpose first so
        # each channel block is one coherent frame.
        rgb = jnp.transpose(rgb, (0, 2, 3, 1, 4)).reshape(b, h, w, c * n)
        lang = obs[self.lang_key][:, -1]
        encoded = self.encoder(rgb, lang, train=train)
        x = self.dense_resnet(encoded, train=train)
        return self.action_projection(x)
