"""Discretized action tokenizer.

Functional JAX re-design of `pytorch_robotics_transformer/tokenizers/action_tokenizer.py`
(`RT1ActionTokenizer`, tokenize `:105-128`, detokenize `:131-159`). Semantics match the
reference exactly:

* a `DiscreteSpec` action contributes 1 token, passed through as its own token id;
* a rank-1 `BoxSpec` action contributes `shape[0]` tokens: values are clipped to
  [low, high], min-max normalized, scaled by `vocab_size - 1`, then **truncated**
  (not rounded) to int32 — the reference uses `.to(torch.int32)`
  (`action_tokenizer.py:124`) which truncates;
* detokenize inverts: `token / (vocab_size - 1) * (high - low) + low`
  (`action_tokenizer.py:154-155`);
* out-of-vocabulary Discrete tokens map to 0 — the reference's quirky comparison is
  `token > n` (strictly greater, `action_tokenizer.py:145`), reproduced verbatim so a
  poor model emitting exactly `n` behaves identically.

Everything is pure jnp on arrays with arbitrary leading batch dims, so the same
functions serve the (b, t) training path and the (1,) inference path, vmap/jit-safe.
"""

from __future__ import annotations

from typing import Dict, Mapping

import jax.numpy as jnp

from rt1_tpu.specs import BoxSpec, DiscreteSpec, Spec


def tokens_per_action(action_space: Mapping[str, Spec]) -> int:
    """Number of tokens one action maps to (action_tokenizer.py:83-98)."""
    n = 0
    for key, spec in action_space.items():
        if isinstance(spec, DiscreteSpec):
            n += 1
        elif isinstance(spec, BoxSpec):
            if len(spec.shape) != 1:
                raise ValueError(
                    f"Only action shapes with single dimension supported, got {spec.shape}"
                )
            n += spec.shape[0]
        else:
            raise ValueError(f"action space entries must be Discrete or Box, got {spec!r} for {key!r}")
    return n


def tokenize(
    action_space: Mapping[str, Spec],
    action: Dict[str, jnp.ndarray],
    vocab_size: int,
) -> jnp.ndarray:
    """Map an action dict to int32 tokens of shape (..., tokens_per_action)."""
    parts = []
    for key, spec in action_space.items():
        a = jnp.asarray(action[key])
        if isinstance(spec, DiscreteSpec):
            parts.append(a.astype(jnp.int32)[..., None])
        elif isinstance(spec, BoxSpec):
            low = jnp.asarray(spec.low_array())
            high = jnp.asarray(spec.high_array())
            a = jnp.clip(a, low, high)
            t = (a - low) / (high - low)
            t = t * (vocab_size - 1)
            parts.append(t.astype(jnp.int32))  # truncation, like torch .to(int32)
        else:
            raise ValueError(f"unsupported spec {spec!r}")
    return jnp.concatenate(parts, axis=-1)


def detokenize(
    action_space: Mapping[str, Spec],
    action_tokens: jnp.ndarray,
    vocab_size: int,
) -> Dict[str, jnp.ndarray]:
    """Invert `tokenize`; tokens shape (..., tokens_per_action) → action dict."""
    action: Dict[str, jnp.ndarray] = {}
    idx = 0
    for key, spec in action_space.items():
        if isinstance(spec, DiscreteSpec):
            tok = action_tokens[..., idx]
            # Reference quirk: strictly-greater comparison (action_tokenizer.py:145).
            action[key] = jnp.where(tok > spec.n, jnp.zeros_like(tok), tok)
            idx += 1
        elif isinstance(spec, BoxSpec):
            dim = spec.shape[0]
            tok = action_tokens[..., idx : idx + dim].astype(jnp.float32)
            low = jnp.asarray(spec.low_array())
            high = jnp.asarray(spec.high_array())
            action[key] = tok / (vocab_size - 1) * (high - low) + low
            idx += dim
        else:
            raise ValueError(f"unsupported spec {spec!r}")
    return action


def box_bin_values(
    action_space: Mapping[str, Spec], vocab_size: int
):
    """Per-token bin centers + Box mask for soft-argmax regression.

    Returns `(values, mask)` with shapes `(tokens_per_action, vocab_size)`
    and `(tokens_per_action,)`: `values[k, v]` is the continuous action the
    detokenizer maps token `v` to for Box token `k` (`detokenize`'s
    `v / (V-1) * (high-low) + low`), rows for Discrete tokens are zero and
    masked out. With these, `E[a_k] = sum_v softmax(logits_k)[v] *
    values[k, v]` is the differentiable expectation of the detokenized
    action — the soft-argmax used by the auxiliary MSE loss
    (`RT1Policy.aux_mse_weight`)."""
    import numpy as np

    if vocab_size < 2:
        raise ValueError(f"vocab_size must be >= 2, got {vocab_size}")
    if not any(isinstance(s, BoxSpec) for s in action_space.values()):
        raise ValueError(
            "soft-argmax regression needs at least one Box action entry; "
            "this action space is all-Discrete"
        )
    rows = []
    mask = []
    grid = np.arange(vocab_size, dtype=np.float32) / float(vocab_size - 1)
    for key, spec in action_space.items():
        if isinstance(spec, DiscreteSpec):
            rows.append(np.zeros((1, vocab_size), np.float32))
            mask.append(np.zeros((1,), np.float32))
        elif isinstance(spec, BoxSpec):
            low = np.asarray(spec.low_array(), np.float32)
            high = np.asarray(spec.high_array(), np.float32)
            rows.append(grid[None, :] * (high - low)[:, None] + low[:, None])
            mask.append(np.ones((spec.shape[0],), np.float32))
        else:
            raise ValueError(f"unsupported spec {spec!r}")
    return np.concatenate(rows, 0), np.concatenate(mask, 0)


def continuous_targets(
    action_space: Mapping[str, Spec], action: Dict[str, jnp.ndarray]
) -> jnp.ndarray:
    """Clipped continuous action values laid out per token (..., A).

    Discrete slots carry zeros (masked by `box_bin_values`' mask); Box slots
    carry the clipped raw values — the regression targets matching the
    tokenizer's clipping (`tokenize`)."""
    parts = []
    for key, spec in action_space.items():
        a = jnp.asarray(action[key])
        if isinstance(spec, DiscreteSpec):
            parts.append(jnp.zeros(a.shape + (1,), jnp.float32))
        elif isinstance(spec, BoxSpec):
            low = jnp.asarray(spec.low_array())
            high = jnp.asarray(spec.high_array())
            parts.append(jnp.clip(a, low, high).astype(jnp.float32))
        else:
            raise ValueError(f"unsupported spec {spec!r}")
    return jnp.concatenate(parts, axis=-1)


def detokenize_expected(
    action_space: Mapping[str, Spec],
    logits: jnp.ndarray,
    vocab_size: int,
) -> Dict[str, jnp.ndarray]:
    """Soft decode: Box entries are E[a] under the token softmax.

    `logits`: (..., tokens_per_action, vocab_size). Discrete entries decode
    by argmax (a probability-weighted mean of category ids is meaningless);
    Box entries return `sum_v p_v * detokenize(v)` — smoother than argmax
    for CE-trained policies whose distribution mass straddles a bin edge,
    and consistent with the `aux_mse_weight` training objective.
    """
    import jax

    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    # Single source of truth for the token→value mapping: the same bin
    # table the aux-MSE training objective uses (box_bin_values), so the
    # train-time expectation and this decode can never drift apart.
    values, _ = box_bin_values(action_space, vocab_size)
    values = jnp.asarray(values)                        # (A, V)
    expected = jnp.einsum("...av,av->...a", probs, values)
    action: Dict[str, jnp.ndarray] = {}
    idx = 0
    for key, spec in action_space.items():
        if isinstance(spec, DiscreteSpec):
            tok = jnp.argmax(logits[..., idx, :], axis=-1).astype(jnp.int32)
            # Reference OOV quirk, as in `detokenize`.
            action[key] = jnp.where(tok > spec.n, jnp.zeros_like(tok), tok)
            idx += 1
        elif isinstance(spec, BoxSpec):
            dim = spec.shape[0]
            action[key] = expected[..., idx : idx + dim]
            idx += dim
        else:
            raise ValueError(f"unsupported spec {spec!r}")
    return action
