"""TokenLearner (Ryoo et al. 2021).

Re-design of `pytorch_robotics_transformer/tokenizers/token_learner.py:26-95`
(`TokenLearnerModule`): LayerNorm over channels → 1×1 conv to a bottleneck (64) →
tanh-approximate GELU → 1×1 conv to `num_tokens` attention maps → softmax over h·w →
weighted spatial pooling producing `num_tokens` tokens per image.

NHWC in (B, H, W, C); out (B, num_tokens, C). The weighted pooling is a single
einsum — batched matmul on the MXU.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from rt1_tpu.models.quant import QuantConv


class TokenLearner(nn.Module):
    num_tokens: int = 8
    bottleneck_dim: int = 64
    dropout_rate: float = 0.0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        b, h, w, c = inputs.shape
        x = nn.LayerNorm(dtype=self.dtype, name="norm")(inputs)
        # QuantConv == nn.Conv until an int8 serving tree arrives.
        x = QuantConv(self.bottleneck_dim, (1, 1), dtype=self.dtype, name="conv1")(x)
        x = nn.gelu(x, approximate=True)  # reference uses GELU(approximate='tanh') (:43)
        if self.dropout_rate > 0:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = QuantConv(self.num_tokens, (1, 1), dtype=self.dtype, name="conv2")(x)
        if self.dropout_rate > 0:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        # (B, H, W, T) → (B, T, H*W) softmax-normalized spatial attention maps.
        maps = x.reshape(b, h * w, self.num_tokens).transpose(0, 2, 1)
        maps = nn.softmax(maps, axis=-1)
        feats = inputs.reshape(b, h * w, c)
        # (B, T, HW) @ (B, HW, C) → (B, T, C): one MXU batched matmul (reference bmm :82).
        return jnp.einsum("bts,bsc->btc", maps, feats.astype(maps.dtype))
