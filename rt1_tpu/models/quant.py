"""Low-precision serving: quantizable layers + quantize-at-restore.

The serving engine runs inference in the training master dtype (f32) even
though inference traffic tolerates much less precision. This module is the
mechanics of the `inference_dtype` engine mode (f32 | bf16 | int8):

* **Quantizable layers.** `QuantDense` / `QuantConv` are drop-in
  `nn.Dense` / `nn.Conv` subclasses that override ONLY parameter
  retrieval: when the `kernel` leaf arrives as int8 (a quantized serving
  tree) they dequantize it through the per-output-channel scale stored in
  the sidecar ``quant`` collection — ``(w_int8 * scale) @ x``, the
  weight-only form whose dequant XLA fuses into the consuming matmul/conv.
  With an f32/bf16 tree the override returns the kernel untouched, so
  training, checkpoints, and every f32 code path are bit-identical to the
  stock flax layers (same param names, same init, same compute).
* **Quantize-at-restore.** `quantize_tree` turns an f32 master
  checkpoint tree into the serving tree: per-output-channel scales are
  computed on the host (``scale = max|w| / 127`` over the non-output
  axes), kernels round-clip to int8, and the scales land in a ``quant``
  collection mirroring the module paths (``.../attn/query/kernel`` →
  ``quant/.../attn/query/kernel_scale``). WHICH leaves quantize is not
  decided here: `rt1_tpu/parallel/plan.py` declares the quantization
  group per param path with the same path-regex machinery as the sharding
  rules, so "what gets int8" reads next to "how it shards" — norms,
  embeddings, the action head, BatchNorm statistics, and the fp32 MoE
  router stay at the master dtype by explicit rule.
* **bf16 mode.** `cast_tree` casts every float leaf once at restore;
  paired with a bf16-compute model this is bit-identical to flax's own
  compute-dtype cast at use sites (pinned in tests/test_quant.py), while
  halving resident param bytes.

A quantization bug can never ship silently: `rt1_tpu/serve/parity.py`
gates the quantized engine on canned-episode action-token agreement vs the
f32 engine, enforced in tier-1.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

# The sidecar variable collection carrying per-output-channel dequant
# scales, mirroring the quantized leaves' module paths with a `_scale`
# suffix on the leaf name.
QUANT_COLLECTION = "quant"

INFERENCE_DTYPES = ("f32", "bf16", "int8")

INT8_MAX = 127


def check_inference_dtype(mode: str) -> str:
    if mode not in INFERENCE_DTYPES:
        raise ValueError(
            f"inference_dtype must be one of {INFERENCE_DTYPES}, got {mode!r}"
        )
    return mode


# ------------------------------------------------------------------ layers


def maybe_dequantize(module: nn.Module, value: Any, scale_name: str) -> Any:
    """Inside a bound module: dequantize an int8 param leaf via its sidecar
    scale, or return the leaf untouched when it is not quantized.

    An int8 leaf WITHOUT a scale is a hard error: silently feeding raw
    int8 integers to a matmul would serve garbage with 200 OK — quantized
    trees must come from `quantize_tree`, which always writes the scale.
    """
    if value.dtype != jnp.int8:
        return value
    if not module.has_variable(QUANT_COLLECTION, scale_name):
        raise ValueError(
            f"{type(module).__name__}: param is int8 but no "
            f"'{QUANT_COLLECTION}' collection carries {scale_name!r}; "
            "quantized serving trees must be built by "
            "rt1_tpu.models.quant.quantize_tree (quantize-at-restore)"
        )
    scale = module.get_variable(QUANT_COLLECTION, scale_name)
    # (w_int8 * scale) @ x: the dequant is element-wise on the weight and
    # adjacent to its consuming contraction, where XLA fuses it.
    return value.astype(scale.dtype) * scale


class QuantDense(nn.Dense):
    """`nn.Dense` that transparently dequantizes an int8 kernel.

    Only parameter retrieval is overridden; init, param names, and the
    f32/bf16 compute path are inherited — a model threaded with this layer
    is bit-identical to one built on `nn.Dense` until a quantized tree is
    served through it.
    """

    def param(self, name, *args, **kwargs):
        value = super().param(name, *args, **kwargs)
        if name == "kernel":
            value = maybe_dequantize(self, value, "kernel_scale")
        return value


class QuantConv(nn.Conv):
    """`nn.Conv` that transparently dequantizes an int8 kernel (see
    `QuantDense`; conv kernels are (kh, kw, cin, cout) — the scale is
    per-cout, broadcast over the receptive field)."""

    def param(self, name, *args, **kwargs):
        value = super().param(name, *args, **kwargs)
        if name == "kernel":
            value = maybe_dequantize(self, value, "kernel_scale")
        return value


# ------------------------------------------------------------ quantization


def quantize_per_channel(w: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric per-output-channel int8 quantization of `w` (..., cout).

    Returns (w_int8, scale_f32 (cout,)) with ``w ≈ w_int8 * scale``,
    ``scale = max|w| / 127`` over all non-output axes. An all-zero channel
    (e.g. FiLM's zero-initialized projections) gets scale 1.0, so its
    round-trip is exact instead of 0/0.
    """
    w = np.asarray(w, np.float32)
    if w.ndim < 2:
        raise ValueError(
            f"per-channel quantization needs rank >= 2, got shape {w.shape}"
        )
    axes = tuple(range(w.ndim - 1))
    amax = np.max(np.abs(w), axis=axes)
    scale = np.where(amax > 0, amax / INT8_MAX, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scale), -INT8_MAX, INT8_MAX).astype(np.int8)
    return q, scale


def dequantize(q: np.ndarray, scale: np.ndarray) -> np.ndarray:
    """Host-side inverse of `quantize_per_channel` (tests, error bounds)."""
    return q.astype(np.float32) * scale


def _is_mapping(x: Any) -> bool:
    return hasattr(x, "items") and not hasattr(x, "shape")


def _quantize_mapping(
    tree: Any, prefix: str, rules: List[Tuple[str, str]]
) -> Tuple[Dict[str, Any], Dict[str, Any], int]:
    """Recurse one params mapping: (quantized params, mirrored scales,
    n_quantized). Scale leaves are named `<leaf>_scale` at the leaf's own
    module path, which is exactly where Quant layers look them up."""
    from rt1_tpu.parallel.plan import QUANT_INT8, quant_group_for_path

    out: Dict[str, Any] = {}
    scales: Dict[str, Any] = {}
    n = 0
    for key, value in tree.items():
        path = f"{prefix}/{key}"
        if _is_mapping(value):
            sub, sub_scales, sub_n = _quantize_mapping(value, path, rules)
            out[key] = sub
            n += sub_n
            if sub_scales:
                scales[key] = sub_scales
        else:
            leaf = np.asarray(value)
            if (
                getattr(leaf, "ndim", 0) >= 2
                and quant_group_for_path(path, rules) == QUANT_INT8
            ):
                q, scale = quantize_per_channel(leaf)
                out[key] = q
                scales[f"{key}_scale"] = scale
                n += 1
            else:
                out[key] = leaf
    return out, scales, n


def quantize_tree(
    variables: Any, rules: Optional[List[Tuple[str, str]]] = None
) -> Dict[str, Any]:
    """f32 master variables → int8 serving tree + ``quant`` scale collection.

    Only the ``params`` collection is eligible (BatchNorm statistics in
    ``batch_stats`` are never quantized); WHICH params leaves quantize is
    declared by the plan's quant rules (`parallel/plan.py
    rt1_quant_rules`). Deterministic: the same master tree always produces
    the same serving tree, which is what lets `swap_variables` requantize
    a standby checkpoint and land on the exact compiled dtypes.
    """
    from rt1_tpu.parallel.plan import rt1_quant_rules

    if rules is None:
        rules = rt1_quant_rules()
    if not _is_mapping(variables) or "params" not in variables:
        raise ValueError(
            "quantize_tree expects a variables mapping with a 'params' "
            f"collection, got {type(variables).__name__}"
        )
    out: Dict[str, Any] = {}
    qparams, scales, n = _quantize_mapping(
        variables["params"], "params", rules
    )
    out["params"] = qparams
    for key, value in variables.items():
        if key == "params":
            continue
        out[key] = jax.tree.map(lambda x: np.asarray(x), value)
    if n == 0:
        raise ValueError(
            "quantize_tree: no leaf matched an int8 quant rule — an int8 "
            "engine serving a byte-identical f32 tree would report a "
            "fabricated memory win; check rt1_quant_rules against this "
            "model's param paths"
        )
    out[QUANT_COLLECTION] = scales
    return out


def cast_tree(variables: Any, dtype=jnp.bfloat16) -> Any:
    """Every float leaf cast to `dtype` once, on the host (bf16 restore).
    Integer leaves (none in RT-1 variables today) pass through."""

    def cast(x):
        x = np.asarray(x)
        if np.issubdtype(x.dtype, np.floating):
            return x.astype(dtype)
        return x

    return jax.tree.map(cast, variables)


def serving_preparer(
    inference_dtype: str, rules: Optional[List[Tuple[str, str]]] = None
) -> Optional[Callable[[Any], Any]]:
    """The host-side master-tree → serving-tree transform for an engine
    mode, or None for f32 (identity). Used once at restore and again by
    `PolicyEngine.swap_variables` for every standby checkpoint, so
    `/reload` keeps working — and keeps compile_count = 1 — in quantized
    modes."""
    check_inference_dtype(inference_dtype)
    if inference_dtype == "f32":
        return None
    if inference_dtype == "bf16":
        return cast_tree
    return lambda variables: quantize_tree(variables, rules)


# ---------------------------------------------------------- byte accounting


def tree_bytes(tree: Any) -> int:
    """Total leaf bytes of a pytree (arrays or ShapeDtypeStructs)."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None:
            nbytes = int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
        total += int(nbytes)
    return total


def abstract_serving_variables(config) -> Any:
    """The serving variables tree as shapes/dtypes only (`jax.eval_shape`
    over the model init — no FLOPs, so even the flagship B3 resolves in
    seconds on a laptop)."""
    from rt1_tpu.specs import language_table_action_space, sample_space
    from rt1_tpu.train.train import build_model

    model = build_model(config.model)
    t = config.model.time_sequence_length
    h, w = config.data.height, config.data.width
    obs = {
        "image": jax.ShapeDtypeStruct((1, t, h, w, 3), np.float32),
        "natural_language_embedding": jax.ShapeDtypeStruct(
            (1, t, 512), np.float32
        ),
    }
    actions = sample_space(
        language_table_action_space(), jax.random.PRNGKey(1), (1, t)
    )
    return jax.eval_shape(
        lambda r, o, a: model.init(
            {"params": r, "dropout": r, "crop": r}, o, a, train=False
        ),
        jax.random.PRNGKey(0),
        obs,
        actions,
    )


def quant_byte_report(
    config, rules: Optional[List[Tuple[str, str]]] = None
) -> Dict[str, Any]:
    """Per-dtype serving param-byte accounting for a config, from abstract
    shapes (no init cost). The bench's honesty companion on hosts where
    XLA:CPU has no native int8 matmul: bytes moved is the measurable win
    there, latency is the TPU projection."""
    from rt1_tpu.parallel.plan import QUANT_INT8, quant_group_for_path
    from rt1_tpu.parallel.sharding import _path_str

    if rules is None:
        from rt1_tpu.parallel.plan import rt1_quant_rules

        rules = rt1_quant_rules()
    shapes = abstract_serving_variables(config)
    f32_bytes = 0
    bf16_bytes = 0
    int8_bytes = 0
    quantized_leaves = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(shapes)[0]:
        n = int(np.prod(leaf.shape))
        f32_bytes += n * 4
        bf16_bytes += n * 2
        s = _path_str(path)
        if (
            leaf.ndim >= 2
            and quant_group_for_path(s, rules) == QUANT_INT8
        ):
            # int8 payload + one f32 scale per output channel.
            int8_bytes += n + int(leaf.shape[-1]) * 4
            quantized_leaves += 1
        else:
            int8_bytes += n * 4
    return {
        "config": str(getattr(config.model, "image_tokenizer", "rt1")),
        "quantized_leaves": quantized_leaves,
        "f32_bytes": f32_bytes,
        "bf16_bytes": bf16_bytes,
        "int8_bytes": int8_bytes,
        "bf16_reduction": round(f32_bytes / bf16_bytes, 3),
        "int8_reduction": (
            round(f32_bytes / int8_bytes, 3) if int8_bytes else 0.0
        ),
    }


# ----------------------------------------------------------- path utilities


def quantized_paths(
    variables: Any, rules: Optional[List[Tuple[str, str]]] = None
) -> List[str]:
    """Param paths an int8 restore would quantize (tests, reporting)."""
    from rt1_tpu.parallel.plan import QUANT_INT8, quant_group_for_path
    from rt1_tpu.parallel.sharding import _path_str

    if rules is None:
        from rt1_tpu.parallel.plan import rt1_quant_rules

        rules = rt1_quant_rules()
    out = []
    tree = variables.get("params", variables) if _is_mapping(variables) else variables
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        s = "params/" + _path_str(path)
        if (
            getattr(leaf, "ndim", 0) >= 2
            and quant_group_for_path(s, rules) == QUANT_INT8
        ):
            out.append(s)
    return out
