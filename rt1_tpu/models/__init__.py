"""Model components: RT-1 network, transformer, tokenizers, FiLM-EfficientNet."""
