"""EfficientNet-based image encoder with late FiLM.

Re-design of `pytorch_robotics_transformer/film_efficientnet/pretrained_efficientnet_encoder.py:36-74`
(`EfficientNetEncoder`): FiLM-EfficientNet-B3 (no top) → 1×1 conv to the token
embedding size (no bias, `:45-51`) → one final FiLM layer (`:53,68`) → either the
spatial feature map (pooling=False, the tokenizer path) or a mean-pooled vector
(pooling=True, `:74`).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from rt1_tpu.models.film import FilmConditioning
from rt1_tpu.models.quant import QuantConv


class EfficientNetEncoder(nn.Module):
    token_embedding_size: int = 512
    early_film: bool = True
    pooling: bool = True
    dtype: jnp.dtype = jnp.float32
    # B3 scaling by default; smaller coefficients give the same architecture
    # family at CPU-trainable cost (e.g. 0.35/0.35 ~ a MobileNet-size tower).
    width_coefficient: float = 1.2
    depth_coefficient: float = 1.4
    remat: bool = False  # jax.checkpoint each MBConv block

    @nn.compact
    def __call__(
        self,
        image: jnp.ndarray,
        context: Optional[jnp.ndarray] = None,
        train: bool = False,
    ) -> jnp.ndarray:
        """image: (B, H, W, 3); context: (B, 512). Returns (B, h, w, E) or (B, E)."""
        from rt1_tpu.models.efficientnet import EfficientNet

        net = EfficientNet(
            width_coefficient=self.width_coefficient,
            depth_coefficient=self.depth_coefficient,
            dropout_rate=0.3,
            include_top=False,
            include_film=self.early_film,
            dtype=self.dtype,
            remat=self.remat,
        )
        if self.early_film:
            features = net(image, context=context, train=train)
        else:
            features = net(image, train=train)
        # QuantConv == nn.Conv until an int8 serving tree arrives
        # (models/quant.py).
        features = QuantConv(
            self.token_embedding_size,
            (1, 1),
            use_bias=False,
            dtype=self.dtype,
            name="conv1x1",
        )(features)
        features = FilmConditioning(self.token_embedding_size, dtype=self.dtype, name="film")(
            features, context
        )
        if not self.pooling:
            return features
        return jnp.mean(features, axis=(-3, -2))
