"""Functional train state.

Replaces the mutable module + optimizer of `RT1_Lightning` (`distribute_train.py:
19-110`) and Stack B's `TrainState` flax struct (`language_table/train/bc.py:33-40`:
step/params/opt_state/batch_stats/norm_info). Ours carries step, params,
batch_stats (EfficientNet BatchNorm running stats — SURVEY.md §7 hard-part 2), and
opt_state. Under pjit/GSPMD, BatchNorm's batch-mean over the sharded batch axis is
itself a global collective, so no explicit cross-replica `merge_batch_stats`
(`train.py:258-266`) is needed — stats are identical on every shard by
construction.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import optax


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray                    # scalar int32
    params: Any
    batch_stats: Any                     # {} when the model has no BatchNorm
    opt_state: Any
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)

    def apply_gradients(
        self,
        grads: Any,
        new_batch_stats: Optional[Any] = None,
        return_updates: bool = False,
    ) -> Any:
        """One optimizer step; with ``return_updates`` also returns the
        applied update tree (``new_params = params + updates``) — consumed
        by the model-health pack (rt1_tpu/obs/health.py), which must not
        read the pre-update params (that would pin the donated input
        buffers past the in-place optimizer write)."""
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_state = self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            batch_stats=self.batch_stats if new_batch_stats is None else new_batch_stats,
            opt_state=new_opt_state,
        )
        return (new_state, updates) if return_updates else new_state


def create_train_state(
    model: Any,
    rng: jax.Array,
    example_batch: Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]],
    tx: optax.GradientTransformation,
    init_fn: Optional[Callable] = None,
) -> TrainState:
    """Initialize params (+ batch_stats) from an example (observations, actions)."""
    obs, actions = example_batch
    if init_fn is None:
        variables = model.init({"params": rng, "crop": rng}, obs, actions, train=False)
    else:
        variables = init_fn(model, rng, obs, actions)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
        tx=tx,
    )
