"""The jitted SPMD train/eval step.

Replaces (SURVEY.md §3.1/§3.4):
* `RT1_Lightning.training_step` + Lightning/DDP backward with NCCL bucket
  allreduce (`distribute_train.py:59-73` + `:235`) — here the gradient reduction
  over the batch axis is a GSPMD-inserted `psum` over ICI, emitted because the
  batch is sharded over the mesh's ``data`` axis while params are replicated (or
  sharded over ``model`` for tensor parallelism).
* Stack B's `p_train_step = pmap(multi_train_step)` with explicit
  `lax.pmean(grad)` (`language_table/train/train.py:143-151`, `bc.py:189-191`) —
  no per-device leading axis, no explicit collectives, one global program.

Gradient accumulation generalizes Stack B's `num_steps_per_train_iter` fori_loop
(`train.py:36-57`): with ``accum_steps > 1`` the global batch is split into
microbatches scanned on-device, gradients averaged, ONE optimizer update — the
standard way to grow effective batch beyond HBM.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from rt1_tpu.parallel import plan as planlib
from rt1_tpu.parallel import sharding as shardlib
from rt1_tpu.trainer.state import TrainState

Batch = Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]


@dataclasses.dataclass
class TrainStepFns:
    """Compiled step functions + the shardings they expect.

    With ``guarded=True`` the train step takes and returns an extra
    replicated device scalar — the cumulative guard-skip counter::

        state, skips, metrics = fns.train_step(state, skips, batch, rng)

    (initialize `skips` with :meth:`init_guard_skips`). The unguarded
    signature stays ``(state, batch, rng) -> (state, metrics)``.
    """

    train_step: Callable[..., Tuple]
    eval_step: Callable[[TrainState, Batch], Dict[str, jnp.ndarray]]
    state_sharding: Any
    batch_sharding: NamedSharding
    mesh: Mesh
    guarded: bool = False
    # True when the step casts f32 master params to bf16 for fwd/bwd
    # (optimizer state and the stored params stay f32).
    mixed_precision: bool = False
    # Entry names of the model-health pack vector riding in the metrics
    # under obs.health.PACK_KEY (empty when model_health is off). The host
    # unpacks the fetched vector against these at log steps.
    health_names: Tuple[str, ...] = ()

    def shard_state(self, state: TrainState) -> TrainState:
        """Place the state per the plan. Multi-process meshes cannot
        `device_put` host values onto non-addressable devices; there the
        state round-trips through host numpy into a jitted identity with
        the plan's out_shardings — every process passes the same
        deterministic init (or the same restored globals), and XLA lays
        each leaf out on the global mesh."""
        if jax.process_count() > 1:
            def host_or_global(x):
                # Leaves already laid out on the global mesh (a
                # plan-migrating restore) pass straight through; local
                # leaves (fresh deterministic init) go via host numpy.
                if isinstance(x, jax.Array) and not x.is_fully_addressable:
                    return x
                return jax.device_get(x)

            state = jax.tree.map(host_or_global, state)
            return jax.jit(lambda s: s, out_shardings=self.state_sharding)(
                state
            )
        return jax.device_put(state, self.state_sharding)

    def shard_batch(self, batch: Batch) -> Batch:
        from rt1_tpu.data.pipeline import put_global

        return put_global(batch, self.batch_sharding)

    def init_guard_skips(self) -> jax.Array:
        """Replicated int32 zero: the cumulative skip counter's seed value."""
        repl = NamedSharding(self.mesh, P())
        if jax.process_count() > 1:
            return jax.jit(
                lambda: jnp.zeros((), jnp.int32), out_shardings=repl
            )()
        return jax.device_put(jnp.zeros((), jnp.int32), repl)


def _loss_fn(model, params, batch_stats, batch: Batch, rng: jax.Array, train: bool):
    obs, actions = batch
    variables = {"params": params}
    if batch_stats:
        variables["batch_stats"] = batch_stats
    rngs = {
        "crop": jax.random.fold_in(rng, 0),
        "dropout": jax.random.fold_in(rng, 1),
        "augment": jax.random.fold_in(rng, 2),
    }
    # MoE decoders sow a Switch load-balancing aux loss into intermediates
    # (models/moe.py); without it top-1 routing collapses onto one expert.
    # Train-only: eval loss stays the pure task loss so checkpoint selection
    # and dense-baseline comparisons are unaffected by the regularizer.
    use_moe = train and getattr(model, "ffn_impl", "dense") == "moe"
    mutable = []
    if train and batch_stats:
        mutable.append("batch_stats")
    if use_moe:
        mutable.append("intermediates")

    if mutable:
        out, mutated = model.apply(
            variables,
            obs,
            actions,
            train=train,
            rngs=rngs if train else None,
            mutable=mutable,
        )
        new_bs = mutated.get("batch_stats", batch_stats)
    else:
        out = model.apply(
            variables, obs, actions, train=train, rngs=rngs if train else None
        )
        mutated = {}
        new_bs = batch_stats

    loss = out["loss"]
    if use_moe and "intermediates" in mutated:
        aux_leaves = [
            jnp.asarray(v, jnp.float32)
            for path, v in jax.tree_util.tree_flatten_with_path(
                mutated["intermediates"]
            )[0]
            if "moe_aux_loss" in jax.tree_util.keystr(path)
        ]
        if aux_leaves:
            aux = sum(jnp.mean(a) for a in aux_leaves) / len(aux_leaves)
            loss = loss + getattr(model, "moe_aux_weight", 0.01) * aux
            out = dict(out, loss=loss, moe_aux_loss=aux)
    return loss, (out, new_bs)


def make_train_step_fns(
    model: Any,
    mesh: Mesh,
    state: TrainState,
    param_rules: Optional[Sequence[shardlib.Rule]] = None,
    accum_steps: int = 1,
    batch_axes: Optional[Tuple[str, ...]] = None,
    donate: bool = True,
    loss_fn: Optional[Callable] = None,
    guard_nonfinite: bool = False,
    guard_grad_norm_max: float = 0.0,
    model_health: bool = False,
    health_group_depth: int = 2,
    health_task_names: Sequence[str] = (),
    plan: Optional[planlib.ShardingPlan] = None,
    mixed_precision: bool = False,
    check_coverage: bool = True,
) -> TrainStepFns:
    """Build jitted train/eval steps with explicit in/out shardings.

    `state` is only used to derive the sharding pytree (its structure, not its
    values); call `fns.shard_state(state)` afterwards to place it on the mesh.

    `loss_fn(params, batch_stats, batch, rng, train) -> (loss, (out, new_bs))`
    overrides the default RT-1 token-CE closure — the hook that lets the same
    SPMD step machinery train other model families (LAVA BC MSE via
    `trainer.bc.make_bc_step_loss_fn`, reference Stack B `train.py:105-116`).
    `out` must contain "loss"; extra keys become metrics where recognized.

    ``guard_nonfinite=True`` is the device half of the resilience step guard
    (rt1_tpu/resilience/guard.py): when the step's loss or grad-norm is
    non-finite — or the grad-norm exceeds ``guard_grad_norm_max`` (> 0) —
    the whole state update is dropped (`jnp.where` select against the input
    state; a skipped step leaves params, opt_state, batch_stats, and
    `state.step` untouched). A cumulative skip counter is threaded through
    the step as a replicated device scalar and surfaced as the
    ``guard_skips_cum`` metric, so the host learns the exact skip count at
    log steps without ever syncing per step. When the step is healthy the
    select is the identity — the guarded step is numerically identical to
    the unguarded one (pinned in tests/test_resilience_guard.py).

    ``model_health=True`` packs per-layer-group gradient norms, post-
    optimizer update/param ratios, global param norm, action-logit entropy,
    and per-action-dimension token accuracy into ONE replicated float32
    vector under ``metrics[obs.health.PACK_KEY]`` (rt1_tpu/obs/health.py)
    — computed inside the traced step, fetched only when the host fetches
    metrics, unpacked against ``fns.health_names``. Same guard discipline
    as ``guard_nonfinite``: a Python-level gate, so the ``False`` path
    traces the exact pre-change program (pinned bit-identical in
    tests/test_obs_health.py).

    ``health_task_names`` (with ``model_health=True`` and batches whose
    observations carry ``obs.health.TASK_ID_KEY`` — the sample-ahead
    feeder's ``emit_task_ids``) extends the pack with per-task loss /
    token accuracy / batch share via a one-hot segment reduction inside
    the step (``health/task_*``). The task-id member is stripped from the
    observations before the model forward; batches without it trace the
    exact task-free program.

    Layout comes from the declarative ``plan`` (parallel/plan.py) — the same
    object train, eval, and serve resolve once from ``config.parallel``.
    ``param_rules`` remains as an explicit override; when neither is given
    the default RT-1 plan applies. The plan's coverage check runs on
    ``state.params`` here, so a param group the plan forgot warns loudly
    (or raises in strict mode) at step-build time, not after silently
    replicating for a whole run.

    ``mixed_precision=True`` is TRUE mixed precision, not a compute-dtype
    flag: the TrainState keeps float32 master params + optimizer state
    (restore/checkpoint dtypes unchanged); inside the jitted step the f32
    masters are cast ONCE to bfloat16 and the fwd/bwd runs on the bf16
    copy (activations follow the model's bf16 compute dtype; softmax/CE
    stay f32 — models/rt1.py upcasts logits before the loss). Gradient of
    the cast is a cast back, so grads arrive f32 and the optimizer update
    is pure f32 master arithmetic. Donation-safe: the bf16 copy is a fresh
    buffer read from the donated input before the in-place master update.
    With ``mixed_precision=False`` the traced program is the exact
    pre-change program (Python-level gate, same discipline as
    ``guard_nonfinite``/``model_health``; pinned in tests/test_plan.py).
    """
    if plan is None:
        plan = planlib.ShardingPlan(
            mesh=mesh,
            rules=(
                param_rules if param_rules is not None
                else planlib.rt1_sharding_plan()
            ),
        )
    if batch_axes is None:
        # Batch shards over every data-parallel axis the mesh carries;
        # meshes built before the fsdp axis existed keep ("data",).
        batch_axes = tuple(
            a for a in plan.batch_axes if a in mesh.shape
        ) or ("data",)
    default_rt1_loss = loss_fn is None
    if loss_fn is None:
        def loss_fn(params, batch_stats, batch, rng, train):
            return _loss_fn(model, params, batch_stats, batch, rng, train)

    if mesh.shape.get("fsdp", 1) > 1:
        # FSDP schedule: weights are STORED sharded over `fsdp` between
        # steps (master params + optimizer moments — the ZeRO memory win)
        # and gathered ONCE here for fwd/bwd; the update reshards back at
        # the step's out_shardings boundary (a reduce-scatter). One clean
        # all-gather per step beats per-use resharding, and sidesteps the
        # XLA:CPU partitioner miscompiles on dp×fsdp meshes (plan.py,
        # strip_fsdp_axis). Placed INSIDE the loss closure so the bf16
        # mixed-precision cast below lands before the gather — gathering
        # half the bytes.
        gather_sh = plan.gather_shardings(state.params)
        fsdp_loss_fn = loss_fn

        def loss_fn(params, batch_stats, batch, rng, train):  # noqa: F811
            params = jax.lax.with_sharding_constraint(params, gather_sh)
            return fsdp_loss_fn(params, batch_stats, batch, rng, train)

    if mixed_precision:
        task_loss_fn = loss_fn

        def loss_fn(params, batch_stats, batch, rng, train):  # noqa: F811
            return task_loss_fn(
                _bf16_compute_copy(params), batch_stats, batch, rng, train
            )

    from rt1_tpu.obs import health as health_lib

    health_names: Tuple[str, ...] = ()
    health_action_dims = 0
    health_tasks: Tuple[str, ...] = ()
    if model_health:
        # Action-logit statistics exist only when the default RT-1 token-CE
        # closure runs unaccumulated (the accum scan keeps only the loss;
        # family-override losses have no token logits). The pack layout is
        # decided here, statically, so host names and traced order agree.
        if (
            default_rt1_loss
            and accum_steps == 1
            and hasattr(model, "tokens_per_action")
        ):
            health_action_dims = int(model.tokens_per_action)
            # Per-task loss/accuracy shares the same action-stat gate: the
            # one-hot reduction consumes the per-example action_loss only
            # the unaccumulated RT-1 closure exposes.
            health_tasks = tuple(health_task_names or ())
        health_names = health_lib.pack_names(
            state.params,
            depth=health_group_depth,
            action_dims=health_action_dims,
            task_names=health_tasks,
        )

    # Strip the feeder's per-example task ids from the observations BEFORE
    # the model forward — the model contract never includes them — and
    # stash them into the loss aux for the health pack's per-task segment
    # reduction. Batches without the key (synthetic, tf.data, pre-task
    # corpora) take the untouched path: the Python-level membership check
    # runs at trace time, so the traced program is the exact pre-task one.
    strip_loss_fn = loss_fn

    def loss_fn(params, batch_stats, batch, rng, train):  # noqa: F811
        obs, actions = batch
        if isinstance(obs, dict) and health_lib.TASK_ID_KEY in obs:
            obs = dict(obs)
            task_ids = obs.pop(health_lib.TASK_ID_KEY)
            loss, (out, new_bs) = strip_loss_fn(
                params, batch_stats, (obs, actions), rng, train
            )
            if health_tasks:
                out = dict(out, task_ids=task_ids)
            return loss, (out, new_bs)
        return strip_loss_fn(params, batch_stats, batch, rng, train)
    if check_coverage:
        # The default rules are the RT-1 plan; callers training another
        # family (whose param paths the plan does not describe) pass
        # check_coverage=False rather than getting false "would silently
        # replicate" warnings — or a strict-mode abort — for a model that
        # is correctly replicated.
        plan.check_coverage(state.params)
    state_sharding = plan.tree_shardings(state)
    batch_sh = NamedSharding(mesh, P(batch_axes))
    repl = NamedSharding(mesh, P())

    def train_step(state: TrainState, batch: Batch, rng: jax.Array):
        grad_fn = jax.value_and_grad(
            lambda p, bs, b, r: loss_fn(p, bs, b, r, train=True), has_aux=True
        )

        if accum_steps == 1:
            (loss, (out, new_bs)), grads = grad_fn(state.params, state.batch_stats, batch, rng)
        else:
            # Under the reference loss scaling (mean CE / (b·t·(I+A)),
            # transformer_network.py:314-319) the loss is inversely proportional
            # to the *runtime* batch size, so a microbatch of b/accum yields
            # accum× the full-batch loss/grads; one extra /accum makes
            # accumulation exact (proof in tests/test_trainer.py).
            ref_scale = getattr(model, "loss_scale", "mean") == "reference"
            extra = float(accum_steps) if ref_scale else 1.0

            def micro(carry, xs):
                grads_acc, loss_acc, aux_acc, mse_acc, bs = carry
                mb, r = xs
                (l, (mb_out, bs)), g = grad_fn(state.params, bs, mb, r)
                # Metric only: the aux terms' gradients already flow via l.
                aux_acc = aux_acc + mb_out.get("moe_aux_loss", jnp.zeros(()))
                mse_acc = mse_acc + mb_out.get("aux_mse", jnp.zeros(()))
                return (
                    jax.tree.map(jnp.add, grads_acc, g),
                    loss_acc + l,
                    aux_acc,
                    mse_acc,
                    bs,
                ), None

            def split(x):
                return x.reshape((accum_steps, x.shape[0] // accum_steps) + x.shape[1:])

            micro_batches = jax.tree.map(split, batch)
            rngs = jax.random.split(rng, accum_steps)
            zero_grads = jax.tree.map(jnp.zeros_like, state.params)
            (grads, loss, aux, mse, new_bs), _ = jax.lax.scan(
                micro,
                (zero_grads, jnp.zeros(()), jnp.zeros(()), jnp.zeros(()),
                 state.batch_stats),
                (micro_batches, rngs),
            )
            grads = jax.tree.map(lambda g: g / (accum_steps * extra), grads)
            loss = loss / (accum_steps * extra)
            out = {"loss": loss}
            if getattr(model, "ffn_impl", "dense") == "moe":
                out["moe_aux_loss"] = aux / accum_steps  # mean over micros
            if getattr(model, "aux_mse_weight", 0.0) > 0:
                out["aux_mse"] = mse / accum_steps  # mean over micros

        if model_health:
            new_state, updates = state.apply_gradients(
                grads, new_batch_stats=new_bs, return_updates=True
            )
        else:
            new_state = state.apply_gradients(grads, new_batch_stats=new_bs)
        metrics = {
            "loss": loss,
            "grad_norm": optax_global_norm(grads),
        }
        if "action_loss" in out:
            metrics["action_loss_mean"] = jnp.mean(out["action_loss"])
        if "moe_aux_loss" in out:  # routing-collapse monitor
            metrics["moe_aux_loss"] = out["moe_aux_loss"]
        if "aux_mse" in out:  # soft-argmax regression monitor
            metrics["aux_mse"] = out["aux_mse"]
        if model_health:
            # One small replicated vector; like every other metric it is
            # dispatched with the step and fetched only at log steps. Fed
            # from the optimizer's update tree, NOT (old, new) params —
            # reading pre-update params would pin the donated buffers.
            metrics[health_lib.PACK_KEY] = health_lib.compute_pack(
                updates=updates,
                new_params=new_state.params,
                grads=grads,
                out=out,
                depth=health_group_depth,
                action_dims=health_action_dims,
                task_names=health_tasks,
            )
        return new_state, metrics

    def eval_step(state: TrainState, batch: Batch):
        loss, (out, _) = loss_fn(
            state.params, state.batch_stats, batch, jax.random.PRNGKey(0), train=False
        )
        metrics = {"loss": loss}
        if "action_labels" in out and "action_predictions" in out:
            labels = out["action_labels"]
            preds = out["action_predictions"]
            metrics["token_accuracy"] = jnp.mean(
                (preds == labels).astype(jnp.float32)
            )
        return metrics

    def train_step_guarded(
        state: TrainState, skips: jnp.ndarray, batch: Batch, rng: jax.Array
    ):
        new_state, metrics = train_step(state, batch, rng)
        ok = jnp.isfinite(metrics["loss"]) & jnp.isfinite(metrics["grad_norm"])
        if guard_grad_norm_max > 0:
            ok &= metrics["grad_norm"] <= guard_grad_norm_max
        # Dropped update = pass the INPUT state through unchanged (including
        # `step`: an update that never happened should not count as one).
        new_state = jax.tree.map(
            lambda n, o: jnp.where(ok, n, o), new_state, state
        )
        skips = skips + jnp.where(ok, 0, 1).astype(jnp.int32)
        metrics = dict(metrics, guard_skips_cum=skips)
        return new_state, skips, metrics

    with mesh:
        if guard_nonfinite:
            train_jit = jax.jit(
                train_step_guarded,
                in_shardings=(state_sharding, repl, batch_sh, repl),
                out_shardings=(state_sharding, repl, repl),
                donate_argnums=(0, 1) if donate else (),
            )
        else:
            train_jit = jax.jit(
                train_step,
                in_shardings=(state_sharding, batch_sh, repl),
                out_shardings=(state_sharding, repl),
                donate_argnums=(0,) if donate else (),
            )
        eval_jit = jax.jit(
            eval_step,
            in_shardings=(state_sharding, batch_sh),
            out_shardings=repl,
        )

    return TrainStepFns(
        train_step=train_jit,
        eval_step=eval_jit,
        state_sharding=state_sharding,
        batch_sharding=batch_sh,
        mesh=mesh,
        guarded=guard_nonfinite,
        mixed_precision=mixed_precision,
        health_names=health_names,
    )


def _bf16_compute_copy(tree: Any) -> Any:
    """bf16 copy of the f32 leaves (masters untouched; non-float leaves
    pass through). The single cast site of the mixed-precision step."""
    return jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if jnp.asarray(x).dtype == jnp.float32
        else x,
        tree,
    )


def optax_global_norm(tree: Any) -> jnp.ndarray:
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(x)) for x in jax.tree_util.tree_leaves(tree))
    )
