"""SPMD training harness.

TPU-native replacement for the reference's two trainers (SURVEY.md §2.2, §3.4):
`RT1_Lightning` + DDP (`distribute_train.py:19-247`) and the vendored JAX
`pmap`/`pmean` loop (`language_table/train/train.py:60-218`). One `jit`-compiled
train step with explicit shardings over a `Mesh` replaces both — gradient
reduction is a GSPMD-inserted psum over ICI, not an NCCL allreduce and not an
explicit `lax.pmean`.
"""

from rt1_tpu.trainer.optim import make_optimizer, multistep_lr
from rt1_tpu.trainer.state import TrainState, create_train_state
from rt1_tpu.trainer.train import TrainStepFns, make_train_step_fns

__all__ = [
    "TrainState",
    "create_train_state",
    "make_optimizer",
    "multistep_lr",
    "TrainStepFns",
    "make_train_step_fns",
]
