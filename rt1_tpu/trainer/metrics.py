"""Metrics, logging, and profiling hooks.

Replaces (SURVEY.md §5 metrics/observability + tracing):
* Stack A `self.log(..., sync_dist=True)` + CSV/TensorBoard loggers +
  LearningRateMonitor (`distribute_train.py:69,221-228`),
* Stack B `clu.metric_writers.create_default_writer` + hparams +
  `parameter_overview` + `periodic_actions.ReportProgress` +
  `jax.profiler.StepTraceAnnotation` (`language_table/train/train.py:
  119-121,155-169,182`).

Cross-device metric reduction needs no sync_dist plumbing: metric values come
out of the jitted step already reduced over the mesh (jnp.mean over the
global batch → XLA collective), so hosts just write scalars.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Optional

import jax
import numpy as np


def create_writer(workdir: str, *, just_logging: bool = False):
    """clu metric writer: TensorBoard + logging on host 0, no-op elsewhere."""
    from clu import metric_writers

    return metric_writers.create_default_writer(
        workdir,
        just_logging=just_logging or jax.process_index() > 0,
    )


def flatten_hparams(config: Dict[str, Any], parent: str = "") -> Dict[str, Any]:
    """Nested config dict -> {dotted.key: scalar}.

    The old top-level isinstance filter silently dropped every nested
    block (`config.data`, `config.obs`, `config.resilience`, ...) — the
    TB hparams table showed a handful of top-level scalars and nothing
    else. Non-scalar leaves (tuples, None placeholders) are still skipped.
    """
    out: Dict[str, Any] = {}
    for k, v in config.items():
        key = f"{parent}.{k}" if parent else str(k)
        if isinstance(v, dict):
            out.update(flatten_hparams(v, key))
        elif isinstance(v, (int, float, str, bool)):
            out[key] = v
    return out


def write_hparams(writer, config: Dict[str, Any]):
    writer.write_hparams(flatten_hparams(config))


def log_parameter_overview(params, path: Optional[str] = None):
    """Dump a per-parameter shape/size table (Stack B writes parameters.txt)."""
    from clu import parameter_overview

    overview = parameter_overview.get_parameter_overview(params)
    if path is not None and jax.process_index() == 0:
        with open(path, "w") as f:
            f.write(overview)
    return overview


@contextlib.contextmanager
def step_trace(name: str, step_num: int):
    """`jax.profiler.StepTraceAnnotation` wrapper: marks steps in xplane."""
    with jax.profiler.StepTraceAnnotation(name, step_num=step_num):
        yield


class ThroughputMeter:
    """steps/sec + examples/sec over a sliding window of host time.

    The window baseline starts at construction (anchored at
    `initial_step`), so the FIRST `update` already reports a rate — the
    old lazy-init swallowed the whole first logging interval. Monotonic
    safety: a step rewind (checkpoint restore rolled the loop back)
    rebases the window instead of reporting a negative or infinite rate;
    the rebasing update is the only one that returns no scalars.
    """

    def __init__(self, batch_size: int, initial_step: int = 0):
        self._batch_size = batch_size
        self._t0 = time.perf_counter()
        self._step0 = initial_step

    def update(self, step: int) -> Dict[str, float]:
        now = time.perf_counter()
        dt = now - self._t0
        dsteps = step - self._step0
        if dsteps < 0:
            # Non-monotonic step (restore rewind): rebase, report nothing —
            # a window spanning the rewind has no meaningful rate.
            self._t0, self._step0 = now, step
            return {}
        if dt <= 0 or dsteps == 0:
            # Same-step duplicate update: keep the window open.
            return {}
        self._t0, self._step0 = now, step
        n_chips = max(jax.device_count(), 1)
        return {
            "steps_per_sec": dsteps / dt,
            "steps_per_sec_per_chip": dsteps / dt / n_chips,
            "examples_per_sec": dsteps * self._batch_size / dt,
        }


def scalars_from_metrics(metrics: Dict[str, Any]) -> Dict[str, float]:
    """Pull device metrics to host floats (one transfer per scalar)."""
    out = {}
    for k, v in metrics.items():
        arr = np.asarray(jax.device_get(v))
        out[k] = float(arr.mean()) if arr.ndim else float(arr)
    return out
