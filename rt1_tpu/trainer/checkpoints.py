"""Orbax checkpointing: save / restore-or-initialize / best-keep policy.

Replaces (SURVEY.md §5 checkpoint/resume):
* Stack A Lightning `ModelCheckpoint(save_top_k=-1, save_last=True,
  every_n_epochs)` (`distribute_train.py:214-220`),
* Stack B `clu.checkpoint.MultihostCheckpoint` + flax `save_checkpoint`
  with `keep_every_n_steps` (`language_table/train/train.py:122-138,201-217`).

Orbax is multihost-aware out of the box (each host writes its shards of a
sharded TrainState; restore lays arrays back out on the mesh), which is the
TPU-native replacement for clu's multihost rendezvous.

Resilience (rt1_tpu/resilience/, docs/resilience.md): `CheckpointConfig.
retry` wraps save/restore in exponential-backoff retry so a transient
filesystem error degrades to a logged warning instead of killing the run;
`restore_or_initialize` survives a corrupt/partial latest step by falling
back to older retained steps (loudly); and the `ckpt_save`/`ckpt_restore`
fault-injection sites make both paths provable in tests and chaos runs.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, List, Optional

import orbax.checkpoint as ocp

from rt1_tpu.resilience import faults
from rt1_tpu.resilience.retry import RetryOptions, retry_call


@dataclasses.dataclass
class CheckpointConfig:
    directory: str
    max_to_keep: Optional[int] = None  # None = keep everything (save_top_k=-1)
    save_interval_steps: int = 1000
    keep_period: Optional[int] = None  # also keep every Nth (keep_every_n_steps)
    # Backoff schedule for transient I/O on save/restore; None = no retry
    # (one attempt, errors propagate — the pre-resilience behavior).
    retry: Optional[RetryOptions] = None
    # Observer for checkpoint I/O wall time: called as on_io(name, seconds)
    # with name "ckpt_save"/"ckpt_restore" after every logical operation
    # (retries included in the measured span, failures too — badput is
    # badput). The train loop hands the goodput ledger's note_io here
    # (rt1_tpu/obs/goodput.py); exceptions are swallowed — accounting must
    # never take down checkpointing.
    on_io: Optional[Callable[[str, float], None]] = None


class CheckpointManager:
    """Thin wrapper over ocp.CheckpointManager for TrainState pytrees."""

    def __init__(self, config: CheckpointConfig):
        self._config = config
        options = ocp.CheckpointManagerOptions(
            max_to_keep=config.max_to_keep,
            save_interval_steps=config.save_interval_steps,
            keep_period=config.keep_period,
            create=True,
        )
        self._mgr = ocp.CheckpointManager(
            config.directory,
            options=options,
        )
        # Logical-operation ordinals for fault injection: bumped once per
        # save/restore (NOT per retry attempt), so "ckpt_save@2" means the
        # 2nd save even when an earlier injected failure triggered retries.
        self._save_ops = 0
        self._restore_ops = 0

    def _io(self, fn, name: str):
        """Run an I/O closure, retried per the config (or once when off);
        reports the whole operation's wall time (all attempts) to `on_io`."""
        t0 = time.perf_counter()
        try:
            if self._config.retry is None:
                return fn()
            return retry_call(fn, options=self._config.retry, name=name)
        finally:
            if self._config.on_io is not None:
                try:
                    self._config.on_io(name, time.perf_counter() - t0)
                except Exception:  # noqa: BLE001 - accounting only
                    pass

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        self._save_ops += 1
        op = self._save_ops

        def _save():
            # Injection precedes the real write so a "transient" spec fires
            # once and the retry's next attempt genuinely succeeds. Indexed
            # by the logical save ordinal, not the attempt, so a spec's
            # extra fires (`x<K>`) land on consecutive RETRIES of the same
            # save rather than silently consuming later saves' occurrences.
            faults.maybe_fail("ckpt_save", index=op, what=f"save at step {step}")
            return self._mgr.save(
                step, args=ocp.args.StandardSave(state), force=force
            )

        return bool(self._io(_save, "ckpt_save"))

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        """Restore into the structure/shardings of `state_like`."""
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"No checkpoint found in {self._config.directory}"
            )

        self._restore_ops += 1
        op = self._restore_ops

        def _restore():
            faults.maybe_fail(
                "ckpt_restore", index=op, what=f"restore step {step}"
            )
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(state_like)
            )

        return self._io(_restore, "ckpt_restore")

    def restore_or_initialize(self, state_like: Any):
        """(state, step): restored latest, or the passed-in init at step 0.

        Mirrors `clu.checkpoint.restore_or_initialize` semantics
        (`language_table/train/train.py:125-127`): training resumes from
        `step + 1` after preemption.

        Robust to a corrupt/partial newest step (half-written before a hard
        kill, truncated by a full disk): a failed restore logs loudly and
        falls back to the next-older retained step instead of wedging the
        relaunch; only when EVERY retained step fails does the original
        error propagate.
        """
        steps = sorted(self.all_steps(), reverse=True)
        if not steps:
            return state_like, 0
        last_exc: Optional[Exception] = None
        for step in steps:
            try:
                return self.restore(state_like, step), int(step)
            except Exception as exc:  # noqa: BLE001 - fall back per step
                from absl import logging

                last_exc = exc
                logging.error(
                    "checkpoint: restore of step %d in %s FAILED (%s: %s)%s",
                    step,
                    self._config.directory,
                    type(exc).__name__,
                    exc,
                    " — falling back to the previous retained step"
                    if step != steps[-1]
                    else " — no older step to fall back to",
                )
        raise last_exc

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> List[int]:
        """Retained step numbers (finalized only — Orbax skips tmp dirs)."""
        return [int(s) for s in self._mgr.all_steps()]

    def wait_until_finished(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()


def latest_step(ckpt_dir: str) -> Optional[int]:
    """Newest checkpoint step under `ckpt_dir`, or None — without building a
    CheckpointManager (cheap enough for CLI glue, watchdogs, and provenance
    stamping; Orbax step dirs are plain integer-named directories).

    Defensive against in-flight/aborted writes: Orbax tmp dirs
    (`<step>.orbax-checkpoint-tmp-<ts>`) fail the digit check, and a bare
    EMPTY integer-named directory (mkdir happened, contents never landed)
    is not a checkpoint either.
    """
    import os

    if not os.path.isdir(ckpt_dir):
        return None
    steps = []
    for d in os.listdir(ckpt_dir):
        if not d.isdigit():
            continue  # Orbax tmp dirs and sidecar files
        full = os.path.join(ckpt_dir, d)
        try:
            if not os.path.isdir(full) or not os.listdir(full):
                continue
        except OSError:
            continue
        steps.append(int(d))
    return max(steps) if steps else None
