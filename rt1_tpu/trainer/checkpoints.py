"""Orbax checkpointing: save / restore-or-initialize / best-keep policy.

Replaces (SURVEY.md §5 checkpoint/resume):
* Stack A Lightning `ModelCheckpoint(save_top_k=-1, save_last=True,
  every_n_epochs)` (`distribute_train.py:214-220`),
* Stack B `clu.checkpoint.MultihostCheckpoint` + flax `save_checkpoint`
  with `keep_every_n_steps` (`language_table/train/train.py:122-138,201-217`).

Orbax is multihost-aware out of the box (each host writes its shards of a
sharded TrainState; restore lays arrays back out on the mesh), which is the
TPU-native replacement for clu's multihost rendezvous.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import orbax.checkpoint as ocp


@dataclasses.dataclass
class CheckpointConfig:
    directory: str
    max_to_keep: Optional[int] = None  # None = keep everything (save_top_k=-1)
    save_interval_steps: int = 1000
    keep_period: Optional[int] = None  # also keep every Nth (keep_every_n_steps)


class CheckpointManager:
    """Thin wrapper over ocp.CheckpointManager for TrainState pytrees."""

    def __init__(self, config: CheckpointConfig):
        self._config = config
        options = ocp.CheckpointManagerOptions(
            max_to_keep=config.max_to_keep,
            save_interval_steps=config.save_interval_steps,
            keep_period=config.keep_period,
            create=True,
        )
        self._mgr = ocp.CheckpointManager(
            config.directory,
            options=options,
        )

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )
        return bool(saved)

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        """Restore into the structure/shardings of `state_like`."""
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"No checkpoint found in {self._config.directory}"
            )
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(state_like)
        )

    def restore_or_initialize(self, state_like: Any):
        """(state, step): restored latest, or the passed-in init at step 0.

        Mirrors `clu.checkpoint.restore_or_initialize` semantics
        (`language_table/train/train.py:125-127`): training resumes from
        `step + 1` after preemption.
        """
        latest = self._mgr.latest_step()
        if latest is None:
            return state_like, 0
        return self.restore(state_like, latest), int(latest)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def wait_until_finished(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()


def latest_step(ckpt_dir: str) -> Optional[int]:
    """Newest checkpoint step under `ckpt_dir`, or None — without building a
    CheckpointManager (cheap enough for CLI glue, watchdogs, and provenance
    stamping; Orbax step dirs are plain integer-named directories)."""
    import os

    if not os.path.isdir(ckpt_dir):
        return None
    steps = [int(d) for d in os.listdir(ckpt_dir) if d.isdigit()]
    return max(steps) if steps else None
