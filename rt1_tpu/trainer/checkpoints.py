"""Orbax checkpointing: save / restore-or-initialize / best-keep policy.

Replaces (SURVEY.md §5 checkpoint/resume):
* Stack A Lightning `ModelCheckpoint(save_top_k=-1, save_last=True,
  every_n_epochs)` (`distribute_train.py:214-220`),
* Stack B `clu.checkpoint.MultihostCheckpoint` + flax `save_checkpoint`
  with `keep_every_n_steps` (`language_table/train/train.py:122-138,201-217`).

Orbax is multihost-aware out of the box (each host writes its shards of a
sharded TrainState; restore lays arrays back out on the mesh), which is the
TPU-native replacement for clu's multihost rendezvous.

Plan migration (rt1_tpu/parallel/reshard.py, docs/parallelism.md
"Multi-host"): ``restore(plan=...)`` / ``restore_or_initialize(plan=...)``
restore a checkpoint saved under one sharding plan onto a different
mesh/plan — the template becomes abstract arrays carrying the TARGET
plan's shardings, so Orbax lays every global array out on the new mesh
(dense→fsdp, 4→8 devices, train-mesh→serve-replica) with a single-process
gather→slice fallback for Orbax versions that reject abstract templates.

Multi-process discipline: every process participates in save/restore
(Orbax coordinates the shard writes and the commit internally), but the
side-band artifacts OUR layer adds — the ``saved_under.json`` provenance
marker — are written by process 0 only, and the module-level
`latest_step` scan tolerates another host's in-progress Orbax tmp dirs
(proven under two real processes in tests/test_multiprocess.py).

Resilience (rt1_tpu/resilience/, docs/resilience.md): `CheckpointConfig.
retry` wraps save/restore in exponential-backoff retry so a transient
filesystem error degrades to a logged warning instead of killing the run;
`restore_or_initialize` survives a corrupt/partial latest step by falling
back to older retained steps (loudly); and the `ckpt_save`/`ckpt_restore`
fault-injection sites make both paths provable in tests and chaos runs.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, List, Optional

import orbax.checkpoint as ocp

from rt1_tpu.resilience import faults
from rt1_tpu.resilience.retry import RetryOptions, retry_call


@dataclasses.dataclass
class CheckpointConfig:
    directory: str
    max_to_keep: Optional[int] = None  # None = keep everything (save_top_k=-1)
    save_interval_steps: int = 1000
    keep_period: Optional[int] = None  # also keep every Nth (keep_every_n_steps)
    # Backoff schedule for transient I/O on save/restore; None = no retry
    # (one attempt, errors propagate — the pre-resilience behavior).
    retry: Optional[RetryOptions] = None
    # Observer for checkpoint I/O wall time: called as on_io(name, seconds)
    # with name "ckpt_save"/"ckpt_restore" after every logical operation
    # (retries included in the measured span, failures too — badput is
    # badput). The train loop hands the goodput ledger's note_io here
    # (rt1_tpu/obs/goodput.py); exceptions are swallowed — accounting must
    # never take down checkpointing.
    on_io: Optional[Callable[[str, float], None]] = None


class CheckpointManager:
    """Thin wrapper over ocp.CheckpointManager for TrainState pytrees."""

    def __init__(self, config: CheckpointConfig):
        self._config = config
        options = ocp.CheckpointManagerOptions(
            max_to_keep=config.max_to_keep,
            save_interval_steps=config.save_interval_steps,
            keep_period=config.keep_period,
            create=True,
        )
        self._mgr = ocp.CheckpointManager(
            config.directory,
            options=options,
        )
        # Logical-operation ordinals for fault injection: bumped once per
        # save/restore (NOT per retry attempt), so "ckpt_save@2" means the
        # 2nd save even when an earlier injected failure triggered retries.
        self._save_ops = 0
        self._restore_ops = 0

    def _io(self, fn, name: str):
        """Run an I/O closure, retried per the config (or once when off);
        reports the whole operation's wall time (all attempts) to `on_io`."""
        t0 = time.perf_counter()
        try:
            if self._config.retry is None:
                return fn()
            return retry_call(fn, options=self._config.retry, name=name)
        finally:
            if self._config.on_io is not None:
                try:
                    self._config.on_io(name, time.perf_counter() - t0)
                except Exception:  # noqa: BLE001 - accounting only
                    pass

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        self._save_ops += 1
        op = self._save_ops

        def _save():
            # Injection precedes the real write so a "transient" spec fires
            # once and the retry's next attempt genuinely succeeds. Indexed
            # by the logical save ordinal, not the attempt, so a spec's
            # extra fires (`x<K>`) land on consecutive RETRIES of the same
            # save rather than silently consuming later saves' occurrences.
            faults.maybe_fail("ckpt_save", index=op, what=f"save at step {step}")
            return self._mgr.save(
                step, args=ocp.args.StandardSave(state), force=force
            )

        saved = bool(self._io(_save, "ckpt_save"))
        if saved:
            self._write_provenance(step)
        return saved

    def _write_provenance(self, step: int) -> None:
        """`saved_under.json`: the topology this checkpoint was written
        from (process/device counts + newest step) — what `reshard` names
        in its diagnostics when a migrated restore fails, and the
        restore-on-a-different-slice post-mortem's first question. Process
        0 ONLY (the one multi-process rule for side-band files: N hosts
        racing one marker is how markers get torn), atomic tmp+rename,
        best-effort — provenance must never take down checkpointing."""
        import json
        import os

        import jax

        from rt1_tpu.parallel.distributed import is_primary

        if not is_primary():
            return
        try:
            path = os.path.join(self._config.directory, "saved_under.json")
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(
                    {
                        "step": int(step),
                        "process_count": int(jax.process_count()),
                        "device_count": int(jax.device_count()),
                        "local_device_count": int(jax.local_device_count()),
                        "written_at_unix": time.time(),
                    },
                    f,
                    indent=2,
                    sort_keys=True,
                )
            os.replace(tmp, path)
        except Exception:  # noqa: BLE001 - marker only
            pass

    def restore(
        self, state_like: Any, step: Optional[int] = None, plan: Any = None
    ) -> Any:
        """Restore into the structure/shardings of `state_like`.

        With ``plan`` (a `parallel.ShardingPlan`) the restore is a PLAN
        MIGRATION (parallel/reshard.py): `state_like` contributes only the
        tree structure and shapes/dtypes; placement comes from the target
        plan's rules, so a checkpoint saved under a different mesh/plan
        (dense→fsdp, 4→8 devices, pod→serve-replica) lands directly in the
        layout this process computes with. If this Orbax version rejects
        the abstract sharded template, a single-process gather→slice
        fallback restores into `state_like` and re-places through the plan
        (loudly — on a multi-host mesh the fallback raises instead).
        """
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"No checkpoint found in {self._config.directory}"
            )

        self._restore_ops += 1
        op = self._restore_ops

        def _restore():
            faults.maybe_fail(
                "ckpt_restore", index=op, what=f"restore step {step}"
            )
            if plan is None:
                return self._mgr.restore(
                    step, args=ocp.args.StandardRestore(state_like)
                )
            from rt1_tpu.parallel import reshard

            template = reshard.abstract_target(state_like, plan)
            try:
                return self._mgr.restore(
                    step, args=ocp.args.StandardRestore(template)
                )
            except (TypeError, ValueError, NotImplementedError) as exc:
                # Only template-shape rejections (an Orbax that cannot
                # take abstract sharded templates) — I/O and corruption
                # errors must propagate to restore_or_initialize's
                # older-step fallback WITHOUT a pointless second full
                # restore of the same broken step.
                import jax
                from absl import logging

                if jax.process_count() > 1:
                    raise  # a host cannot materialize other hosts' shards
                logging.warning(
                    "checkpoint: sharded (plan-target) restore of step %d "
                    "rejected (%s: %s) — falling back to host gather→slice",
                    step, type(exc).__name__, exc,
                )
                restored = self._mgr.restore(
                    step, args=ocp.args.StandardRestore(state_like)
                )
                return reshard.place_on_plan(restored, plan)

        return self._io(_restore, "ckpt_restore")

    def restore_or_initialize(self, state_like: Any, plan: Any = None):
        """(state, step): restored latest, or the passed-in init at step 0.

        Mirrors `clu.checkpoint.restore_or_initialize` semantics
        (`language_table/train/train.py:125-127`): training resumes from
        `step + 1` after preemption.

        Robust to a corrupt/partial newest step (half-written before a hard
        kill, truncated by a full disk): a failed restore logs loudly and
        falls back to the next-older retained step instead of wedging the
        relaunch; only when EVERY retained step fails does the original
        error propagate. ``plan`` passes through to :meth:`restore` — the
        resume path is plan-migrating too, so a run relaunched on a
        different slice shape restores the old slice's checkpoint directly
        into the new layout.
        """
        steps = sorted(self.all_steps(), reverse=True)
        if not steps:
            return state_like, 0
        last_exc: Optional[Exception] = None
        for step in steps:
            try:
                return self.restore(state_like, step, plan=plan), int(step)
            except Exception as exc:  # noqa: BLE001 - fall back per step
                from absl import logging

                last_exc = exc
                logging.error(
                    "checkpoint: restore of step %d in %s FAILED (%s: %s)%s",
                    step,
                    self._config.directory,
                    type(exc).__name__,
                    exc,
                    " — falling back to the previous retained step"
                    if step != steps[-1]
                    else " — no older step to fall back to",
                )
        raise last_exc

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> List[int]:
        """Retained step numbers (finalized only — Orbax skips tmp dirs)."""
        return [int(s) for s in self._mgr.all_steps()]

    def wait_until_finished(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()


def latest_step(ckpt_dir: str) -> Optional[int]:
    """Newest checkpoint step under `ckpt_dir`, or None — without building a
    CheckpointManager (cheap enough for CLI glue, watchdogs, and provenance
    stamping; Orbax step dirs are plain integer-named directories).

    Defensive against in-flight/aborted writes: Orbax tmp dirs
    (`<step>.orbax-checkpoint-tmp-<ts>`) fail the digit check, and a bare
    EMPTY integer-named directory (mkdir happened, contents never landed)
    is not a checkpoint either.
    """
    import os

    if not os.path.isdir(ckpt_dir):
        return None
    steps = []
    for d in os.listdir(ckpt_dir):
        if not d.isdigit():
            continue  # Orbax tmp dirs and sidecar files
        full = os.path.join(ckpt_dir, d)
        try:
            if not os.path.isdir(full) or not os.listdir(full):
                continue
        except OSError:
            continue
        steps.append(int(d))
    return max(steps) if steps else None
