"""MSE behavioral-cloning loss + optimizer freezing for the LAVA stack.

Parity source: reference `language_table/train/bc.py`:
* `bc_loss` (`:206-234`): MSE between predicted and target actions,
  normalized by action statistics when provided;
* Adam(eps=1e-7) with per-path freezing via `optax.multi_transform`
  (`:119-140`) — used to freeze the pretrained text/image towers;
* pretrained-checkpoint key remapping (`:94-110`) generalized to a
  prefix-rewrite over flat param paths.

These compose with the shared SPMD machinery (`rt1_tpu/trainer/train.py`):
pass `loss_fn=bc_mse_loss_fn(model)` style closures into jitted steps, or use
the generic train step with an MSE-returning model wrapper.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import flax
import jax
import jax.numpy as jnp
import optax


def bc_mse_loss(
    predicted: jnp.ndarray,
    target: jnp.ndarray,
    norm_mean: Optional[jnp.ndarray] = None,
    norm_std: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Mean-squared BC loss, optionally in normalized action space."""
    if norm_mean is not None and norm_std is not None:
        target = (target - norm_mean) / (norm_std + 1e-8)
    return jnp.mean(jnp.square(predicted - target))


def make_bc_optimizer(
    learning_rate: float = 1e-3,
    eps: float = 1e-7,
    frozen_prefixes: Sequence[str] = (),
) -> optax.GradientTransformation:
    """Adam with optional frozen parameter subtrees.

    `frozen_prefixes` are '/'-joined path prefixes into the param tree, e.g.
    ("encoder/TextEncoder_0",) — matching params get zero updates
    (reference freezes `TextEncoder_0`,
    `configs/language_table_sim_local.py:50-58`).
    """
    adam = optax.adam(learning_rate, eps=eps)
    if not frozen_prefixes:
        return adam

    def label(params):
        flat = flax.traverse_util.flatten_dict(params)
        labels = {}
        for path in flat:
            joined = "/".join(str(p) for p in path)
            # Match whole path segments: "enc/conv" must not freeze a
            # sibling like "enc/conv_extra".
            frozen = any(
                joined == prefix or joined.startswith(prefix + "/")
                for prefix in frozen_prefixes
            )
            labels[path] = "frozen" if frozen else "trainable"
        return flax.traverse_util.unflatten_dict(labels)

    return optax.multi_transform(
        {"trainable": adam, "frozen": optax.set_to_zero()}, label
    )


def remap_pretrained_params(
    params: Dict[str, Any],
    pretrained: Dict[str, Any],
    prefix_map: Dict[str, str],
) -> Dict[str, Any]:
    """Copy pretrained subtrees into params under new path prefixes.

    `prefix_map`: {pretrained_prefix: target_prefix} over '/'-joined flat
    paths (generalizes the reference's key rewriting, `bc.py:94-110`).
    Returns a new param tree; paths not covered keep their initialized
    values. Raises if a remapped source path is missing.
    """
    flat_params = flax.traverse_util.flatten_dict(params)
    flat_pre = flax.traverse_util.flatten_dict(pretrained)
    joined_pre = {
        "/".join(str(p) for p in k): (k, v) for k, v in flat_pre.items()
    }

    out = dict(flat_params)
    for src_prefix, dst_prefix in prefix_map.items():
        hits = 0
        for joined, (_, value) in joined_pre.items():
            if not joined.startswith(src_prefix):
                continue
            dst_joined = dst_prefix + joined[len(src_prefix):]
            dst_key = tuple(dst_joined.split("/"))
            if dst_key not in out:
                raise KeyError(
                    f"Remap target {dst_joined!r} not present in params"
                )
            if out[dst_key].shape != value.shape:
                raise ValueError(
                    f"Shape mismatch at {dst_joined!r}: "
                    f"{out[dst_key].shape} vs {value.shape}"
                )
            out[dst_key] = value
            hits += 1
        if hits == 0:
            raise KeyError(
                f"No pretrained params matched prefix {src_prefix!r}"
            )
    return flax.traverse_util.unflatten_dict(out)


def adapt_obs_for_lava(obs: Dict[str, Any]) -> Dict[str, Any]:
    """Windowed-pipeline observations -> LAVA's: rename `image` -> `rgb` and
    convert the wire dtype (uint8 by default since the H2D-bytes change) to
    the [0,1] floats LAVA's conv towers and ImageNet normalization expect —
    the same on-device conversion RT-1 does in `rt1.py::_preprocess`."""
    from rt1_tpu.ops.image import convert_dtype

    lava_obs = dict(obs)
    if "rgb" not in lava_obs and "image" in lava_obs:
        lava_obs["rgb"] = lava_obs.pop("image")
    if "rgb" in lava_obs:
        lava_obs["rgb"] = convert_dtype(lava_obs["rgb"])
    return lava_obs


def make_bc_step_loss_fn(model: Any) -> Callable:
    """LAVA/BC loss in the unified SPMD-step signature.

    Plugs a LAVA-family model into `make_train_step_fns(loss_fn=...)` — the
    equivalent of the reference Stack B training LAVA end to end
    (`language_table/train/train.py:105-116`). Adapts the windowed pipeline's
    observation keys (`image` -> `rgb`) and takes the LAST frame's action as
    the BC target (LAVA predicts one action per window).
    """

    def loss_fn(params, batch_stats, batch, rng, train):
        obs, actions = batch
        lava_obs = adapt_obs_for_lava(obs)
        target = actions["action"] if isinstance(actions, dict) else actions
        if target.ndim == 3:
            target = target[:, -1]
        variables = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats
        predicted = model.apply(
            variables,
            lava_obs,
            train=train,
            rngs={"dropout": rng} if train else {},
        )
        loss = bc_mse_loss(predicted, target)
        # The frozen resnet tower never updates batch_stats (always applied
        # with use_running_average), so stats pass through unchanged.
        return loss, ({"loss": loss}, batch_stats)

    return loss_fn


def make_bc_loss_fn(
    model: Any,
    batch_stats: Optional[Any] = None,
) -> Callable:
    """(params, batch, rng, train) -> (loss, metrics) for MSE-head models.

    `batch` = (observations, actions) where actions is either the raw (b, d)
    target array or a dict with an "action" entry (windowed pipeline format,
    in which case the LAST frame's action is the target — the LAVA models
    predict one action per window).

    `batch_stats`: the model's BatchNorm stats collection, required when the
    image tower uses BatchNorm (lava_image_encoder="resnet"). The tower is
    frozen (always applied with use_running_average), so stats are read-only
    and can be closed over.
    """

    def loss_fn(params, batch, rng, train=True):
        obs, actions = batch
        target = actions["action"] if isinstance(actions, dict) else actions
        if target.ndim == 3:
            target = target[:, -1]
        variables = {"params": params}
        if batch_stats is not None:
            variables["batch_stats"] = batch_stats
        predicted = model.apply(
            variables,
            obs,
            train=train,
            rngs={"dropout": rng} if train else {},
        )
        loss = bc_mse_loss(predicted, target)
        return loss, {"loss": loss}

    return loss_fn
