"""Optimizer & LR schedule parity with the reference.

Reference (`distribute_train.py:99-110`): `torch.optim.Adam(lr=args.lr)` (5e-4,
`:278`) + `MultiStepLR(milestones=[50, 75, 90], gamma=0.1)` stepped **per epoch**.
Here the schedule is expressed in optimizer steps (JAX schedules are step-indexed);
`multistep_lr` converts epoch milestones given steps-per-epoch.

Torch-Adam vs optax note: `optax.adam` defaults (b1=0.9, b2=0.999, eps=1e-8) match
`torch.optim.Adam` defaults, and optax's eps is applied like torch's (outside the
bias-corrected sqrt — `optax.scale_by_adam` uses eps_root=0 for the sqrt), so the
update rule is numerically equivalent.
"""

from __future__ import annotations

from typing import Optional, Sequence

import optax


def multistep_lr(
    base_lr: float,
    milestones: Sequence[int],
    gamma: float = 0.1,
    steps_per_epoch: int = 1,
) -> optax.Schedule:
    """torch `MultiStepLR` as an optax schedule (milestones in epochs)."""
    boundaries = {int(m) * steps_per_epoch: gamma for m in milestones}
    return optax.piecewise_constant_schedule(base_lr, boundaries)


def make_optimizer(
    learning_rate: float = 5e-4,
    milestones: Sequence[int] = (50, 75, 90),
    gamma: float = 0.1,
    steps_per_epoch: int = 1,
    grad_clip_norm: Optional[float] = None,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """Adam + MultiStepLR, with optional extras the reference lacks (clip, wd)."""
    schedule = multistep_lr(learning_rate, milestones, gamma, steps_per_epoch)
    parts = []
    if grad_clip_norm is not None:
        parts.append(optax.clip_by_global_norm(grad_clip_norm))
    if weight_decay:
        parts.append(optax.add_decayed_weights(weight_decay))
    parts.append(optax.adam(schedule))
    return optax.chain(*parts) if len(parts) > 1 else parts[0]
