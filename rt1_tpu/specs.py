"""Tensor/action space specs.

The reference uses `gym.spaces.Dict` throughout the model core
(`pytorch_robotics_transformer/transformer_network.py:40-41`,
`tokenizers/action_tokenizer.py:68-98`). Gym spaces are host-Python objects with
numpy state — fine at the environment boundary, but inside a jitted TPU program we
want hashable, static pytree-free metadata. These dataclasses carry the same
information (bounds, shape, cardinality) as frozen, hashable Python objects that can
be closed over by `jax.jit` without retracing hazards.

`sample_spec`/`sample_space` replace the reference's `batched_space_sampler`
(`tokenizers/utils.py:8-18`), which fabricates random network_state/action batches
for tests and the training path.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Mapping, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class DiscreteSpec:
    """A categorical value in [0, n). Mirrors `gym.spaces.Discrete(n)`."""

    n: int

    @property
    def shape(self) -> Tuple[int, ...]:
        return ()

    @property
    def dtype(self):
        return jnp.int32


@dataclasses.dataclass(frozen=True)
class BoxSpec:
    """A bounded continuous vector. Mirrors 1-D `gym.spaces.Box`.

    `low`/`high` are tuples (hashable) broadcastable to `shape`. Only rank-1 boxes
    are tokenizable, matching the reference's restriction
    (`tokenizers/action_tokenizer.py:92-95`).
    """

    low: Tuple[float, ...]
    high: Tuple[float, ...]
    shape: Tuple[int, ...]

    def __post_init__(self):
        if len(self.low) not in (1, int(np.prod(self.shape)) if self.shape else 1):
            raise ValueError(f"low {self.low} not broadcastable to {self.shape}")
        if len(self.high) not in (1, int(np.prod(self.shape)) if self.shape else 1):
            raise ValueError(f"high {self.high} not broadcastable to {self.shape}")

    @property
    def dtype(self):
        return jnp.float32

    def low_array(self) -> np.ndarray:
        return np.broadcast_to(np.asarray(self.low, np.float32), self.shape)

    def high_array(self) -> np.ndarray:
        return np.broadcast_to(np.asarray(self.high, np.float32), self.shape)


@dataclasses.dataclass(frozen=True)
class ImageSpec:
    """An image observation; values in [0, 1] (or uint8 [0,255] pre-normalization).

    NOTE: TPU-native layout is NHWC (height, width, channels) — the reference is
    NCHW (`transformer_network.py:424`); layout conversion happens at the data/env
    boundary, never inside the model.
    """

    height: int
    width: int
    channels: int = 3

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.height, self.width, self.channels)

    @property
    def dtype(self):
        return jnp.float32


@dataclasses.dataclass(frozen=True)
class VectorSpec:
    """An unbounded float vector (e.g. a 512-d language embedding)."""

    size: int

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.size,)

    @property
    def dtype(self):
        return jnp.float32


Spec = Union[DiscreteSpec, BoxSpec, ImageSpec, VectorSpec]
SpecDict = Mapping[str, Spec]


def sample_spec(spec: Spec, rng: jax.Array, batch_shape: Tuple[int, ...] = ()):
    """Sample a random value of `spec` with leading `batch_shape` dims.

    Replaces `batched_space_sampler` + `np_to_tensor`
    (`tokenizers/utils.py:8-26`) — returns device arrays directly.
    """
    if isinstance(spec, DiscreteSpec):
        return jax.random.randint(rng, batch_shape, 0, spec.n, dtype=jnp.int32)
    if isinstance(spec, BoxSpec):
        lo = jnp.asarray(spec.low_array())
        hi = jnp.asarray(spec.high_array())
        u = jax.random.uniform(rng, batch_shape + spec.shape, jnp.float32)
        return lo + u * (hi - lo)
    if isinstance(spec, (ImageSpec, VectorSpec)):
        return jax.random.uniform(rng, batch_shape + spec.shape, jnp.float32)
    raise TypeError(f"unknown spec {spec!r}")


def sample_space(space: SpecDict, rng: jax.Array, batch_shape: Tuple[int, ...] = ()) -> Dict[str, jax.Array]:
    """Sample every entry of a spec dict (ordered, like the reference's OrderedDict)."""
    rngs = jax.random.split(rng, len(space))
    return {k: sample_spec(s, r, batch_shape) for (k, s), r in zip(space.items(), rngs)}


# ---------------------------------------------------------------------------
# Canonical Language-Table spaces (reference: distribute_train.py:28-55).
# ---------------------------------------------------------------------------

def language_table_observation_space(height: int = 256, width: int = 456) -> Dict[str, Spec]:
    return {
        "image": ImageSpec(height=height, width=width, channels=3),
        "natural_language_embedding": VectorSpec(512),
    }


def language_table_action_space() -> Dict[str, Spec]:
    # Order matters for tokenization (action_tokenizer.py:81). The reference uses
    # OrderedDict([('terminate_episode', Discrete(2)), ('action', Box(-0.1, 0.1, (2,)))])
    # (distribute_train.py:40-46) → tokens_per_action == 3.
    return {
        "terminate_episode": DiscreteSpec(2),
        "action": BoxSpec(low=(-0.1,), high=(0.1,), shape=(2,)),
    }


def rt1_generic_action_space() -> Dict[str, Spec]:
    # The 4-key generic RT-1 action space used by the reference's network tests
    # (transformer_network_test_set_up.py:79-110) → tokens_per_action == 8.
    return {
        "terminate_episode": DiscreteSpec(2),
        "world_vector": BoxSpec(low=(-1.0,), high=(1.0,), shape=(3,)),
        "rotation_delta": BoxSpec(low=(-np.pi / 2.0,), high=(np.pi / 2.0,), shape=(3,)),
        "gripper_closedness_action": BoxSpec(low=(-1.0,), high=(1.0,), shape=(1,)),
    }
