"""CLIP byte-pair-encoding tokenizer, pure Python.

Parity source: reference `language_table/common/clip_tokenizer.py:42-152` —
an in-graph TF reimplementation of OpenAI CLIP's SimpleTokenizer used to
feed the LAVA text tower. Ours implements the same algorithm (byte-unicode
mapping, greedy lowest-rank BPE merges, `</w>` word terminals, the CLIP
regex split, SOT/EOT framing, zero-padded 77-token context) without the TF
/ tensorflow_text / `clip` package dependencies.

The real CLIP vocabulary (`bpe_simple_vocab_16e6.txt.gz`) is not bundled in
this image; pass its path to `ClipBPETokenizer.from_bpe_file` when
available. The tokenizer also accepts any custom merge list, which the tests
use to verify the algorithm.
"""

import functools
import gzip
import html
import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

try:
    import regex as _re  # Unicode \p{L}/\p{N} classes, like CLIP's regex
except ImportError:  # pragma: no cover - regex ships with transformers
    _re = None

CLIP_VOCAB_SIZE = 49408
CLIP_CONTEXT_LENGTH = 77

# CLIP SimpleTokenizer's split pattern (contractions, letters, digits,
# punctuation runs). With the `regex` module the Unicode property classes
# match CLIP exactly; the stdlib-`re` fallback is ASCII-only (non-Latin
# letters fall into the punctuation class).
_RAW_PATTERN = r"""<\|startoftext\|>|<\|endoftext\|>|'s|'t|'re|'ve|'m|'ll|'d|[\p{L}]+|[\p{N}]|[^\s\p{L}\p{N}]+"""
if _re is not None:
    _PATTERN = _re.compile(_RAW_PATTERN, _re.IGNORECASE)
else:
    _PATTERN = re.compile(
        _RAW_PATTERN.replace(r"\p{L}", "a-zA-Z").replace(r"\p{N}", "0-9"),
        re.IGNORECASE,
    )


@functools.lru_cache()
def bytes_to_unicode() -> Dict[int, str]:
    """Reversible byte -> printable-unicode mapping (BPE works on these)."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, (chr(c) for c in cs)))


def _get_pairs(word: Tuple[str, ...]):
    return {(word[i], word[i + 1]) for i in range(len(word) - 1)}


class ClipBPETokenizer:
    """Greedy lowest-rank BPE with CLIP's word-terminal convention."""

    def __init__(
        self,
        merges: Sequence[Tuple[str, str]],
        context_length: int = CLIP_CONTEXT_LENGTH,
    ):
        self.byte_encoder = bytes_to_unicode()
        self.byte_decoder = {v: k for k, v in self.byte_encoder.items()}
        # Vocabulary layout matches CLIP exactly: 256 byte symbols, their
        # </w> variants, one entry per merge, then SOT/EOT
        # (reference `create_vocab`, clip_tokenizer.py:117-135).
        vocab: List[str] = list(bytes_to_unicode().values())
        vocab = vocab + [v + "</w>" for v in vocab]
        vocab.extend("".join(m) for m in merges)
        vocab.extend(["<|startoftext|>", "<|endoftext|>"])
        self.encoder = {tok: i for i, tok in enumerate(vocab)}
        self.decoder = {i: tok for tok, i in self.encoder.items()}
        self.bpe_ranks = {tuple(m): i for i, m in enumerate(merges)}
        self.context_length = context_length
        self.sot_token = self.encoder["<|startoftext|>"]
        self.eot_token = self.encoder["<|endoftext|>"]
        self._cache = {
            "<|startoftext|>": "<|startoftext|>",
            "<|endoftext|>": "<|endoftext|>",
        }

    @classmethod
    def from_bpe_file(cls, path: str, **kwargs) -> "ClipBPETokenizer":
        """Load the standard CLIP `bpe_simple_vocab_16e6.txt.gz`."""
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            lines = f.read().decode("utf-8").split("\n")
        merges = lines[1 : 49152 - 256 - 2 + 1]
        merges = [tuple(m.split()) for m in merges]
        return cls(merges, **kwargs)

    @property
    def vocab_size(self) -> int:
        return len(self.encoder)

    def _bpe(self, token: str) -> str:
        cached = self._cache.get(token)
        if cached is not None:
            return cached
        word = tuple(token[:-1]) + (token[-1] + "</w>",)
        pairs = _get_pairs(word)
        if not pairs:
            return token + "</w>"
        while True:
            bigram = min(
                pairs, key=lambda p: self.bpe_ranks.get(p, float("inf"))
            )
            if bigram not in self.bpe_ranks:
                break
            first, second = bigram
            new_word: List[str] = []
            i = 0
            while i < len(word):
                try:
                    j = word.index(first, i)
                except ValueError:
                    new_word.extend(word[i:])
                    break
                new_word.extend(word[i:j])
                i = j
                if (
                    word[i] == first
                    and i < len(word) - 1
                    and word[i + 1] == second
                ):
                    new_word.append(first + second)
                    i += 2
                else:
                    new_word.append(word[i])
                    i += 1
            word = tuple(new_word)
            if len(word) == 1:
                break
            pairs = _get_pairs(word)
        out = " ".join(word)
        self._cache[token] = out
        return out

    def encode(self, text: str) -> List[int]:
        """Text -> BPE token ids (no SOT/EOT framing)."""
        # Cleaning parity with SimpleTokenizer: unescape HTML (the in-graph
        # TF version can't, clip_tokenizer.py:73-76 — we can), collapse
        # whitespace, lowercase.
        text = html.unescape(html.unescape(text))
        text = re.sub(r"\s+", " ", text).strip().lower()
        ids: List[int] = []
        for token in _PATTERN.findall(text):
            token_bytes = "".join(
                self.byte_encoder[b] for b in token.encode("utf-8")
            )
            for piece in self._bpe(token_bytes).split(" "):
                ids.append(self.encoder[piece])
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        text = "".join(
            self.decoder[i]
            for i in ids
            if i not in (self.sot_token, self.eot_token)
        )
        # '</w>' survives byte-decoding (its chars are all in the byte map);
        # swap it for a space afterwards, like CLIP's SimpleTokenizer.decode.
        raw = bytearray(self.byte_decoder[c] for c in text)
        return (
            raw.decode("utf-8", errors="replace")
            .replace("</w>", " ")
            .strip()
        )

    def tokenize_text(
        self, texts, context_length: Optional[int] = None
    ) -> np.ndarray:
        """[str] -> (n, 77) int32, SOT + ids + EOT, zero padded
        (reference `tokenize_text`, clip_tokenizer.py:138-152)."""
        if isinstance(texts, str):
            texts = [texts]
        context_length = context_length or self.context_length
        out = np.zeros((len(texts), context_length), np.int32)
        for row, text in enumerate(texts):
            ids = [self.sot_token] + self.encode(text) + [self.eot_token]
            if len(ids) > context_length:
                raise ValueError(
                    f"Input too long ({len(ids)} > {context_length}): "
                    f"{text!r}"
                )
            out[row, : len(ids)] = ids
        return out


def default_tokenizer(context_length: int = CLIP_CONTEXT_LENGTH) -> ClipBPETokenizer:
    """Byte-level CLIP tokenizer (no merges): 514-entry vocab of byte symbols
    + SOT/EOT, every word split into its byte</w> sequence.

    This is the zero-asset fallback — the exact CLIP framing and special
    tokens, but each character costs one token, so only short instructions
    fit in 77 (Language-Table's longest grammar strings do). For parity with
    public CLIP checkpoints load the real merges via `from_bpe_file`.
    """
    return ClipBPETokenizer(merges=[], context_length=context_length)
