"""Text tokenization utilities."""

from rt1_tpu.text.clip_bpe import (
    CLIP_CONTEXT_LENGTH,
    CLIP_VOCAB_SIZE,
    ClipBPETokenizer,
    bytes_to_unicode,
)

__all__ = [
    "CLIP_CONTEXT_LENGTH",
    "CLIP_VOCAB_SIZE",
    "ClipBPETokenizer",
    "bytes_to_unicode",
]
