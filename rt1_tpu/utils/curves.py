"""Training-curve IO: parse clu/TensorBoard event files, plot loss curves.

Extracted from `scripts/learn_proof.py` (VERDICT r4 weak #7). The
reference publishes its converged loss curve as a screenshot
(`/root/reference/README.md:55-59`, `assets/train_log.jpg`); here the curve
is re-derived from the run's own event files so the artifact is
reproducible from the workdir alone.
"""

from __future__ import annotations

import glob
import os


def read_scalar_curves(train_dir: str, tags=("loss", "eval_loss")) -> dict:
    """Parse scalar series from the clu TensorBoard events under
    `train_dir`. Returns {tag: [(step, value), ...] sorted by step}."""
    import tensorflow as tf

    curves = {tag: [] for tag in tags}
    for path in sorted(glob.glob(os.path.join(train_dir, "events.*"))):
        for event in tf.compat.v1.train.summary_iterator(path):
            for value in event.summary.value:
                if value.tag in curves:
                    t = tf.make_ndarray(value.tensor) if value.HasField(
                        "tensor") else value.simple_value
                    curves[value.tag].append((event.step, float(t)))
    return {k: sorted(v) for k, v in curves.items()}


def plot_loss_curves(curves: dict, path: str,
                     title: str = "training loss") -> None:
    """Log-scale loss plot of `read_scalar_curves` output to `path`."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(7, 4))
    for tag, series in curves.items():
        if series:
            steps, vals = zip(*series)
            ax.plot(steps, vals, label=tag)
    ax.set_xlabel("step")
    ax.set_ylabel("loss")
    ax.set_yscale("log")
    ax.legend()
    ax.set_title(title)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
