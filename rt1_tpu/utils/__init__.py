"""Cross-cutting utilities: proof-artifact archiving, training-curve IO."""

from rt1_tpu.utils.artifacts import archive_file, copy_proof_videos
from rt1_tpu.utils.curves import plot_loss_curves, read_scalar_curves

__all__ = [
    "archive_file",
    "copy_proof_videos",
    "plot_loss_curves",
    "read_scalar_curves",
]
