"""Proof-artifact archiving: copy run outputs into a committable directory
without ever clobbering an earlier round's record.

Extracted from `scripts/learn_proof.py` (VERDICT r4 weak #7). The
no-overwrite discipline exists because unattended pipeline runs re-invoke
stages with the same --run_tag after crashes; a rerun must add a sibling,
not silently replace committed evidence.
"""

from __future__ import annotations

import glob
import os
import shutil


def archive_file(src: str, artifacts_dir: str, dest_name: str) -> str | None:
    """Copy `src` to `<artifacts_dir>/<dest_name>`, uniquifying on conflict
    (`name-1.ext`, `name-2.ext`, ...). Returns the destination path, or
    None when `src` does not exist."""
    if not os.path.exists(src):
        return None
    dest = os.path.join(artifacts_dir, dest_name)
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    stem, ext = os.path.splitext(dest)
    n = 1
    while os.path.exists(dest):
        dest = f"{stem}-{n}{ext}"
        n += 1
    shutil.copy2(src, dest)
    return dest


def copy_proof_videos(video_dir: str, artifacts_dir: str, prefix: str,
                      max_videos: int = 3) -> list[str]:
    """Stage up to `max_videos` episode videos (successes preferred) into
    `<artifacts_dir>/learn_proof_videos/`, prefixed so reruns/rounds never
    clobber earlier proof records. Returns the archived paths."""
    if not os.path.isdir(video_dir):
        return []
    vids = sorted(glob.glob(os.path.join(video_dir, "*success*"))) + sorted(
        glob.glob(os.path.join(video_dir, "*failure*"))
    )
    out = []
    for src in vids[:max_videos]:
        dest = archive_file(
            src, artifacts_dir,
            os.path.join(
                "learn_proof_videos", f"{prefix}_{os.path.basename(src)}"
            ),
        )
        if dest:
            out.append(dest)
    return out
