"""Serve-side episode capture: sessions become training episodes.

`EpisodeCaptureSink` hangs off the serving app (`--capture_dir`, OFF by
default): every successful `/act` appends one step — the uint8 frame the
client sent, the de-normalized action the policy answered, its action
tokens, and the instruction (embedding, or text embedded once at finalize)
— to that session's buffer, and a session END writes the buffer as a
standard episode `.npz` (`rt1_tpu/data/episodes.py` schema: rgb / action /
is_first / is_terminal / instruction, plus `action_tokens`, the `task` id,
and the `outcome` that ended it). The files are exactly what
`data/pack.py::append_shard` packs and what `data/convert_rlds.py` /
`data/collect.py` consumers already read — captured traffic re-enters
training with zero new formats.

A session ends when the client `/release`s or `/reset`s it, when the
policy emits `terminate_episode`, when the engine's LRU reclaim started it
a fresh window (`session_started` on an already-open buffer), when the
open-session bound evicts the oldest buffer, or at drain.

Bounded everywhere, opt-in everywhere: `max_steps` caps a runaway
session's buffer (further steps are counted and dropped), `max_episodes`
is a disk ring (oldest capture files pruned), `max_open_sessions` caps
buffer memory, and a `None` sink (the default) leaves the serve path
byte-identical — the hot path pays one `is None` check. Writes are
tmp+rename atomic so the packer/sweeper never reads a torn file, and a
failed write (full disk; chaos site `capture_write@N`) drops that episode
and keeps serving.

Privacy note (docs/serving.md): capture records client-sent observations.
It is OFF unless an operator passes `--capture_dir`, and the bounds above
are also retention bounds.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from rt1_tpu.data import episodes as ep_lib
from rt1_tpu.resilience import faults

EPISODE_PREFIX = "episode_"


class _SessionBuffer:
    __slots__ = (
        "images", "actions", "tokens", "embeddings", "texts", "task",
        "terminates", "dropped_steps", "opened_unix",
    )

    def __init__(self):
        self.images: List[np.ndarray] = []
        self.actions: List[np.ndarray] = []
        self.tokens: List[np.ndarray] = []
        self.embeddings: List[Optional[np.ndarray]] = []
        self.texts: List[Optional[str]] = []
        self.task: Optional[str] = None
        self.terminates: List[bool] = []
        self.dropped_steps = 0
        self.opened_unix = time.time()


class EpisodeCaptureSink:
    """Bounded, opt-in sink turning served sessions into episode files."""

    def __init__(
        self,
        capture_dir: str,
        *,
        max_episodes: int = 512,
        max_steps: int = 512,
        min_steps: int = 2,
        max_open_sessions: int = 64,
        embed_fn: Optional[Callable[[str], np.ndarray]] = None,
    ):
        if max_episodes < 1 or max_steps < 1 or max_open_sessions < 1:
            raise ValueError(
                "capture bounds must be >= 1 "
                f"(max_episodes={max_episodes}, max_steps={max_steps}, "
                f"max_open_sessions={max_open_sessions})"
            )
        self.capture_dir = capture_dir
        self.max_episodes = max_episodes
        self.max_steps = max_steps
        self.min_steps = min_steps
        self.max_open_sessions = max_open_sessions
        self._embed_fn = embed_fn
        self._embed_cache: Dict[str, np.ndarray] = {}
        os.makedirs(capture_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._buffers: Dict[str, _SessionBuffer] = {}
        # File names must be unique across replicas (whose captures meet
        # in one staging dir) and across sink generations: pid alone
        # collides for two sinks in one process, so add a random token.
        self._token = f"{os.getpid()}_{os.urandom(3).hex()}"
        self._seq = 0
        self._writes = 0  # write ATTEMPTS (the capture_write fault index)
        # Disk ring: adopt files from a previous sink generation (a
        # respawned replica) oldest-first so the bound covers them too.
        # The mtime key must tolerate a file vanishing between listdir and
        # stat — the fleet sweep moves completed files concurrently, and a
        # raced stat must not crash the replica at startup.
        def _mtime(path: str) -> float:
            try:
                return os.path.getmtime(path)
            except OSError:
                return 0.0

        self._ring: List[str] = sorted(
            (
                os.path.join(capture_dir, f)
                for f in os.listdir(capture_dir)
                if f.startswith(EPISODE_PREFIX) and f.endswith(".npz")
            ),
            key=_mtime,
        )
        # Counters (read lock-free by stats()).
        self.episodes_total = 0
        self.steps_total = 0
        self.dropped_steps_total = 0
        self.dropped_episodes_total = 0
        self.write_errors_total = 0
        self.pruned_total = 0

    # ------------------------------------------------------------ recording

    def record_step(
        self,
        session_id: str,
        *,
        image: np.ndarray,
        action: Sequence[float],
        action_tokens: Optional[Sequence[int]] = None,
        embedding: Optional[np.ndarray] = None,
        instruction: Optional[str] = None,
        task: Optional[str] = None,
        session_started: bool = False,
        terminate: bool = False,
    ) -> None:
        """Append one served step; never raises into the request path.

        `image` is the float [0, 1] (H, W, 3) frame the engine saw (or
        already uint8); `session_started` on an open buffer means the
        engine gave this session a fresh window (LRU eviction) — the old
        buffer is finalized as its own episode first.
        """
        try:
            self._record_step(
                session_id, image, action, action_tokens, embedding,
                instruction, task, session_started, terminate,
            )
        except Exception:  # noqa: BLE001 - capture must not fail serving
            with self._lock:
                self.write_errors_total += 1
                self._buffers.pop(session_id, None)

    def _record_step(
        self, session_id, image, action, action_tokens, embedding,
        instruction, task, session_started, terminate,
    ) -> None:
        image = np.asarray(image)
        if image.dtype != np.uint8:
            # Round-trips exactly for frames that arrived as raw uint8
            # (`image_b64`), quantizes float-list payloads once.
            image = np.clip(np.rint(image * 255.0), 0, 255).astype(np.uint8)
        flush = None
        expired = None
        with self._lock:
            buf = self._buffers.get(session_id)
            if buf is not None and session_started:
                # The engine reclaimed this session's slot and restarted
                # its window — what we buffered is a complete episode of
                # its own, not a prefix of the new one.
                flush = self._buffers.pop(session_id)
            buf = self._buffers.get(session_id)
            if buf is None:
                if len(self._buffers) >= self.max_open_sessions:
                    # Oldest open buffer pays for the bound; it still has
                    # real served steps, so it is written, not dropped.
                    oldest = min(
                        self._buffers,
                        key=lambda s: self._buffers[s].opened_unix,
                    )
                    expired = self._buffers.pop(oldest)
                buf = _SessionBuffer()
                self._buffers[session_id] = buf
            if buf.task is None and task:
                buf.task = task
            if len(buf.images) >= self.max_steps:
                buf.dropped_steps += 1
                self.dropped_steps_total += 1
            else:
                buf.images.append(image)
                buf.actions.append(
                    np.asarray(action, np.float32).reshape(-1)
                )
                buf.tokens.append(
                    np.asarray(action_tokens, np.int64).reshape(-1)
                    if action_tokens is not None
                    else np.zeros((0,), np.int64)
                )
                buf.embeddings.append(
                    np.asarray(embedding, np.float32).reshape(-1)
                    if embedding is not None
                    else None
                )
                buf.texts.append(instruction)
                buf.terminates.append(bool(terminate))
            done = None
            if terminate:
                done = self._buffers.pop(session_id, None)
        if expired is not None:
            self._write_episode(expired, "expired")
        if flush is not None:
            self._write_episode(flush, "evicted")
        if done is not None:
            self._write_episode(done, "terminated")

    def finalize(self, session_id: str, outcome: str) -> bool:
        """Close a session's buffer and write it (release/reset paths).
        Returns True when an episode file was written."""
        with self._lock:
            buf = self._buffers.pop(session_id, None)
        if buf is None:
            return False
        return self._write_episode(buf, outcome)

    def drain(self) -> int:
        """Finalize every open session (serve shutdown); returns writes."""
        with self._lock:
            buffers = list(self._buffers.values())
            self._buffers.clear()
        return sum(
            1 for buf in buffers if self._write_episode(buf, "drain")
        )

    @property
    def open_sessions(self) -> int:
        return len(self._buffers)

    # ------------------------------------------------------------ writing

    def _resolve_embeddings(
        self, buf: _SessionBuffer
    ) -> Optional[np.ndarray]:
        """(T, D) float32 instruction member, or None when unresolvable."""
        dim = next(
            (e.shape[0] for e in buf.embeddings if e is not None), None
        )
        rows: List[Optional[np.ndarray]] = []
        for emb, text in zip(buf.embeddings, buf.texts):
            if emb is None and text is not None and self._embed_fn is not None:
                cached = self._embed_cache.get(text)
                if cached is None:
                    cached = np.asarray(
                        self._embed_fn(text), np.float32
                    ).reshape(-1)
                    # Tiny per-process cache: capture traffic repeats a
                    # handful of instructions per workload.
                    if len(self._embed_cache) < 1024:
                        self._embed_cache[text] = cached
                emb = cached
            rows.append(emb)
            if emb is not None and dim is None:
                dim = emb.shape[0]
        if dim is None:
            return None
        # A step that carried neither embedding nor embeddable text rides
        # its neighbors' instruction (sessions serve one instruction).
        fallback = next((r for r in rows if r is not None), None)
        if fallback is None:
            return None
        return np.stack(
            [r if r is not None else fallback for r in rows]
        ).astype(np.float32)

    def _write_episode(self, buf: _SessionBuffer, outcome: str) -> bool:
        t = len(buf.images)
        if t < self.min_steps:
            with self._lock:
                self.dropped_episodes_total += 1
            return False
        instruction = self._resolve_embeddings(buf)
        if instruction is None:
            # No embedding and no way to derive one: the episode cannot
            # carry the task specification training needs.
            with self._lock:
                self.dropped_episodes_total += 1
            return False
        is_first = np.zeros((t,), bool)
        is_first[0] = True
        ep = {
            "rgb": np.stack(buf.images),
            "action": np.stack(buf.actions),
            "is_first": is_first,
            # Honest terminal labels: only a policy-emitted terminate (or
            # nothing) — an outcome like "released" is provenance, not a
            # terminate-token training label.
            "is_terminal": np.asarray(buf.terminates, bool),
            "instruction": instruction,
            "outcome": ep_lib.encode_instruction_text(outcome),
        }
        token_dims = {tok.shape[0] for tok in buf.tokens}
        if len(token_dims) == 1 and 0 not in token_dims:
            ep["action_tokens"] = np.stack(buf.tokens)
        if buf.task:
            ep["task"] = ep_lib.encode_instruction_text(buf.task)
        text = next((x for x in buf.texts if x), None)
        if text:
            ep["instruction_text"] = ep_lib.encode_instruction_text(text)
        with self._lock:
            self._writes += 1
            ordinal = self._writes
            self._seq += 1
            name = f"{EPISODE_PREFIX}{self._token}_{self._seq:06d}.npz"
        path = os.path.join(self.capture_dir, name)
        tmp = os.path.join(self.capture_dir, f".tmp_{name}")
        try:
            faults.maybe_fail("capture_write", index=ordinal, what=path)
            ep_lib.validate_episode(ep)
            with open(tmp, "wb") as f:
                np.savez(f, **ep)
            os.replace(tmp, path)
        except (OSError, ValueError, KeyError):
            with self._lock:
                self.write_errors_total += 1
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False
        with self._lock:
            self.episodes_total += 1
            self.steps_total += t
            self._ring.append(path)
            pruned = []
            while len(self._ring) > self.max_episodes:
                pruned.append(self._ring.pop(0))
        for old in pruned:
            try:
                os.remove(old)
            except OSError:
                continue
            with self._lock:
                self.pruned_total += 1
        return True

    # --------------------------------------------------------------- gauges

    def stats(self) -> Dict[str, float]:
        """Serve-metrics gauges (`rt1_serve_capture_*` families)."""
        return {
            "capture_enabled": 1,
            "capture_episodes_total": self.episodes_total,
            "capture_steps_total": self.steps_total,
            "capture_dropped_episodes_total": self.dropped_episodes_total,
            "capture_dropped_steps_total": self.dropped_steps_total,
            "capture_write_errors_total": self.write_errors_total,
            "capture_pruned_total": self.pruned_total,
            "capture_open_sessions": self.open_sessions,
        }


def capture_files(capture_dir: str) -> List[str]:
    """Completed (atomically renamed) capture episode files, sorted."""
    try:
        names = os.listdir(capture_dir)
    except OSError:
        return []
    return sorted(
        os.path.join(capture_dir, f)
        for f in names
        if f.startswith(EPISODE_PREFIX) and f.endswith(".npz")
    )


def sweep_captures(src_dirs: Sequence[str], staging_dir: str) -> int:
    """Move completed capture files from per-replica dirs into one staging
    dir (the fleet supervisor's sweep; `append_shard` packs staging).

    Same-filesystem renames, so a file is either fully in staging or still
    in its replica dir; basenames are already unique per writer process
    (pid + sequence). Returns the number of files moved.
    """
    os.makedirs(staging_dir, exist_ok=True)
    moved = 0
    for src in src_dirs:
        for path in capture_files(src):
            dst = os.path.join(staging_dir, os.path.basename(path))
            try:
                os.replace(path, dst)
                moved += 1
            except OSError:
                continue  # vanished mid-sweep / cross-device: next pass
    return moved
