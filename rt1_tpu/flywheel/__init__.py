"""rt1_tpu.flywheel — serve traffic back into the training corpus.

The data flywheel closes the collect -> train -> serve loop (docs/data.md
"Sharded pack format v2 & the flywheel"): served sessions are the corpus.

* :mod:`rt1_tpu.flywheel.capture` — the serve-side episode-capture sink:
  an opt-in, bounded ring of completed sessions written as standard
  episode `.npz` files (`rt1_tpu/data/episodes.py` schema), plus the
  fleet sweep that funnels per-replica capture dirs into one staging dir.
* `rt1_tpu/data/pack.py::append_shard` — turns a staging dir into a new
  pack shard with an atomically bumped `freshness_epoch`.
* `rt1_tpu/data/feeder.py::SampleAheadFeeder(refresh_at_epoch=True)` —
  a running train job picks the new shard up at the next epoch boundary,
  no restart.

Import hygiene matches `rt1_tpu.obs`: stdlib + numpy only at module scope —
the capture sink runs inside serve replicas and the sweep inside the
model-free fleet supervisor (pinned by tests/test_obs_imports.py).
"""

from rt1_tpu.flywheel.capture import EpisodeCaptureSink, sweep_captures

__all__ = ["EpisodeCaptureSink", "sweep_captures"]
