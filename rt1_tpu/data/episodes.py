"""Episode record format and storage.

An episode is a dict of arrays stacked over time (T steps):

* ``rgb``          (T, H, W, 3) uint8 — raw simulator frames (180×320 for
  Language-Table, `environments/constants.py:46-47`)
* ``action``       (T, 2) float32 — 2-D effector deltas
* ``is_first``     (T,) bool
* ``is_terminal``  (T,) bool
* ``instruction``  (T, 512) float32 — USE embedding of the instruction
  (`rlds_np_convert.py:28`), or (T, L) int32 raw encoded bytes pre-embedding

Optional keys:

* ``instruction_text`` (L,) uint8 — the raw instruction as UTF-8 bytes
  (`encode_instruction_text`). Stored as bytes, not a unicode array, so the
  native C++ reader's numeric-dtype fast path still covers the whole file.
  Enables re-embedding with a different provider and in-pipeline CLIP BPE
  tokenization for the LAVA "clip" language encoder.

Stored as one compressed-free `.npz` per episode (zero-copy mmap-able, no pickle),
vs the reference's pickled list-of-dicts `.npy` (`rlds_np_convert.py:31`) which
must be fully unpickled per access. `read_reference_episode` reads that legacy
format for drop-in compatibility with already-converted datasets.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np

Episode = Dict[str, np.ndarray]

REQUIRED_KEYS = ("rgb", "action", "is_first", "is_terminal", "instruction")


def encode_instruction_text(text: str) -> np.ndarray:
    """Instruction string -> (L,) uint8 UTF-8 bytes (native-reader friendly)."""
    return np.frombuffer(text.encode("utf-8"), np.uint8).copy()


def decode_instruction_text(arr: np.ndarray) -> str:
    return bytes(np.asarray(arr, np.uint8)).decode("utf-8")


def validate_episode(ep: Episode) -> None:
    for k in REQUIRED_KEYS:
        if k not in ep:
            raise KeyError(f"episode missing key {k!r}; has {sorted(ep)}")
    t = ep["rgb"].shape[0]
    for k in REQUIRED_KEYS:
        if ep[k].shape[0] != t:
            raise ValueError(f"{k} has {ep[k].shape[0]} steps, rgb has {t}")


def save_episode(path: str, ep: Episode) -> None:
    validate_episode(ep)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez(path, **ep)


def load_episode(path: str) -> Episode:
    """Load an episode, preferring the native C++ reader when built.

    The native path (native/episode_reader.cc via ctypes) mmaps the file and
    parses npy/npz headers in C++ — one syscall + header parse instead of
    Python-side zipfile machinery per access. Set RT1_TPU_NO_NATIVE=1 to
    force the numpy path.
    """
    if not os.environ.get("RT1_TPU_NO_NATIVE"):
        try:
            from rt1_tpu.data import native

            if native.available():
                return native.load_episode_native(path)
        except Exception:
            pass  # fall back to numpy on any native failure
    with np.load(path) as z:
        return {k: z[k] for k in z.files}


def read_reference_episode(path: str) -> Episode:
    """Read the reference's pickled list-of-step-dicts `.npy` format
    (`rlds_np_convert.py:13-31`, consumed by `load_np_dataset.py:79-83`)."""
    steps = np.load(path, allow_pickle=True)
    ep = {
        "rgb": np.stack([s["rgb"] for s in steps]).astype(np.uint8),
        "action": np.stack([s["action"] for s in steps]).astype(np.float32),
        "is_first": np.array([bool(s["is_first"]) for s in steps]),
        "is_terminal": np.array([bool(s["is_terminal"]) for s in steps]),
        "instruction": np.stack([s["instruction"] for s in steps]).astype(np.float32),
    }
    validate_episode(ep)
    return ep


def generate_synthetic_episode(
    rng: np.random.Generator,
    num_steps: Optional[int] = None,
    height: int = 180,
    width: int = 320,
    instruction_dim: int = 512,
) -> Episode:
    """Random episode with the Language-Table schema, for tests and benchmarks."""
    t = int(num_steps if num_steps is not None else rng.integers(8, 40))
    instruction = rng.standard_normal(instruction_dim).astype(np.float32)
    is_terminal = np.zeros(t, bool)
    is_terminal[-1] = True
    is_first = np.zeros(t, bool)
    is_first[0] = True
    return {
        "rgb": rng.integers(0, 256, (t, height, width, 3), dtype=np.uint8),
        "action": rng.uniform(-0.1, 0.1, (t, 2)).astype(np.float32),
        "is_first": is_first,
        "is_terminal": is_terminal,
        "instruction": np.tile(instruction, (t, 1)),
    }
