"""Sliding-window dataset + loaders.

Reproduces the reference's sample distribution exactly (SURVEY.md §7.4/§7.7,
`load_np_dataset.py:49-116`): each episode is front-padded by repeating the first
step `window-1` times (padding copies get ``is_first=False``), every length-
`window` window is one sample, each frame is independently random-cropped at
`crop_factor` and bilinear-resized to (height, width), labels are
``terminate_episode`` (is_terminal as int) and ``action``.

Improvements over the reference, same distribution:
* episodes are read once into an LRU cache of stacked arrays, not re-unpickled
  per `__getitem__` (the reference's I/O hot spot, `load_np_dataset.py:79-83`);
* loading/augment runs under tf.data with parallel map + prefetch instead of 15
  fork-per-batch DataLoader workers (`distribute_train.py:200`);
* per-host sharding for multi-host SPMD feeding (each host loads 1/N of the
  windows, `jax.process_index` style), then `device_feeder` lays batches out on
  the mesh as sharded `jax.Array`s.
"""

from __future__ import annotations

import collections
import functools
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from rt1_tpu.data import episodes as ep_lib


class WindowedEpisodeDataset:
    """Index of all (episode, start) windows over a set of episode files."""

    def __init__(
        self,
        paths: Sequence[str],
        window: int = 6,
        crop_factor: Optional[float] = 0.95,
        height: int = 256,
        width: int = 456,
        reader: Callable[[str], ep_lib.Episode] = ep_lib.load_episode,
        cache_episodes: int = 64,
        image_dtype: str = "uint8",
        clip_tokenizer=None,
    ):
        if image_dtype not in ("uint8", "float32"):
            raise ValueError(f"image_dtype must be uint8|float32, got {image_dtype}")
        self.paths = list(paths)
        self.window = window
        self.crop_factor = crop_factor
        self.height = height
        self.width = width
        # uint8 (default) ships 4x fewer H2D bytes than float32 — the model
        # converts on device (`ops/image.py::convert_dtype`), and the
        # reference stores/augments uint8 rgb anyway (VERDICT r1 weak #2).
        self.image_dtype = image_dtype
        # Optional ClipBPETokenizer: windows gain an
        # "instruction_tokenized_clip" (window, context) observation, fed to
        # LAVA's in-graph CLIP text tower (reference tokenizes in the input
        # pipeline, `input_pipeline_rlds.py` + clip_tokenizer.py).
        self._clip_tokenizer = clip_tokenizer
        self._clip_token_cache: Dict[int, np.ndarray] = {}
        self._reader = reader
        self._cache: "collections.OrderedDict[int, ep_lib.Episode]" = collections.OrderedDict()
        self._cache_size = cache_episodes
        # tf.data's parallel map calls get_window from multiple threads; the
        # LRU mutations must be atomic.
        import threading

        self._cache_lock = threading.Lock()
        # Index construction mirrors `_create_samples` (load_np_dataset.py:65-74):
        # padded length T + window - 1 → exactly T windows per episode.
        self.index: List[Tuple[int, int]] = []
        for i, p in enumerate(self.paths):
            t = self._episode_len(i)
            self.index.extend((i, s) for s in range(t))

    def _episode_len(self, i: int) -> int:
        # Read only the length, not the payload: npz members are lazy, so
        # loading one small member avoids pulling the rgb arrays of every
        # episode at startup. Falls back to a full read for .npy episodes.
        path = self.paths[i]
        if path.endswith(".npz"):
            with np.load(path) as z:
                return int(z["is_first"].shape[0])
        return self._episode(i)["rgb"].shape[0]

    def _episode(self, i: int) -> ep_lib.Episode:
        with self._cache_lock:
            ep = self._cache.get(i)
            if ep is not None:
                self._cache.move_to_end(i)
                return ep
        ep = self._reader(self.paths[i])
        with self._cache_lock:
            self._cache[i] = ep
            if len(self._cache) > self._cache_size:
                self._cache.popitem(last=False)
        return ep

    def __len__(self) -> int:
        return len(self.index)

    # ------------------------------------------------------------------ samples

    def _padded_step(self, ep: ep_lib.Episode, j: int, key: str):
        """Step j of the padded episode: j < window-1 reads the first step."""
        pad = self.window - 1
        src = 0 if j < pad else j - pad
        return ep[key][src]

    def get_window(
        self, idx: int, rng: Optional[np.random.Generator] = None
    ) -> Dict[str, Dict[str, np.ndarray]]:
        ep_i, start = self.index[idx]
        ep = self._episode(ep_i)
        rng = rng or np.random.default_rng()

        frames, embeds, actions, terms = [], [], [], []
        boxes = []
        for j in range(start, start + self.window):
            rgb = self._padded_step(ep, j, "rgb")
            frames.append(rgb)
            boxes.append(
                _crop_box(rgb.shape[0], rgb.shape[1], self.crop_factor, rng)
            )
            embeds.append(self._padded_step(ep, j, "instruction"))
            actions.append(self._padded_step(ep, j, "action"))
            terms.append(np.int32(bool(self._padded_step(ep, j, "is_terminal"))))
        images = self._crop_resize_frames(frames, boxes)

        observations = {
            "image": images,
            "natural_language_embedding": np.stack(embeds).astype(np.float32),
        }
        if self._clip_tokenizer is not None:
            tokens = self._episode_clip_tokens(ep_i)
            observations["instruction_tokenized_clip"] = np.tile(
                tokens, (self.window, 1)
            )
        return {
            "observations": observations,
            "actions": {
                "terminate_episode": np.asarray(terms, np.int32),
                "action": np.stack(actions).astype(np.float32),
            },
        }

    def _crop_resize_frames(self, frames, boxes) -> np.ndarray:
        """(window,) frames + crop boxes -> (window, H, W, 3) in image_dtype."""
        out = crop_resize_frames(frames, boxes, self.height, self.width)
        if self.image_dtype == "float32":
            return out.astype(np.float32) / 255.0
        return out

    def _episode_clip_tokens(self, ep_i: int) -> np.ndarray:
        """(context,) int32 CLIP BPE frame for the episode's instruction."""
        tokens = self._clip_token_cache.get(ep_i)
        if tokens is None:
            ep = self._episode(ep_i)
            if "instruction_text" not in ep:
                raise KeyError(
                    f"{self.paths[ep_i]} has no 'instruction_text' member; "
                    "re-collect with a current rt1_tpu.data.collect to use "
                    "clip_tokenizer"
                )
            text = ep_lib.decode_instruction_text(ep["instruction_text"])
            tokens = self._clip_tokenizer.tokenize_text(text)[0].astype(np.int32)
            self._clip_token_cache[ep_i] = tokens
        return tokens

    # ------------------------------------------------------------------ loaders

    def numpy_batches(
        self,
        batch_size: int,
        shuffle: bool = True,
        seed: int = 0,
        num_epochs: Optional[int] = None,
        process_index: int = 0,
        process_count: int = 1,
        drop_remainder: bool = True,
    ) -> Iterator[Dict]:
        """Dependency-free batch iterator (tests, debugging, tiny runs)."""
        rng = np.random.default_rng(seed)
        epoch = 0
        while num_epochs is None or epoch < num_epochs:
            order = np.arange(len(self.index))
            if shuffle:
                rng.shuffle(order)
            order = order[process_index::process_count]
            for i in range(0, len(order) - (batch_size - 1 if drop_remainder else 0), batch_size):
                chunk = order[i : i + batch_size]
                samples = [self.get_window(int(j), rng) for j in chunk]
                yield _stack_tree(samples)
            epoch += 1

    def as_tf_dataset(
        self,
        batch_size: int,
        shuffle: bool = True,
        seed: int = 0,
        num_parallel_calls: int = 16,
        shuffle_buffer: int = 2048,
        process_index: int = 0,
        process_count: int = 1,
        repeat: bool = True,
    ):
        """tf.data pipeline: parallel window assembly + augment, shuffle, batch,
        prefetch. Replaces the reference's DataLoader(num_workers=15) path."""
        import tensorflow as tf

        tf.config.set_visible_devices([], "GPU")

        n = len(self.index)
        ds = tf.data.Dataset.range(n)
        ds = ds.shard(process_count, process_index)
        if repeat:
            ds = ds.repeat()
        if shuffle:
            ds = ds.shuffle(min(n, shuffle_buffer), seed=seed, reshuffle_each_iteration=True)

        with_tokens = self._clip_tokenizer is not None

        def _load(idx):
            def _py(i):
                s = self.get_window(int(i))
                out = [
                    s["observations"]["image"],
                    s["observations"]["natural_language_embedding"],
                    s["actions"]["terminate_episode"],
                    s["actions"]["action"],
                ]
                if with_tokens:
                    out.append(s["observations"]["instruction_tokenized_clip"])
                return tuple(out)

            img_tf_dtype = (
                tf.uint8 if self.image_dtype == "uint8" else tf.float32
            )
            dtypes = [img_tf_dtype, tf.float32, tf.int32, tf.float32]
            if with_tokens:
                dtypes.append(tf.int32)
            tensors = tf.numpy_function(_py, [idx], dtypes)
            img, emb, term, act = tensors[:4]
            w = self.window
            img.set_shape((w, self.height, self.width, 3))
            emb.set_shape((w, None))
            term.set_shape((w,))
            act.set_shape((w, None))
            observations = {
                "image": img, "natural_language_embedding": emb,
            }
            if with_tokens:
                tokens = tensors[4]
                tokens.set_shape((w, self._clip_tokenizer.context_length))
                observations["instruction_tokenized_clip"] = tokens
            return {
                "observations": observations,
                "actions": {"terminate_episode": term, "action": act},
            }

        ds = ds.map(_load, num_parallel_calls=num_parallel_calls, deterministic=False)
        ds = ds.batch(batch_size, drop_remainder=True)
        return ds.prefetch(tf.data.AUTOTUNE)


def _crop_box(
    h: int, w: int, crop_factor: Optional[float], rng: np.random.Generator
) -> Tuple[int, int, int, int]:
    """(top, left, crop_h, crop_w) — `DecodeAndRandomResizedCrop` parity
    (load_np_dataset.py:8-39): a `crop_factor` box at a uniform random
    offset (the full frame when crop_factor is None)."""
    if crop_factor is None:
        return 0, 0, h, w
    ch, cw = int(h * crop_factor), int(w * crop_factor)
    top = int(rng.integers(0, h - ch + 1))
    left = int(rng.integers(0, w - cw + 1))
    return top, left, ch, cw


def crop_resize_frames(frames, boxes, height: int, width: int) -> np.ndarray:
    """Crop + bilinear-resize a batch of frames -> (n, height, width, 3).

    The one augmentation backend every loader shares (tf.data window
    assembly, the packed-cache packer, and the sample-ahead feeder's general
    path all call this), so their pixel semantics agree by construction:
    cv2 (SIMD bilinear, GIL-released) when importable; otherwise the native
    C++ sampler (native/window_sampler.cc) keeps the pipeline
    dependency-free. Both follow cv2.INTER_LINEAR half-pixel-center
    semantics, so the sample distribution matches to +/-1 LSB.
    Set RT1_TPU_FORCE_NATIVE_SAMPLER=1 to force the native path.
    """
    import os

    use_native = bool(os.environ.get("RT1_TPU_FORCE_NATIVE_SAMPLER"))
    if use_native and frames[0].dtype != np.uint8:
        raise RuntimeError(
            "RT1_TPU_FORCE_NATIVE_SAMPLER: the native sampler only "
            f"handles uint8 frames, got {frames[0].dtype}"
        )
    if not use_native:
        try:
            import cv2  # noqa: F401
        except ImportError:
            if frames[0].dtype != np.uint8:
                raise RuntimeError(
                    "cv2 is unavailable and the native sampler only "
                    f"handles uint8 frames, got {frames[0].dtype}; "
                    "install opencv-python"
                ) from None
            use_native = True
    if use_native:
        from rt1_tpu.data import native

        if not native.sampler_available():
            raise RuntimeError(
                "Neither cv2 nor the native window sampler is available "
                "(build native/ with `make` or install opencv-python)"
            )
        # Threads=1: tf.data's parallel map / feeder workers already fan out
        # across windows; the call releases the GIL so those threads
        # genuinely run in parallel.
        return native.crop_resize_batch(frames, boxes, height, width, threads=1)
    return np.stack(
        [_cv2_crop_resize(rgb, box, height, width) for rgb, box in zip(frames, boxes)]
    )


def _cv2_crop_resize(rgb: np.ndarray, box, height: int, width: int) -> np.ndarray:
    """Single-frame crop + cv2.INTER_LINEAR resize (`DecodeAndRandomResizedCrop`
    parity, load_np_dataset.py:8-39); dtype preserved (uint8 in, uint8 out)."""
    import cv2

    top, left, ch, cw = box
    crop = rgb[top : top + ch, left : left + cw]
    return cv2.resize(crop, (width, height), interpolation=cv2.INTER_LINEAR)


def _stack_tree(samples: List[Dict]) -> Dict:
    """collate_fn parity (load_np_dataset.py:131-146): stack nested dicts."""
    out = {}
    for k, v in samples[0].items():
        if isinstance(v, dict):
            out[k] = {kk: np.stack([s[k][kk] for s in samples]) for kk in v}
        else:
            out[k] = np.stack([s[k] for s in samples])
    return out


def put_global(batch, sharding):
    """Lay one host batch out per `sharding` — multi-process aware.

    Single process: one async `jax.device_put` (the fast path, unchanged).
    Multi-process: each host holds only ITS rows of the global batch (the
    feeder's per-host block slice), so the global array is assembled with
    `jax.make_array_from_process_local_data` — every leaf's global leading
    dim is local_rows × process_count, matching a batch dim sharded over
    the host-major (data, fsdp) mesh axes where each host's devices own
    exactly its contiguous row block. No cross-host data moves: the
    "assembly" is metadata + local H2D.
    """
    import jax

    if jax.process_count() == 1:
        return jax.device_put(batch, sharding)
    nproc = jax.process_count()

    def put(x):
        x = np.asarray(x)
        global_shape = (x.shape[0] * nproc,) + x.shape[1:]
        return jax.make_array_from_process_local_data(
            sharding, x, global_shape
        )

    return jax.tree.map(put, batch)


def prefetch_to_device(iterator, sharding, depth: int = 2) -> Iterator:
    """Double-buffered H2D: keep `depth` batches resident on device.

    `jax.device_put` is asynchronous, so enqueueing batch N+1 before the
    consumer blocks on batch N overlaps its host->device copy with the
    device compute of step N (VERDICT r1 weak #3 — the single-buffered loop
    serialized H2D into the step). Equivalent of
    `flax.jax_utils.prefetch_to_device`, but laying batches out with an
    explicit (mesh) sharding instead of pmap's leading device axis. On
    multi-process runs each host feeds its shard of the global batch
    (`put_global`).
    """
    queue = collections.deque()
    for batch in iterator:
        queue.append(put_global(batch, sharding))
        if len(queue) >= max(depth, 1):
            yield queue.popleft()
    while queue:
        yield queue.popleft()


def to_obs_actions(batch):
    """Loader batch dict -> the (observations, actions) tuple steps consume.

    tf.data yields dicts whose leaves are EagerTensors; numpy loaders yield
    dicts of ndarrays. Normalize leaves, not the container.
    """
    import jax

    b = jax.tree.map(
        lambda x: x.numpy() if hasattr(x, "numpy") else np.asarray(x),
        batch,
    )
    return b["observations"], b["actions"]


def device_feeder(iterator, batch_sharding, depth: int = 1) -> Iterator:
    """Lay host batches out on the mesh as (observations, actions) tuples of
    sharded jax.Arrays. On a multi-process run each host's iterator yields
    its block of the global batch and `put_global` assembles the global
    `jax.Array` via `jax.make_array_from_process_local_data`; single-process
    keeps the plain async `device_put`. `depth=2` double-buffers (see
    `prefetch_to_device`)."""
    return prefetch_to_device(
        map(to_obs_actions, iterator), batch_sharding, depth=depth
    )
