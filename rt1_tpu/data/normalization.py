"""Dataset normalization statistics (Chan's parallel algorithm) + rendezvous.

Parity source: reference `language_table/train/normalization.py:28-105`
(ChanRunningStatistics over observation features, min/max + mean/std over
actions) and the multihost rendezvous in `input_pipeline_rlds.py:195-234`:
process 0 computes statistics and writes a JSON file; other processes
poll-wait for it. Pure numpy — no tf_agents dependency.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Iterable, Optional, Tuple

import numpy as np

EPS = np.finfo(np.float32).eps


def chan_merge(n_a, mean_a, m2_a, n_b, mean_b, m2_b):
    """Merge two (count, mean, M2) partials; returns the combined triple.

    Chan et al.'s parallel variance update (see the Wikipedia "Algorithms
    for calculating variance # Parallel algorithm" article the reference
    cites, `normalization.py:36-40`).
    """
    n = n_a + n_b
    if n == 0:
        return 0, mean_a, m2_a
    delta = mean_b - mean_a
    mean = mean_a + delta * (n_b / n)
    m2 = m2_a + m2_b + np.square(delta) * (n_a * n_b / n)
    return n, mean, m2


class ChanRunningStatistics:
    """Streaming per-feature mean/std over the LAST axis of samples."""

    def __init__(self, feature_dim: Optional[int] = None):
        self._n = 0
        self._mean = (
            np.zeros(feature_dim) if feature_dim is not None else None
        )
        self._m2 = 0.0

    def update(self, sample: np.ndarray):
        sample = np.asarray(sample, np.float64)
        if sample.ndim > 1:
            sample = sample.reshape(-1, sample.shape[-1])
            n_b = sample.shape[0]
            mean_b = sample.mean(axis=0)
            m2_b = sample.var(axis=0) * n_b
        else:
            n_b, mean_b, m2_b = 1, sample, 0.0
        if self._mean is None:
            self._mean = np.zeros_like(mean_b)
        self._n, self._mean, self._m2 = chan_merge(
            self._n, self._mean, self._m2, n_b, mean_b, m2_b
        )

    @property
    def n(self):
        return self._n

    @property
    def mean(self):
        return self._mean

    @property
    def variance(self):
        return self._m2 / self._n

    @property
    def std(self):
        return np.sqrt(self.variance)


def compute_dataset_statistics(
    batches: Iterable,
    num_samples: int,
    obs_keys: Tuple[str, ...] = ("natural_language_embedding",),
) -> Dict:
    """Streaming stats over our batch format ({'observations', 'actions'}).

    Returns {obs_statistics: {key: {mean, std}}, act_statistics:
    {mean, std, min, max}} with JSON-serializable lists.
    """
    obs_stats = {k: ChanRunningStatistics() for k in obs_keys}
    act_stats = ChanRunningStatistics()
    act_min, act_max = None, None

    seen = 0
    for batch in batches:
        actions = np.asarray(batch["actions"]["action"], np.float64)
        flat = actions.reshape(-1, actions.shape[-1])
        act_stats.update(flat)
        batch_min = flat.min(axis=0)
        batch_max = flat.max(axis=0)
        if act_min is None:
            act_min, act_max = batch_min, batch_max
        else:
            act_min = np.minimum(act_min, batch_min)
            act_max = np.maximum(act_max, batch_max)
        for k in obs_keys:
            obs_stats[k].update(np.asarray(batch["observations"][k]))
        seen += flat.shape[0]
        if seen >= num_samples:
            break

    return {
        "num_samples": int(seen),
        "obs_statistics": {
            k: {
                "mean": obs_stats[k].mean.tolist(),
                "std": (obs_stats[k].std + EPS).tolist(),
            }
            for k in obs_keys
        },
        "act_statistics": {
            "mean": act_stats.mean.tolist(),
            "std": (act_stats.std + EPS).tolist(),
            "min": act_min.tolist(),
            "max": act_max.tolist(),
        },
    }


def get_or_compute_statistics(
    path: str,
    compute_fn,
    is_lead_host: bool = True,
    timeout_s: float = 600.0,
    poll_s: float = 1.0,
) -> Dict:
    """Multihost stats rendezvous: lead host computes + writes, others wait.

    Mirrors the reference's cross-process file rendezvous
    (`input_pipeline_rlds.py:195-234`): a `.tmp` write + atomic rename so
    waiters never read a partial file.
    """
    if os.path.exists(path):
        with open(path) as f:
            return json.load(f)
    if is_lead_host:
        stats = compute_fn()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(stats, f)
        os.replace(tmp, path)
        return stats
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                return json.load(f)
        time.sleep(poll_s)
    raise TimeoutError(
        f"Timed out waiting for dataset statistics at {path}"
    )
