"""Packed mmap frame cache: decode-once episodes at augmentation headroom.

The tf.data path pays the full augmentation bill per *sample*: every window
re-reads decoded 256x456-class frames and random-resize-crops each one
(~42 ms/batch on the single-core bench host against an 8 ms device step —
the 78% input stall in docs/performance.md). The fix is to move every
per-pixel operation that does not depend on the random crop offset to an
offline pass:

* `pack_episodes` decodes each episode ONCE and stores its frames resized to
  the *packed* resolution — the smallest frame from which every random crop
  of the training distribution can be cut as a pure slice — appended into a
  single corpus-wide uint8 `frames.bin` (mmap-able, no headers), with the
  small step-aligned members (action/instruction/flags) concatenated into
  raw `meta_<member>.npy` files and a JSON manifest carrying geometry,
  per-episode frame offsets, and source fingerprints. One file per array,
  not per episode: a 7800-episode corpus costs two open fds and zero
  per-window parsing (per-episode `.npz` sidecars measured 3.2 ms/load —
  reintroducing the exact per-sample I/O tax this cache removes).
* `PackedEpisodeCache` maps `frames.bin` once and assembles a training
  window as h x w uint8 slices out of the mmap — no decode, no resize, no
  float math, no handle churn.

Crop-distribution parity (tested in tests/test_packed_cache.py): the random
box is still drawn by `pipeline._crop_box` in SOURCE-frame coordinates —
bit-identical draws to the tf.data path for the same rng — then mapped into
packed coordinates, where it is exactly (height, width) by construction:

    source (H0, W0) -- crop (ch0, cw0) = (int(H0*cf), int(W0*cf)) -> (h, w)
    packed (ph, pw) = (round(H0*h/ch0), round(W0*w/cw0))

so a ch0-tall source crop spans h packed rows, and the gather is
`frames[t, top_p:top_p+h, left_p:left_p+w]`. The only pixel-semantics
difference vs the tf.data path is resize-once-then-slice instead of
slice-then-resize (the same interpolation family, applied once offline).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from rt1_tpu.data import episodes as ep_lib
from rt1_tpu.data.pipeline import _crop_box, crop_resize_frames

MANIFEST_NAME = "pack_manifest.json"
FRAMES_NAME = "frames.bin"
FORMAT_VERSION = 2
# Step-aligned members consolidated into meta_<name>.npy (concatenated over
# episodes along axis 0, raw .npy so the cache opens them mmap_mode="r").
META_MEMBERS = ("action", "instruction", "is_first", "is_terminal")
TEXT_NAME = "meta_instruction_text.npy"


# --------------------------------------------------------------------- geometry


def crop_size(dim: int, crop_factor: Optional[float]) -> int:
    """Source-coordinate crop size along one dim (`_crop_box` parity)."""
    return dim if crop_factor is None else int(dim * crop_factor)


def packed_dims(
    src_h: int,
    src_w: int,
    height: int,
    width: int,
    crop_factor: Optional[float],
) -> Tuple[int, int]:
    """Packed (ph, pw): a `crop_factor` source crop spans exactly (h, w).

    crop_factor None degenerates to (height, width) — the gather is then the
    whole packed frame.
    """
    ch0 = crop_size(src_h, crop_factor)
    cw0 = crop_size(src_w, crop_factor)
    ph = int(round(src_h * height / ch0))
    pw = int(round(src_w * width / cw0))
    # round() cannot undershoot the slice size by construction (ch0 <= src_h
    # implies src_h*h/ch0 >= h) except through the 0.5-rounding edge; clamp
    # so the (h, w) gather slice always fits.
    return max(ph, height), max(pw, width)


def map_box_to_packed(
    box: Tuple[int, int, int, int],
    src_h: int,
    src_w: int,
    ph: int,
    pw: int,
    height: int,
    width: int,
) -> Tuple[int, int]:
    """Source-coordinate crop box -> (top, left) of its (h, w) packed slice."""
    top, left, ch, cw = box
    top_p = int(round(top * height / max(ch, 1)))
    left_p = int(round(left * width / max(cw, 1)))
    return min(max(top_p, 0), ph - height), min(max(left_p, 0), pw - width)


# --------------------------------------------------------------------- packer


def _fingerprint(path: str) -> Dict[str, object]:
    st = os.stat(path)
    return {"name": os.path.basename(path), "bytes": st.st_size,
            "mtime": round(st.st_mtime, 3)}


def _resize_episode_frames(rgb: np.ndarray, ph: int, pw: int) -> np.ndarray:
    """(T, H0, W0, 3) uint8 -> (T, ph, pw, 3) uint8, full-frame resize."""
    t, h0, w0, _ = rgb.shape
    if (h0, w0) == (ph, pw):
        return np.ascontiguousarray(rgb)
    boxes = np.tile(np.array([[0, 0, h0, w0]], np.int32), (t, 1))
    return crop_resize_frames(list(rgb), boxes, ph, pw)


def pack_episodes(
    paths: Sequence[str],
    out_dir: str,
    height: int,
    width: int,
    crop_factor: Optional[float],
    force: bool = False,
) -> Dict[str, object]:
    """Decode each episode once, write packed frames + sidecars + manifest.

    Returns the manifest dict. Skips work when `pack_is_fresh` already holds
    (unless `force`). Source frames must share one (H0, W0) across the
    corpus — the packed geometry is corpus-wide.
    """
    paths = sorted(paths)
    if not paths:
        raise ValueError("pack_episodes: no episode paths given")
    if not force and pack_is_fresh(out_dir, paths, height, width, crop_factor):
        with open(os.path.join(out_dir, MANIFEST_NAME)) as f:
            return json.load(f)

    os.makedirs(out_dir, exist_ok=True)
    src_h = src_w = None
    episodes: List[Dict[str, object]] = []
    ph = pw = None
    meta_parts: Dict[str, List[np.ndarray]] = {k: [] for k in META_MEMBERS}
    text_parts: List[np.ndarray] = []
    have_text = True
    frame_offset = 0
    text_offset = 0
    frames_tmp = os.path.join(out_dir, FRAMES_NAME + ".tmp")
    with open(frames_tmp, "wb") as frames_f:
        for path in paths:
            ep = ep_lib.load_episode(path)
            ep_lib.validate_episode(ep)
            rgb = np.asarray(ep["rgb"], np.uint8)
            t, h0, w0, _ = rgb.shape
            if src_h is None:
                src_h, src_w = h0, w0
                ph, pw = packed_dims(src_h, src_w, height, width, crop_factor)
            elif (h0, w0) != (src_h, src_w):
                raise ValueError(
                    f"{path}: source frames {h0}x{w0} differ from corpus "
                    f"{src_h}x{src_w}; the packed geometry is corpus-wide"
                )
            _resize_episode_frames(rgb, ph, pw).tofile(frames_f)
            for k in META_MEMBERS:
                meta_parts[k].append(np.asarray(ep[k]))
            entry = {
                "steps": int(t),
                "frame_offset": int(frame_offset),
                "source": _fingerprint(path),
            }
            if have_text and "instruction_text" in ep:
                text = np.asarray(ep["instruction_text"], np.uint8)
                text_parts.append(text)
                entry["text_offset"] = int(text_offset)
                entry["text_len"] = int(text.shape[0])
                text_offset += int(text.shape[0])
            else:
                # All-or-nothing: a corpus with only some instruction_text
                # members packs without any (mirrors the tf path, which
                # KeyErrors per missing episode at clip-token time).
                have_text = False
            episodes.append(entry)
            frame_offset += t
    os.replace(frames_tmp, os.path.join(out_dir, FRAMES_NAME))
    for k in META_MEMBERS:
        _atomic_save_npy(
            os.path.join(out_dir, f"meta_{k}.npy"),
            np.concatenate(meta_parts[k], axis=0),
        )
    if have_text and text_parts:
        _atomic_save_npy(
            os.path.join(out_dir, TEXT_NAME), np.concatenate(text_parts)
        )
    else:
        for e in episodes:
            e.pop("text_offset", None)
            e.pop("text_len", None)
    manifest = {
        "format_version": FORMAT_VERSION,
        "source": {"height": int(src_h), "width": int(src_w)},
        "train": {
            "height": int(height),
            "width": int(width),
            "crop_factor": crop_factor,
        },
        "packed": {"height": int(ph), "width": int(pw)},
        "total_steps": int(frame_offset),
        "has_instruction_text": bool(have_text and text_parts),
        "episodes": episodes,
    }
    tmp = os.path.join(out_dir, MANIFEST_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    os.replace(tmp, os.path.join(out_dir, MANIFEST_NAME))
    return manifest


def _atomic_save_npy(path: str, arr: np.ndarray) -> None:
    tmp = path + ".tmp.npy"  # .npy suffix keeps np.save from appending one
    np.save(tmp, arr)
    os.replace(tmp, path)


def pack_is_fresh(
    pack_dir: str,
    paths: Sequence[str],
    height: int,
    width: int,
    crop_factor: Optional[float],
) -> bool:
    """True when `pack_dir` holds a current pack of exactly `paths`.

    Current = same train geometry, same episode basenames in the same order,
    unchanged source size/mtime fingerprints, all packed files present with
    the expected byte counts.
    """
    manifest_path = os.path.join(pack_dir, MANIFEST_NAME)
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return False
    if manifest.get("format_version") != FORMAT_VERSION:
        return False
    train = manifest.get("train", {})
    if (
        train.get("height") != height
        or train.get("width") != width
        or train.get("crop_factor") != crop_factor
    ):
        return False
    episodes = manifest.get("episodes", [])
    paths = sorted(paths)
    if len(episodes) != len(paths):
        return False
    for entry, path in zip(episodes, paths):
        try:
            fp = _fingerprint(path)
        except OSError:
            return False
        if entry.get("source") != fp:
            return False
    ph = manifest["packed"]["height"]
    pw = manifest["packed"]["width"]
    total = manifest.get("total_steps", 0)
    try:
        if os.path.getsize(os.path.join(pack_dir, FRAMES_NAME)) != total * ph * pw * 3:
            return False
    except OSError:
        return False
    for k in META_MEMBERS:
        if not os.path.exists(os.path.join(pack_dir, f"meta_{k}.npy")):
            return False
    if manifest.get("has_instruction_text") and not os.path.exists(
        os.path.join(pack_dir, TEXT_NAME)
    ):
        return False
    return True


# --------------------------------------------------------------------- cache


class PackedEpisodeCache:
    """Window sampler over a packed cache: mmap slices, not decodes.

    Mirrors `WindowedEpisodeDataset`'s sample distribution exactly (same
    (episode, start) index, same front-padding, `_crop_box` draws in source
    coordinates) but a window's frames are (h, w) uint8 slices out of ONE
    corpus-wide frame mmap. `get_window` returns the same nested dict the
    tf.data path produces; `fill_batch` writes a whole batch straight into
    caller-provided buffers (the feeder's arrays). Total open handles: the
    frames mmap + one mmap per meta member, regardless of corpus size —
    there is no per-episode state to cache or evict.
    """

    def __init__(self, pack_dir: str, window: int = 6, clip_tokenizer=None):
        self.pack_dir = pack_dir
        with open(os.path.join(pack_dir, MANIFEST_NAME)) as f:
            self.manifest = json.load(f)
        if self.manifest.get("format_version") != FORMAT_VERSION:
            raise ValueError(
                f"{pack_dir}: pack format "
                f"{self.manifest.get('format_version')} != {FORMAT_VERSION} "
                "— re-pack with scripts/pack_dataset.py"
            )
        self.window = window
        self.height = int(self.manifest["train"]["height"])
        self.width = int(self.manifest["train"]["width"])
        self.crop_factor = self.manifest["train"]["crop_factor"]
        self.src_h = int(self.manifest["source"]["height"])
        self.src_w = int(self.manifest["source"]["width"])
        self.packed_h = int(self.manifest["packed"]["height"])
        self.packed_w = int(self.manifest["packed"]["width"])
        self.episodes = self.manifest["episodes"]
        self.total_steps = int(self.manifest["total_steps"])
        self._clip_tokenizer = clip_tokenizer
        self._clip_token_cache: Dict[int, np.ndarray] = {}
        self._lock = threading.Lock()
        # One mapping for every frame in the corpus; the kernel pages in
        # only what gets sliced.
        self._frames = np.memmap(
            os.path.join(pack_dir, FRAMES_NAME),
            dtype=np.uint8,
            mode="r",
            shape=(self.total_steps, self.packed_h, self.packed_w, 3),
        )
        # Raw .npy metas opened mmap_mode="r": header parsed once here,
        # window access is a page-cached fancy-index (the per-episode
        # .npz sidecars this replaces cost 3.2 ms of zipfile parsing per
        # load — a per-sample tax at corpus scale).
        self._meta = {
            k: np.load(
                os.path.join(pack_dir, f"meta_{k}.npy"), mmap_mode="r"
            )
            for k in META_MEMBERS
        }
        self._text = None
        if self.manifest.get("has_instruction_text"):
            self._text = np.load(
                os.path.join(pack_dir, TEXT_NAME), mmap_mode="r"
            )
        self._frame_offsets = np.array(
            [int(e["frame_offset"]) for e in self.episodes], np.int64
        )
        self.index: List[Tuple[int, int]] = []
        for i, entry in enumerate(self.episodes):
            self.index.extend((i, s) for s in range(int(entry["steps"])))

    def __len__(self) -> int:
        return len(self.index)

    # ------------------------------------------------------------ file access

    def frames(self, ep_i: int) -> np.ndarray:
        """(T, ph, pw, 3) uint8 view of episode `ep_i`'s packed frames."""
        off = int(self._frame_offsets[ep_i])
        return self._frames[off : off + int(self.episodes[ep_i]["steps"])]

    def meta(self, ep_i: int) -> Dict[str, np.ndarray]:
        """Step-aligned member views for episode `ep_i` (zero copies)."""
        off = int(self._frame_offsets[ep_i])
        end = off + int(self.episodes[ep_i]["steps"])
        return {k: v[off:end] for k, v in self._meta.items()}

    # ------------------------------------------------------------ sampling

    def draw_box(self, rng: np.random.Generator) -> Tuple[int, int, int, int]:
        """One source-coordinate crop box — the tf.data path's distribution,
        drawn by the same `_crop_box` (bit-identical for the same rng)."""
        return _crop_box(self.src_h, self.src_w, self.crop_factor, rng)

    def draw_packed_offsets(
        self, rng: np.random.Generator, n: int
    ) -> np.ndarray:
        """(n, 2) packed-coordinate (top, left) offsets, drawn vectorized.

        Identical distribution to mapping `draw_box` results one by one
        (uniform integers over the same source ranges, the same
        round-and-clip into packed coordinates) but one rng call per axis
        for the whole batch — the feeder's hot path. Not the same *stream*
        as per-frame `_crop_box` draws; the byte-parity contract with the
        tf.data path lives on `get_window`/`gather_frames`, which keep the
        sequential draw order.
        """
        h, w = self.height, self.width
        ph, pw = self.packed_h, self.packed_w
        if self.crop_factor is None:
            return np.zeros((n, 2), np.int32)
        ch0 = int(self.src_h * self.crop_factor)
        cw0 = int(self.src_w * self.crop_factor)
        tops = rng.integers(0, self.src_h - ch0 + 1, size=n)
        lefts = rng.integers(0, self.src_w - cw0 + 1, size=n)
        out = np.empty((n, 2), np.int32)
        # np.rint is round-half-even, matching map_box_to_packed's
        # int(round(.)) on the scalar path.
        out[:, 0] = np.clip(np.rint(tops * (h / ch0)), 0, ph - h)
        out[:, 1] = np.clip(np.rint(lefts * (w / cw0)), 0, pw - w)
        return out

    def _padded_src(self, start: int, j: int) -> int:
        """Index into the unpadded episode for step j of the padded window."""
        pad = self.window - 1
        k = start + j
        return 0 if k < pad else k - pad

    def _padded_src_indices(self, start: int) -> np.ndarray:
        """(window,) int64 unpadded source steps for the whole window."""
        k = np.arange(start, start + self.window, dtype=np.int64)
        return np.maximum(k - (self.window - 1), 0)

    def gather_frames(
        self,
        ep_i: int,
        start: int,
        rng: Optional[np.random.Generator] = None,
        out: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        """(window, h, w, 3) uint8 for window `start` of episode `ep_i`.

        Each frame is an independent random crop; boxes are drawn
        per-frame in source coordinates with the tf.data path's exact rng
        consumption order (the byte-parity path — `fill_batch` is the
        vectorized fast path). `out` lets callers fill a buffer in place.
        """
        mm = self.frames(ep_i)
        h, w = self.height, self.width
        if out is None:
            out = np.empty((self.window, h, w, 3), np.uint8)
        rng = rng or np.random.default_rng()
        boxes = [self.draw_box(rng) for _ in range(self.window)]
        use_native = _native_gather_available()
        if use_native:
            from rt1_tpu.data import native

            src = np.empty((self.window,), np.int64)
            pboxes = np.empty((self.window, 4), np.int32)
            for j in range(self.window):
                src[j] = self._padded_src(start, j)
                top_p, left_p = map_box_to_packed(
                    boxes[j], self.src_h, self.src_w,
                    self.packed_h, self.packed_w, h, w,
                )
                pboxes[j] = (top_p, left_p, h, w)
            native.packed_gather(mm, src, pboxes, out, threads=1)
            return out
        for j in range(self.window):
            frame = mm[self._padded_src(start, j)]
            top_p, left_p = map_box_to_packed(
                boxes[j], self.src_h, self.src_w,
                self.packed_h, self.packed_w, h, w,
            )
            out[j] = frame[top_p : top_p + h, left_p : left_p + w]
        return out

    def get_window(
        self, idx: int, rng: Optional[np.random.Generator] = None
    ) -> Dict[str, Dict[str, np.ndarray]]:
        """Same nested sample dict as `WindowedEpisodeDataset.get_window`."""
        ep_i, start = self.index[idx]
        meta = self.meta(ep_i)
        images = self.gather_frames(ep_i, start, rng)
        embeds, actions, terms = [], [], []
        for j in range(self.window):
            src = self._padded_src(start, j)
            embeds.append(meta["instruction"][src])
            actions.append(meta["action"][src])
            terms.append(np.int32(bool(meta["is_terminal"][src])))
        observations = {
            "image": images,
            "natural_language_embedding": np.stack(embeds).astype(np.float32),
        }
        if self._clip_tokenizer is not None:
            observations["instruction_tokenized_clip"] = np.tile(
                self._episode_clip_tokens(ep_i), (self.window, 1)
            )
        return {
            "observations": observations,
            "actions": {
                "terminate_episode": np.asarray(terms, np.int32),
                "action": np.stack(actions).astype(np.float32),
            },
        }

    def fill_window(
        self,
        idx: int,
        rng: np.random.Generator,
        image_out: np.ndarray,
        embed_out: np.ndarray,
        term_out: np.ndarray,
        action_out: np.ndarray,
    ) -> None:
        """Assemble window `idx` straight into batch-row buffers (no stack)."""
        ep_i, start = self.index[idx]
        meta = self.meta(ep_i)
        self.gather_frames(ep_i, start, rng, out=image_out)
        for j in range(self.window):
            src = self._padded_src(start, j)
            embed_out[j] = meta["instruction"][src]
            action_out[j] = meta["action"][src]
            term_out[j] = int(bool(meta["is_terminal"][src]))

    def fill_batch(
        self,
        indices: np.ndarray,
        rng: np.random.Generator,
        images: np.ndarray,
        embeds: np.ndarray,
        terms: np.ndarray,
        actions: np.ndarray,
        threads: int = 1,
    ) -> None:
        """Assemble a whole batch into preallocated buffers, vectorized.

        The feeder's hot path: one vectorized crop-offset draw, one global
        frame-index computation, and ONE native gather call (or a numpy
        slice loop) for the entire batch against the corpus mmap; meta
        members fill via one fancy-index each. Crop distribution matches
        the per-window path (`draw_packed_offsets`); byte-level stream
        parity with `get_window` is not a goal here — determinism is the
        feeder's (seed, ticket) contract.
        """
        n = len(indices)
        w = self.window
        h, wd = self.height, self.width
        offsets = self.draw_packed_offsets(rng, n * w)
        # Global frame indices: episode frame offset + padded source step.
        gidx = np.empty((n, w), np.int64)
        for i, idx in enumerate(indices):
            ep_i, start = self.index[int(idx)]
            gidx[i] = self._frame_offsets[ep_i] + self._padded_src_indices(start)
        flat_idx = gidx.reshape(-1)
        if _native_gather_available():
            from rt1_tpu.data import native

            boxes = np.empty((n * w, 4), np.int32)
            boxes[:, :2] = offsets
            boxes[:, 2] = h
            boxes[:, 3] = wd
            native.packed_gather(
                self._frames,
                flat_idx,
                boxes,
                images.reshape(n * w, h, wd, 3),
                threads=threads,
            )
        else:
            flat_img = images.reshape(n * w, h, wd, 3)
            for j in range(n * w):
                top, left = offsets[j]
                flat_img[j] = self._frames[
                    flat_idx[j], top : top + h, left : left + wd
                ]
        embeds[:] = self._meta["instruction"][gidx]
        actions[:] = self._meta["action"][gidx]
        terms[:] = self._meta["is_terminal"][gidx]

    def _episode_clip_tokens(self, ep_i: int) -> np.ndarray:
        with self._lock:
            tokens = self._clip_token_cache.get(ep_i)
        if tokens is None:
            entry = self.episodes[ep_i]
            if self._text is None or "text_offset" not in entry:
                raise KeyError(
                    f"episode {ep_i} in {self.pack_dir} has no "
                    "'instruction_text'; re-pack from a corpus collected "
                    "with a current rt1_tpu.data.collect to use clip_tokens"
                )
            off, ln = int(entry["text_offset"]), int(entry["text_len"])
            text = ep_lib.decode_instruction_text(self._text[off : off + ln])
            tokens = self._clip_tokenizer.tokenize_text(text)[0].astype(np.int32)
            with self._lock:
                self._clip_token_cache[ep_i] = tokens
        return tokens


def _native_gather_available() -> bool:
    if os.environ.get("RT1_TPU_NO_NATIVE"):
        return False
    try:
        from rt1_tpu.data import native

        return native.packed_gather_available()
    except Exception:
        return False


def default_pack_dir(data_dir: str, split: str) -> str:
    """Convention: the packed cache lives next to its split's episodes."""
    return os.path.join(data_dir, f"{split}_packed")
