"""Packed mmap frame cache: decode-once episodes at augmentation headroom.

The tf.data path pays the full augmentation bill per *sample*: every window
re-reads decoded 256x456-class frames and random-resize-crops each one
(~42 ms/batch on the single-core bench host against an 8 ms device step —
the 78% input stall in docs/performance.md). The fix is to move every
per-pixel operation that does not depend on the random crop offset to an
offline pass:

* `pack_episodes` decodes each episode ONCE and stores its frames resized to
  the *packed* resolution — the smallest frame from which every random crop
  of the training distribution can be cut as a pure slice — appended into a
  corpus-wide uint8 frames file (mmap-able, no headers), with the small
  step-aligned members (action/instruction/flags) concatenated into raw
  `meta_<member>.npy` files and a JSON manifest carrying geometry,
  per-episode frame offsets, and source fingerprints. One file per array,
  not per episode: a 7800-episode corpus costs a handful of open fds and
  zero per-window parsing (per-episode `.npz` sidecars measured 3.2 ms/load
  — reintroducing the exact per-sample I/O tax this cache removes).
* `PackedEpisodeCache` maps the frames files once and assembles a training
  window as h x w uint8 slices out of the mmaps — no decode, no resize, no
  float math, no handle churn.

Sharded pack format v2 (the data flywheel, docs/data.md): the corpus is a
list of **shards** — `frames.bin` plus zero or more `frames_<k>.bin` — each
with its own meta sidecars and fingerprints, listed in the manifest with a
monotonically increasing `freshness_epoch`. `append_shard` adds newly
collected/captured episodes as a NEW shard and atomically rewrites the
manifest (shard files land fully before the manifest rename, so readers
see either the old corpus or the whole new shard — never a torn append),
and `PackedEpisodeCache.refresh()` picks new shards up in a live process.
Pre-shard manifests (format_version 2, one `frames.bin`) load unchanged as
a single-shard corpus — same files, same bytes, same samples.

Crop-distribution parity (tested in tests/test_packed_cache.py): the random
box is still drawn by `pipeline._crop_box` in SOURCE-frame coordinates —
bit-identical draws to the tf.data path for the same rng — then mapped into
packed coordinates, where it is exactly (height, width) by construction:

    source (H0, W0) -- crop (ch0, cw0) = (int(H0*cf), int(W0*cf)) -> (h, w)
    packed (ph, pw) = (round(H0*h/ch0), round(W0*w/cw0))

so a ch0-tall source crop spans h packed rows, and the gather is
`frames[t, top_p:top_p+h, left_p:left_p+w]`. The only pixel-semantics
difference vs the tf.data path is resize-once-then-slice instead of
slice-then-resize (the same interpolation family, applied once offline).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from rt1_tpu.data import episodes as ep_lib
from rt1_tpu.data.pipeline import _crop_box, crop_resize_frames
from rt1_tpu.resilience import faults

MANIFEST_NAME = "pack_manifest.json"
FRAMES_NAME = "frames.bin"
# Sharded manifests. Format 2 (one frames.bin, no shard list) is the
# pre-flywheel layout; it loads as a single-shard corpus with no byte
# rewritten on disk.
FORMAT_VERSION = 3
LEGACY_FORMAT_VERSION = 2
# Step-aligned members consolidated into meta_<name><suffix>.npy
# (concatenated over episodes along axis 0, raw .npy so the cache opens
# them mmap_mode="r").
META_MEMBERS = ("action", "instruction", "is_first", "is_terminal")
TEXT_MEMBER = "instruction_text"
TEXT_NAME = "meta_instruction_text.npy"
#: Task id reported for episodes whose manifest entry carries no `task`
#: meta (legacy format-2 packs, pre-task corpora). THE definition of the
#: slug — pack.py is numpy+stdlib only, so every consumer (collect's
#: stamping path, the feeder's mixture weights, the eval matrix) imports
#: this one spelling.
UNKNOWN_TASK = "unknown"


def shard_suffix(k: int) -> str:
    """File-name suffix of shard `k`: shard 0 keeps the pre-shard names
    (`frames.bin`, `meta_action.npy`) so a fresh pack stays byte-identical
    to the format-2 layout; appended shards are `frames_00001.bin`, ..."""
    return "" if k == 0 else f"_{k:05d}"


def shard_frames_name(suffix: str) -> str:
    return f"frames{suffix}.bin" if suffix else FRAMES_NAME


def shard_meta_name(member: str, suffix: str) -> str:
    return f"meta_{member}{suffix}.npy"


# --------------------------------------------------------------------- geometry


def crop_size(dim: int, crop_factor: Optional[float]) -> int:
    """Source-coordinate crop size along one dim (`_crop_box` parity)."""
    return dim if crop_factor is None else int(dim * crop_factor)


def packed_dims(
    src_h: int,
    src_w: int,
    height: int,
    width: int,
    crop_factor: Optional[float],
) -> Tuple[int, int]:
    """Packed (ph, pw): a `crop_factor` source crop spans exactly (h, w).

    crop_factor None degenerates to (height, width) — the gather is then the
    whole packed frame.
    """
    ch0 = crop_size(src_h, crop_factor)
    cw0 = crop_size(src_w, crop_factor)
    ph = int(round(src_h * height / ch0))
    pw = int(round(src_w * width / cw0))
    # round() cannot undershoot the slice size by construction (ch0 <= src_h
    # implies src_h*h/ch0 >= h) except through the 0.5-rounding edge; clamp
    # so the (h, w) gather slice always fits.
    return max(ph, height), max(pw, width)


def map_box_to_packed(
    box: Tuple[int, int, int, int],
    src_h: int,
    src_w: int,
    ph: int,
    pw: int,
    height: int,
    width: int,
) -> Tuple[int, int]:
    """Source-coordinate crop box -> (top, left) of its (h, w) packed slice."""
    top, left, ch, cw = box
    top_p = int(round(top * height / max(ch, 1)))
    left_p = int(round(left * width / max(cw, 1)))
    return min(max(top_p, 0), ph - height), min(max(left_p, 0), pw - width)


# --------------------------------------------------------------------- packer


def _fingerprint(path: str) -> Dict[str, object]:
    st = os.stat(path)
    return {"name": os.path.basename(path), "bytes": st.st_size,
            "mtime": round(st.st_mtime, 3)}


def _fingerprint_key(fp: Dict[str, object]) -> Tuple:
    return (fp.get("name"), fp.get("bytes"), fp.get("mtime"))


def _resize_episode_frames(rgb: np.ndarray, ph: int, pw: int) -> np.ndarray:
    """(T, H0, W0, 3) uint8 -> (T, ph, pw, 3) uint8, full-frame resize."""
    t, h0, w0, _ = rgb.shape
    if (h0, w0) == (ph, pw):
        return np.ascontiguousarray(rgb)
    boxes = np.tile(np.array([[0, 0, h0, w0]], np.int32), (t, 1))
    return crop_resize_frames(list(rgb), boxes, ph, pw)


def _write_shard(
    out_dir: str,
    paths: Sequence[str],
    suffix: str,
    src_h: Optional[int],
    src_w: Optional[int],
    ph: Optional[int],
    pw: Optional[int],
    height: int,
    width: int,
    crop_factor: Optional[float],
    frame_base: int,
    shard_index: int,
) -> Tuple[List[Dict[str, object]], Dict[str, object], int, int, int]:
    """Decode `paths` once into one shard's frames + meta files.

    Returns (episode_entries, shard_entry, steps, src_h, src_w). Frame
    offsets in the episode entries are GLOBAL (frame_base + local); text
    offsets are LOCAL to this shard's text file. `src_h`/`src_w` None means
    "infer from the first episode" (fresh pack); a fixed value enforces the
    corpus-wide geometry on append.
    """
    os.makedirs(out_dir, exist_ok=True)
    episodes: List[Dict[str, object]] = []
    meta_parts: Dict[str, List[np.ndarray]] = {k: [] for k in META_MEMBERS}
    text_parts: List[np.ndarray] = []
    have_text = True
    frame_offset = frame_base
    text_offset = 0
    frames_name = shard_frames_name(suffix)
    frames_tmp = os.path.join(out_dir, frames_name + ".tmp")
    with open(frames_tmp, "wb") as frames_f:
        for path in paths:
            ep = ep_lib.load_episode(path)
            ep_lib.validate_episode(ep)
            rgb = np.asarray(ep["rgb"], np.uint8)
            t, h0, w0, _ = rgb.shape
            if src_h is None:
                src_h, src_w = h0, w0
                ph, pw = packed_dims(src_h, src_w, height, width, crop_factor)
            elif (h0, w0) != (src_h, src_w):
                raise ValueError(
                    f"{path}: source frames {h0}x{w0} differ from corpus "
                    f"{src_h}x{src_w}; the packed geometry is corpus-wide"
                )
            _resize_episode_frames(rgb, ph, pw).tofile(frames_f)
            for k in META_MEMBERS:
                meta_parts[k].append(np.asarray(ep[k]))
            entry = {
                "steps": int(t),
                "frame_offset": int(frame_offset),
                "shard": int(shard_index),
                "source": _fingerprint(path),
            }
            # The per-episode task id (reward family / capture workload tag)
            # rides the manifest so task-mixture sampling can weight windows
            # without reopening any episode file.
            if "task" in ep:
                entry["task"] = ep_lib.decode_instruction_text(ep["task"])
            if have_text and "instruction_text" in ep:
                text = np.asarray(ep["instruction_text"], np.uint8)
                text_parts.append(text)
                entry["text_offset"] = int(text_offset)
                entry["text_len"] = int(text.shape[0])
                text_offset += int(text.shape[0])
            else:
                # All-or-nothing per shard: a shard with only some
                # instruction_text members packs without any (mirrors the tf
                # path, which KeyErrors per missing episode at clip-token
                # time).
                have_text = False
            episodes.append(entry)
            frame_offset += t
    os.replace(frames_tmp, os.path.join(out_dir, frames_name))
    for k in META_MEMBERS:
        _atomic_save_npy(
            os.path.join(out_dir, shard_meta_name(k, suffix)),
            np.concatenate(meta_parts[k], axis=0),
        )
    has_text = bool(have_text and text_parts)
    if has_text:
        _atomic_save_npy(
            os.path.join(out_dir, shard_meta_name(TEXT_MEMBER, suffix)),
            np.concatenate(text_parts),
        )
    else:
        for e in episodes:
            e.pop("text_offset", None)
            e.pop("text_len", None)
    steps = frame_offset - frame_base
    shard_entry = {
        "suffix": suffix,
        "frames": frames_name,
        "steps": int(steps),
        "frame_base": int(frame_base),
        "episodes": len(episodes),
        "bytes": int(steps) * int(ph) * int(pw) * 3,
        "has_text": has_text,
    }
    return episodes, shard_entry, steps, int(src_h), int(src_w)


def _write_manifest(out_dir: str, manifest: Dict[str, object]) -> None:
    tmp = os.path.join(out_dir, MANIFEST_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    os.replace(tmp, os.path.join(out_dir, MANIFEST_NAME))


def pack_episodes(
    paths: Sequence[str],
    out_dir: str,
    height: int,
    width: int,
    crop_factor: Optional[float],
    force: bool = False,
) -> Dict[str, object]:
    """Decode each episode once, write packed frames + sidecars + manifest.

    Returns the manifest dict. Skips work when `pack_is_fresh` already holds
    (unless `force`). Source frames must share one (H0, W0) across the
    corpus — the packed geometry is corpus-wide. The result is a one-shard
    sharded manifest whose shard-0 files keep the pre-shard names, so the
    on-disk frame/meta bytes are identical to a format-2 pack.
    """
    paths = sorted(paths)
    if not paths:
        raise ValueError("pack_episodes: no episode paths given")
    if not force and pack_is_fresh(out_dir, paths, height, width, crop_factor):
        return load_manifest(out_dir)

    # Geometry is inferred inside _write_shard from the first episode.
    episodes, shard_entry, steps, src_h, src_w = _write_shard(
        out_dir, paths, shard_suffix(0), None, None, None, None,
        height, width, crop_factor, frame_base=0, shard_index=0,
    )
    ph, pw = packed_dims(src_h, src_w, height, width, crop_factor)
    manifest = {
        "format_version": FORMAT_VERSION,
        "freshness_epoch": 0,
        "source": {"height": src_h, "width": src_w},
        "train": {
            "height": int(height),
            "width": int(width),
            "crop_factor": crop_factor,
        },
        "packed": {"height": int(ph), "width": int(pw)},
        "total_steps": int(steps),
        "has_instruction_text": bool(shard_entry["has_text"]),
        "shards": [shard_entry],
        "episodes": episodes,
    }
    _write_manifest(out_dir, manifest)
    return manifest


def append_shard(
    pack_dir: str, paths: Sequence[str]
) -> Dict[str, object]:
    """Append newly collected episodes to an existing pack as a NEW shard.

    The data-flywheel write path: episodes already present (matched by
    source fingerprint) are skipped, the remainder are decoded once into
    `frames_<k>.bin` + meta sidecars, and the manifest is atomically
    rewritten with the new shard, extended episode list, and a bumped
    `freshness_epoch`. Shard files are fully on disk BEFORE the manifest
    rename, so a crash mid-append (chaos site `pack_append@N`) leaves at
    worst orphaned shard files next to a valid old manifest — readers never
    observe a torn corpus. Returns the (possibly unchanged) manifest.
    """
    manifest = load_manifest(pack_dir)
    known = {
        _fingerprint_key(e.get("source", {}))
        for e in manifest["episodes"]
    }
    new_paths = [
        p for p in sorted(paths)
        if _fingerprint_key(_fingerprint(p)) not in known
    ]
    if not new_paths:
        return manifest
    k = len(manifest["shards"])
    train = manifest["train"]
    episodes, shard_entry, steps, _, _ = _write_shard(
        pack_dir,
        new_paths,
        shard_suffix(k),
        int(manifest["source"]["height"]),
        int(manifest["source"]["width"]),
        int(manifest["packed"]["height"]),
        int(manifest["packed"]["width"]),
        int(train["height"]),
        int(train["width"]),
        train["crop_factor"],
        frame_base=int(manifest["total_steps"]),
        shard_index=k,
    )
    shard_entry["appended"] = True
    # Chaos site: shard files are written, the manifest rename has not
    # happened — the torn-append window readers must be immune to.
    faults.maybe_fail(
        "pack_append",
        index=int(manifest["freshness_epoch"]) + 1,
        what=f"shard {shard_entry['frames']} in {pack_dir}",
    )
    manifest["episodes"] = list(manifest["episodes"]) + episodes
    manifest["shards"] = list(manifest["shards"]) + [shard_entry]
    manifest["total_steps"] = int(manifest["total_steps"]) + int(steps)
    manifest["freshness_epoch"] = int(manifest["freshness_epoch"]) + 1
    manifest["has_instruction_text"] = bool(
        manifest["has_instruction_text"] and shard_entry["has_text"]
    )
    manifest["format_version"] = FORMAT_VERSION
    _write_manifest(pack_dir, manifest)
    return manifest


def _atomic_save_npy(path: str, arr: np.ndarray) -> None:
    tmp = path + ".tmp.npy"  # .npy suffix keeps np.save from appending one
    np.save(tmp, arr)
    os.replace(tmp, path)


# ----------------------------------------------------------------- manifests


def load_manifest(pack_dir: str) -> Dict[str, object]:
    """Read + normalize a pack manifest to the sharded (v3) shape.

    A legacy format-2 manifest (one `frames.bin`, no shard list) is
    presented as a single-shard corpus: `shards` synthesized, every episode
    stamped `shard: 0`, `freshness_epoch` 0. Nothing is rewritten on disk —
    old packs keep loading byte-identically. Raises ValueError for unknown
    versions.
    """
    with open(os.path.join(pack_dir, MANIFEST_NAME)) as f:
        manifest = json.load(f)
    version = manifest.get("format_version")
    if version == FORMAT_VERSION:
        return manifest
    if version != LEGACY_FORMAT_VERSION:
        raise ValueError(
            f"{pack_dir}: pack format {version} is not "
            f"{LEGACY_FORMAT_VERSION} or {FORMAT_VERSION} — re-pack with "
            "scripts/pack_dataset.py"
        )
    total = int(manifest.get("total_steps", 0))
    ph = int(manifest["packed"]["height"])
    pw = int(manifest["packed"]["width"])
    manifest["freshness_epoch"] = 0
    manifest["shards"] = [
        {
            "suffix": "",
            "frames": FRAMES_NAME,
            "steps": total,
            "frame_base": 0,
            "episodes": len(manifest.get("episodes", [])),
            "bytes": total * ph * pw * 3,
            "has_text": bool(manifest.get("has_instruction_text")),
        }
    ]
    for e in manifest.get("episodes", []):
        e.setdefault("shard", 0)
    return manifest


def verify_shards(
    pack_dir: str, manifest: Dict[str, object]
) -> List[str]:
    """Validate EVERY shard's files; returns problem strings naming the
    missing/corrupt shard (empty = intact). Checked on cache open, on
    `refresh`, and by the staleness gate — a pack with a torn or deleted
    shard must fail loudly with the shard's name, not stream garbage."""
    problems: List[str] = []
    for shard in manifest.get("shards", []):
        suffix = shard.get("suffix", "")
        frames = os.path.join(pack_dir, shard_frames_name(suffix))
        expected = int(shard.get("bytes", 0))
        try:
            size = os.path.getsize(frames)
        except OSError:
            problems.append(f"shard {shard_frames_name(suffix)!r}: missing")
            continue
        if size != expected:
            problems.append(
                f"shard {shard_frames_name(suffix)!r}: {size} bytes on "
                f"disk, manifest expects {expected}"
            )
        for member in META_MEMBERS:
            meta = os.path.join(pack_dir, shard_meta_name(member, suffix))
            if not os.path.exists(meta):
                problems.append(
                    f"shard {shard_frames_name(suffix)!r}: sidecar "
                    f"{shard_meta_name(member, suffix)!r} missing"
                )
        if shard.get("has_text") and not os.path.exists(
            os.path.join(pack_dir, shard_meta_name(TEXT_MEMBER, suffix))
        ):
            problems.append(
                f"shard {shard_frames_name(suffix)!r}: sidecar "
                f"{shard_meta_name(TEXT_MEMBER, suffix)!r} missing"
            )
    return problems


def pack_status(
    pack_dir: str,
    paths: Sequence[str],
    height: int,
    width: int,
    crop_factor: Optional[float],
) -> Tuple[bool, str]:
    """(fresh, reason) for `pack_dir` against base episode set `paths`.

    Fresh = same train geometry, shard 0 built from exactly `paths` (same
    basenames in order, unchanged size/mtime fingerprints), and EVERY shard
    — including flywheel-appended ones, which are not part of the base set
    — present and intact on disk. The reason string names what failed
    (which shard, which episode) so the fallback log is actionable.
    """
    try:
        manifest = load_manifest(pack_dir)
    except (OSError, ValueError) as exc:
        return False, f"manifest unreadable: {exc}"
    train = manifest.get("train", {})
    if (
        train.get("height") != height
        or train.get("width") != width
        or train.get("crop_factor") != crop_factor
    ):
        return False, (
            f"train geometry {train.get('height')}x{train.get('width')}"
            f"@{train.get('crop_factor')} != requested "
            f"{height}x{width}@{crop_factor}"
        )
    base = [e for e in manifest.get("episodes", []) if e.get("shard") == 0]
    paths = sorted(paths)
    if len(base) != len(paths):
        return False, (
            f"base shard has {len(base)} episodes, source dir has "
            f"{len(paths)}"
        )
    for entry, path in zip(base, paths):
        try:
            fp = _fingerprint(path)
        except OSError:
            return False, f"source episode {path!r} unreadable"
        if entry.get("source") != fp:
            return False, (
                f"source episode {os.path.basename(path)!r} changed since "
                "packing"
            )
    problems = verify_shards(pack_dir, manifest)
    if problems:
        return False, "; ".join(problems)
    return True, "fresh"


def pack_is_fresh(
    pack_dir: str,
    paths: Sequence[str],
    height: int,
    width: int,
    crop_factor: Optional[float],
) -> bool:
    """True when `pack_dir` holds a current pack of exactly `paths` (plus
    any intact appended shards); see `pack_status` for the reason string."""
    return pack_status(pack_dir, paths, height, width, crop_factor)[0]


# --------------------------------------------------------------------- cache


class _OpenShard:
    """One shard's open mmaps: frames + step-aligned meta (+ text)."""

    __slots__ = ("frames", "meta", "text", "base", "steps")

    def __init__(self, frames, meta, text, base, steps):
        self.frames = frames
        self.meta = meta
        self.text = text
        self.base = base
        self.steps = steps


class PackedEpisodeCache:
    """Window sampler over a packed cache: mmap slices, not decodes.

    Mirrors `WindowedEpisodeDataset`'s sample distribution exactly (same
    (episode, start) index, same front-padding, `_crop_box` draws in source
    coordinates) but a window's frames are (h, w) uint8 slices out of the
    per-shard frame mmaps. `get_window` returns the same nested dict the
    tf.data path produces; `fill_batch` writes a whole batch straight into
    caller-provided buffers (the feeder's arrays). Total open handles: one
    frames mmap + one mmap per meta member PER SHARD, regardless of corpus
    size — there is no per-episode state to cache or evict.

    Flywheel semantics: `refresh()` re-reads the manifest and opens any
    newly appended shards in place — existing episode indices, window
    index entries, and open mmaps are never disturbed, so concurrent
    readers (feeder workers mid-batch) are safe; the feeder calls it at
    epoch boundaries only, keeping every epoch's stream a pure function of
    (seed, epoch, corpus-at-epoch-start).
    """

    def __init__(self, pack_dir: str, window: int = 6, clip_tokenizer=None):
        self.pack_dir = pack_dir
        self.manifest = load_manifest(pack_dir)
        problems = verify_shards(pack_dir, self.manifest)
        if problems:
            raise ValueError(
                f"{pack_dir}: packed cache is torn — " + "; ".join(problems)
            )
        self.window = window
        self.height = int(self.manifest["train"]["height"])
        self.width = int(self.manifest["train"]["width"])
        self.crop_factor = self.manifest["train"]["crop_factor"]
        self.src_h = int(self.manifest["source"]["height"])
        self.src_w = int(self.manifest["source"]["width"])
        self.packed_h = int(self.manifest["packed"]["height"])
        self.packed_w = int(self.manifest["packed"]["width"])
        self.episodes = list(self.manifest["episodes"])
        self.total_steps = int(self.manifest["total_steps"])
        self.freshness_epoch = int(self.manifest.get("freshness_epoch", 0))
        self.refreshes = 0  # successful mid-run shard pickups
        self.last_refresh_unix = time.time()
        self._clip_tokenizer = clip_tokenizer
        self._clip_token_cache: Dict[int, np.ndarray] = {}
        self._lock = threading.Lock()
        self._shards: List[_OpenShard] = [
            self._open_shard(s) for s in self.manifest["shards"]
        ]
        self._shard_bases = np.array(
            [s.base for s in self._shards], np.int64
        )
        self._frame_offsets = np.array(
            [int(e["frame_offset"]) for e in self.episodes], np.int64
        )
        self.index: List[Tuple[int, int]] = []
        for i, entry in enumerate(self.episodes):
            self.index.extend((i, s) for s in range(int(entry["steps"])))

    def _open_shard(self, shard: Dict[str, object]) -> _OpenShard:
        suffix = shard.get("suffix", "")
        steps = int(shard["steps"])
        # One mapping for every frame in the shard; the kernel pages in
        # only what gets sliced.
        frames = np.memmap(
            os.path.join(self.pack_dir, shard_frames_name(suffix)),
            dtype=np.uint8,
            mode="r",
            shape=(steps, self.packed_h, self.packed_w, 3),
        )
        # Raw .npy metas opened mmap_mode="r": header parsed once here,
        # window access is a page-cached fancy-index (the per-episode
        # .npz sidecars this replaces cost 3.2 ms of zipfile parsing per
        # load — a per-sample tax at corpus scale).
        meta = {
            k: np.load(
                os.path.join(self.pack_dir, shard_meta_name(k, suffix)),
                mmap_mode="r",
            )
            for k in META_MEMBERS
        }
        text = None
        if shard.get("has_text"):
            text = np.load(
                os.path.join(
                    self.pack_dir, shard_meta_name(TEXT_MEMBER, suffix)
                ),
                mmap_mode="r",
            )
        return _OpenShard(
            frames, meta, text, base=int(shard["frame_base"]), steps=steps
        )

    def __len__(self) -> int:
        return len(self.index)

    # ------------------------------------------------------------ flywheel

    @property
    def num_shards(self) -> int:
        return len(self._shards)

    @property
    def appended_episodes(self) -> int:
        """Episodes living in flywheel-appended shards (shard > 0)."""
        return sum(
            int(s.get("episodes", 0))
            for s in self.manifest["shards"]
            if s.get("appended")
        )

    def episode_task(self, ep_i: int) -> str:
        """The per-episode task id carried through capture/pack metas
        (reward family, capture workload tag) — the hook task-mixture
        sampling weights against. Episodes packed before task stamping
        existed (legacy format-2 manifests, untagged corpora) report the
        stable ``UNKNOWN_TASK`` slug instead of None/raising, so mixture
        weights and per-task telemetry always see a string id."""
        return self.episodes[ep_i].get("task") or UNKNOWN_TASK

    @property
    def tasks(self) -> List[str]:
        """Per-episode task ids, index-aligned with `episodes` (untagged
        episodes report ``UNKNOWN_TASK``)."""
        return [e.get("task") or UNKNOWN_TASK for e in self.episodes]

    def refresh(self) -> bool:
        """Pick up shards appended since open; True when the corpus grew.

        Re-reads the manifest; on a bumped `freshness_epoch` the new
        shards are validated (a torn append is skipped loudly, the old
        view keeps serving) and opened, and `episodes`/`index`/offset
        tables are EXTENDED in place — entries already handed to readers
        never move. Geometry is append-invariant by construction
        (`append_shard` enforces it)."""
        with self._lock:
            try:
                manifest = load_manifest(self.pack_dir)
            except (OSError, ValueError):
                return False  # mid-rewrite or gone; keep the current view
            self.last_refresh_unix = time.time()
            fresh_epoch = int(manifest.get("freshness_epoch", 0))
            if (
                fresh_epoch <= self.freshness_epoch
                or len(manifest["episodes"]) < len(self.episodes)
                or len(manifest["shards"]) <= len(self._shards)
            ):
                return False
            problems = verify_shards(self.pack_dir, manifest)
            if problems:
                import logging

                logging.getLogger(__name__).warning(
                    "packed cache refresh skipped — %s", "; ".join(problems)
                )
                return False
            self.manifest = manifest
            for shard in manifest["shards"][len(self._shards):]:
                self._shards.append(self._open_shard(shard))
            new_eps = manifest["episodes"][len(self.episodes):]
            base_i = len(self.episodes)
            self.episodes.extend(new_eps)
            self._shard_bases = np.array(
                [s.base for s in self._shards], np.int64
            )
            self._frame_offsets = np.array(
                [int(e["frame_offset"]) for e in self.episodes], np.int64
            )
            for i, entry in enumerate(new_eps, start=base_i):
                self.index.extend(
                    (i, s) for s in range(int(entry["steps"]))
                )
            self.total_steps = int(manifest["total_steps"])
            self.freshness_epoch = fresh_epoch
            self.refreshes += 1
            return True

    # ------------------------------------------------------------ file access

    def _episode_shard(self, ep_i: int) -> Tuple[_OpenShard, int]:
        """(shard, local frame offset) for episode `ep_i` — episodes never
        span shards."""
        entry = self.episodes[ep_i]
        shard = self._shards[int(entry.get("shard", 0))]
        return shard, int(entry["frame_offset"]) - shard.base

    def frames(self, ep_i: int) -> np.ndarray:
        """(T, ph, pw, 3) uint8 view of episode `ep_i`'s packed frames."""
        shard, off = self._episode_shard(ep_i)
        return shard.frames[off : off + int(self.episodes[ep_i]["steps"])]

    def meta(self, ep_i: int) -> Dict[str, np.ndarray]:
        """Step-aligned member views for episode `ep_i` (zero copies)."""
        shard, off = self._episode_shard(ep_i)
        end = off + int(self.episodes[ep_i]["steps"])
        return {k: v[off:end] for k, v in shard.meta.items()}

    # ------------------------------------------------------------ sampling

    def draw_box(self, rng: np.random.Generator) -> Tuple[int, int, int, int]:
        """One source-coordinate crop box — the tf.data path's distribution,
        drawn by the same `_crop_box` (bit-identical for the same rng)."""
        return _crop_box(self.src_h, self.src_w, self.crop_factor, rng)

    def draw_packed_offsets(
        self, rng: np.random.Generator, n: int
    ) -> np.ndarray:
        """(n, 2) packed-coordinate (top, left) offsets, drawn vectorized.

        Identical distribution to mapping `draw_box` results one by one
        (uniform integers over the same source ranges, the same
        round-and-clip into packed coordinates) but one rng call per axis
        for the whole batch — the feeder's hot path. Not the same *stream*
        as per-frame `_crop_box` draws; the byte-parity contract with the
        tf.data path lives on `get_window`/`gather_frames`, which keep the
        sequential draw order.
        """
        h, w = self.height, self.width
        ph, pw = self.packed_h, self.packed_w
        if self.crop_factor is None:
            return np.zeros((n, 2), np.int32)
        ch0 = int(self.src_h * self.crop_factor)
        cw0 = int(self.src_w * self.crop_factor)
        tops = rng.integers(0, self.src_h - ch0 + 1, size=n)
        lefts = rng.integers(0, self.src_w - cw0 + 1, size=n)
        out = np.empty((n, 2), np.int32)
        # np.rint is round-half-even, matching map_box_to_packed's
        # int(round(.)) on the scalar path.
        out[:, 0] = np.clip(np.rint(tops * (h / ch0)), 0, ph - h)
        out[:, 1] = np.clip(np.rint(lefts * (w / cw0)), 0, pw - w)
        return out

    def _padded_src(self, start: int, j: int) -> int:
        """Index into the unpadded episode for step j of the padded window."""
        pad = self.window - 1
        k = start + j
        return 0 if k < pad else k - pad

    def _padded_src_indices(self, start: int) -> np.ndarray:
        """(window,) int64 unpadded source steps for the whole window."""
        k = np.arange(start, start + self.window, dtype=np.int64)
        return np.maximum(k - (self.window - 1), 0)

    def gather_frames(
        self,
        ep_i: int,
        start: int,
        rng: Optional[np.random.Generator] = None,
        out: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        """(window, h, w, 3) uint8 for window `start` of episode `ep_i`.

        Each frame is an independent random crop; boxes are drawn
        per-frame in source coordinates with the tf.data path's exact rng
        consumption order (the byte-parity path — `fill_batch` is the
        vectorized fast path). `out` lets callers fill a buffer in place.
        """
        mm = self.frames(ep_i)
        h, w = self.height, self.width
        if out is None:
            out = np.empty((self.window, h, w, 3), np.uint8)
        rng = rng or np.random.default_rng()
        boxes = [self.draw_box(rng) for _ in range(self.window)]
        use_native = _native_gather_available()
        if use_native:
            from rt1_tpu.data import native

            src = np.empty((self.window,), np.int64)
            pboxes = np.empty((self.window, 4), np.int32)
            for j in range(self.window):
                src[j] = self._padded_src(start, j)
                top_p, left_p = map_box_to_packed(
                    boxes[j], self.src_h, self.src_w,
                    self.packed_h, self.packed_w, h, w,
                )
                pboxes[j] = (top_p, left_p, h, w)
            native.packed_gather(mm, src, pboxes, out, threads=1)
            return out
        for j in range(self.window):
            frame = mm[self._padded_src(start, j)]
            top_p, left_p = map_box_to_packed(
                boxes[j], self.src_h, self.src_w,
                self.packed_h, self.packed_w, h, w,
            )
            out[j] = frame[top_p : top_p + h, left_p : left_p + w]
        return out

    def get_window(
        self, idx: int, rng: Optional[np.random.Generator] = None
    ) -> Dict[str, Dict[str, np.ndarray]]:
        """Same nested sample dict as `WindowedEpisodeDataset.get_window`."""
        ep_i, start = self.index[idx]
        meta = self.meta(ep_i)
        images = self.gather_frames(ep_i, start, rng)
        embeds, actions, terms = [], [], []
        for j in range(self.window):
            src = self._padded_src(start, j)
            embeds.append(meta["instruction"][src])
            actions.append(meta["action"][src])
            terms.append(np.int32(bool(meta["is_terminal"][src])))
        observations = {
            "image": images,
            "natural_language_embedding": np.stack(embeds).astype(np.float32),
        }
        if self._clip_tokenizer is not None:
            observations["instruction_tokenized_clip"] = np.tile(
                self._episode_clip_tokens(ep_i), (self.window, 1)
            )
        return {
            "observations": observations,
            "actions": {
                "terminate_episode": np.asarray(terms, np.int32),
                "action": np.stack(actions).astype(np.float32),
            },
        }

    def fill_window(
        self,
        idx: int,
        rng: np.random.Generator,
        image_out: np.ndarray,
        embed_out: np.ndarray,
        term_out: np.ndarray,
        action_out: np.ndarray,
    ) -> None:
        """Assemble window `idx` straight into batch-row buffers (no stack)."""
        ep_i, start = self.index[idx]
        meta = self.meta(ep_i)
        self.gather_frames(ep_i, start, rng, out=image_out)
        for j in range(self.window):
            src = self._padded_src(start, j)
            embed_out[j] = meta["instruction"][src]
            action_out[j] = meta["action"][src]
            term_out[j] = int(bool(meta["is_terminal"][src]))

    def _gather_meta(self, member: str, gidx: np.ndarray) -> np.ndarray:
        """Fancy-index a step-aligned member by GLOBAL frame index across
        shards; single-shard corpora stay the one-mmap fast path."""
        if len(self._shards) == 1:
            return self._shards[0].meta[member][gidx]
        flat = gidx.reshape(-1)
        shard_ids = (
            np.searchsorted(self._shard_bases, flat, side="right") - 1
        )
        first = self._shards[0].meta[member]
        out = np.empty((flat.shape[0],) + first.shape[1:], first.dtype)
        for k in np.unique(shard_ids):
            rows = np.nonzero(shard_ids == k)[0]
            shard = self._shards[int(k)]
            out[rows] = shard.meta[member][flat[rows] - shard.base]
        return out.reshape(gidx.shape + first.shape[1:])

    def fill_batch(
        self,
        indices: np.ndarray,
        rng: np.random.Generator,
        images: np.ndarray,
        embeds: np.ndarray,
        terms: np.ndarray,
        actions: np.ndarray,
        threads: int = 1,
        offsets: Optional[np.ndarray] = None,
    ) -> None:
        """Assemble a whole batch into preallocated buffers, vectorized.

        The feeder's hot path: one vectorized crop-offset draw, one global
        frame-index computation, and ONE native gather call per shard
        touched (or a numpy slice loop) for the entire batch against the
        shard mmaps; meta members fill via one fancy-index each. Crop
        distribution matches the per-window path (`draw_packed_offsets`);
        byte-level stream parity with `get_window` is not a goal here —
        determinism is the feeder's (seed, epoch, batch) contract.

        ``offsets`` ((n·window, 2) int32 packed crop offsets) substitutes
        for the rng draw — the multi-host path: each host of a
        process-sharded feeder draws the GLOBAL batch's offsets from the
        shared (seed, epoch, batch) rng and passes only its rows here, so
        per-host shards concatenate to the exact single-host batch,
        augmentation included (rt1_tpu/data/feeder.py `_assemble`).
        """
        n = len(indices)
        w = self.window
        h, wd = self.height, self.width
        if offsets is None:
            offsets = self.draw_packed_offsets(rng, n * w)
        # Global frame indices: episode frame offset + padded source step.
        gidx = np.empty((n, w), np.int64)
        for i, idx in enumerate(indices):
            ep_i, start = self.index[int(idx)]
            gidx[i] = self._frame_offsets[ep_i] + self._padded_src_indices(start)
        flat_idx = gidx.reshape(-1)
        boxes = np.empty((n * w, 4), np.int32)
        boxes[:, :2] = offsets
        boxes[:, 2] = h
        boxes[:, 3] = wd
        flat_img = images.reshape(n * w, h, wd, 3)
        use_native = _native_gather_available()
        if len(self._shards) == 1:
            self._gather_shard(
                self._shards[0], flat_idx, boxes, flat_img, threads,
                use_native,
            )
        else:
            shard_ids = (
                np.searchsorted(self._shard_bases, flat_idx, side="right")
                - 1
            )
            for k in np.unique(shard_ids):
                rows = np.nonzero(shard_ids == k)[0]
                shard = self._shards[int(k)]
                sub = np.empty((len(rows), h, wd, 3), np.uint8)
                self._gather_shard(
                    shard, flat_idx[rows] - shard.base, boxes[rows], sub,
                    threads, use_native,
                )
                flat_img[rows] = sub
        embeds[:] = self._gather_meta("instruction", gidx)
        actions[:] = self._gather_meta("action", gidx)
        terms[:] = self._gather_meta("is_terminal", gidx)

    @staticmethod
    def _gather_shard(
        shard: _OpenShard,
        local_idx: np.ndarray,
        boxes: np.ndarray,
        out: np.ndarray,
        threads: int,
        use_native: bool,
    ) -> None:
        if use_native:
            from rt1_tpu.data import native

            native.packed_gather(
                shard.frames, local_idx, boxes, out, threads=threads
            )
            return
        h, wd = out.shape[1], out.shape[2]
        for j in range(len(local_idx)):
            top, left = boxes[j, 0], boxes[j, 1]
            out[j] = shard.frames[
                local_idx[j], top : top + h, left : left + wd
            ]

    def _episode_clip_tokens(self, ep_i: int) -> np.ndarray:
        with self._lock:
            tokens = self._clip_token_cache.get(ep_i)
        if tokens is None:
            entry = self.episodes[ep_i]
            shard = self._shards[int(entry.get("shard", 0))]
            if shard.text is None or "text_offset" not in entry:
                raise KeyError(
                    f"episode {ep_i} in {self.pack_dir} has no "
                    "'instruction_text'; re-pack from a corpus collected "
                    "with a current rt1_tpu.data.collect to use clip_tokens"
                )
            off, ln = int(entry["text_offset"]), int(entry["text_len"])
            text = ep_lib.decode_instruction_text(shard.text[off : off + ln])
            tokens = self._clip_tokenizer.tokenize_text(text)[0].astype(np.int32)
            with self._lock:
                self._clip_token_cache[ep_i] = tokens
        return tokens


def _native_gather_available() -> bool:
    if os.environ.get("RT1_TPU_NO_NATIVE"):
        return False
    try:
        from rt1_tpu.data import native

        return native.packed_gather_available()
    except Exception:
        return False


def default_pack_dir(data_dir: str, split: str) -> str:
    """Convention: the packed cache lives next to its split's episodes."""
    return os.path.join(data_dir, f"{split}_packed")
