"""Data pipeline: episode storage, windowing, and host→device feeding.

TPU-native re-design of the reference's Stack-A data path (SURVEY.md §2.3):
`rlds_np_convert.py` (offline RLDS→numpy with USE instruction embeddings) and
`load_np_dataset.py` (`EmbodiedIntelligenceDataset` sliding windows +
`DecodeAndRandomResizedCrop`). Same sample distribution — pad-with-first-frame,
every `window`-length window, random crop factor 0.95 → 456×256 — but stored as
stacked-array `.npz` episodes (the reference re-loads a whole pickled `.npy`
episode per sample, its I/O hot spot — SURVEY.md §7.7), streamed through tf.data
with per-host sharding, and fed to the mesh as sharded `jax.Array`s.
"""

from rt1_tpu.data.episodes import (
    Episode,
    generate_synthetic_episode,
    load_episode,
    read_reference_episode,
    save_episode,
)
from rt1_tpu.data.pipeline import WindowedEpisodeDataset, device_feeder

__all__ = [
    "Episode",
    "save_episode",
    "load_episode",
    "read_reference_episode",
    "generate_synthetic_episode",
    "WindowedEpisodeDataset",
    "device_feeder",
    # Packed mmap frame cache + sample-ahead feeder (lazy imports below
    # keep `import rt1_tpu.data` light): rt1_tpu.data.pack.pack_episodes /
    # PackedEpisodeCache, rt1_tpu.data.feeder.SampleAheadFeeder.
]
