"""ctypes binding for the native episode reader (native/episode_reader.cc).

The shared library is built on demand with g++ (no pybind11 needed). Arrays
backed by stored (uncompressed) members are zero-copy views into the mmap,
valid for the lifetime of the `NativeEpisode`; deflated members are owned
buffers. `load_episode_native` copies into regular numpy arrays by default
so callers never hold dangling views.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libepisode_reader.so")
_WS_LIB_PATH = os.path.join(_NATIVE_DIR, "libwindow_sampler.so")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False

_ws_lib = None
_ws_lock = threading.Lock()
_ws_build_failed = False


def _build_lib(source: str, lib_path: str, link_flags=()) -> bool:
    """Ensure `lib_path` exists and is newer than `source`; compile if not.

    The freshness check runs BEFORE any write (a read-only install with a
    prebuilt current .so must work). Compilation happens under an flock so
    racing worker processes serialize, to a temp name atomically renamed so
    no process ever dlopens (or has mapped) a half-written .so. The commands
    mirror native/Makefile (kept for manual/dev builds).
    """
    src_path = os.path.join(_NATIVE_DIR, source)
    if os.path.exists(lib_path) and not _source_newer(src_path, lib_path):
        return True
    try:
        import fcntl

        lock_path = os.path.join(_NATIVE_DIR, ".build.lock")
        with open(lock_path, "w") as lock_f:
            fcntl.flock(lock_f, fcntl.LOCK_EX)
            try:
                # Re-check under the lock: another process may have built.
                if not os.path.exists(lib_path) or _source_newer(
                    src_path, lib_path
                ):
                    tmp = lib_path + f".tmp.{os.getpid()}"
                    subprocess.run(
                        [
                            "g++", "-O2", "-std=c++17", "-fPIC", "-Wall",
                            "-shared", src_path, *link_flags, "-o", tmp,
                        ],
                        check=True,
                        capture_output=True,
                    )
                    os.replace(tmp, lib_path)
            finally:
                fcntl.flock(lock_f, fcntl.LOCK_UN)
        return True
    except (subprocess.CalledProcessError, FileNotFoundError, OSError):
        return False


def _build() -> bool:
    return _build_lib("episode_reader.cc", _LIB_PATH, ("-lz",))


def _source_newer(src: str, lib_path: str) -> bool:
    """Rebuild when the source is newer than the built library."""
    try:
        return os.path.getmtime(src) > os.path.getmtime(lib_path)
    except OSError:
        return True


def get_library() -> Optional[ctypes.CDLL]:
    """Load (building/rebuilding if needed) the library; None if unavailable."""
    global _lib, _build_failed
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        if not _build():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            _build_failed = True
            return None
        lib.er_open.restype = ctypes.c_void_p
        lib.er_open.argtypes = [ctypes.c_char_p]
        lib.er_num_members.argtypes = [ctypes.c_void_p]
        lib.er_member_name.restype = ctypes.c_char_p
        lib.er_member_name.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.er_member_dtype.restype = ctypes.c_char_p
        lib.er_member_dtype.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.er_member_ndim.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.er_member_shape.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.er_member_data.restype = ctypes.c_void_p
        lib.er_member_data.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.er_member_nbytes.restype = ctypes.c_int64
        lib.er_member_nbytes.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.er_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return get_library() is not None


def get_window_sampler() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native window sampler; None if n/a."""
    global _ws_lib, _ws_build_failed
    with _ws_lock:
        if _ws_lib is not None:
            return _ws_lib
        if _ws_build_failed:
            return None
        if not _build_lib("window_sampler.cc", _WS_LIB_PATH, ("-lpthread",)):
            _ws_build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_WS_LIB_PATH)
        except OSError:
            _ws_build_failed = True
            return None
        lib.ws_crop_resize_batch.argtypes = [
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_void_p,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
        ]
        # ws_packed_gather is absent from .so files built before the packed
        # cache landed; probe so a stale prebuilt library degrades to the
        # Python gather instead of an AttributeError mid-training.
        if hasattr(lib, "ws_packed_gather"):
            lib.ws_packed_gather.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int,
                ctypes.c_int,
                ctypes.c_int,
                ctypes.c_void_p,
                ctypes.c_int,
                ctypes.c_int,
                ctypes.c_int,
            ]
        _ws_lib = lib
        return _ws_lib


def sampler_available() -> bool:
    return (
        not os.environ.get("RT1_TPU_NO_NATIVE")
        and get_window_sampler() is not None
    )


def packed_gather_available() -> bool:
    """True when the built sampler exports the packed-format gather."""
    return sampler_available() and hasattr(
        get_window_sampler(), "ws_packed_gather"
    )


def packed_gather(
    frames: np.ndarray,
    frame_idx: np.ndarray,
    boxes: np.ndarray,
    out: np.ndarray,
    threads: int = 0,
) -> np.ndarray:
    """Gather n crops out of a packed (T, ph, pw, 3) uint8 frame block.

    frames: the episode's packed frames (typically an np.memmap);
    frame_idx: (n,) int64 frame indices; boxes: (n, 4) int32
    (top, left, crop_h, crop_w) in packed coordinates; out: (n, oh, ow, 3)
    uint8, written in place and returned. Crops already at (oh, ow) are
    strided memcpys (the packed-cache hot path); others bilinear-resample
    with cv2.INTER_LINEAR semantics. GIL-free and threaded like
    `crop_resize_batch`.
    """
    lib = get_window_sampler()
    if lib is None or not hasattr(lib, "ws_packed_gather"):
        raise RuntimeError("native packed gather unavailable")
    if frames.dtype != np.uint8 or frames.ndim != 4 or frames.shape[-1] != 3:
        raise ValueError(f"frames must be (T, ph, pw, 3) uint8, got "
                         f"{frames.dtype} {frames.shape}")
    if out.dtype != np.uint8 or not out.flags["C_CONTIGUOUS"]:
        raise ValueError("out must be C-contiguous uint8")
    n = len(frame_idx)
    t, ph, pw, _ = frames.shape
    idx = np.ascontiguousarray(frame_idx, np.int64)
    if n and (idx.min() < 0 or idx.max() >= t):
        raise IndexError(f"frame_idx out of range [0, {t})")
    boxes_arr = np.ascontiguousarray(boxes, np.int32)
    oh, ow = out.shape[1], out.shape[2]
    if n and (
        (boxes_arr[:, 0] < 0).any()
        or (boxes_arr[:, 1] < 0).any()
        or (boxes_arr[:, 0] + boxes_arr[:, 2] > ph).any()
        or (boxes_arr[:, 1] + boxes_arr[:, 3] > pw).any()
    ):
        raise IndexError("crop box out of packed-frame bounds")
    # np.memmap satisfies the buffer protocol; ctypes.data is the mapping.
    lib.ws_packed_gather(
        frames.ctypes.data_as(ctypes.c_void_p),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        boxes_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n,
        ph,
        pw,
        out.ctypes.data_as(ctypes.c_void_p),
        oh,
        ow,
        threads or (os.cpu_count() or 1),
    )
    return out


def crop_resize_batch(
    frames, boxes, out_h: int, out_w: int, threads: int = 0
) -> np.ndarray:
    """Crop+bilinear-resize a batch of frames in C++ (GIL-free, threaded).

    frames: sequence of (h, w, 3) uint8 arrays, all the same shape;
    boxes: (n, 4) int32 (top, left, crop_h, crop_w) per frame.
    Returns (n, out_h, out_w, 3) uint8. Matches cv2.INTER_LINEAR
    half-pixel-center semantics to +/-1 LSB.
    """
    lib = get_window_sampler()
    if lib is None:
        raise RuntimeError("native window sampler unavailable")
    n = len(frames)
    frames = [np.ascontiguousarray(f, np.uint8) for f in frames]
    h, w = frames[0].shape[:2]
    ptrs = (ctypes.c_void_p * n)(*[f.ctypes.data for f in frames])
    boxes_arr = np.ascontiguousarray(boxes, np.int32)
    out = np.empty((n, out_h, out_w, 3), np.uint8)
    lib.ws_crop_resize_batch(
        ptrs,
        boxes_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n,
        h,
        w,
        out.ctypes.data_as(ctypes.c_void_p),
        out_h,
        out_w,
        threads or (os.cpu_count() or 1),
    )
    return out


_DTYPES = {
    "<f4": np.float32,
    "<f8": np.float64,
    "<i4": np.int32,
    "<i8": np.int64,
    "<u4": np.uint32,
    "<u8": np.uint64,
    "|u1": np.uint8,
    "|i1": np.int8,
    "|b1": np.bool_,
    "<f2": np.float16,
}


class NativeEpisode:
    """Handle over one open episode file; arrays are materialized on read."""

    def __init__(self, path: str):
        lib = get_library()
        if lib is None:
            raise RuntimeError("native episode reader unavailable")
        self._lib = lib
        self._handle = lib.er_open(path.encode())
        if not self._handle:
            raise IOError(f"native reader failed to open {path}")

    def keys(self):
        return [
            self._lib.er_member_name(self._handle, i).decode()
            for i in range(self._lib.er_num_members(self._handle))
        ]

    def _array(self, i: int, copy: bool = True) -> np.ndarray:
        descr = self._lib.er_member_dtype(self._handle, i).decode()
        dtype = _DTYPES.get(descr)
        if dtype is None:
            raise ValueError(f"unsupported dtype {descr!r}")
        ndim = self._lib.er_member_ndim(self._handle, i)
        shape = (ctypes.c_int64 * max(ndim, 1))()
        self._lib.er_member_shape(self._handle, i, shape)
        nbytes = self._lib.er_member_nbytes(self._handle, i)
        ptr = self._lib.er_member_data(self._handle, i)
        buf = (ctypes.c_char * nbytes).from_address(ptr)
        arr = np.frombuffer(buf, dtype=dtype).reshape(tuple(shape[:ndim]))
        return arr.copy() if copy else arr

    def to_dict(self, copy: bool = True) -> Dict[str, np.ndarray]:
        return {
            self._lib.er_member_name(self._handle, i).decode(): self._array(
                i, copy=copy
            )
            for i in range(self._lib.er_num_members(self._handle))
        }

    def close(self):
        if self._handle:
            self._lib.er_close(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def load_episode_native(path: str) -> Dict[str, np.ndarray]:
    """Drop-in native replacement for `episodes.load_episode`."""
    with NativeEpisode(path) as ep:
        return ep.to_dict(copy=True)
