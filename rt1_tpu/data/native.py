"""ctypes binding for the native episode reader (native/episode_reader.cc).

The shared library is built on demand with g++ (no pybind11 needed). Arrays
backed by stored (uncompressed) members are zero-copy views into the mmap,
valid for the lifetime of the `NativeEpisode`; deflated members are owned
buffers. `load_episode_native` copies into regular numpy arrays by default
so callers never hold dangling views.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libepisode_reader.so")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _build() -> bool:
    """Ensure the library exists and is current; compile when needed.

    The freshness check runs BEFORE any write (a read-only install with a
    prebuilt current .so must work). Compilation happens under an flock so
    racing worker processes serialize, to a temp name atomically renamed so
    no process ever dlopens (or has mapped) a half-written .so. The command
    mirrors native/Makefile (kept for manual/dev builds).
    """
    if os.path.exists(_LIB_PATH) and not _source_newer():
        return True
    try:
        import fcntl

        lock_path = os.path.join(_NATIVE_DIR, ".build.lock")
        with open(lock_path, "w") as lock_f:
            fcntl.flock(lock_f, fcntl.LOCK_EX)
            try:
                # Re-check under the lock: another process may have built.
                if not os.path.exists(_LIB_PATH) or _source_newer():
                    tmp = _LIB_PATH + f".tmp.{os.getpid()}"
                    subprocess.run(
                        [
                            "g++", "-O2", "-std=c++17", "-fPIC", "-Wall",
                            "-shared",
                            os.path.join(_NATIVE_DIR, "episode_reader.cc"),
                            "-lz", "-o", tmp,
                        ],
                        check=True,
                        capture_output=True,
                    )
                    os.replace(tmp, _LIB_PATH)
            finally:
                fcntl.flock(lock_f, fcntl.LOCK_UN)
        return True
    except (subprocess.CalledProcessError, FileNotFoundError, OSError):
        return False


def _source_newer() -> bool:
    """Rebuild when episode_reader.cc is newer than the built library."""
    src = os.path.join(_NATIVE_DIR, "episode_reader.cc")
    try:
        return os.path.getmtime(src) > os.path.getmtime(_LIB_PATH)
    except OSError:
        return True


def get_library() -> Optional[ctypes.CDLL]:
    """Load (building/rebuilding if needed) the library; None if unavailable."""
    global _lib, _build_failed
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        if not _build():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            _build_failed = True
            return None
        lib.er_open.restype = ctypes.c_void_p
        lib.er_open.argtypes = [ctypes.c_char_p]
        lib.er_num_members.argtypes = [ctypes.c_void_p]
        lib.er_member_name.restype = ctypes.c_char_p
        lib.er_member_name.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.er_member_dtype.restype = ctypes.c_char_p
        lib.er_member_dtype.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.er_member_ndim.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.er_member_shape.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.er_member_data.restype = ctypes.c_void_p
        lib.er_member_data.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.er_member_nbytes.restype = ctypes.c_int64
        lib.er_member_nbytes.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.er_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return get_library() is not None


_DTYPES = {
    "<f4": np.float32,
    "<f8": np.float64,
    "<i4": np.int32,
    "<i8": np.int64,
    "<u4": np.uint32,
    "<u8": np.uint64,
    "|u1": np.uint8,
    "|i1": np.int8,
    "|b1": np.bool_,
    "<f2": np.float16,
}


class NativeEpisode:
    """Handle over one open episode file; arrays are materialized on read."""

    def __init__(self, path: str):
        lib = get_library()
        if lib is None:
            raise RuntimeError("native episode reader unavailable")
        self._lib = lib
        self._handle = lib.er_open(path.encode())
        if not self._handle:
            raise IOError(f"native reader failed to open {path}")

    def keys(self):
        return [
            self._lib.er_member_name(self._handle, i).decode()
            for i in range(self._lib.er_num_members(self._handle))
        ]

    def _array(self, i: int, copy: bool = True) -> np.ndarray:
        descr = self._lib.er_member_dtype(self._handle, i).decode()
        dtype = _DTYPES.get(descr)
        if dtype is None:
            raise ValueError(f"unsupported dtype {descr!r}")
        ndim = self._lib.er_member_ndim(self._handle, i)
        shape = (ctypes.c_int64 * max(ndim, 1))()
        self._lib.er_member_shape(self._handle, i, shape)
        nbytes = self._lib.er_member_nbytes(self._handle, i)
        ptr = self._lib.er_member_data(self._handle, i)
        buf = (ctypes.c_char * nbytes).from_address(ptr)
        arr = np.frombuffer(buf, dtype=dtype).reshape(tuple(shape[:ndim]))
        return arr.copy() if copy else arr

    def to_dict(self, copy: bool = True) -> Dict[str, np.ndarray]:
        return {
            self._lib.er_member_name(self._handle, i).decode(): self._array(
                i, copy=copy
            )
            for i in range(self._lib.er_num_members(self._handle))
        }

    def close(self):
        if self._handle:
            self._lib.er_close(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def load_episode_native(path: str) -> Dict[str, np.ndarray]:
    """Drop-in native replacement for `episodes.load_episode`."""
    with NativeEpisode(path) as ep:
        return ep.to_dict(copy=True)
