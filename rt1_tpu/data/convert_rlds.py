"""RLDS -> episode-store conversion (offline, one-shot).

Parity source: reference `rlds_np_convert.py:9-72`: iterate the TFDS
`language_table_blocktoblock_sim` RLDS dataset, turn each episode's steps
into arrays (`action`, `is_first`, `is_terminal`, `rgb`, `instruction`),
replace the byte-encoded instruction with its Universal-Sentence-Encoder
embedding, and write per-episode files split 7800/100/100.

Differences (documented): output is our `.npz` episode store instead of
pickled `.npy` step lists, and the embedder is pluggable
(`rt1_tpu/eval/embedding.py`) since TF-hub/USE weights are not bundled —
pass `--embedder use` when tensorflow_hub is installed to match the
reference exactly.

Requires `tensorflow_datasets` (gated import): run where the RLDS dataset
is materialized.

Run:
  python -m rt1_tpu.data.convert_rlds --dataset_dir /path/to/rlds \
      --output_dir /data/language_table_npz --embedder hash
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np


def decode_instruction_bytes(bytes_array: np.ndarray) -> str:
    """Strip zero padding and utf-8 decode (reference `decode_inst:9-11`)."""
    arr = np.asarray(bytes_array)
    non_zero = arr[arr != 0]
    if non_zero.shape[0] == 0:
        return ""
    return bytes(non_zero.astype(np.uint8).tolist()).decode("utf-8")


def episode_from_rlds(rlds_episode, embed_fn) -> Optional[dict]:
    """One RLDS episode -> our episode dict (None if empty)."""
    actions, firsts, terminals, rgbs, embeds = [], [], [], [], []
    cached_embedding = None
    text = ""
    for step in rlds_episode["steps"].as_numpy_iterator():
        obs = step["observation"]
        if cached_embedding is None:
            # One instruction per episode; embed once
            # (reference embeds per step, same value each time). The stored
            # text is captured at the SAME step, so it can never diverge
            # from the embedding.
            text = decode_instruction_bytes(obs["instruction"])
            cached_embedding = np.asarray(embed_fn(text), np.float32)
        actions.append(np.asarray(step["action"], np.float32))
        firsts.append(bool(step["is_first"]))
        terminals.append(bool(step["is_terminal"]))
        rgbs.append(np.asarray(obs["rgb"], np.uint8))
        embeds.append(cached_embedding)
    if not actions:
        return None
    from rt1_tpu.data.episodes import encode_instruction_text

    return {
        "action": np.stack(actions),
        "is_first": np.array(firsts),
        "is_terminal": np.array(terminals),
        "rgb": np.stack(rgbs),
        "instruction": np.stack(embeds),
        # Raw text survives conversion: enables re-embedding and in-pipeline
        # CLIP tokenization on real-robot RLDS data (not just oracle demos).
        "instruction_text": encode_instruction_text(text),
    }


def convert(
    dataset_dir: str,
    output_dir: str,
    embedder="hash",
    num_train: int = 7800,
    num_val: int = 100,
    num_test: int = 100,
    progress_every: int = 100,
):
    """Convert the RLDS dataset into train/val/test episode directories."""
    try:
        import tensorflow_datasets as tfds
    except ImportError as e:
        raise ImportError(
            "RLDS conversion requires tensorflow_datasets; install it or "
            "use `python -m rt1_tpu.data.collect` to generate data with "
            "the scripted oracle instead."
        ) from e

    from rt1_tpu.data.episodes import save_episode
    from rt1_tpu.eval.embedding import get_embedder

    embed_fn = get_embedder(embedder)
    builder = tfds.builder_from_directory(dataset_dir)
    total = num_train + num_val + num_test
    ds = builder.as_dataset(split=f"train[:{total}]")

    splits = (
        ("train", num_train),
        ("val", num_val),
        ("test", num_test),
    )
    for name, _ in splits:
        os.makedirs(os.path.join(output_dir, name), exist_ok=True)

    split_iter = iter(splits)
    split_name, split_quota = next(split_iter)
    split_count = 0
    written = 0
    for rlds_episode in ds:
        ep = episode_from_rlds(rlds_episode, embed_fn)
        if ep is None:
            continue
        while split_count >= split_quota:
            split_name, split_quota = next(split_iter)
            split_count = 0
        save_episode(
            os.path.join(
                output_dir, split_name, f"episode_{split_count}.npz"
            ),
            ep,
        )
        split_count += 1
        written += 1
        if progress_every and written % progress_every == 0:
            print(f"converted {written}/{total}")
    return written


def main(argv):
    del argv
    from absl import flags

    FLAGS = flags.FLAGS
    n = convert(
        FLAGS.dataset_dir,
        FLAGS.output_dir,
        embedder=FLAGS.embedder,
        num_train=FLAGS.num_train,
        num_val=FLAGS.num_val,
        num_test=FLAGS.num_test,
    )
    print(f"done: {n} episodes")


if __name__ == "__main__":
    from absl import app, flags

    flags.DEFINE_string("dataset_dir", None, "RLDS dataset directory.")
    flags.DEFINE_string("output_dir", None, "Episode-store output dir.")
    flags.DEFINE_string("embedder", "hash", "Instruction embedder spec.")
    flags.DEFINE_integer("num_train", 7800, "Train episodes.")
    flags.DEFINE_integer("num_val", 100, "Val episodes.")
    flags.DEFINE_integer("num_test", 100, "Test episodes.")
    flags.mark_flags_as_required(["dataset_dir", "output_dir"])
    app.run(main)
