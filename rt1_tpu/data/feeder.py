"""Sample-ahead feeder: background batch assembly over the packed cache.

The tf.data loader interleaves window assembly with the train loop's own
host time slice; on a single-core host the two serialize and the device
starves (the 78% input stall, docs/performance.md). This feeder runs batch
assembly on background threads against `PackedEpisodeCache` — where a
window is mmap slices, not decodes, so assembly is memcpy-bound and the
GIL-free native gather lets N threads genuinely overlap — and parks
finished batches in a bounded ring of queues. The consumer (the train
loop, via `data.pipeline.device_feeder`) pops ready uint8 batches and
spends its host slice only on `jax.device_put`.

Determinism: the batch schedule and every crop draw are functions of
(seed, epoch, batch-in-epoch) only — never of thread count or timing — so
two feeders with the same seed yield identical batch streams, and a
1-thread feeder reproduces an 8-thread one bit-for-bit (pinned in
tests/test_feeder.py).

Multi-host (``process_count > 1``, docs/parallelism.md "Multi-host"): the
epoch order is drawn GLOBALLY — one permutation (or weighted draw), a pure
function of (seed, epoch, corpus[, weights]) that no process identity
enters — and each global batch of ``batch_size × process_count`` windows
is split into per-host blocks: host p assembles rows
``[p·batch_size, (p+1)·batch_size)`` of global batch b. Host slices are
therefore disjoint, jointly exhaustive over the batched prefix, and
CONCATENATE to the exact single-host batch (the layout
`jax.make_array_from_process_local_data` expects for a batch sharded over
a host-major mesh, data/pipeline.py `device_feeder`) — all pinned in
tests/test_feeder.py. Every host draws the same global order, so no
cross-host coordination happens at epoch boundaries, and — unlike a
per-host strided slice — every host sees the same per-epoch batch count
even when the corpus size is not process-divisible (a strided split can
hand one host an extra batch, which deadlocks the collective at the
epoch's last step).

Flywheel (`refresh_at_epoch=True`): at every epoch boundary the feeder asks
the cache to re-read its manifest and open any newly appended shards
(`PackedEpisodeCache.refresh`), then draws that epoch's shuffle over the
grown window set. The epoch stream stays a pure function of
(seed, epoch, corpus-at-epoch-start): because the crop rng is keyed on
(epoch, batch-in-epoch) — not on the flat ticket — a feeder that picked a
shard up mid-run emits byte-identical epochs to one constructed after the
append (pinned in tests/test_flywheel.py). A mid-epoch append never
perturbs the epoch in flight.

Lifecycle: `close()` (or the context manager / garbage collection) stops
the workers promptly even when queues are full; a finite `num_epochs`
stream raises StopIteration after exactly the per-epoch batch counts sum.
"""

from __future__ import annotations

import bisect
import queue
import threading
import time
import zlib
from typing import Dict, Iterator, List, Mapping, Optional, Tuple

import numpy as np

from rt1_tpu.data.pack import UNKNOWN_TASK, PackedEpisodeCache
from rt1_tpu.obs.health import TASK_ID_KEY
from rt1_tpu.obs import trace as obs_trace
from rt1_tpu.resilience import faults

#: Trailing task-id bucket for episodes whose task appeared AFTER feeder
#: construction (a flywheel append introducing a brand-new workload tag):
#: the health pack's layout is frozen at step-build time, so late tasks
#: land in one stable overflow bucket instead of shifting the layout.
OTHER_TASK = "other"


def parse_task_weights(spec) -> Optional[Dict[str, float]]:
    """``"block2block:3,corner:1"`` -> ``{"block2block": 3.0, "corner": 1.0}``.

    The config-string form of per-task sampling weights
    (``config.data.task_weights``) — a string so a single
    ``--config.data.task_weights=...`` CLI override works. ``None``/empty
    returns None (mixture sampling off, the bit-identical pre-task
    stream). A mapping passes through (validated). Weights must be
    non-negative with at least one positive; a task absent from the
    corpus simply never matches (the feeder validates coverage against
    the actual corpus at order-draw time). The special key ``"*"`` sets
    the weight for every task not named explicitly (default 0 = excluded).
    """
    if spec is None:
        return None
    if isinstance(spec, Mapping):
        items = dict(spec)
    else:
        text = str(spec).strip()
        if not text:
            return None
        items = {}
        for part in text.split(","):
            part = part.strip()
            if not part:
                continue
            # rsplit: task slugs may themselves contain ':' ("unknown:foo").
            name, _, weight = part.rpartition(":")
            if not name:
                raise ValueError(
                    f"task_weights entry {part!r} is not '<task>:<weight>'"
                )
            items[name] = weight
    out = {}
    for name, weight in items.items():
        try:
            w = float(weight)
        except (TypeError, ValueError) as exc:
            raise ValueError(
                f"task_weights[{name!r}] = {weight!r} is not a number"
            ) from exc
        if w < 0 or not np.isfinite(w):
            raise ValueError(
                f"task_weights[{name!r}] = {w} must be finite and >= 0"
            )
        out[name] = w
    if not out:
        return None
    if not any(v > 0 for v in out.values()):
        raise ValueError(f"task_weights {out} has no positive weight")
    return out


class FeederStalledError(RuntimeError):
    """The consumer waited past `stall_timeout_s` with no batch and no error.

    A worker that raises is already surfaced by `_raise_or_stop`; this
    covers the worse case — a worker that deadlocks or dies *silently*
    (native-code hang, a thread killed without unwinding) — where a plain
    `q.get()` would block the train loop forever. The message names which
    worker threads are still alive and the per-queue depths, so the
    post-mortem starts with the right thread instead of a generic hang.
    """


class SampleAheadFeeder:
    """Iterator of training batch dicts assembled ahead of the consumer.

    Yields the same nested {"observations": ..., "actions": ...} dict as
    `WindowedEpisodeDataset`'s loaders, with uint8 images.
    """

    def __init__(
        self,
        cache: PackedEpisodeCache,
        batch_size: int,
        *,
        seed: int = 0,
        shuffle: bool = True,
        num_epochs: Optional[int] = None,
        num_threads: int = 2,
        depth: int = 2,
        process_index: int = 0,
        process_count: int = 1,
        start: bool = True,
        stall_timeout_s: Optional[float] = None,
        refresh_at_epoch: bool = False,
        task_weights: Optional[Mapping[str, float]] = None,
        emit_task_ids: bool = False,
    ):
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        if stall_timeout_s is not None and stall_timeout_s <= 0:
            raise ValueError(
                f"stall_timeout_s must be positive or None, got "
                f"{stall_timeout_s}"
            )
        self.cache = cache
        self.batch_size = batch_size
        self.stall_timeout_s = stall_timeout_s
        self.seed = seed
        self.shuffle = shuffle
        self.num_epochs = num_epochs
        self.num_threads = max(1, num_threads)
        self.depth = max(1, depth)
        self.process_index = process_index
        self.process_count = process_count
        if refresh_at_epoch and process_count > 1:
            # The multi-host contract is "every host draws the same global
            # order by construction" — a pure function of (seed, epoch,
            # corpus). A flywheel refresh is a per-host filesystem read
            # with no cross-host barrier: host 0 could see an appended
            # shard at an epoch boundary that host 1's (slightly earlier,
            # or failed-and-swallowed) refresh missed, after which the
            # hosts draw different orders AND different per-epoch batch
            # counts — overlapping slices and a deadlocked collective at
            # the shorter host's epoch end. Refuse here, loudly, instead
            # of corrupting the stream; train/train.py disables the
            # flywheel hook on multi-process runs for the same reason.
            raise ValueError(
                "refresh_at_epoch (the flywheel's mid-run corpus pickup) "
                "is single-process only: epoch-boundary manifest reads "
                "have no cross-host synchronization, so hosts could draw "
                "orders over different corpus snapshots. Restart training "
                "to absorb appended shards on multi-host runs."
            )
        self.refresh_at_epoch = refresh_at_epoch
        # Task-mixture sampling (docs/data.md "Task-mixture sampling"):
        # with weights, each epoch's order is a weighted draw WITH
        # replacement over the corpus windows (p_i ∝ weight of window i's
        # task), still a pure function of (seed, epoch, corpus, weights) —
        # the weights fold into the shuffle rng key, so two feeders with
        # the same tuple emit byte-identical streams and weights=None is
        # the exact pre-task permutation path.
        self.task_weights = parse_task_weights(task_weights)
        if self.task_weights is not None and not shuffle:
            raise ValueError(
                "task_weights requires shuffle=True (a weighted epoch is "
                "a sampled mixture, not a deterministic corpus walk)"
            )
        self._weights_key = (
            zlib.crc32(
                repr(sorted(self.task_weights.items())).encode("utf-8")
            )
            if self.task_weights is not None
            else 0
        )
        # Per-task telemetry: emit a (batch,) int32 `task_id` member the
        # jitted step's one-hot segment reduction consumes. The id table
        # is frozen at construction (sorted unique corpus tasks + one
        # trailing OTHER_TASK overflow bucket), so the health-pack layout
        # is static even while the flywheel grows the corpus mid-run. A
        # corpus that already carries a literal "other" task shares that
        # bucket with post-append novel tasks (no duplicate pack entry).
        self.emit_task_ids = emit_task_ids
        self._task_index = {
            name: i for i, name in enumerate(sorted(set(cache.tasks)))
        }
        names = tuple(sorted(self._task_index))
        if OTHER_TASK not in self._task_index:
            names = names + (OTHER_TASK,)
        self.health_task_names: Tuple[str, ...] = (
            names if emit_task_ids else ()
        )

        # Per-epoch corpus snapshots: each entry pins the window count and
        # shuffle order one epoch's batches are drawn from, so a flywheel
        # append only ever changes epochs whose order has not been drawn
        # yet. `_firsts[e]` = the first global ticket of epoch e (epochs
        # have different batch counts once the corpus grows).
        self._order_lock = threading.Lock()
        self._epochs: List[Dict] = []
        self._firsts: List[int] = []
        self._materialize_next_epoch_locked_unsafe()
        self.batches_per_epoch = self._epochs[0]["batches"]
        if self.batches_per_epoch == 0:
            raise ValueError(
                f"global batch ({batch_size} per host x "
                f"{self.process_count} processes) exceeds the corpus's "
                f"{len(self._epochs[0]['order'])} windows"
            )
        # Static corpora keep the exact pre-flywheel exhaustion arithmetic;
        # a refreshing feeder's end is located per-epoch (counts can grow).
        self.total_batches = (
            self.batches_per_epoch * num_epochs
            if num_epochs is not None and not refresh_at_epoch
            else None
        )

        meta0 = cache.meta(0)
        self._embed_dim = int(meta0["instruction"].shape[1])
        self._action_dim = int(meta0["action"].shape[1])

        self._error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._queues = [
            queue.Queue(maxsize=self.depth) for _ in range(self.num_threads)
        ]
        self._threads = [
            threading.Thread(
                target=self._worker, args=(k,), daemon=True,
                name=f"rt1-feeder-{k}",
            )
            for k in range(self.num_threads)
        ]
        self._next_ticket = 0
        self._started = False
        # Per-worker observability counters (rt1_tpu/obs): index-assigned
        # list writes are GIL-atomic, so workers update lock-free and
        # `stats()` reads a consistent-enough snapshot for gauges.
        self._assembled = [0] * self.num_threads
        self._assembly_s = [0.0] * self.num_threads
        if start:
            self.start()

    # ------------------------------------------------------------ schedule

    def _compute_order(self, epoch: int, n_windows: int) -> np.ndarray:
        """The GLOBAL window order for `epoch` over an `n_windows` corpus —
        a pure function of (seed, epoch, n_windows[, weights]) that the
        process identity never enters: every host of a multi-process run
        draws this same order and takes its block of each global batch
        (`_host_indices`), so the global stream is exactly the
        single-host stream no matter how many hosts split it.

        task_weights=None keeps the EXACT pre-task permutation draw (same
        rng key, same shuffle — bit-identical, pinned in tests). With
        weights, the epoch becomes a weighted draw with replacement
        (p_window ∝ weight of its episode's task), the weights digest
        folded into the rng key so different mixtures give different —
        but individually reproducible — streams.
        """
        if self.task_weights is not None:
            w = self._window_weights(n_windows)
            total = w.sum()
            if total <= 0:
                raise ValueError(
                    f"task_weights {self.task_weights} give zero total "
                    f"weight over this corpus (tasks: "
                    f"{sorted(set(self.cache.tasks[:]))})"
                )
            rng = np.random.default_rng(
                [self.seed, epoch, self._weights_key]
            )
            return rng.choice(
                n_windows, size=n_windows, replace=True, p=w / total
            )
        order = np.arange(n_windows)
        if self.shuffle:
            np.random.default_rng([self.seed, epoch]).shuffle(order)
        return order

    @property
    def global_batch_size(self) -> int:
        """Windows per GLOBAL batch (all hosts' shards together)."""
        return self.batch_size * self.process_count

    def _host_indices(self, order: np.ndarray, b: int) -> np.ndarray:
        """This host's `batch_size` window indices of global batch `b`:
        rows [p·B, (p+1)·B) of the order's b-th global-batch block. Hosts'
        slices concatenate (in process order) to the exact single-host
        batch — the contract `jax.make_array_from_process_local_data`
        needs for a batch dim sharded over a host-major mesh."""
        base = b * self.global_batch_size + self.process_index * self.batch_size
        return order[base : base + self.batch_size]

    def host_order(self, epoch: int) -> np.ndarray:
        """This host's window sequence for `epoch` (batched prefix only:
        the order's tail that fills no complete global batch is dropped on
        every host alike). Observability/test accessor — assembly reads
        `_host_indices` per batch."""
        order = self._order_for(epoch)
        nb = len(order) // self.global_batch_size
        if self.process_count == 1:
            return order[: nb * self.global_batch_size]
        return (
            order[: nb * self.global_batch_size]
            .reshape(nb, self.process_count, self.batch_size)[
                :, self.process_index
            ]
            .reshape(-1)
        )

    def _window_weights(self, n_windows: int) -> np.ndarray:
        """(n_windows,) float64 sampling weight per window: the window's
        episode task looked up in `task_weights` (missing tasks fall back
        to the ``"*"`` wildcard weight, default 0 = excluded). Windows are
        laid out episode-by-episode in `cache.index`, so the first
        `n_windows` entries are an episode prefix and one np.repeat
        covers them."""
        default = self.task_weights.get("*", 0.0)
        ep_weights, ep_steps, covered = [], [], 0
        for entry in self.cache.episodes:
            if covered >= n_windows:
                break
            steps = min(int(entry["steps"]), n_windows - covered)
            task = entry.get("task") or UNKNOWN_TASK
            ep_weights.append(self.task_weights.get(task, default))
            ep_steps.append(steps)
            covered += steps
        return np.repeat(
            np.asarray(ep_weights, np.float64), np.asarray(ep_steps, np.int64)
        )

    def _materialize_next_epoch_locked_unsafe(self) -> None:
        """Append the next epoch's snapshot; caller holds `_order_lock`
        (or is the constructor). Refresh happens HERE — at the boundary,
        exactly once per epoch, under the lock — so the whole epoch is
        drawn from one corpus snapshot."""
        e = len(self._epochs)
        if e > 0 and self.refresh_at_epoch:
            try:
                self.cache.refresh()
            except Exception:  # noqa: BLE001 - keep feeding the old view
                pass
        n_windows = len(self.cache.index)
        order = self._compute_order(e, n_windows)
        first = (
            0
            if e == 0
            else self._firsts[-1] + self._epochs[-1]["batches"]
        )
        self._epochs.append(
            {
                "first": first,
                # Batch counts are GLOBAL-batch counts: identical on every
                # host by construction, so multi-process epochs end in
                # lockstep (a per-host count could differ when the corpus
                # is not process-divisible — a collective deadlock).
                "batches": len(order) // self.global_batch_size,
                "order": order,
                "windows": n_windows,
            }
        )
        self._firsts.append(first)
        # Workers straddle at most a couple of epochs (bounded by queue
        # depth); drop older order arrays to bound memory — they are
        # recomputable from the pinned window count if ever needed.
        for old in self._epochs[: max(0, e - 2)]:
            old["order"] = None

    def _locate(self, ticket: int) -> Tuple[int, int]:
        """Global ticket -> (epoch, batch-in-epoch), materializing epoch
        snapshots (and boundary refreshes) as the schedule reaches them."""
        with self._order_lock:
            while (
                ticket
                >= self._firsts[-1] + self._epochs[-1]["batches"]
            ):
                self._materialize_next_epoch_locked_unsafe()
            e = bisect.bisect_right(self._firsts, ticket) - 1
            return e, ticket - self._firsts[e]

    def _order_for(self, epoch: int) -> np.ndarray:
        with self._order_lock:
            while len(self._epochs) <= epoch:
                self._materialize_next_epoch_locked_unsafe()
            entry = self._epochs[epoch]
            if entry["order"] is None:
                entry["order"] = self._compute_order(
                    epoch, entry["windows"]
                )
            return entry["order"]

    def _epoch_order(self, epoch: int) -> np.ndarray:
        """This process's window order for `epoch` (thread-count-free)."""
        return self.host_order(epoch)

    def _past_end(self, ticket: int) -> bool:
        if self.num_epochs is None:
            return False
        if self.total_batches is not None:
            return ticket >= self.total_batches
        epoch, _ = self._locate(ticket)
        return epoch >= self.num_epochs

    def _batch_rng(self, epoch: int, b: int) -> np.random.Generator:
        # Philox keyed directly on (seed, epoch, batch-in-epoch):
        # counter-based, so construction is ~10us vs ~500us for
        # default_rng's SeedSequence entropy pooling — this runs once per
        # batch on the hot path. Keying on the epoch-local coordinates
        # (not the flat ticket) makes each epoch's draws independent of
        # how many batches earlier epochs had — the property that lets a
        # flywheel feeder that grew mid-run match one built after the
        # append. The 0x5EED word keeps the stream disjoint from the
        # shuffle rng.
        key = (self.seed & 0xFFFFFFFFFFFFFFFF) ^ (0x5EED << 48)
        counter = (np.uint64(epoch) << np.uint64(32)) | np.uint64(b)
        return np.random.Generator(
            np.random.Philox(key=np.array([key, counter], np.uint64))
        )

    # ------------------------------------------------------------ workers

    def _assemble(self, ticket: int) -> Dict:
        epoch, b = self._locate(ticket)
        order = self._order_for(epoch)
        indices = self._host_indices(order, b)
        rng = self._batch_rng(epoch, b)
        n, w = len(indices), self.cache.window
        h, wd = self.cache.height, self.cache.width
        images = np.empty((n, w, h, wd, 3), np.uint8)
        embeds = np.empty((n, w, self._embed_dim), np.float32)
        terms = np.empty((n, w), np.int32)
        actions = np.empty((n, w, self._action_dim), np.float32)
        offsets = None
        if self.process_count > 1:
            # Multi-host crop parity: the crop rng is keyed on the GLOBAL
            # (epoch, batch) coordinates, so every host must consume it
            # identically — draw the full global batch's offsets and keep
            # this host's rows. One extra (global_batch·window, 2) integer
            # draw per batch; the frame gather stays per-host-sized.
            all_offsets = self.cache.draw_packed_offsets(
                rng, self.global_batch_size * w
            )
            lo = self.process_index * self.batch_size * w
            offsets = all_offsets[lo : lo + n * w]
        self.cache.fill_batch(
            indices, rng, images, embeds, terms, actions, offsets=offsets
        )
        observations = {
            "image": images,
            "natural_language_embedding": embeds,
        }
        if self.emit_task_ids:
            # (batch,) int32 ids into `health_task_names`; tasks unseen at
            # construction (post-append workloads) ride the OTHER_TASK
            # bucket so the step's one-hot layout never shifts.
            other = self._task_index.get(OTHER_TASK, len(self._task_index))
            tid = np.empty((n,), np.int32)
            for j, idx in enumerate(indices):
                entry = self.cache.episodes[self.cache.index[int(idx)][0]]
                tid[j] = self._task_index.get(
                    entry.get("task") or UNKNOWN_TASK, other
                )
            observations[TASK_ID_KEY] = tid
        if self.cache._clip_tokenizer is not None:
            tokens = np.stack(
                [
                    self.cache._episode_clip_tokens(self.cache.index[int(i)][0])
                    for i in indices
                ]
            )
            observations["instruction_tokenized_clip"] = np.tile(
                tokens[:, None, :], (1, w, 1)
            )
        return {
            "observations": observations,
            "actions": {"terminate_episode": terms, "action": actions},
        }

    def _worker(self, k: int) -> None:
        ticket = k
        q = self._queues[k]
        try:
            while not self._stop.is_set():
                if self._past_end(ticket):
                    return
                # resilience: deterministic fault sites (one global read
                # when no plan is installed). feeder_hang dies silently —
                # the simulated deadlock the consumer-side stall timeout
                # exists to diagnose; feeder_kill exercises the loud path.
                plan = faults.active()
                if plan is not None:
                    if plan.should_fire("feeder_hang", index=ticket):
                        return
                    if plan.should_fire("feeder_kill", index=ticket):
                        raise RuntimeError(
                            f"injected fault [feeder_kill]: worker {k} "
                            f"at ticket {ticket}"
                        )
                # obs: the span makes this worker's assembly visible on the
                # shared host timeline; no-op (one global read) untraced.
                t0 = time.perf_counter()
                with obs_trace.span("feeder_assemble", ticket=ticket):
                    batch = self._assemble(ticket)
                self._assembly_s[k] += time.perf_counter() - t0
                self._assembled[k] += 1
                # Bounded put that stays responsive to close(): a plain
                # q.put would deadlock a full queue against a consumer gone.
                while not self._stop.is_set():
                    try:
                        q.put(batch, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if obs_trace.enabled():
                    obs_trace.counter(
                        "feeder_queue_depth",
                        sum(qq.qsize() for qq in self._queues),
                    )
                ticket += self.num_threads
        except BaseException as e:  # noqa: BLE001 - re-raised in __next__
            # A dying worker must not strand the consumer in q.get():
            # stash the error, flip the stop flag, and let __next__
            # re-raise it on the train loop's thread (a truncated
            # frames.bin, a bad clip tokenizer — all surface loudly
            # instead of hanging training).
            self._error = e
            self._stop.set()

    # ---------------------------------------------------------- observability

    def stats(self) -> Dict[str, float]:
        """Flat numeric gauges for the obs layer (train-side Prometheus
        listener, flight-recorder step records): ready-queue fill and
        per-worker assembly counters. Lock-free reads of GIL-atomic
        counters — safe to call from any thread at any rate."""
        depth = sum(q.qsize() for q in self._queues)
        out = {
            "queue_depth": depth,
            "queue_capacity": self.num_threads * self.depth,
            "next_ticket": self._next_ticket,
            "workers_alive": sum(t.is_alive() for t in self._threads),
            "corpus_windows": len(self.cache.index),
            "epochs_started": len(self._epochs),
        }
        for k in range(self.num_threads):
            n = self._assembled[k]
            out[f"assembled_w{k}"] = n
            out[f"assembly_ms_mean_w{k}"] = (
                self._assembly_s[k] / n * 1e3 if n else 0.0
            )
        return out

    def flywheel_stats(self) -> Dict[str, float]:
        """Corpus-growth gauges for the train loop's `flywheel/*` scalars
        and the `rt1_flywheel_*` Prometheus families: shard count,
        freshness epoch, corpus size, appended-episode count, and how
        stale the feeder's view of the manifest is. Lock-free reads."""
        c = self.cache
        now = time.time()
        return {
            "shards": float(getattr(c, "num_shards", 1)),
            "freshness_epoch": float(getattr(c, "freshness_epoch", 0)),
            "corpus_windows": float(len(c.index)),
            "corpus_steps": float(getattr(c, "total_steps", 0)),
            "corpus_episodes": float(len(c.episodes)),
            "corpus_tasks": float(len(set(c.tasks))),
            "appended_episodes": float(getattr(c, "appended_episodes", 0)),
            "refreshes": float(getattr(c, "refreshes", 0)),
            "staleness_s": max(
                0.0, now - getattr(c, "last_refresh_unix", now)
            ),
            "epochs_started": float(len(self._epochs)),
        }

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "SampleAheadFeeder":
        if not self._started:
            self._started = True
            for t in self._threads:
                t.start()
        return self

    def close(self) -> None:
        """Stop workers and join them; the iterator is exhausted after."""
        self._stop.set()
        for q in self._queues:
            # Drain so a worker blocked in put() sees the stop event.
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
        for t in self._threads:
            if t.is_alive():
                t.join(timeout=5.0)

    def __enter__(self) -> "SampleAheadFeeder":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            self._stop.set()
        except Exception:
            pass

    # ------------------------------------------------------------ iteration

    def __iter__(self) -> Iterator[Dict]:
        return self

    def __next__(self) -> Dict:
        if not self._started:
            self.start()
        if self._stop.is_set():
            self._raise_or_stop()
        t = self._next_ticket
        if self._past_end(t):
            raise StopIteration
        q = self._queues[t % self.num_threads]
        waited = 0.0
        while True:
            try:
                batch = q.get(timeout=0.1)
                break
            except queue.Empty:
                if self._stop.is_set():
                    self._raise_or_stop()
                waited += 0.1
                if (
                    self.stall_timeout_s is not None
                    and waited >= self.stall_timeout_s
                ):
                    raise self._stalled_error(t, waited)
                if not any(th.is_alive() for th in self._threads) and q.empty():
                    # Every worker died without raising (so no stashed
                    # error) and nothing is queued: no batch can ever
                    # arrive. Diagnose immediately instead of waiting out
                    # the timeout — or forever, when none is configured.
                    raise self._stalled_error(t, waited)
        self._next_ticket = t + 1
        return batch

    def _stalled_error(self, ticket: int, waited: float) -> "FeederStalledError":
        alive = [th.name for th in self._threads if th.is_alive()]
        dead = [th.name for th in self._threads if not th.is_alive()]
        depths = [qq.qsize() for qq in self._queues]
        return FeederStalledError(
            f"feeder stalled: waited {waited:.1f}s for ticket {ticket} "
            f"(queue {ticket % self.num_threads}). Worker threads alive: "
            f"{alive or 'NONE'}; dead: {dead or 'none'}; queue depths: "
            f"{depths} (capacity {self.depth} each). A dead worker with no "
            f"stashed error means it deadlocked or was killed without "
            f"unwinding — check the flight-recorder dump and the host "
            f"trace for its last feeder_assemble span."
        )

    def _raise_or_stop(self) -> None:
        """Re-raise a worker's stashed error on the consumer thread, or end
        the stream cleanly when the stop came from close()."""
        if self._error is not None:
            raise RuntimeError(
                "sample-ahead feeder worker failed"
            ) from self._error
        raise StopIteration
