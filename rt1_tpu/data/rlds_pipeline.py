"""Direct tf.data RLDS training pipeline (pure-TF graph, service-distributable).

Capability parity with the reference's Stack-B input pipeline
(`language_table/train/input_pipeline_rlds.py`):

* episode padding by repeating the first step `window-1` times with
  `is_first` forced False on the copies (reference `:105-126`);
* every length-`window` sliding window is one sample (reverb-pattern
  windows, reference `:134-149`), built fully vectorized with a gather of a
  (T, window) index grid instead of per-step Python;
* terminal-step filter (reference `:151-158`);
* on-graph image random crop + bilinear resize + optional photometric
  distortions (reference `:325-457`) — all `tf.image`, no `numpy_function`,
  so the whole preprocessing graph serializes;
* optional 3-level batching device x multistep x batch (reference
  `:299-321`) for grad-accumulation/`multi_train_step`-style consumers;
* optional **tf.data service** distribution (reference `:307-317`, sharding
  OFF): because the graph is pure TF it can run on remote tf.data workers,
  unlike `pipeline.py::as_tf_dataset`, whose `numpy_function` window loader
  is host-process-bound (that path is for local npz episode stores).

The episode source is any `tf.data.Dataset` of per-episode step arrays
(`make_episode_dataset_from_arrays` builds one from in-memory episodes;
`create_rlds_datasets` is the gated TFDS/RLDS front-end mirroring the
reference's `create_datasets:47-64`).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Sequence


@dataclasses.dataclass
class RldsPipelineConfig:
    window: int = 6
    crop_factor: Optional[float] = 0.95
    height: int = 256
    width: int = 456
    photometric: bool = False
    # Drop windows whose *input* frames cross a terminal step (the reference
    # filters windows ending in terminals so labels stay in-episode, :151-158).
    filter_terminal_windows: bool = False
    batch_size: int = 8
    # Extra leading batch levels (reference :299-321). None disables a level.
    multistep: Optional[int] = None
    num_devices: Optional[int] = None
    shuffle_buffer: int = 2048
    seed: int = 0
    repeat: bool = True
    # tf.data service endpoint ("grpc://host:port"); None = run locally.
    data_service_address: Optional[str] = None
    data_service_job_name: Optional[str] = "rt1_tpu_train"
    # "uint8" ships 4x fewer H2D bytes (model converts on device);
    # "float32" keeps the legacy [0,1] host representation.
    image_dtype: str = "uint8"

    def __post_init__(self):
        if self.image_dtype not in ("uint8", "float32"):
            raise ValueError(
                f"image_dtype must be uint8|float32, got {self.image_dtype!r}"
            )


def pad_episode(steps: Dict, window: int):
    """Front-pad by repeating the first step `window-1` times (`:105-126`).

    `is_first` is False on the padding copies so downstream logic can still
    find the true episode start.
    """
    import tensorflow as tf

    pad = window - 1
    out = {}
    for key, v in steps.items():
        first = tf.repeat(v[:1], pad, axis=0)
        if key == "is_first":
            first = tf.zeros_like(first)
        out[key] = tf.concat([first, v], axis=0)
    return out


def episode_windows(steps: Dict, window: int):
    """All sliding windows of the padded episode as a (T, window, ...) stack.

    The padded episode has T + window - 1 steps -> exactly T windows, the
    reference's sample distribution (`load_np_dataset.py:65-74` and
    `input_pipeline_rlds.py:134-149`). One vectorized gather per key.
    """
    import tensorflow as tf

    padded = pad_episode(steps, window)
    t = tf.shape(padded["is_first"])[0] - (window - 1)
    grid = tf.range(t)[:, None] + tf.range(window)[None, :]  # (T, window)
    return {k: tf.gather(v, grid) for k, v in padded.items()}


def _augment_images(rgb, cfg: RldsPipelineConfig, training: bool):
    """uint8 (window, h, w, 3) -> (window, H, W, 3), cfg.image_dtype.

    Random-crop at `crop_factor` with a uniform offset per frame (parity
    with `DecodeAndRandomResizedCrop`, independent offsets per frame), then
    bilinear resize; eval takes the central crop (`eval/wrappers.py` parity).
    """
    import tensorflow as tf

    rgb = tf.image.convert_image_dtype(rgb, tf.float32)  # uint8 -> [0,1]
    shape = tf.shape(rgb)
    w_frames, h, w = shape[0], shape[1], shape[2]
    if cfg.crop_factor is not None:
        ch = tf.cast(tf.cast(h, tf.float32) * cfg.crop_factor, tf.int32)
        cw = tf.cast(tf.cast(w, tf.float32) * cfg.crop_factor, tf.int32)
        if training:
            def crop_one(frame):
                return tf.image.random_crop(frame, (ch, cw, 3))

            rgb = tf.map_fn(crop_one, rgb)
        else:
            top = (h - ch) // 2
            left = (w - cw) // 2
            rgb = rgb[:, top : top + ch, left : left + cw, :]
    rgb = tf.image.resize(rgb, (cfg.height, cfg.width), method="bilinear")
    if training and cfg.photometric:
        # Photometric distortions (reference `:391-457`): brightness /
        # contrast / saturation / hue jitter, drawn independently per frame
        # (matching the reference's per-frame application).
        def jitter(frame):
            frame = tf.image.random_brightness(frame, 0.1)
            frame = tf.image.random_contrast(frame, 0.8, 1.2)
            frame = tf.image.random_saturation(frame, 0.8, 1.2)
            frame = tf.image.random_hue(frame, 0.02)
            return frame

        rgb = tf.map_fn(jitter, rgb)
        rgb = tf.clip_by_value(rgb, 0.0, 1.0)
    if cfg.image_dtype == "uint8":
        # Quantize back for the wire; the model's on-device convert_dtype
        # restores [0,1] floats. Round-trip error is <= 1/510 per channel.
        rgb = tf.cast(tf.round(rgb * 255.0), tf.uint8)
    return rgb


def window_to_sample(win: Dict, cfg: RldsPipelineConfig, training: bool):
    """One window dict -> the model's (observations, actions) sample tree."""
    import tensorflow as tf

    obs = {
        "image": _augment_images(win["rgb"], cfg, training),
        "natural_language_embedding": tf.cast(win["instruction"], tf.float32),
    }
    actions = {
        "terminate_episode": tf.cast(win["is_terminal"], tf.int32),
        "action": tf.cast(win["action"], tf.float32),
    }
    return {"observations": obs, "actions": actions}


def windowed_rlds_dataset(
    episode_ds,
    cfg: RldsPipelineConfig,
    training: bool = True,
):
    """episodes -> shuffled/batched/prefetched sample dataset (pure TF).

    `episode_ds`: tf.data.Dataset of dicts with per-episode arrays
    `rgb` (T,h,w,3) uint8, `instruction` (T,D) float, `action` (T,A) float,
    `is_first`/`is_terminal` (T,) bool.
    """
    import tensorflow as tf

    ds = episode_ds
    if cfg.repeat and training:
        ds = ds.repeat()

    def to_windows(steps):
        wins = episode_windows(steps, cfg.window)
        return tf.data.Dataset.from_tensor_slices(wins)

    # Training interleaves windows across episodes for decorrelation; eval
    # keeps strict episode order (sequential flat-map) for determinism.
    if training:
        ds = ds.interleave(
            to_windows,
            cycle_length=4,
            num_parallel_calls=tf.data.AUTOTUNE,
            deterministic=False,
        )
    else:
        ds = ds.interleave(to_windows, cycle_length=1)
    if cfg.filter_terminal_windows:
        # Keep windows whose non-final input frames are non-terminal.
        ds = ds.filter(
            lambda w: tf.logical_not(tf.reduce_any(w["is_terminal"][:-1]))
        )
    if training:
        ds = ds.shuffle(cfg.shuffle_buffer, seed=cfg.seed)
    ds = ds.map(
        lambda w: window_to_sample(w, cfg, training),
        num_parallel_calls=tf.data.AUTOTUNE,
    )
    ds = ds.batch(cfg.batch_size, drop_remainder=True)
    if cfg.multistep:
        ds = ds.batch(cfg.multistep, drop_remainder=True)
    if cfg.num_devices:
        ds = ds.batch(cfg.num_devices, drop_remainder=True)

    if cfg.data_service_address:
        # Distributed preprocessing (reference `:307-317`): every consumer
        # sees the full dataset (sharding OFF); workers execute the pure-TF
        # graph above, the trainer host only pulls ready batches. Remote
        # (out-of-process) workers additionally require `episode_ds` itself
        # to be pure TF — a `from_generator` source (npz store) limits
        # service mode to in-process/colocated workers because its Python
        # generator cannot be shipped; `create_rlds_datasets` with an
        # `InGraphTableEmbedder` satisfies this.
        ds = ds.apply(
            tf.data.experimental.service.distribute(
                processing_mode=tf.data.experimental.service.ShardingPolicy.OFF,
                service=cfg.data_service_address,
                job_name=cfg.data_service_job_name,
            )
        )
    return ds.prefetch(tf.data.AUTOTUNE)


def make_episode_dataset_from_paths(paths: Sequence[str], reader=None):
    """Lazy episode source over a stored dataset: one episode is read per
    generator step, so host memory stays bounded by the shuffle buffer
    instead of the dataset size. `reader` defaults to the npz episode store
    (`rt1_tpu.data.episodes.load_episode`; the native C++ reader also fits).

    Note: like every `from_generator` source, the Python reader lives in
    *this* process — tf.data service can only parallelize this graph with
    in-process/colocated workers, not remote ones (see
    `windowed_rlds_dataset`). Use `create_rlds_datasets` with an
    `InGraphTableEmbedder` for a fully serializable graph.
    """
    import numpy as np
    import tensorflow as tf

    if reader is None:
        from rt1_tpu.data.episodes import load_episode as reader
    if not paths:
        raise ValueError("no episode paths")
    probe = reader(paths[0])

    def gen():
        for p in paths:
            e = reader(p)
            yield {
                "rgb": np.asarray(e["rgb"], np.uint8),
                "instruction": np.asarray(e["instruction"], np.float32),
                "action": np.asarray(e["action"], np.float32),
                "is_first": np.asarray(e["is_first"], bool),
                "is_terminal": np.asarray(e["is_terminal"], bool),
            }

    sig = {
        "rgb": tf.TensorSpec((None,) + np.asarray(probe["rgb"]).shape[1:], tf.uint8),
        "instruction": tf.TensorSpec(
            (None,) + np.asarray(probe["instruction"]).shape[1:], tf.float32
        ),
        "action": tf.TensorSpec(
            (None,) + np.asarray(probe["action"]).shape[1:], tf.float32
        ),
        "is_first": tf.TensorSpec((None,), tf.bool),
        "is_terminal": tf.TensorSpec((None,), tf.bool),
    }
    return tf.data.Dataset.from_generator(gen, output_signature=sig)


def make_episode_dataset_from_arrays(episodes: Sequence[Dict]):
    """In-memory episodes (dicts of numpy arrays) -> episode tf.data.Dataset.

    Variable-length episodes are supported via a generator source. Useful for
    tests and for serving the npz episode store through the pure-TF pipeline.
    """
    import numpy as np
    import tensorflow as tf

    if not episodes:
        raise ValueError("no episodes")
    e0 = episodes[0]

    def gen():
        for e in episodes:
            yield {
                "rgb": np.asarray(e["rgb"], np.uint8),
                "instruction": np.asarray(e["instruction"], np.float32),
                "action": np.asarray(e["action"], np.float32),
                "is_first": np.asarray(e["is_first"], bool),
                "is_terminal": np.asarray(e["is_terminal"], bool),
            }

    sig = {
        "rgb": tf.TensorSpec((None,) + tuple(np.asarray(e0["rgb"]).shape[1:]), tf.uint8),
        "instruction": tf.TensorSpec(
            (None,) + tuple(np.asarray(e0["instruction"]).shape[1:]), tf.float32
        ),
        "action": tf.TensorSpec(
            (None,) + tuple(np.asarray(e0["action"]).shape[1:]), tf.float32
        ),
        "is_first": tf.TensorSpec((None,), tf.bool),
        "is_terminal": tf.TensorSpec((None,), tf.bool),
    }
    return tf.data.Dataset.from_generator(gen, output_signature=sig)


class InGraphTableEmbedder:
    """Instruction-bytes -> embedding lookup as pure TF ops.

    The Language-Table instruction set is closed and enumerable
    (`rt1_tpu.envs.rewards.generate_all_instructions`), so the reference's
    host-side USE embedding call can become a `tf.lookup.StaticHashTable`
    from instruction string to a row of a precomputed embedding matrix —
    entirely in-graph, which is what lets the whole RLDS pipeline serialize
    to remote tf.data-service workers. Build the matrix once offline with
    any host embedder (`rt1_tpu/eval/embedding.py::TableInstructionEmbedder
    .build` writes the same .npz consumed here).
    """

    def __init__(self, instructions: Sequence[str], embeddings):
        import numpy as np
        import tensorflow as tf

        matrix = tf.constant(np.asarray(embeddings, np.float32))
        # Unknown instruction -> the appended zero vector (visible in
        # training curves without crashing the input graph).
        self.embeddings = tf.concat([matrix, tf.zeros_like(matrix[:1])], axis=0)
        self.table = tf.lookup.StaticHashTable(
            tf.lookup.KeyValueTensorInitializer(
                tf.constant(list(instructions)),
                tf.range(len(instructions), dtype=tf.int64),
            ),
            default_value=len(instructions),
        )

    @classmethod
    def from_npz(cls, path: str):
        import numpy as np

        with np.load(path, allow_pickle=False) as z:
            return cls([str(s) for s in z["instructions"]], z["embeddings"])

    def __call__(self, text):
        """text: scalar tf.string -> (dim,) float32."""
        import tensorflow as tf

        return tf.gather(self.embeddings, self.table.lookup(text))


def decode_instruction_bytes_tf(instr):
    """(L,) zero-padded byte array -> scalar tf.string (pure TF).

    Graph twin of `rt1_tpu.data.convert_rlds.decode_instruction_bytes`
    (reference `decode_inst:9-11`). Language-Table instructions are ASCII,
    so utf-8 bytes coincide with unicode code points.
    """
    import tensorflow as tf

    instr = tf.cast(instr, tf.int32)
    non_zero = tf.boolean_mask(instr, instr != 0)
    return tf.strings.unicode_encode(non_zero, "UTF-8")


def rlds_episode_to_tensors(dense_steps: Dict, embedder: "InGraphTableEmbedder"):
    """Densified RLDS steps -> our per-episode tensor dict, all TF ops.

    `dense_steps`: the result of batching an episode's `steps` sub-dataset
    into one element: {'action': (T,A), 'is_first': (T,), 'is_terminal':
    (T,), 'observation': {'rgb': (T,h,w,3), 'instruction': (T,L) bytes}}.
    The instruction is embedded ONCE per episode (one instruction per
    episode; the reference embeds the same string per step) and tiled.
    """
    import tensorflow as tf

    obs = dense_steps["observation"]
    t = tf.shape(dense_steps["is_first"])[0]
    emb = embedder(decode_instruction_bytes_tf(obs["instruction"][0]))
    return {
        "rgb": tf.cast(obs["rgb"], tf.uint8),
        "instruction": tf.tile(emb[None, :], (t, 1)),
        "action": tf.cast(dense_steps["action"], tf.float32),
        "is_first": tf.cast(dense_steps["is_first"], tf.bool),
        "is_terminal": tf.cast(dense_steps["is_terminal"], tf.bool),
    }


# Upper bound on steps per episode when densifying the RLDS steps
# sub-dataset (Language-Table episodes are O(100) steps).
MAX_EPISODE_STEPS = 4096


def create_rlds_datasets(
    dataset_dir: str,
    cfg: RldsPipelineConfig,
    embedder=None,
    splits=("train[:7800]", "train[7800:7900]", "train[7900:8000]"),
):
    """TFDS/RLDS front-end (gated; mirrors reference `create_datasets:47-64`).

    Loads RLDS episodes with `tfds.builder_from_directory` and runs the
    conversion fully in-graph: densify steps, decode + table-embed the byte
    instruction (`InGraphTableEmbedder`), window, augment, batch. With an
    in-graph embedder the resulting graph has no Python ops, so
    `cfg.data_service_address` works with genuinely remote workers.

    `embedder`: an `InGraphTableEmbedder` (preferred), the path to its .npz
    table, or a host callable (str -> vec; falls back to a py_function wrap,
    which loses remote-service support). Requires `tensorflow_datasets`.
    """
    try:
        import tensorflow_datasets as tfds  # noqa: F401
    except ImportError as e:  # pragma: no cover - gated dependency
        raise ImportError(
            "create_rlds_datasets needs tensorflow_datasets; for environments "
            "without it, convert offline with rt1_tpu.data.convert_rlds and "
            "use make_episode_dataset_from_paths over the npz store."
        ) from e
    import tensorflow as tf

    if isinstance(embedder, str):
        embedder = InGraphTableEmbedder.from_npz(embedder)
    if embedder is None or not isinstance(embedder, InGraphTableEmbedder):
        host_fn = embedder
        if host_fn is None:
            from rt1_tpu.eval.embedding import get_embedder

            host_fn = get_embedder("hash")

        def embed(text):
            return tf.numpy_function(
                lambda s: host_fn(s.decode("utf-8")), [text], tf.float32
            )

        embedder_fn = embed
    else:
        embedder_fn = embedder

    def to_tensors(episode):
        dense = episode["steps"].batch(MAX_EPISODE_STEPS).get_single_element()
        return rlds_episode_to_tensors(dense, embedder_fn)

    builder = tfds.builder_from_directory(dataset_dir)
    out = []
    for i, split in enumerate(splits):
        episode_ds = builder.as_dataset(split=split).map(
            to_tensors, num_parallel_calls=tf.data.AUTOTUNE
        )
        out.append(windowed_rlds_dataset(episode_ds, cfg, training=(i == 0)))
    return tuple(out)
