"""Demonstration-data collection with the scripted RRT push oracle.

The reference converts Google's pre-recorded RLDS dataset
(`rlds_np_convert.py`) — the episodes themselves were originally collected
with the same scripted oracle it vendors. This module closes that loop
in-framework: roll out `RRTPushOracle` on the simulator and write episodes in
the pipeline's native format (`rt1_tpu/data/episodes.py`: action, is_first,
is_terminal, rgb, instruction-embedding per step), so training data can be
generated hermetically at any scale.

Run:
  python -m rt1_tpu.data.collect --data_dir /tmp/lt_data --episodes 100
"""

from __future__ import annotations

import os

import numpy as np

from rt1_tpu.envs import LanguageTable, blocks
from rt1_tpu.envs import rewards as rewards_module
from rt1_tpu.envs.oracles import RRTPushOracle
from rt1_tpu.eval.embedding import get_embedder


def collect_episode(
    env,
    oracle,
    embedder,
    max_steps=80,
    image_hw=None,
):
    """One oracle rollout -> episode dict, or None if init/solve failed."""
    import cv2

    obs = env.reset()
    oracle.reset()
    if not oracle.get_plan(env.compute_state()):
        return None

    embedding = np.asarray(
        embedder(env.instruction_str), np.float32
    )
    steps = {"action": [], "is_first": [], "is_terminal": [], "rgb": [],
             "instruction": []}
    done = False
    t = 0
    while not done and t < max_steps:
        rgb = obs["rgb"]
        if image_hw is not None:
            rgb = cv2.resize(
                rgb, (image_hw[1], image_hw[0]),
                interpolation=cv2.INTER_LINEAR,
            )
        action = oracle.action(env.compute_state())
        obs, _, done, _ = env.step(action)
        steps["action"].append(np.asarray(action, np.float32))
        steps["is_first"].append(t == 0)
        steps["is_terminal"].append(bool(done))
        steps["rgb"].append(rgb.astype(np.uint8))
        steps["instruction"].append(embedding)
        t += 1
    if not done:
        return None  # oracle failed; skip unsuccessful demos
    return {k: np.stack(v) for k, v in steps.items()}


def collect_dataset(
    data_dir,
    num_episodes,
    block_mode=blocks.BlockMode.BLOCK_8,
    reward_name="block2block",
    seed=0,
    max_steps=80,
    splits=(("train", 0.975), ("val", 0.0125), ("test", 0.0125)),
    embedder="hash",
    image_hw=None,
    progress_every=25,
):
    """Collect `num_episodes` successful demos and write split directories.

    Split sizing follows the reference's 7800/100/100 proportions
    (`rlds_np_convert.py:57-66`).
    """
    from rt1_tpu.data.episodes import save_episode

    env = LanguageTable(
        block_mode=block_mode,
        reward_factory=rewards_module.get_reward_factory(reward_name),
        seed=seed,
    )
    oracle = RRTPushOracle(env, use_ee_planner=True, seed=seed)
    embed_fn = get_embedder(embedder)

    counts = {name: 0 for name, _ in splits}
    quotas = {
        name: int(round(frac * num_episodes)) for name, frac in splits
    }
    # Rounding drift goes to the first (train) split.
    first = splits[0][0]
    quotas[first] += num_episodes - sum(quotas.values())
    for name, _ in splits:
        os.makedirs(os.path.join(data_dir, name), exist_ok=True)

    collected = 0
    attempts = 0
    while collected < num_episodes:
        attempts += 1
        ep = collect_episode(
            env, oracle, embed_fn, max_steps=max_steps, image_hw=image_hw
        )
        if ep is None:
            continue
        # Fill splits in order: train first, then val, then test.
        for name, _ in splits:
            if counts[name] < quotas[name]:
                break
        save_episode(
            os.path.join(data_dir, name, f"episode_{counts[name]}.npz"), ep
        )
        counts[name] += 1
        collected += 1
        if progress_every and collected % progress_every == 0:
            print(
                f"collected {collected}/{num_episodes} "
                f"({attempts} attempts)"
            )
    return counts


def main(argv):
    del argv
    from absl import flags

    FLAGS = flags.FLAGS
    counts = collect_dataset(
        FLAGS.data_dir,
        FLAGS.episodes,
        block_mode=blocks.BlockMode(FLAGS.block_mode),
        reward_name=FLAGS.reward,
        seed=FLAGS.seed,
        max_steps=FLAGS.max_steps,
        embedder=FLAGS.embedder,
    )
    print("done:", counts)


if __name__ == "__main__":
    from absl import app, flags

    flags.DEFINE_string("data_dir", "/tmp/lt_data", "Output directory.")
    flags.DEFINE_integer("episodes", 100, "Successful episodes to collect.")
    flags.DEFINE_string("block_mode", "BLOCK_8", "Block variant.")
    flags.DEFINE_string("reward", "block2block", "Reward family.")
    flags.DEFINE_integer("seed", 0, "Env seed.")
    flags.DEFINE_integer("max_steps", 80, "Max steps per episode.")
    flags.DEFINE_string("embedder", "hash", "Instruction embedder spec.")
    app.run(main)
