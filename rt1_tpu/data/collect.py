"""Demonstration-data collection with the scripted RRT push oracle.

The reference converts Google's pre-recorded RLDS dataset
(`rlds_np_convert.py`) — the episodes themselves were originally collected
with the same scripted oracle it vendors. This module closes that loop
in-framework: roll out `RRTPushOracle` on the simulator and write episodes in
the pipeline's native format (`rt1_tpu/data/episodes.py`: action, is_first,
is_terminal, rgb, instruction-embedding per step), so training data can be
generated hermetically at any scale.

Run:
  python -m rt1_tpu.data.collect --data_dir /tmp/lt_data --episodes 100
"""

from __future__ import annotations

import functools
import json
import os
import shutil

import numpy as np

from rt1_tpu.envs import LanguageTable, blocks
from rt1_tpu.envs import rewards as rewards_module
from rt1_tpu.envs.oracles import RRTPushOracle
from rt1_tpu.eval.embedding import get_embedder

# ONE spelling of the untagged-episode slug for every consumer (pack
# cache, feeder mixture weights, eval matrix, serve labels). Defined in
# pack.py (numpy+stdlib only — importable from anywhere); re-exported
# here because collect.py is the task-stamping authority callers import.
from rt1_tpu.data.pack import UNKNOWN_TASK


def canonical_task_id(reward_name) -> str:
    """The per-episode task id stamped into episodes and pack manifests.

    Reward names in the canonical family registry pass through unchanged
    (the task id IS the reward family); anything else — a custom reward
    class, an experimental family, a typo — maps to the stable
    ``"unknown:<reward_name>"`` slug instead of being dropped, so the
    episode still lands in a (distinguishable) mixture bucket and the
    task-frequency dashboards show *something* rather than silently
    folding it into a canonical family. An empty/None name degrades to
    plain ``"unknown"``.
    """
    if not reward_name:
        return UNKNOWN_TASK
    name = str(reward_name)
    if name in rewards_module.REWARD_FAMILIES:
        return name
    return f"{UNKNOWN_TASK}:{name}"


def collect_episode(
    env,
    oracle,
    embedder,
    max_steps=80,
    image_hw=None,
    exec_noise_std=0.0,
    noise_rng=None,
    task=None,
):
    """One oracle rollout -> episode dict, or None if init/solve failed.

    `exec_noise_std` > 0 enables DART-style noise injection (Laskey et al.
    2017): the EXECUTED action is the oracle's action plus Gaussian noise,
    while the RECORDED label stays the clean corrective action the oracle
    computed for the actually-reached state. The corpus then covers
    off-distribution states with recovery labels — the scale-independent
    mitigation for the round-3 closed-loop drift failure (a policy trained
    on noise-free demos collapses to the marginal action the moment its
    own imperfect actions leave the demo state distribution; diagnosis in
    RESULTS.md, `artifacts/cpu_t1_diag_ck7500.json`). The reference never
    needed this because its corpus is human teleop, which carries this
    state coverage naturally.
    """
    import cv2

    if exec_noise_std and noise_rng is None:
        raise ValueError("exec_noise_std > 0 requires a noise_rng")

    obs = env.reset()
    oracle.reset()
    if not oracle.get_plan(env.compute_state()):
        return None

    embedding = np.asarray(
        embedder(env.instruction_str), np.float32
    )
    steps = {"action": [], "is_first": [], "is_terminal": [], "rgb": [],
             "instruction": []}
    done = False
    t = 0
    while not done and t < max_steps:
        rgb = obs["rgb"]
        if image_hw is not None:
            rgb = cv2.resize(
                rgb, (image_hw[1], image_hw[0]),
                interpolation=cv2.INTER_LINEAR,
            )
        action = oracle.action(env.compute_state())
        exec_action = action
        if exec_noise_std:
            action = np.asarray(action, np.float32)
            exec_action = action + noise_rng.normal(
                0.0, exec_noise_std, size=action.shape
            ).astype(np.float32)
        obs, _, done, _ = env.step(exec_action)
        steps["action"].append(np.asarray(action, np.float32))
        steps["is_first"].append(t == 0)
        steps["is_terminal"].append(bool(done))
        steps["rgb"].append(rgb.astype(np.uint8))
        steps["instruction"].append(embedding)
        t += 1
    if not done:
        return None  # oracle failed; skip unsuccessful demos
    episode = {k: np.stack(v) for k, v in steps.items()}
    # Raw instruction alongside its embedding: enables re-embedding with a
    # different provider and in-pipeline CLIP tokenization (LAVA "clip").
    from rt1_tpu.data.episodes import encode_instruction_text

    episode["instruction_text"] = encode_instruction_text(env.instruction_str)
    if task:
        # The per-episode task id (normally the reward family). Carried
        # through the pack manifest (`data/pack.py`) and exposed by
        # `PackedEpisodeCache.episode_task` — the hook task-mixture
        # sampling weights against.
        episode["task"] = encode_instruction_text(task)
    return episode


def collect_dataset(
    data_dir,
    num_episodes,
    block_mode=blocks.BlockMode.BLOCK_8,
    reward_name="block2block",
    seed=0,
    max_steps=80,
    splits=(("train", 0.975), ("val", 0.0125), ("test", 0.0125)),
    embedder="hash",
    image_hw=None,
    progress_every=25,
    exec_noise_std=0.0,
):
    """Collect `num_episodes` successful demos and write split directories.

    Split sizing follows the reference's 7800/100/100 proportions
    (`rlds_np_convert.py:57-66`). `exec_noise_std` enables DART noise
    injection (see `collect_episode`).
    """
    from rt1_tpu.data.episodes import save_episode

    env = LanguageTable(
        block_mode=block_mode,
        reward_factory=rewards_module.get_reward_factory(reward_name),
        seed=seed,
    )
    oracle = RRTPushOracle(env, use_ee_planner=True, seed=seed)
    embed_fn = get_embedder(embedder)
    noise_rng = np.random.default_rng(seed + 7919)

    counts = {name: 0 for name, _ in splits}
    quotas = _split_quotas(splits, num_episodes)
    for name, _ in splits:
        os.makedirs(os.path.join(data_dir, name), exist_ok=True)

    collected = 0
    attempts = 0
    while collected < num_episodes:
        attempts += 1
        ep = collect_episode(
            env, oracle, embed_fn, max_steps=max_steps, image_hw=image_hw,
            exec_noise_std=exec_noise_std, noise_rng=noise_rng,
            task=canonical_task_id(reward_name),
        )
        if ep is None:
            continue
        # Fill splits in order: train first, then val, then test.
        for name, _ in splits:
            if counts[name] < quotas[name]:
                break
        save_episode(
            os.path.join(data_dir, name, f"episode_{counts[name]}.npz"), ep
        )
        counts[name] += 1
        collected += 1
        if progress_every and collected % progress_every == 0:
            print(
                f"collected {collected}/{num_episodes} "
                f"({attempts} attempts)"
            )
    write_manifest(
        data_dir,
        embedder=embedder,
        reward=reward_name,
        block_mode=block_mode.value,
        max_steps=max_steps,
        image_hw=image_hw,
        episodes=num_episodes,
        seed=seed,
        exec_noise_std=exec_noise_std,
    )
    return counts


def _split_quotas(splits, num_episodes):
    """Episode quota per split; rounding drift goes to the first (train)."""
    quotas = {name: int(round(frac * num_episodes)) for name, frac in splits}
    quotas[splits[0][0]] += num_episodes - sum(quotas.values())
    return quotas


def check_embedder_compatibility(
    data_dir, embedder_spec, context="", manifest_name="manifest.json"
):
    """Raise if the dataset manifest records a different instruction embedder.

    The embedding IS the task specification: a policy trained on data
    embedded with one provider decodes garbage from another. No-op for
    pre-manifest datasets. Returns the manifest (or None).
    """
    manifest = read_manifest(data_dir, manifest_name)
    if manifest is None:
        return None
    recorded = manifest.get("embedder")
    requested = (
        embedder_spec
        if isinstance(embedder_spec, str)
        else getattr(embedder_spec, "name", None)
    )
    if recorded and requested and recorded != requested:
        raise ValueError(
            f"Embedder mismatch{' (' + context + ')' if context else ''}: "
            f"dataset {data_dir!r} was embedded with {recorded!r} but "
            f"{requested!r} was requested. Re-collect/convert the data or "
            f"pass the matching embedder."
        )
    return manifest


def write_manifest(data_dir, **fields):
    """Stamp collection provenance — most importantly the instruction
    embedder — into `<data_dir>/manifest.json`, so consumers can verify that
    data embedded with one provider is never silently mixed with a policy
    using another (the embedding IS the task specification). See
    `check_embedder_compatibility` for the enforcement hook."""
    fields = dict(fields)
    emb = fields.get("embedder")
    if emb is not None and not isinstance(emb, str):
        fields["embedder"] = getattr(emb, "name", str(emb))
    # pid-unique tmp + rename: atomic for readers, an update never
    # truncates a shared inode (hardlink-copied corpora: cp -al seeding,
    # DAgger aggregation), and concurrent writers can't interleave inside
    # one shared tmp file.
    path = os.path.join(data_dir, "manifest.json")
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(fields, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    return fields


def read_manifest(data_dir, manifest_name="manifest.json"):
    """Return the manifest dict, or None for pre-manifest datasets."""
    path = os.path.join(data_dir, manifest_name)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def _collect_shard(shard_dir, count, seed, kwargs):
    """One worker: collect `count` successful episodes into `shard_dir`."""
    from rt1_tpu.data.episodes import save_episode

    env = LanguageTable(
        block_mode=blocks.BlockMode(kwargs.get("block_mode", "BLOCK_8")),
        reward_factory=rewards_module.get_reward_factory(
            kwargs.get("reward_name", "block2block")
        ),
        seed=seed,
    )
    oracle = RRTPushOracle(env, use_ee_planner=True, seed=seed)
    embed_fn = get_embedder(kwargs.get("embedder", "hash"))
    noise_rng = np.random.default_rng(seed + 7919)
    os.makedirs(shard_dir, exist_ok=True)
    done = 0
    while done < count:
        ep = collect_episode(
            env,
            oracle,
            embed_fn,
            max_steps=kwargs.get("max_steps", 80),
            image_hw=kwargs.get("image_hw"),
            exec_noise_std=kwargs.get("exec_noise_std", 0.0),
            noise_rng=noise_rng,
            task=canonical_task_id(kwargs.get("reward_name", "block2block")),
        )
        if ep is None:
            continue
        save_episode(os.path.join(shard_dir, f"episode_{done}.npz"), ep)
        done += 1
    return done


def collect_dataset_parallel(
    data_dir,
    num_episodes,
    workers=8,
    block_mode=blocks.BlockMode.BLOCK_8,
    reward_name="block2block",
    seed=0,
    max_steps=80,
    splits=(("train", 0.975), ("val", 0.0125), ("test", 0.0125)),
    embedder="hash",
    image_hw=None,
    exec_noise_std=0.0,
):
    """`collect_dataset` fanned out over `workers` processes.

    Each worker runs its own env/oracle/embedder seeded at `seed + w` and
    writes to a private shard directory; the parent then deals shards into
    split directories round-robin (so every split mixes all worker seeds)
    and writes the manifest. Rollout collection is embarrassingly parallel —
    the reference leans on a pre-recorded RLDS corpus instead, so it never
    needed this, but hermetic data generation does.
    """
    import multiprocessing as mp

    per = [num_episodes // workers] * workers
    for i in range(num_episodes % workers):
        per[i] += 1
    kwargs = dict(
        block_mode=block_mode.value,
        reward_name=reward_name,
        embedder=embedder,
        max_steps=max_steps,
        image_hw=image_hw,
        exec_noise_std=exec_noise_std,
    )
    shard_root = os.path.join(data_dir, "_shards")
    # A crashed prior run leaves stale shard files that os.walk would
    # otherwise deal into the new dataset (possibly collected under
    # different settings than this manifest records).
    shutil.rmtree(shard_root, ignore_errors=True)
    ctx = mp.get_context("spawn")  # fork is unsafe under JAX/TF runtimes
    procs = []
    for w, count in enumerate(per):
        if count == 0:
            continue
        p = ctx.Process(
            target=_collect_shard,
            args=(os.path.join(shard_root, f"shard_{w}"), count,
                  seed + w, kwargs),
        )
        p.start()
        procs.append(p)
    for p in procs:
        p.join()
        if p.exitcode != 0:
            raise RuntimeError(f"collect worker failed (exit {p.exitcode})")

    all_eps = sorted(
        os.path.join(root, f)
        for root, _, files in os.walk(shard_root)
        for f in files
        if f.endswith(".npz")
    )
    if len(all_eps) < num_episodes:
        raise RuntimeError(
            f"workers produced {len(all_eps)} episodes, need {num_episodes}"
        )
    return _deal_shards(
        data_dir,
        shard_root,
        all_eps[:num_episodes],
        splits,
        seed,
        embedder=embedder,
        reward=reward_name,
        block_mode=block_mode.value,
        max_steps=max_steps,
        image_hw=image_hw,
        workers=workers,
        exec_noise_std=exec_noise_std,
    )


def _deal_shards(data_dir, shard_root, all_eps, splits, seed,
                 **manifest_fields):
    """Shuffle shard episodes, deal them into split dirs, stamp the manifest.

    The shuffle across worker shards is what mixes every worker seed into
    each split. Shared by the normal parallel-collection finish and by
    `finalize_shards` (partial-corpus salvage).
    """
    quotas = _split_quotas(splits, len(all_eps))
    counts = {name: 0 for name, _ in splits}
    order = []
    for name, _ in splits:
        order.extend([name] * quotas[name])
    rng = np.random.default_rng(seed)
    all_eps = list(all_eps)
    rng.shuffle(all_eps)
    for path, name in zip(all_eps, order):
        dst = os.path.join(data_dir, name)
        os.makedirs(dst, exist_ok=True)
        shutil.move(path, os.path.join(dst, f"episode_{counts[name]}.npz"))
        counts[name] += 1
    shutil.rmtree(shard_root, ignore_errors=True)
    write_manifest(
        data_dir, episodes=len(all_eps), seed=seed, **manifest_fields
    )
    return counts


def finalize_shards(
    data_dir,
    splits=(("train", 0.975), ("val", 0.0125), ("test", 0.0125)),
    seed=0,
    **manifest_fields,
):
    """Deal whatever `_shards/` holds into split dirs and stamp a manifest.

    Salvage path for a collection stopped early (slow host, session
    deadline): `collect_dataset_parallel`'s spawn workers write shard files
    continuously and outlive a killed parent, so the episodes on disk are
    complete and valid — only the final deal + manifest is missing. The
    caller must pass manifest fields matching how collection was launched
    (embedder, reward, block_mode, exec_noise_std, ...): shard files don't
    record them.
    """
    shard_root = os.path.join(data_dir, "_shards")
    for name, _ in splits:
        split_dir = os.path.join(data_dir, name)
        if os.path.isdir(split_dir) and os.listdir(split_dir):
            raise RuntimeError(
                f"refusing to finalize: {split_dir} already has episodes "
                "(a prior deal?) — dealing would renumber from episode_0 "
                "and silently mix two corpora under one manifest."
            )
    candidates = sorted(
        os.path.join(root, f)
        for root, _, files in os.walk(shard_root)
        for f in files
        if f.endswith(".npz")
    )
    all_eps = []
    for path in candidates:
        try:
            # A worker killed inside np.savez leaves a truncated zip that
            # the loader would only discover mid-training.
            with np.load(path) as z:
                z.files  # noqa: B018 — forces the header parse
            all_eps.append(path)
        except Exception as e:
            print(f"finalize: skipping corrupt shard file {path}: {e!r}")
    if not all_eps:
        raise RuntimeError(f"no intact shard episodes under {shard_root}")
    return _deal_shards(
        data_dir, shard_root, all_eps, splits, seed, **manifest_fields
    )


def main(argv):
    del argv
    from absl import flags

    FLAGS = flags.FLAGS
    if FLAGS.finalize_shards:
        counts = finalize_shards(
            FLAGS.data_dir,
            seed=FLAGS.seed,
            embedder=FLAGS.embedder,
            reward=FLAGS.reward,
            block_mode=blocks.BlockMode(FLAGS.block_mode).value,
            max_steps=FLAGS.max_steps,
            image_hw=None,
            workers=FLAGS.workers,
            exec_noise_std=FLAGS.exec_noise_std,
        )
        print("finalized:", counts)
        return
    collect = (
        collect_dataset
        if FLAGS.workers <= 1
        else functools.partial(collect_dataset_parallel, workers=FLAGS.workers)
    )
    counts = collect(
        FLAGS.data_dir,
        FLAGS.episodes,
        block_mode=blocks.BlockMode(FLAGS.block_mode),
        reward_name=FLAGS.reward,
        seed=FLAGS.seed,
        max_steps=FLAGS.max_steps,
        embedder=FLAGS.embedder,
        exec_noise_std=FLAGS.exec_noise_std,
    )
    print("done:", counts)


def corpus_accounting(data_dir, manifest=None):
    """Corpus identity from the manifest + files on disk — NEVER the flags.

    Round 3's DART artifact claimed ``episodes_collected: 800`` (the
    requested ``--episodes``) against an actual 125-episode corpus
    (VERDICT r3 weak #3). Returns (episodes_collected, episodes_by_split).
    """
    if manifest is None:
        manifest = read_manifest(data_dir)
    split_counts = {
        name: sum(
            1 for f in os.listdir(os.path.join(data_dir, name))
            if f.endswith(".npz")
        )
        for name in ("train", "val", "test")
        if os.path.isdir(os.path.join(data_dir, name))
    }
    disk_total = sum(split_counts.values())
    episodes = (
        manifest.get("episodes", disk_total) if manifest is not None
        else disk_total
    )
    return episodes, split_counts


if __name__ == "__main__":
    from absl import app, flags

    flags.DEFINE_string("data_dir", "/tmp/lt_data", "Output directory.")
    flags.DEFINE_integer("episodes", 100, "Successful episodes to collect.")
    flags.DEFINE_string("block_mode", "BLOCK_8", "Block variant.")
    flags.DEFINE_string("reward", "block2block", "Reward family.")
    flags.DEFINE_integer("seed", 0, "Env seed.")
    flags.DEFINE_integer("max_steps", 80, "Max steps per episode.")
    flags.DEFINE_string("embedder", "hash", "Instruction embedder spec.")
    flags.DEFINE_integer("workers", 1, "Parallel collection processes.")
    flags.DEFINE_float(
        "exec_noise_std", 0.0,
        "DART execution-noise std: executed action = oracle action + "
        "N(0, std); the recorded label stays clean (see collect_episode).")
    flags.DEFINE_bool(
        "finalize_shards", False,
        "Deal an interrupted parallel collection's _shards/ into split "
        "dirs + manifest instead of collecting. Manifest fields come from "
        "the flags — pass the SAME values the collection was launched "
        "with (shard files don't record them).")
    app.run(main)
