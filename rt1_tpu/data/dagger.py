"""DAgger corrective relabeling: on-policy states, oracle labels.

Round-3 measured mechanism of the closed-loop 0/20s: a BC policy trained on
oracle demos leaves the demo state distribution after one imperfect action
and collapses to the marginal action (RESULTS.md, `artifacts/
cpu_t1_diag_ck7500.json` — action std 0.0009, oracle cosine −0.73, zero
block progress). DART (execution noise at collection) covers *near-demo*
states; DAgger (Ross et al. 2011) covers the states the TRAINED policy
actually visits: roll the policy out, have the scripted RRT oracle label
every visited state with its corrective action, aggregate those episodes
into the corpus, retrain, iterate.

The reference has no counterpart — its corpus is fixed pre-recorded human
teleop (`/root/reference/rlds_np_convert.py`), which carries off-
distribution recovery coverage naturally and cannot be extended. Hermetic
in-framework data generation (`rt1_tpu/data/collect.py`) is what makes
iterative corrective collection possible here.

Episode format matches `collect_episode` exactly (native-resolution uint8
rgb, per-step instruction embedding, clean oracle labels), so aggregated
corpora stay loadable by the standard pipeline with no special casing.
"""

from __future__ import annotations

import os

import numpy as np

from rt1_tpu.data.collect import read_manifest, write_manifest
from rt1_tpu.data.episodes import encode_instruction_text, save_episode

# Policies see the standard eval observation; the collector additionally
# needs the native-resolution frame, so the env must be built with this
# history-key set (extra keys are ignored by RT1EvalPolicy.action).
DAGGER_HISTORY_KEYS = (
    "rgb", "rgb_sequence", "natural_language_embedding",
    "effector_translation", "effector_target_translation",
)


def collect_dagger_episode(
    env,
    policy,
    oracle,
    max_steps=80,
    beta=0.0,
    rng=None,
    image_hw=None,
):
    """One on-policy rollout with per-step oracle relabeling.

    `env` is the wrapped eval env (`build_eval_env`) whose `history_keys`
    include `"rgb"` (see DAGGER_HISTORY_KEYS). The EXECUTED action is the
    policy's (or, with probability `beta`, the oracle's — the DAgger
    beta-mixing knob); the RECORDED label is always the oracle's corrective
    action for the actually-visited state. Unlike demonstration collection,
    unsuccessful episodes are KEPT: they are exactly the off-distribution
    coverage this exists to gather.

    Returns (episode dict | None, succeeded). None = no collision-free
    plan existed for the initial state (init invalid, same as collection).
    """
    if beta and rng is None:
        raise ValueError("beta > 0 requires an rng")
    import cv2

    obs = env.reset()
    policy.reset()
    oracle.reset()
    if not oracle.get_plan(env.compute_state()):
        return None, False

    steps = {"action": [], "is_first": [], "is_terminal": [], "rgb": [],
             "instruction": []}
    done = False
    t = 0
    while not done and t < max_steps:
        label = np.asarray(
            oracle.action(env.compute_state()), np.float32
        )
        # The policy is queried EVERY step, even when the oracle's action is
        # the one executed (beta-mixing): RT1EvalPolicy advances its rolling
        # network_state only inside action(), so skipping the query on
        # oracle-executed steps would condition later policy actions on a
        # gapped temporal window unlike eval-time execution (ADVICE r4).
        proposed = np.asarray(policy.action(obs), np.float32)
        exec_action = proposed
        if beta and rng.random() < beta:
            exec_action = label
        rgb = np.asarray(obs["rgb"][-1])  # native uint8 frame
        if image_hw is not None:
            rgb = cv2.resize(
                rgb, (image_hw[1], image_hw[0]),
                interpolation=cv2.INTER_LINEAR,
            )
        steps["action"].append(label)
        steps["is_first"].append(t == 0)
        steps["rgb"].append(rgb.astype(np.uint8))
        steps["instruction"].append(
            np.asarray(obs["natural_language_embedding"][-1], np.float32)
        )
        obs, _, done, _ = env.step(exec_action)
        steps["is_terminal"].append(bool(done))
        t += 1
    # is_terminal is recorded HONESTLY: it becomes the terminate_episode
    # action-token label downstream (data/pipeline.py), and the oracle
    # would keep acting in a horizon-exhausted mid-task state — forcing a
    # terminal flag there would teach the policy to emit terminate=1 at
    # step 80 of every failed rollout. Windowing needs no end marker (it
    # slices per-episode arrays), so an all-False episode is valid.
    episode = {k: np.stack(v) for k, v in steps.items()}
    episode["instruction_text"] = encode_instruction_text(env.instruction_str)
    return episode, bool(env.succeeded)


def append_episodes_to_corpus(data_dir, episodes, split="train"):
    """Aggregate DAgger episodes into an existing corpus split.

    Continues the split's episode numbering and updates the manifest's
    total + a `dagger_episodes` counter, so `learn_proof.json`'s
    manifest-sourced accounting (VERDICT r3 weak #3) stays truthful after
    aggregation. The embedder/reward/block_mode stamps are left untouched —
    callers must roll out under the corpus' own settings
    (`scripts/learn_proof.py::stage_dagger` validates its flags against
    the manifest before collecting).

    Crash-safety (ADVICE r4): episodes are staged in a hidden temp subdir
    and renamed into the split only when all are written, and the manifest's
    episode totals are RECONCILED from the on-disk file count rather than
    incremented — so a kill between the renames and the manifest write (or
    any orphan files a previous crash left behind) is absorbed by the next
    successful aggregation instead of silently diverging from disk.
    """
    manifest = read_manifest(data_dir)
    if manifest is None:
        raise FileNotFoundError(
            f"{data_dir} has no manifest.json — aggregate only into "
            f"corpora produced by rt1_tpu.data.collect"
        )
    import shutil
    import uuid

    def _count(d):
        return sum(
            1 for f in os.listdir(d)
            if f.startswith("episode_") and f.endswith(".npz")
        )

    def _disk_total():
        total = 0
        for entry in os.listdir(data_dir):
            sub = os.path.join(data_dir, entry)
            if os.path.isdir(sub) and not entry.startswith((".", "_")):
                total += _count(sub)
        return total

    split_dir = os.path.join(data_dir, split)
    os.makedirs(split_dir, exist_ok=True)
    # Sweep stage dirs a crashed aggregation left behind (their contents
    # were never renamed in, so they are safe to drop).
    for entry in os.listdir(split_dir):
        if entry.startswith(".dagger_stage."):
            shutil.rmtree(os.path.join(split_dir, entry), ignore_errors=True)

    # The collect-time episode count, stamped once on first aggregation;
    # dagger_episodes is everything on disk beyond it. Clamped to the
    # pre-append disk total so a manifest that over-counts reality (e.g. a
    # truncated corpus) can't freeze a baseline that drives the dagger
    # counter negative.
    baseline = manifest.get("collected_episodes")
    if baseline is None:
        baseline = manifest.get("episodes", 0) - manifest.get(
            "dagger_episodes", 0
        )
    baseline = min(baseline, _disk_total())

    existing = _count(split_dir)
    stage_dir = os.path.join(split_dir, f".dagger_stage.{uuid.uuid4().hex}")
    os.makedirs(stage_dir)
    try:
        names = [f"episode_{existing + i}.npz" for i in range(len(episodes))]
        for name, episode in zip(names, episodes):
            save_episode(os.path.join(stage_dir, name), episode)
        for name in names:
            os.replace(
                os.path.join(stage_dir, name), os.path.join(split_dir, name)
            )
    finally:
        shutil.rmtree(stage_dir, ignore_errors=True)

    manifest["collected_episodes"] = baseline
    manifest["episodes"] = _disk_total()
    manifest["dagger_episodes"] = manifest["episodes"] - baseline
    write_manifest(data_dir, **manifest)
    return existing + len(episodes)


def collect_dagger_batch(
    env,
    policy,
    oracle,
    num_episodes,
    rng,
    max_steps=80,
    beta=0.0,
    max_attempts_factor=5,
):
    """Collect `num_episodes` relabeled on-policy episodes (failures kept).

    Invalid inits (no collision-free oracle plan) are skipped and
    re-randomized, bounded by `max_attempts_factor * num_episodes` total
    attempts so a pathological board distribution cannot spin forever.
    Returns (episodes, successes, attempts).
    """
    episodes, successes, attempts = [], 0, 0
    while (
        len(episodes) < num_episodes
        and attempts < max_attempts_factor * num_episodes
    ):
        attempts += 1
        ep, success = collect_dagger_episode(
            env, policy, oracle, max_steps=max_steps, beta=beta, rng=rng,
        )
        if ep is None:
            continue
        episodes.append(ep)
        successes += int(success)
    return episodes, successes, attempts
