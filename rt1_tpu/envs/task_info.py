"""Task-description records returned by reward `reset()`.

Parity source: reference `language_table/environments/rewards/task_info.py`.
`FAILURE` is the sentinel a reward returns when it cannot construct a valid
task from the current board, prompting the env to re-randomize.
"""

import dataclasses
from typing import List

import numpy as np


@dataclasses.dataclass
class Block2BlockTaskInfo:
    instruction: str
    block1: str
    block2: str


@dataclasses.dataclass
class Block2LocationTaskInfo:
    instruction: str
    block: str
    target_translation: np.ndarray
    location: str


@dataclasses.dataclass
class Block2LineTaskInfo:
    instruction: str
    block: str
    target_translation: np.ndarray


@dataclasses.dataclass
class Block2PoleTaskInfo:
    instruction: str
    block1: str
    goal: str


@dataclasses.dataclass
class Block2RelativeLocationTaskInfo:
    instruction: str
    block: str
    target_translation: np.ndarray
    location: str


@dataclasses.dataclass
class Block2BlockRelativeLocationTaskInfo:
    instruction: str
    block: str
    target_block: str
    direction: str
    target_translation: np.ndarray


@dataclasses.dataclass
class SeparateBlocksTaskInfo:
    instruction: str
    block: str
    avoid_blocks: List[str]
    target_translation: np.ndarray


@dataclasses.dataclass
class Point2BlockTaskInfo:
    instruction: str
    block_target: str


ALL_TASKS = [
    Block2BlockTaskInfo,
    Block2LocationTaskInfo,
    Block2RelativeLocationTaskInfo,
    Block2BlockRelativeLocationTaskInfo,
    SeparateBlocksTaskInfo,
    Point2BlockTaskInfo,
    Block2LineTaskInfo,
    Block2PoleTaskInfo,
]

FAILURE = "failure"
