"""Oriented push oracle + RRT-planned obstacle-avoiding variant.

Parity source: reference `language_table/environments/oracles/
oriented_push_oracle.py:44-240` (phase state machine: approach the pre-block
point on the block-target line, orient the block when its yaw error is large,
then push) and `push_oracle_rrt_slowdown.py:95-731` (RRT* subgoal planning
for both the pushed block and the free-space end-effector approach, replan /
backoff recovery, near-goal slowdown).

These are plain Python policies over the env's raw state dict — no tf_agents
dependency. `action(raw_state)` returns a (2,) delta; `get_plan(raw_state)`
is used by the eval harness to validate episode inits.
"""

import collections
import dataclasses
from typing import Any, Optional

import numpy as np

from rt1_tpu.envs import constants

# Planning constants (reference `push_oracle_rrt_slowdown.py:29-76`).
BLOCK_DIAMETER = 0.015
ADVANCE_TO_NEXT_SUBGOAL_THRESHOLD = 0.025
PREBLOCK_OFFSET = 0.05
EE_BACKOFF_OFFSETS = [0.06, 0.07, 0.08]
RRT_COLLISION_THRESHOLD = 0.015
RRT_STEP_LENGTH = 0.05
RRT_GOAL_SAMPLE_RATE = 0.1
RRT_SEARCH_RADIUS = 0.5
RRT_MAX_ITERS = 1024
REPLAN_IF_FAILURE = True
RETRY_FOR_NEW_PLAN_EVERY = 10
ADVANCE_TO_NEXT_EE_SUBGOAL_THRESHOLD = 0.01
EPS = 1e-5
BEYOND_TABLE_THRESHOLD = 2.0
EE_RRT_STEP_LENGTH = 0.025
EE_RRT_DELTA = 0.01
EE_RRT_OBSTACLE_RADIUS = 0.02
EE_RRT_ITER_MAX = 2048
RETRY_FOR_NEW_EE_PLAN_EVERY = 1
EXTRA_BOUNDARY_BUFFER = 0.04

X_RANGE_RRT = (constants.X_MIN, constants.X_MAX + EXTRA_BOUNDARY_BUFFER)
Y_RANGE_RRT = (
    constants.Y_MIN - EXTRA_BOUNDARY_BUFFER,
    constants.Y_MAX + EXTRA_BOUNDARY_BUFFER,
)


@dataclasses.dataclass
class PushingInfo:
    """Geometry snapshot consumed by the pushing state machine."""

    xy_block: Any = None
    xy_ee: Any = None
    xy_pre_block: Any = None
    xy_dir_block_to_target: Any = None
    xy_delta_to_nexttoblock: Any = None
    xy_delta_to_touchingblock: Any = None
    xy_dir_block_to_ee: Any = None
    theta_threshold_to_orient: Any = None
    theta_threshold_flat_enough: Any = None
    theta_error: Any = None
    obstacle_poses: Any = None
    distance_to_target: Any = None


def _rotate(theta, v):
    c, s = np.cos(theta), np.sin(theta)
    return np.array([[c, -s], [s, c]]) @ v


def filter_subgoals(path, min_distance):
    """Thin a goal->start path so consecutive kept subgoals are spaced out."""
    path = collections.deque(path)
    keep = collections.deque([path.pop()])
    for _ in range(len(path)):
        candidate = path.pop()
        if np.linalg.norm(np.array(candidate) - np.array(keep[0])) >= min_distance:
            keep.appendleft(candidate)
    return keep


class OrientedPushOracle:
    """Phase machine: move to pre-block -> approach -> orient -> push."""

    def __init__(self, env, action_noise_std=0.0, seed=0):
        self._env = env
        self._rng = np.random.RandomState(seed)
        self._action_noise_std = action_noise_std
        self.phase = "move_to_pre_block"

    def reset(self):
        self.phase = "move_to_pre_block"

    def action(self, raw_state=None):
        if raw_state is None:
            raw_state = self._env.compute_state()
        return self._get_action_for_block_target(raw_state)

    # -- geometry -------------------------------------------------------

    def _control_period(self):
        return 1.0 / getattr(self._env, "_control_frequency", 10.0)

    def _get_action_info(self, raw_state):
        xy_ee = raw_state["effector_target_translation"][:2]
        xy_target = (
            xy_ee
            + raw_state["effector_target_to_task_target_translation"][:2]
        )
        xy_block = (
            xy_ee
            + raw_state["effector_target_to_start_block_translation"][:2]
        )
        theta_block = raw_state["start_block_orientation"]

        to_target = xy_target - xy_block
        dir_to_target = to_target / (
            np.linalg.norm(to_target) + np.finfo(np.float32).eps
        )
        theta_to_target = np.arctan2(dir_to_target[1], dir_to_target[0])

        # Square-ish blocks have 4-way symmetry: wrap into (-pi/4, pi/4].
        theta_error = theta_to_target - theta_block
        while theta_error > np.pi / 4:
            theta_error -= np.pi / 2
        while theta_error < -np.pi / 4:
            theta_error += np.pi / 2

        xy_pre_block = xy_block + -dir_to_target * PREBLOCK_OFFSET
        xy_nexttoblock = xy_block + -dir_to_target * 0.03
        xy_touchingblock = xy_block + -dir_to_target * 0.01

        to_ee = xy_ee - xy_block
        dir_to_ee = to_ee / (np.linalg.norm(to_ee) + np.finfo(np.float32).eps)

        return PushingInfo(
            xy_block=xy_block,
            xy_ee=xy_ee,
            xy_pre_block=xy_pre_block,
            xy_dir_block_to_target=dir_to_target,
            xy_delta_to_nexttoblock=xy_nexttoblock - xy_ee,
            xy_delta_to_touchingblock=xy_touchingblock - xy_ee,
            xy_dir_block_to_ee=dir_to_ee,
            theta_threshold_to_orient=0.2,
            theta_threshold_flat_enough=0.03,
            theta_error=float(np.asarray(theta_error).reshape(-1)[0]),
            distance_to_target=float(np.linalg.norm(to_target)),
        )

    # -- phases ---------------------------------------------------------

    def _phase_move_to_pre_block(self, info):
        delta = info.xy_pre_block - info.xy_ee
        if np.linalg.norm(delta) < 0.001:
            self.phase = "move_to_block"
        return delta, 0.3

    def _phase_move_to_block(self, info, advance_threshold=0.001):
        if np.linalg.norm(info.xy_delta_to_nexttoblock) < advance_threshold:
            self.phase = "push_block"
        if info.theta_error > info.theta_threshold_to_orient:
            self.phase = "orient_block_left"
        if info.theta_error < -info.theta_threshold_to_orient:
            self.phase = "orient_block_right"
        return info.xy_delta_to_nexttoblock

    def _phase_push_block(self, info):
        if abs(info.theta_error) > info.theta_threshold_to_orient:
            self.phase = "move_to_pre_block"
        return info.xy_delta_to_touchingblock

    def _phase_orient(self, info, sign):
        """Circle around the block to spin it; sign=+1 left, -1 right."""
        orient_circle_diameter = 0.025
        direction = _rotate(sign * 0.2, info.xy_dir_block_to_ee)
        spot = info.xy_block + direction * orient_circle_diameter
        if sign > 0 and info.theta_error < info.theta_threshold_flat_enough:
            self.phase = "move_to_pre_block"
        if sign < 0 and info.theta_error > -info.theta_threshold_flat_enough:
            self.phase = "move_to_pre_block"
        return spot - info.xy_ee

    def _get_action_for_block_target(self, raw_state):
        max_step_velocity = 0.35
        info = self._get_action_info(raw_state)

        if self.phase == "move_to_pre_block":
            xy_delta, max_step_velocity = self._phase_move_to_pre_block(info)
        if self.phase == "move_to_block":
            xy_delta = self._phase_move_to_block(info)
        if self.phase == "push_block":
            xy_delta = self._phase_push_block(info)
        if self.phase in ("orient_block_left", "orient_block_right"):
            max_step_velocity = 0.15
        if self.phase == "orient_block_left":
            xy_delta = self._phase_orient(info, +1)
        if self.phase == "orient_block_right":
            xy_delta = self._phase_orient(info, -1)

        if self._action_noise_std:
            xy_delta = xy_delta + self._rng.randn(2) * self._action_noise_std

        max_step = max_step_velocity * self._control_period()
        length = np.linalg.norm(xy_delta)
        if length > max_step:
            xy_delta = xy_delta / length * max_step
        return np.asarray(xy_delta, dtype=np.float32)


class RRTPushOracle(OrientedPushOracle):
    """Push oracle that plans collision-free subgoal chains with RRT*.

    Two planners: one for the *block's* path to the task target, one for the
    *end effector's* free-space approach to the pre-block point. Both replan
    on failure with back-off offsets; near-goal actions are slowed for
    precision (reference `push_oracle_rrt_slowdown.py:311-319`).
    """

    def __init__(
        self,
        env,
        use_ee_planner=True,
        action_noise_std=0.0,
        slowdown_freespace=False,
        backoff_subgoal_rrt=True,
        replan_ee_rrt=True,
        backoff_ee_rrt=True,
        filter_ee_obstacle_poses=True,
        block_diameter=BLOCK_DIAMETER,
        rrt_collision_threshold=RRT_COLLISION_THRESHOLD,
        seed=0,
    ):
        super().__init__(env, action_noise_std=action_noise_std, seed=seed)
        self.phase = "move_to_pre_block_avoid"
        self._use_ee_planner = use_ee_planner
        self._slowdown_freespace = slowdown_freespace
        self._backoff_subgoal_rrt = backoff_subgoal_rrt
        self._replan_ee_rrt = replan_ee_rrt
        self._backoff_ee_rrt = backoff_ee_rrt
        self._filter_ee_obstacle_poses = filter_ee_obstacle_poses
        self._block_diameter = block_diameter
        self._rrt_collision_threshold = rrt_collision_threshold

        self._plan = None
        self._current_rrt_target = None
        self._need_replan = False
        self._replan_counter = 0
        self._ee_plan = None
        self._current_ee_target = None
        self._ee_plan_success = None
        self._need_ee_replan = None
        self._ee_replan_counter = 0
        self._prev_instruction = None

    def reset(self):
        self.phase = "move_to_pre_block_avoid"
        self._current_rrt_target = None
        self._current_ee_target = None
        self._ee_plan = None
        self._replan_counter = 0
        self._ee_replan_counter = 0

    # -- obstacle extraction -------------------------------------------

    def _get_obstacle_poses(self, raw_state):
        poses = [
            raw_state[k][:2]
            for k in raw_state
            if k.startswith("block_") and "translation" in k
        ]
        # On-table blocks only (parked blocks live at (5, 5)).
        return [p for p in poses if np.max(p) < BEYOND_TABLE_THRESHOLD]

    # -- block-path planning -------------------------------------------

    def get_plan(self, raw_state):
        """Plan block subgoals to the task target. Returns plan success."""
        from rt1_tpu.envs.oracles.rrt_star import plan_shortest_path

        xy_ee = raw_state["effector_target_translation"][:2]
        xy_target = (
            xy_ee
            + raw_state["effector_target_to_task_target_translation"][:2]
        )
        xy_block = (
            xy_ee
            + raw_state["effector_target_to_start_block_translation"][:2]
        )
        obstacles = self._get_obstacle_poses(raw_state)
        # Neither the pushed block nor a block-target counts as an obstacle.
        obstacles = [
            o
            for o in obstacles
            if np.linalg.norm(xy_block - o) > EPS
            and np.linalg.norm(xy_target - o) > EPS
        ]

        def _plan_to(goal):
            path, ok = plan_shortest_path(
                xy_start=xy_block,
                xy_goal=goal,
                x_range=X_RANGE_RRT,
                y_range=Y_RANGE_RRT,
                obstacle_xy=obstacles,
                obstacle_widths=[self._block_diameter] * len(obstacles),
                delta=self._rrt_collision_threshold,
                step_length=RRT_STEP_LENGTH,
                goal_sample_rate=RRT_GOAL_SAMPLE_RATE,
                search_radius=RRT_SEARCH_RADIUS,
                iter_max=RRT_MAX_ITERS,
                rng=self._rng,
            )
            return collections.deque(path), ok

        path, success = _plan_to(xy_target)

        if not success and self._backoff_subgoal_rrt:
            # block2block-relative targets sit right next to a block; back the
            # goal off along the offset ray until it becomes plannable.
            from rt1_tpu.envs.rewards.block2block_relative import (
                is_block2block_relative_pair,
            )

            near = [
                o
                for o in obstacles
                if is_block2block_relative_pair(o, xy_target)
            ]
            if near:
                anchor = near[0]
                ray = xy_target - anchor
                for scale in [1.1, 1.2, 1.3, 1.4, 1.5]:
                    new_path, success = _plan_to(anchor + ray * scale)
                    if success:
                        new_path.appendleft(list(xy_target))
                        path = new_path
                        break

        self._need_replan = not success and REPLAN_IF_FAILURE

        if len(path) > 1:
            path.pop()  # rightmost is xy_start
        path = filter_subgoals(path, ADVANCE_TO_NEXT_SUBGOAL_THRESHOLD)
        self._current_rrt_target = np.asarray(path.pop())
        self._plan = path
        return success

    def _maybe_advance_subgoal(self, info, raw_state):
        if (
            info.distance_to_target <= ADVANCE_TO_NEXT_SUBGOAL_THRESHOLD
            and self._plan
        ):
            self._current_rrt_target = np.asarray(self._plan.pop())
            info = self._get_action_info(raw_state)
        return info

    # -- ee-path planning ----------------------------------------------

    def _filtered_ee_obstacles(self, obstacles, xy_target, pushing_block):
        """Drop blocks already touching the ee goal (except the push block)."""
        out = []
        for o in obstacles:
            in_collision = np.linalg.norm(o - xy_target) < 0.05
            is_push_block = np.linalg.norm(o - pushing_block) < 1e-6
            if in_collision and not is_push_block:
                continue
            out.append(o)
        return out

    def _get_ee_plan(self, raw_state, info):
        from rt1_tpu.envs.oracles.rrt_star import plan_shortest_path

        xy_ee = raw_state["effector_target_translation"][:2]
        offsets = [PREBLOCK_OFFSET]
        if self._backoff_ee_rrt:
            offsets = offsets + EE_BACKOFF_OFFSETS
        success, path = False, None
        for offset in offsets:
            xy_target = info.xy_block + -info.xy_dir_block_to_target * offset
            obstacles = self._get_obstacle_poses(raw_state)
            if self._filter_ee_obstacle_poses:
                obstacles = self._filtered_ee_obstacles(
                    obstacles, xy_target, info.xy_block
                )
            path, success = plan_shortest_path(
                xy_start=xy_ee,
                xy_goal=xy_target,
                x_range=X_RANGE_RRT,
                y_range=Y_RANGE_RRT,
                obstacle_xy=obstacles,
                obstacle_widths=[EE_RRT_OBSTACLE_RADIUS] * len(obstacles),
                delta=EE_RRT_DELTA,
                step_length=EE_RRT_STEP_LENGTH,
                goal_sample_rate=RRT_GOAL_SAMPLE_RATE,
                search_radius=RRT_SEARCH_RADIUS,
                iter_max=EE_RRT_ITER_MAX,
                rng=self._rng,
            )
            if success:
                break

        self._need_ee_replan = not success and self._replan_ee_rrt
        path = filter_subgoals(path, ADVANCE_TO_NEXT_EE_SUBGOAL_THRESHOLD)
        # The plan targets a backed-off point; make the true pre-block point
        # the final subgoal.
        final = list(info.xy_pre_block)
        if np.linalg.norm(np.array(path[0]) - np.array(final)) >= EPS:
            path.appendleft(final)
        if len(path) > 1:
            path.pop()
        self._current_ee_target = np.asarray(path.pop())
        self._ee_plan = path
        self._ee_plan_success = success

    def _maybe_advance_ee_subgoal(self, info, raw_state):
        diff = np.linalg.norm(self._current_ee_target - info.xy_ee)
        if diff < ADVANCE_TO_NEXT_EE_SUBGOAL_THRESHOLD and self._ee_plan:
            self._current_ee_target = np.asarray(self._ee_plan.pop())
            info = self._get_action_info(raw_state)
        if not self._ee_plan:
            # Track the live pre-block point once the open-loop plan is spent.
            self._current_ee_target = info.xy_pre_block
        return info

    # -- freespace approach phase --------------------------------------

    def _phase_move_to_pre_block_avoid(self, info, raw_state):
        if self._current_ee_target is None and self._use_ee_planner:
            self._get_ee_plan(raw_state, info)
        self._ee_replan_counter += 1
        if (
            self._replan_ee_rrt
            and self._need_ee_replan
            and self._ee_replan_counter % RETRY_FOR_NEW_EE_PLAN_EVERY == 0
        ):
            self._get_ee_plan(raw_state, info)

        if self._use_ee_planner:
            info = self._maybe_advance_ee_subgoal(info, raw_state)
        if self._use_ee_planner and self._ee_plan_success:
            delta = self._current_ee_target - info.xy_ee
            if np.linalg.norm(delta) < 0.001:
                self.phase = "move_to_block"
            return info, delta, 0.3
        return info, *self._phase_avoid_potential(info)

    def _phase_avoid_potential(self, info):
        """Potential-field fallback when the ee planner failed."""
        to_preblock = info.xy_pre_block - info.xy_ee
        delta = np.zeros(2)

        for pose in info.obstacle_poses or []:
            d = np.linalg.norm(info.xy_ee - pose)
            theta = np.arctan2(
                pose[1] - info.xy_ee[1], pose[0] - info.xy_ee[0]
            )
            r, s = 0.029, 0.03
            if d < r:
                delta += -np.sign([np.cos(theta), np.sin(theta)]) * 1e9
            elif d <= s + r:
                delta += (
                    -500 * (s + r - d) * np.array([np.cos(theta), np.sin(theta)])
                )

        gd = np.linalg.norm(to_preblock)
        gtheta = np.arctan2(to_preblock[1], to_preblock[0])
        r = 0.03
        if gd > 2 * r:
            delta += 300 * 0.03 * np.array([np.cos(gtheta), np.sin(gtheta)])
        elif gd >= r:
            delta += 550 * 0.03 * np.array([np.cos(gtheta), np.sin(gtheta)])
        else:
            delta += 1000 * r * np.array([np.cos(gtheta), np.sin(gtheta)])

        if gd < 0.015:
            delta = to_preblock
        if gd < 0.01:
            self.phase = "move_to_block"
            delta = to_preblock
        return delta, 0.3

    # -- slowdown + main dispatch --------------------------------------

    @staticmethod
    def _maybe_slowdown(dist, max_step):
        for thresh, slow in zip(
            [0.02, 0.04, 0.06, 0.08, 0.1], [0.2, 0.3, 0.4, 0.5, 0.6]
        ):
            if dist < thresh:
                return max_step * slow
        return max_step

    def _get_action_info(self, raw_state):
        info = super()._get_action_info(raw_state)
        # Retarget geometry at the current RRT subgoal while subgoals remain;
        # only the final leg chases the live task target.
        if self._plan:
            xy_target = np.asarray(self._current_rrt_target)
            to_target = xy_target - info.xy_block
            dir_to_target = to_target / (
                np.linalg.norm(to_target) + np.finfo(np.float32).eps
            )
            theta_to_target = np.arctan2(dir_to_target[1], dir_to_target[0])
            theta_block = raw_state["start_block_orientation"]
            theta_error = theta_to_target - theta_block
            while theta_error > np.pi / 4:
                theta_error -= np.pi / 2
            while theta_error < -np.pi / 4:
                theta_error += np.pi / 2
            info.xy_dir_block_to_target = dir_to_target
            info.theta_error = float(np.asarray(theta_error).reshape(-1)[0])
            info.xy_pre_block = info.xy_block + -dir_to_target * PREBLOCK_OFFSET
            info.xy_delta_to_nexttoblock = (
                info.xy_block + -dir_to_target * 0.03 - info.xy_ee
            )
            info.xy_delta_to_touchingblock = (
                info.xy_block + -dir_to_target * 0.01 - info.xy_ee
            )
            info.distance_to_target = float(np.linalg.norm(to_target))
        info.obstacle_poses = self._get_obstacle_poses(raw_state)
        return info

    def _get_action_for_block_target(self, raw_state):
        if "instruction" in raw_state:
            cur = raw_state["instruction"]
            if self._prev_instruction is not None and np.linalg.norm(
                self._prev_instruction - cur
            ) > 0.0:
                self.reset()
            self._prev_instruction = cur

        if self._current_rrt_target is None:
            self.get_plan(raw_state)
        self._replan_counter += 1
        if (
            REPLAN_IF_FAILURE
            and self._need_replan
            and self._replan_counter % RETRY_FOR_NEW_PLAN_EVERY == 0
        ):
            self.get_plan(raw_state)

        info = self._get_action_info(raw_state)
        info = self._maybe_advance_subgoal(info, raw_state)

        max_step_velocity = 0.35
        if self.phase == "move_to_pre_block_avoid":
            info, xy_delta, max_step_velocity = (
                self._phase_move_to_pre_block_avoid(info, raw_state)
            )
        if self.phase == "move_to_pre_block":
            xy_delta, max_step_velocity = self._phase_move_to_pre_block(info)
        if self.phase == "move_to_block":
            xy_delta = self._phase_move_to_block(info, advance_threshold=0.01)
        if self.phase == "push_block":
            xy_delta = self._phase_push_block(info)
        if self.phase in ("orient_block_left", "orient_block_right"):
            max_step_velocity = 0.15
        if self.phase == "orient_block_left":
            xy_delta = self._phase_orient(info, +1)
        if self.phase == "orient_block_right":
            xy_delta = self._phase_orient(info, -1)

        if self._action_noise_std:
            xy_delta = xy_delta + self._rng.randn(2) * self._action_noise_std

        max_step = max_step_velocity * self._control_period()
        in_freespace = self.phase == "move_to_pre_block_avoid"
        if not in_freespace or self._slowdown_freespace:
            max_step = self._maybe_slowdown(info.distance_to_target, max_step)
        length = np.linalg.norm(xy_delta)
        if length > max_step:
            xy_delta = xy_delta / length * max_step
        return np.asarray(xy_delta, dtype=np.float32)
