"""Planner / oracle debug visualization.

Capability parity with the reference's `language_table/environments/oracles/
plot.py` (matplotlib scatter of RRT* tree, obstacles, and planned path, used
while tuning the push oracle), rebuilt on PIL so it shares the coordinate
mapping and dependency footprint of `rt1_tpu/envs/rendering.py` — the frames
compose directly with `render_board` output and can go straight into the
eval-video writer (`rt1_tpu/eval/evaluate.py`).

All drawing is in board/world coordinates; `image_size` is (height, width).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
from PIL import Image, ImageDraw

from rt1_tpu.envs import constants, rendering
from rt1_tpu.envs.rendering import _scale, _world_to_px

TREE_COLOR = (120, 200, 255, 110)
OBSTACLE_COLOR = (230, 90, 70, 90)
OBSTACLE_EDGE = (230, 90, 70, 220)
PATH_COLOR = (255, 230, 60, 255)
START_COLOR = (60, 220, 90, 255)
GOAL_COLOR = (255, 90, 200, 255)


def _blank_board(image_size):
    """Empty board in the palette of `rendering.render_board` so debug frames
    compose consistently with real board frames."""
    h, w = image_size
    img = Image.new("RGB", (w, h), rendering.BORDER_COLOR)
    draw = ImageDraw.Draw(img, "RGBA")
    x0, y0 = _world_to_px((constants.X_MIN, constants.Y_MIN), image_size)
    x1, y1 = _world_to_px((constants.X_MAX, constants.Y_MAX), image_size)
    draw.rectangle([x0, y0, x1, y1], fill=rendering.BOARD_COLOR)
    return img, draw


def draw_planner(
    planner,
    image: Optional[np.ndarray] = None,
    image_size=(360, 640),
    show_tree: bool = True,
) -> np.ndarray:
    """Render an `RRTStarPlanner` (tree, obstacles, path) to an RGB array.

    Args:
      planner: a planned `rt1_tpu.envs.oracles.rrt_star.RRTStarPlanner`
        (after `.plan()`; a failed plan still draws its tree + obstacles).
      image: optional background frame (e.g. `render_board` output) to draw
        over; resized to `image_size`.
      image_size: (height, width) of the output.
      show_tree: include the expanded tree edges, not just the path.
    """
    if image is not None:
        img = Image.fromarray(np.asarray(image, np.uint8)).resize(
            (image_size[1], image_size[0]), Image.BILINEAR
        )
        draw = ImageDraw.Draw(img, "RGBA")
    else:
        img, draw = _blank_board(image_size)
    px_per_m = _scale(image_size)

    # Inflated obstacles as seen by the collision checker.
    for c, r in zip(planner.obstacles, planner.radii):
        cx, cy = _world_to_px(c, image_size)
        pr = float(r) * px_per_m
        draw.ellipse(
            [cx - pr, cy - pr, cx + pr, cy + pr],
            fill=OBSTACLE_COLOR,
            outline=OBSTACLE_EDGE,
        )

    if show_tree and len(planner.tree_points):
        pts_px = [_world_to_px(p, image_size) for p in planner.tree_points]
        for i, par in enumerate(planner.tree_parent):
            if par < 0:
                continue
            draw.line([pts_px[int(par)], pts_px[i]], fill=TREE_COLOR, width=1)

    draw_path(img, planner.path, image_size=image_size)

    for p, color in ((planner.start, START_COLOR), (planner.goal, GOAL_COLOR)):
        cx, cy = _world_to_px(p, image_size)
        draw.ellipse([cx - 4, cy - 4, cx + 4, cy + 4], fill=color)

    return np.asarray(img, dtype=np.uint8)


def draw_path(
    img,
    path: Sequence[Sequence[float]],
    image_size=(360, 640),
    color=PATH_COLOR,
) -> None:
    """Draw a subgoal polyline (planner `path` is goal->start order) onto a
    PIL image in place."""
    if path is None or len(path) < 2:
        return
    draw = ImageDraw.Draw(img, "RGBA")
    px = [_world_to_px(p, image_size) for p in path]
    draw.line(px, fill=color, width=2)
    for p in px:
        draw.ellipse([p[0] - 2, p[1] - 2, p[0] + 2, p[1] + 2], fill=color)


def draw_oracle_plan(
    oracle,
    raw_state,
    image: Optional[np.ndarray] = None,
    image_size=(360, 640),
) -> np.ndarray:
    """Visualize an `RRTPushOracle`'s current block plan for `raw_state`.

    Plans with the oracle's own `get_plan` (same obstacles/parameters the
    eval init-validation uses, `rt1_tpu/eval/evaluate.py`), then draws the
    block subgoal sequence over the board. The oracle's planning state
    (`_plan`, `_current_rrt_target`, `_need_replan`) and RNG stream are
    snapshotted and restored, so per-frame visualization during a rollout
    does not change the oracle's subsequent actions.
    """
    saved = (oracle._plan, oracle._current_rrt_target, oracle._need_replan)
    rng_state = oracle._rng.get_state()
    try:
        oracle.get_plan(raw_state)
        path = [list(p) for p in oracle._plan] + [
            list(oracle._current_rrt_target)
        ]
    finally:
        oracle._plan, oracle._current_rrt_target, oracle._need_replan = saved
        oracle._rng.set_state(rng_state)
    if image is None:
        img, _ = _blank_board(image_size)
    else:
        img = Image.fromarray(np.asarray(image, np.uint8)).resize(
            (image_size[1], image_size[0]), Image.BILINEAR
        )
    draw_path(img, path, image_size=image_size)
    return np.asarray(img, dtype=np.uint8)
