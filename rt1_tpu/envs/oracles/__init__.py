"""Scripted-expert oracles for the Language-Table board.

Parity source: reference `language_table/environments/oracles/` — an
RRT*-planned oriented push oracle used to validate episode inits at eval
time and (originally) to collect demonstration data.
"""

from rt1_tpu.envs.oracles.push_oracle import (
    OrientedPushOracle,
    RRTPushOracle,
)
from rt1_tpu.envs.oracles.rrt_star import plan_shortest_path

__all__ = ["OrientedPushOracle", "RRTPushOracle", "plan_shortest_path"]
