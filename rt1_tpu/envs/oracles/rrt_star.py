"""Vectorized RRT* planner over a 2-D board with circular obstacles.

Parity source: reference `language_table/environments/oracles/rrt_star.py:
25-357` (same algorithm, same tuning-parameter meanings). This version keeps
the vertex set in growing numpy arrays so nearest-neighbor / neighborhood
queries and segment-circle collision checks are vectorized instead of Python
loops over node objects — the planner runs every few control steps in the
eval loop, so host-side speed matters.
"""

import math

import numpy as np


def _segment_hits_circles(p0, p1, centers, radii):
    """Does segment p0->p1 pass within radii of any center? Vectorized."""
    if len(centers) == 0:
        return False
    d = p1 - p0
    d2 = float(d @ d)
    if d2 == 0.0:
        return False
    t = np.clip(((centers - p0) @ d) / d2, 0.0, 1.0)
    closest = p0 + t[:, None] * d
    dist = np.linalg.norm(closest - centers, axis=1)
    return bool(np.any(dist <= radii))


def _inside_circles(p, centers, radii):
    if len(centers) == 0:
        return False
    return bool(np.any(np.linalg.norm(centers - p, axis=1) <= radii))


def _inside_boundary(p, delta, x_range, y_range, boundary_width):
    """Inside any of the four thin boundary strips (with margin delta)."""
    x, y = p
    x_min, x_max = x_range
    y_min, y_max = y_range
    w = boundary_width
    return (
        x <= x_min + w + delta
        or x >= x_max - delta
        or y <= y_min + w + delta
        or y >= y_max - delta
    )


class RRTStarPlanner:
    """RRT* over a rectangle with circular obstacles."""

    def __init__(
        self,
        start,
        goal,
        x_range,
        y_range,
        obstacle_xy,
        obstacle_radii,
        delta,
        step_length,
        goal_sample_rate,
        search_radius,
        iter_max,
        boundary_width=0.01,
        rng=None,
    ):
        self.start = np.asarray(start, dtype=np.float64)
        self.goal = np.asarray(goal, dtype=np.float64)
        self.x_range = x_range
        self.y_range = y_range
        self.obstacles = (
            np.asarray(obstacle_xy, dtype=np.float64).reshape(-1, 2)
        )
        # Inflate obstacle radii by delta once, up front.
        self.radii = (
            np.asarray(obstacle_radii, dtype=np.float64).reshape(-1) + delta
        )
        self.delta = delta
        self.step_length = step_length
        self.goal_sample_rate = goal_sample_rate
        self.search_radius = search_radius
        self.iter_max = iter_max
        self.boundary_width = boundary_width
        self.rng = rng or np.random
        self.success = False
        self.path = []
        # Filled by plan() on success; consumed by oracles/plot.py.
        self.tree_points = np.zeros((0, 2))
        self.tree_parent = np.zeros((0,), dtype=np.int64)

    def _collision_free(self, p0, p1):
        if _inside_circles(p1, self.obstacles, self.radii):
            return False
        if _inside_boundary(
            p1, self.delta, self.x_range, self.y_range, self.boundary_width
        ):
            return False
        return not _segment_hits_circles(p0, p1, self.obstacles, self.radii)

    def plan(self):
        """Grow the tree; on success `self.path` is goal->start subgoals."""
        if _inside_circles(self.start, self.obstacles, self.radii):
            # Start embedded in an obstacle: unplannable configuration.
            self.success = False
            return self

        n_cap = self.iter_max + 2
        pts = np.empty((n_cap, 2))
        parent = np.full(n_cap, -1, dtype=np.int64)
        cost = np.zeros(n_cap)
        pts[0] = self.start
        n = 1

        for _ in range(self.iter_max):
            if self.rng.random() > self.goal_sample_rate:
                sample = np.array(
                    [
                        self.rng.uniform(
                            self.x_range[0] + self.delta,
                            self.x_range[1] - self.delta,
                        ),
                        self.rng.uniform(
                            self.y_range[0] + self.delta,
                            self.y_range[1] - self.delta,
                        ),
                    ]
                )
            else:
                sample = self.goal

            dists = np.linalg.norm(pts[:n] - sample, axis=1)
            near_i = int(np.argmin(dists))
            step = min(self.step_length, dists[near_i])
            if dists[near_i] == 0.0:
                continue
            new = pts[near_i] + (sample - pts[near_i]) / dists[near_i] * step

            if not self._collision_free(pts[near_i], new):
                continue

            # Neighborhood radius shrinks as the tree grows (standard RRT*).
            r = min(
                self.search_radius * math.sqrt(math.log(n + 1) / (n + 1)),
                self.step_length,
            )
            nd = np.linalg.norm(pts[:n] - new, axis=1)
            neighbors = [
                j
                for j in np.flatnonzero(nd <= r)
                if self._collision_free(pts[j], new)
            ]

            pts[n] = new
            if neighbors:
                costs = [cost[j] + nd[j] for j in neighbors]
                best = neighbors[int(np.argmin(costs))]
                parent[n] = best
                cost[n] = cost[best] + nd[best]
                # Rewire: adopt the new node as parent where it shortens paths.
                for j in neighbors:
                    through_new = cost[n] + nd[j]
                    if through_new < cost[j]:
                        parent[j] = n
                        cost[j] = through_new
            else:
                parent[n] = near_i
                cost[n] = cost[near_i] + step
            n += 1

        # Retain the tree for debug visualization (oracles/plot.py) — saved
        # before goal connection so failed plans can be inspected too.
        self.tree_points = pts[:n].copy()
        self.tree_parent = parent[:n].copy()

        # Connect the tree to the goal.
        gd = np.linalg.norm(pts[:n] - self.goal, axis=1)
        candidates = np.flatnonzero(gd <= self.step_length)
        best_i, best_c = None, np.inf
        for j in candidates:
            if not self._collision_free(pts[j], self.goal):
                continue
            c = cost[j] + gd[j]
            if c < best_c:
                best_i, best_c = int(j), c
        if best_i is None:
            if len(candidates):
                self.success = False
                return self
            # Mirror the reference's fallback: no vertex reached the goal
            # radius; treat the most recently added vertex as the endpoint.
            best_i = n - 1

        path = [list(self.goal)]
        node = best_i
        while node != -1:
            path.append([float(pts[node][0]), float(pts[node][1])])
            node = int(parent[node])
        self.path = path
        self.success = True
        return self


def plan_shortest_path(
    xy_start,
    xy_goal,
    x_range,
    y_range,
    obstacle_xy,
    obstacle_widths,
    delta,
    step_length,
    goal_sample_rate,
    search_radius,
    iter_max,
    boundary_width=0.01,
    rng=None,
    raise_error_on_plan_failure=False,
):
    """Plan goal->start subgoal list; falls back to the direct segment.

    Mirrors `rrt_star.get_shortest_path_no_collisions` (reference `:25-85`)
    including the "just try the direct path and replan later" compromise on
    failure.
    """
    planner = RRTStarPlanner(
        xy_start,
        xy_goal,
        x_range,
        y_range,
        obstacle_xy,
        obstacle_widths,
        delta,
        step_length,
        goal_sample_rate,
        search_radius,
        iter_max,
        boundary_width=boundary_width,
        rng=rng,
    )
    planner.plan()
    if not planner.success:
        if raise_error_on_plan_failure:
            raise ValueError("Could not find path! Consider retuning RRT-*.")
        return [list(np.asarray(xy_goal, float)),
                list(np.asarray(xy_start, float))], False
    return planner.path, True
