"""Vectorized RRT* planner over a 2-D board with circular obstacles.

Parity source: reference `language_table/environments/oracles/rrt_star.py:
25-357` (same algorithm, same tuning-parameter meanings). This version keeps
the vertex set in growing numpy arrays so nearest-neighbor / neighborhood
queries are vectorized instead of Python loops over node objects — the
planner runs every few control steps in the eval loop, so host-side speed
matters.

Collision checks, by contrast, are deliberately SCALAR: the board carries at
most a handful of circular obstacles, and profiling the round-4 collector
showed ~80% of episode-collection wall-clock inside numpy-per-call overhead
of the old array-based `_collision_free` (≈130 µs/call across ~180k calls
for six episodes). Plain float arithmetic over a prebuilt obstacle tuple
list runs the same check in a few µs, which multiplies the throughput of
demo collection, DAgger relabeling, and every oracle-driven eval.
"""

import math

import numpy as np


def _obstacle_tuples(centers, radii):
    """Precompute [(cx, cy, r^2), ...] Python floats for the scalar checks."""
    return [
        (float(c[0]), float(c[1]), float(r) * float(r))
        for c, r in zip(np.asarray(centers).reshape(-1, 2), radii)
    ]


def _inside_circles(p, obstacle_tuples):
    """Point inside any (inflated) obstacle; takes the prebuilt tuples."""
    x, y = float(p[0]), float(p[1])
    for cx, cy, r2 in obstacle_tuples:
        px, py = cx - x, cy - y
        if px * px + py * py <= r2:
            return True
    return False


class RRTStarPlanner:
    """RRT* over a rectangle with circular obstacles."""

    def __init__(
        self,
        start,
        goal,
        x_range,
        y_range,
        obstacle_xy,
        obstacle_radii,
        delta,
        step_length,
        goal_sample_rate,
        search_radius,
        iter_max,
        boundary_width=0.01,
        rng=None,
    ):
        self.start = np.asarray(start, dtype=np.float64)
        self.goal = np.asarray(goal, dtype=np.float64)
        self.x_range = x_range
        self.y_range = y_range
        self.obstacles = (
            np.asarray(obstacle_xy, dtype=np.float64).reshape(-1, 2)
        )
        # Inflate obstacle radii by delta once, up front.
        self.radii = (
            np.asarray(obstacle_radii, dtype=np.float64).reshape(-1) + delta
        )
        # Scalar-check working set (see module docstring): built once per
        # plan, consumed millions of times.
        self._obs = _obstacle_tuples(self.obstacles, self.radii)
        self.delta = delta
        self.step_length = step_length
        self.goal_sample_rate = goal_sample_rate
        self.search_radius = search_radius
        self.iter_max = iter_max
        self.boundary_width = boundary_width
        self.rng = rng or np.random
        self.success = False
        self.path = []
        # Filled by plan() on success; consumed by oracles/plot.py.
        self.tree_points = np.zeros((0, 2))
        self.tree_parent = np.zeros((0,), dtype=np.int64)

    def _collision_free(self, p0, p1):
        """Fused scalar form of: p1 outside every (inflated) obstacle AND
        outside the boundary strips AND segment p0->p1 clear of every
        obstacle. Semantics identical to the three vectorized helpers; the
        per-call numpy overhead they carried dominated collection/eval
        profiles (module docstring)."""
        x1, y1 = float(p1[0]), float(p1[1])
        x_min, x_max = self.x_range
        y_min, y_max = self.y_range
        margin = self.boundary_width + self.delta
        if (
            x1 <= x_min + margin
            or x1 >= x_max - self.delta
            or y1 <= y_min + margin
            or y1 >= y_max - self.delta
        ):
            return False
        x0, y0 = float(p0[0]), float(p0[1])
        dx, dy = x1 - x0, y1 - y0
        d2 = dx * dx + dy * dy
        for cx, cy, r2 in self._obs:
            px, py = cx - x1, cy - y1
            if px * px + py * py <= r2:
                return False
            if d2 > 0.0:
                t = ((cx - x0) * dx + (cy - y0) * dy) / d2
                if t < 0.0:
                    t = 0.0
                elif t > 1.0:
                    t = 1.0
                qx, qy = x0 + t * dx - cx, y0 + t * dy - cy
                if qx * qx + qy * qy <= r2:
                    return False
        return True

    def plan(self):
        """Grow the tree; on success `self.path` is goal->start subgoals."""
        if _inside_circles(self.start, self._obs):
            # Start embedded in an obstacle: unplannable configuration.
            self.success = False
            return self

        n_cap = self.iter_max + 2
        pts = np.empty((n_cap, 2))
        parent = np.full(n_cap, -1, dtype=np.int64)
        cost = np.zeros(n_cap)
        pts[0] = self.start
        n = 1

        for _ in range(self.iter_max):
            if self.rng.random() > self.goal_sample_rate:
                sample = np.array(
                    [
                        self.rng.uniform(
                            self.x_range[0] + self.delta,
                            self.x_range[1] - self.delta,
                        ),
                        self.rng.uniform(
                            self.y_range[0] + self.delta,
                            self.y_range[1] - self.delta,
                        ),
                    ]
                )
            else:
                sample = self.goal

            dists = np.linalg.norm(pts[:n] - sample, axis=1)
            near_i = int(np.argmin(dists))
            step = min(self.step_length, dists[near_i])
            if dists[near_i] == 0.0:
                continue
            new = pts[near_i] + (sample - pts[near_i]) / dists[near_i] * step

            if not self._collision_free(pts[near_i], new):
                continue

            # Neighborhood radius shrinks as the tree grows (standard RRT*).
            r = min(
                self.search_radius * math.sqrt(math.log(n + 1) / (n + 1)),
                self.step_length,
            )
            nd = np.linalg.norm(pts[:n] - new, axis=1)
            neighbors = [
                j
                for j in np.flatnonzero(nd <= r)
                if self._collision_free(pts[j], new)
            ]

            pts[n] = new
            if neighbors:
                costs = [cost[j] + nd[j] for j in neighbors]
                best = neighbors[int(np.argmin(costs))]
                parent[n] = best
                cost[n] = cost[best] + nd[best]
                # Rewire: adopt the new node as parent where it shortens paths.
                for j in neighbors:
                    through_new = cost[n] + nd[j]
                    if through_new < cost[j]:
                        parent[j] = n
                        cost[j] = through_new
            else:
                parent[n] = near_i
                cost[n] = cost[near_i] + step
            n += 1

        # Retain the tree for debug visualization (oracles/plot.py) — saved
        # before goal connection so failed plans can be inspected too.
        self.tree_points = pts[:n].copy()
        self.tree_parent = parent[:n].copy()

        # Connect the tree to the goal.
        gd = np.linalg.norm(pts[:n] - self.goal, axis=1)
        candidates = np.flatnonzero(gd <= self.step_length)
        best_i, best_c = None, np.inf
        for j in candidates:
            if not self._collision_free(pts[j], self.goal):
                continue
            c = cost[j] + gd[j]
            if c < best_c:
                best_i, best_c = int(j), c
        if best_i is None:
            if len(candidates):
                self.success = False
                return self
            # Mirror the reference's fallback: no vertex reached the goal
            # radius; treat the most recently added vertex as the endpoint.
            best_i = n - 1

        path = [list(self.goal)]
        node = best_i
        while node != -1:
            path.append([float(pts[node][0]), float(pts[node][1])])
            node = int(parent[node])
        self.path = path
        self.success = True
        return self


def plan_shortest_path(
    xy_start,
    xy_goal,
    x_range,
    y_range,
    obstacle_xy,
    obstacle_widths,
    delta,
    step_length,
    goal_sample_rate,
    search_radius,
    iter_max,
    boundary_width=0.01,
    rng=None,
    raise_error_on_plan_failure=False,
):
    """Plan goal->start subgoal list; falls back to the direct segment.

    Mirrors `rrt_star.get_shortest_path_no_collisions` (reference `:25-85`)
    including the "just try the direct path and replan later" compromise on
    failure.
    """
    planner = RRTStarPlanner(
        xy_start,
        xy_goal,
        x_range,
        y_range,
        obstacle_xy,
        obstacle_widths,
        delta,
        step_length,
        goal_sample_rate,
        search_radius,
        iter_max,
        boundary_width=boundary_width,
        rng=rng,
    )
    planner.plan()
    if not planner.success:
        if raise_error_on_plan_failure:
            raise ValueError("Could not find path! Consider retuning RRT-*.")
        return [list(np.asarray(xy_goal, float)),
                list(np.asarray(xy_start, float))], False
    return planner.path, True
