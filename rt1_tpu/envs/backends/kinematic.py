"""Pure-numpy quasi-static physics for the Language-Table board.

Replaces the reference's PyBullet simulation (`language_table.py:599-646`,
xArm IK + 24x stepSimulation per control step) with a deterministic 2-D
quasi-static contact model: the cylindrical effector sweeps toward its target
and pushes disc-approximated blocks out of its way; block-block overlap is
relaxed iteratively. Blocks on a felt table have negligible momentum at
10 Hz control, so quasi-static pushing is a good model of the real dynamics.

No arm kinematics are simulated: the effector is position-controlled directly
(the reference's IK + position control converges to the target within one
control step anyway). This keeps the backend dependency-free and fast enough
to run thousands of eval episodes on host CPU while the TPU runs the policy.
"""

import numpy as np

from rt1_tpu.envs import constants

# Object footprints (meters). The real blocks are ~4cm across, the effector
# cylinder ~2.5cm diameter.
EFFECTOR_RADIUS = 0.0125
BLOCK_RADIUS = 0.02

# Where off-board blocks are parked (reference casts them to (5, 5),
# `language_table.py:883-888`).
FAR_AWAY = np.array([5.0, 5.0])

_RELAX_ITERS = 4


class KinematicBackend:
    """Quasi-static 2-D board physics."""

    name = "kinematic"

    def __init__(self, block_names=None):
        if block_names is None:
            from rt1_tpu.envs import blocks as blocks_module

            block_names = list(blocks_module.ALL_BLOCKS)
        self._block_names = list(block_names)
        n = len(self._block_names)
        self._index = {b: i for i, b in enumerate(self._block_names)}
        self._block_xy = np.tile(FAR_AWAY, (n, 1))
        self._block_yaw = np.zeros(n)
        self._effector_xy = np.array(
            [constants.CENTER_X, constants.CENTER_Y], dtype=np.float64
        )
        self._effector_target_xy = self._effector_xy.copy()

    # -- poses ----------------------------------------------------------

    @property
    def block_names(self):
        return list(self._block_names)

    def block_pose(self, name):
        i = self._index[name]
        return self._block_xy[i].copy(), float(self._block_yaw[i])

    def set_block_pose(self, name, xy, yaw=0.0):
        i = self._index[name]
        self._block_xy[i] = np.asarray(xy, dtype=np.float64)
        self._block_yaw[i] = float(yaw)

    def park_block(self, name):
        self.set_block_pose(name, FAR_AWAY, 0.0)

    def effector_xy(self):
        return self._effector_xy.copy()

    def effector_target_xy(self):
        return self._effector_target_xy.copy()

    def teleport_effector(self, xy):
        self._effector_xy = np.asarray(xy, dtype=np.float64).copy()
        self._effector_target_xy = self._effector_xy.copy()

    def set_effector_target(self, xy):
        self._effector_target_xy = np.asarray(xy, dtype=np.float64).copy()

    # -- stepping -------------------------------------------------------

    def step(self, n_substeps=24):
        """Advance one control period: sweep effector to target, push blocks."""
        start = self._effector_xy
        end = self._effector_target_xy
        for k in range(1, n_substeps + 1):
            self._effector_xy = start + (end - start) * (k / n_substeps)
            self._resolve_contacts()
        # Eliminate residual drift so repeated zero-actions are stable.
        self._effector_xy = end.copy()
        self._resolve_contacts()

    def stabilize(self, nsteps=100):
        """Quasi-static model has no residual dynamics; just settle contacts."""
        del nsteps
        self._resolve_contacts()

    def _resolve_contacts(self):
        xy = self._block_xy
        # Effector -> block pushout.
        delta = xy - self._effector_xy
        dist = np.linalg.norm(delta, axis=1)
        min_sep = EFFECTOR_RADIUS + BLOCK_RADIUS
        hit = dist < min_sep
        if hit.any():
            # Push along the contact normal to exactly touching; blocks
            # sitting exactly on the effector center get a fixed normal.
            normal = np.where(
                dist[:, None] > 1e-9, delta / np.maximum(dist, 1e-9)[:, None],
                np.array([1.0, 0.0]),
            )
            xy[hit] = self._effector_xy + normal[hit] * min_sep
            # Pushed blocks rotate slightly toward the push direction,
            # approximating the frictional spin of a real shove.
            spin = np.arctan2(normal[hit][:, 1], normal[hit][:, 0])
            self._block_yaw[hit] += 0.02 * np.sin(
                spin - self._block_yaw[hit]
            )
        # Block <-> block overlap relaxation.
        for _ in range(_RELAX_ITERS):
            moved = False
            for i in range(len(xy)):
                d = xy - xy[i]
                dd = np.linalg.norm(d, axis=1)
                close = (dd < 2 * BLOCK_RADIUS) & (dd > 0)
                for j in np.flatnonzero(close):
                    n = d[j] / max(dd[j], 1e-9)
                    push = (2 * BLOCK_RADIUS - dd[j]) / 2
                    xy[i] -= n * push
                    xy[j] += n * push
                    moved = True
            if not moved:
                break

    # -- state save/restore --------------------------------------------

    def get_state(self):
        """Deep-copied snapshot; `set_state` restores it bit-for-bit."""
        return {
            "block_xy": self._block_xy.copy(),
            "block_yaw": self._block_yaw.copy(),
            "effector_xy": self._effector_xy.copy(),
            "effector_target_xy": self._effector_target_xy.copy(),
        }

    def set_state(self, state):
        self._block_xy = np.array(state["block_xy"], dtype=np.float64)
        self._block_yaw = np.array(state["block_yaw"], dtype=np.float64)
        self._effector_xy = np.array(state["effector_xy"], dtype=np.float64)
        self._effector_target_xy = np.array(
            state["effector_target_xy"], dtype=np.float64
        )
