"""Pure-numpy quasi-static physics for the Language-Table board.

Replaces the reference's PyBullet simulation (`language_table.py:599-646`,
xArm IK + 24x stepSimulation per control step) with a deterministic 2-D
quasi-static contact model: the cylindrical effector sweeps toward its target
and pushes disc-approximated blocks out of its way; block-block overlap is
relaxed iteratively. Blocks on a felt table have negligible momentum at
10 Hz control, so quasi-static pushing is a good model of the real dynamics.

No arm kinematics are simulated: the effector is position-controlled directly
(the reference's IK + position control converges to the target within one
control step anyway). This keeps the backend dependency-free and fast enough
to run thousands of eval episodes on host CPU while the TPU runs the policy.
"""

import numpy as np

from rt1_tpu.envs import constants

# Object footprints (meters). The real blocks are ~4cm across, the effector
# cylinder ~2.5cm diameter.
EFFECTOR_RADIUS = 0.0125
BLOCK_RADIUS = 0.02

# Where off-board blocks are parked (reference casts them to (5, 5),
# `language_table.py:883-888`).
FAR_AWAY = np.array([5.0, 5.0])

_RELAX_ITERS = 4


class KinematicBackend:
    """Quasi-static 2-D board physics.

    `arm="kinematic"` puts the xArm6 kinematic chain in the loop (the role
    PyBullet's URDF arm plays in the reference, `language_table.py:599-646` +
    `utils/xarm_sim_robot.py:154-187`): each control step solves
    damped-least-squares IK for the target effector pose and sweeps the
    effector along the joint-space interpolation's FK trace, so motion
    follows arm-feasible arcs instead of straight board-frame lines.
    """

    name = "kinematic"

    def __init__(self, block_names=None, arm="none"):
        if block_names is None:
            from rt1_tpu.envs import blocks as blocks_module

            block_names = list(blocks_module.ALL_BLOCKS)
        self._block_names = list(block_names)
        n = len(self._block_names)
        self._index = {b: i for i, b in enumerate(self._block_names)}
        self._block_xy = np.tile(FAR_AWAY, (n, 1))
        self._block_yaw = np.zeros(n)
        self._effector_xy = np.array(
            [constants.CENTER_X, constants.CENTER_Y], dtype=np.float64
        )
        self._effector_target_xy = self._effector_xy.copy()

        if arm not in ("none", "kinematic"):
            raise ValueError(f"arm must be 'none'|'kinematic', got {arm!r}")
        self._arm = None
        self._arm_joints = None
        if arm == "kinematic":
            from rt1_tpu.envs.utils.xarm import (
                HOME_JOINT_POSITIONS,
                XArmKinematics,
            )

            self._arm = XArmKinematics()
            self._arm_joints = np.array(HOME_JOINT_POSITIONS, np.float64)
            self._sync_arm_to_effector()

    # -- arm-in-the-loop ------------------------------------------------

    def _effector_pose(self, xy):
        """Board-frame effector pose for IK: tool at the pushing height,
        flange pointing down (reference cylinder orientation)."""
        from scipy.spatial import transform

        from rt1_tpu.envs.utils.pose3d import Pose3d

        return Pose3d(
            rotation=transform.Rotation.from_euler("xyz", [np.pi, 0.0, 0.0]),
            translation=np.array(
                [xy[0], xy[1], constants.EFFECTOR_HEIGHT]
            ),
        )

    def _sync_arm_to_effector(self):
        q = self._arm.inverse(
            self._effector_pose(self._effector_xy),
            initial_joints=self._arm_joints,
        )
        if q is not None:
            self._arm_joints = q

    def arm_joints(self):
        """Current joint configuration (None when the arm is disabled)."""
        return None if self._arm_joints is None else self._arm_joints.copy()

    # -- poses ----------------------------------------------------------

    @property
    def block_names(self):
        return list(self._block_names)

    def block_pose(self, name):
        i = self._index[name]
        return self._block_xy[i].copy(), float(self._block_yaw[i])

    def set_block_pose(self, name, xy, yaw=0.0):
        i = self._index[name]
        self._block_xy[i] = np.asarray(xy, dtype=np.float64)
        self._block_yaw[i] = float(yaw)

    def park_block(self, name):
        self.set_block_pose(name, FAR_AWAY, 0.0)

    def effector_xy(self):
        return self._effector_xy.copy()

    def effector_target_xy(self):
        return self._effector_target_xy.copy()

    def teleport_effector(self, xy):
        self._effector_xy = np.asarray(xy, dtype=np.float64).copy()
        self._effector_target_xy = self._effector_xy.copy()
        if self._arm is not None:
            self._sync_arm_to_effector()

    def set_effector_target(self, xy):
        self._effector_target_xy = np.asarray(xy, dtype=np.float64).copy()

    # -- stepping -------------------------------------------------------

    def step(self, n_substeps=24):
        """Advance one control period: sweep effector to target, push blocks."""
        start = self._effector_xy
        end = self._effector_target_xy
        sweep = None
        if self._arm is not None:
            sweep = self._arm_sweep(end, n_substeps)
        for k in range(1, n_substeps + 1):
            if sweep is not None:
                self._effector_xy = sweep[k - 1]
            else:
                self._effector_xy = start + (end - start) * (k / n_substeps)
            self._resolve_contacts()
        # Eliminate residual drift so repeated zero-actions are stable.
        self._effector_xy = end.copy()
        self._resolve_contacts()
        # A successful sweep already left _arm_joints at IK(end); only the
        # (rare, out-of-workspace) straight-line fallback needs a re-sync.
        if self._arm is not None and sweep is None:
            self._sync_arm_to_effector()

    def _arm_sweep(self, target_xy, n_substeps):
        """FK trace of the joint-space interpolation toward IK(target).

        Falls back to None (straight-line sweep) when the target is outside
        the arm's reachable workspace — mirroring the reference, where an
        unreachable IK target leaves the arm at its best-effort pose.
        """
        q_target = self._arm.inverse(
            self._effector_pose(target_xy), initial_joints=self._arm_joints
        )
        if q_target is None:
            return None
        q0 = self._arm_joints
        trace = []
        for k in range(1, n_substeps + 1):
            q = q0 + (q_target - q0) * (k / n_substeps)
            trace.append(self._arm.forward(q).translation[:2])
        self._arm_joints = q_target
        return trace

    def stabilize(self, nsteps=100):
        """Quasi-static model has no residual dynamics; just settle contacts."""
        del nsteps
        self._resolve_contacts()

    def _resolve_contacts(self):
        """Quasi-static contact resolution, in scalar float math.

        Runs 25x per control step on a board of at most a few blocks, so
        (as with the RRT* collision checks) per-call numpy overhead on
        tiny arrays dominated the env-step profile; plain float arithmetic
        is ~20x faster here and arithmetically IDENTICAL — including the
        deliberate quirk that block<->block pair distances are computed
        once per `i` iteration and NOT refreshed after a push within it
        (the bit-exact snapshot tests in tests/test_backends.py pin this).
        """
        import math

        xy = self._block_xy
        yaw = self._block_yaw
        ex, ey = float(self._effector_xy[0]), float(self._effector_xy[1])
        min_sep = EFFECTOR_RADIUS + BLOCK_RADIUS
        n_blocks = len(xy)
        # Effector -> block pushout: along the contact normal to exactly
        # touching; a block sitting exactly on the effector center gets a
        # fixed normal. Pushed blocks rotate slightly toward the push
        # direction, approximating the frictional spin of a real shove.
        for i in range(n_blocks):
            dx = float(xy[i, 0]) - ex
            dy = float(xy[i, 1]) - ey
            dist = math.sqrt(dx * dx + dy * dy)
            if dist < min_sep:
                if dist > 1e-9:
                    nx, ny = dx / dist, dy / dist
                else:
                    nx, ny = 1.0, 0.0
                xy[i, 0] = ex + nx * min_sep
                xy[i, 1] = ey + ny * min_sep
                spin = math.atan2(ny, nx)
                yaw[i] += 0.02 * math.sin(spin - float(yaw[i]))
        # Block <-> block overlap relaxation.
        two_r = 2 * BLOCK_RADIUS
        for _ in range(_RELAX_ITERS):
            moved = False
            for i in range(n_blocks):
                # Pair geometry snapshotted at i-loop entry (see docstring).
                xi, yi = float(xy[i, 0]), float(xy[i, 1])
                pair = [
                    (float(xy[j, 0]) - xi, float(xy[j, 1]) - yi)
                    for j in range(n_blocks)
                ]
                for j in range(n_blocks):
                    dx, dy = pair[j]
                    dd = math.sqrt(dx * dx + dy * dy)
                    if dd < two_r and dd > 0:
                        denom = dd if dd > 1e-9 else 1e-9
                        nx, ny = dx / denom, dy / denom
                        push = (two_r - dd) / 2
                        xy[i, 0] -= nx * push
                        xy[i, 1] -= ny * push
                        xy[j, 0] += nx * push
                        xy[j, 1] += ny * push
                        moved = True
            if not moved:
                break

    # -- state save/restore --------------------------------------------

    def get_state(self):
        """Deep-copied snapshot; `set_state` restores it bit-for-bit."""
        state = {
            "block_xy": self._block_xy.copy(),
            "block_yaw": self._block_yaw.copy(),
            "effector_xy": self._effector_xy.copy(),
            "effector_target_xy": self._effector_target_xy.copy(),
        }
        if self._arm_joints is not None:
            state["arm_joints"] = self._arm_joints.copy()
        return state

    def set_state(self, state):
        self._block_xy = np.array(state["block_xy"], dtype=np.float64)
        self._block_yaw = np.array(state["block_yaw"], dtype=np.float64)
        self._effector_xy = np.array(state["effector_xy"], dtype=np.float64)
        self._effector_target_xy = np.array(
            state["effector_target_xy"], dtype=np.float64
        )
        if self._arm is not None:
            if "arm_joints" in state:
                self._arm_joints = np.array(state["arm_joints"], np.float64)
            else:
                # Snapshot from an arm-less backend (cross-backend restore):
                # re-derive joints from the restored effector pose so the
                # next sweep doesn't interpolate from a stale configuration.
                self._sync_arm_to_effector()
