"""Pure-numpy quasi-static physics for the Language-Table board.

Replaces the reference's PyBullet simulation (`language_table.py:599-646`,
xArm IK + 24x stepSimulation per control step) with a deterministic 2-D
quasi-static contact model: the cylindrical effector sweeps toward its target
and pushes disc-approximated blocks out of its way; block-block overlap is
relaxed iteratively. Blocks on a felt table have negligible momentum at
10 Hz control, so quasi-static pushing is a good model of the real dynamics.

No arm kinematics are simulated: the effector is position-controlled directly
(the reference's IK + position control converges to the target within one
control step anyway). This keeps the backend dependency-free and fast enough
to run thousands of eval episodes on host CPU while the TPU runs the policy.
"""

import numpy as np

from rt1_tpu.envs import constants

# Object footprints (meters). The real blocks are ~4cm across, the effector
# cylinder ~2.5cm diameter.
EFFECTOR_RADIUS = 0.0125
BLOCK_RADIUS = 0.02

# Where off-board blocks are parked (reference casts them to (5, 5),
# `language_table.py:883-888`).
FAR_AWAY = np.array([5.0, 5.0])

_RELAX_ITERS = 4


class KinematicBackend:
    """Quasi-static 2-D board physics.

    `arm="kinematic"` puts the xArm6 kinematic chain in the loop (the role
    PyBullet's URDF arm plays in the reference, `language_table.py:599-646` +
    `utils/xarm_sim_robot.py:154-187`): each control step solves
    damped-least-squares IK for the target effector pose and sweeps the
    effector along the joint-space interpolation's FK trace, so motion
    follows arm-feasible arcs instead of straight board-frame lines.
    """

    name = "kinematic"

    def __init__(self, block_names=None, arm="none"):
        if block_names is None:
            from rt1_tpu.envs import blocks as blocks_module

            block_names = list(blocks_module.ALL_BLOCKS)
        self._block_names = list(block_names)
        n = len(self._block_names)
        self._index = {b: i for i, b in enumerate(self._block_names)}
        self._block_xy = np.tile(FAR_AWAY, (n, 1))
        self._block_yaw = np.zeros(n)
        self._effector_xy = np.array(
            [constants.CENTER_X, constants.CENTER_Y], dtype=np.float64
        )
        self._effector_target_xy = self._effector_xy.copy()

        if arm not in ("none", "kinematic"):
            raise ValueError(f"arm must be 'none'|'kinematic', got {arm!r}")
        self._arm = None
        self._arm_joints = None
        if arm == "kinematic":
            from rt1_tpu.envs.utils.xarm import (
                HOME_JOINT_POSITIONS,
                XArmKinematics,
            )

            self._arm = XArmKinematics()
            self._arm_joints = np.array(HOME_JOINT_POSITIONS, np.float64)
            self._sync_arm_to_effector()

    # -- arm-in-the-loop ------------------------------------------------

    def _effector_pose(self, xy):
        """Board-frame effector pose for IK: tool at the pushing height,
        flange pointing down (reference cylinder orientation)."""
        from scipy.spatial import transform

        from rt1_tpu.envs.utils.pose3d import Pose3d

        return Pose3d(
            rotation=transform.Rotation.from_euler("xyz", [np.pi, 0.0, 0.0]),
            translation=np.array(
                [xy[0], xy[1], constants.EFFECTOR_HEIGHT]
            ),
        )

    def _sync_arm_to_effector(self):
        q = self._arm.inverse(
            self._effector_pose(self._effector_xy),
            initial_joints=self._arm_joints,
        )
        if q is not None:
            self._arm_joints = q

    def arm_joints(self):
        """Current joint configuration (None when the arm is disabled)."""
        return None if self._arm_joints is None else self._arm_joints.copy()

    # -- poses ----------------------------------------------------------

    @property
    def block_names(self):
        return list(self._block_names)

    def block_pose(self, name):
        i = self._index[name]
        return self._block_xy[i].copy(), float(self._block_yaw[i])

    def set_block_pose(self, name, xy, yaw=0.0):
        i = self._index[name]
        self._block_xy[i] = np.asarray(xy, dtype=np.float64)
        self._block_yaw[i] = float(yaw)

    def park_block(self, name):
        self.set_block_pose(name, FAR_AWAY, 0.0)

    def effector_xy(self):
        return self._effector_xy.copy()

    def effector_target_xy(self):
        return self._effector_target_xy.copy()

    def teleport_effector(self, xy):
        self._effector_xy = np.asarray(xy, dtype=np.float64).copy()
        self._effector_target_xy = self._effector_xy.copy()
        if self._arm is not None:
            self._sync_arm_to_effector()

    def set_effector_target(self, xy):
        self._effector_target_xy = np.asarray(xy, dtype=np.float64).copy()

    # -- stepping -------------------------------------------------------

    def step(self, n_substeps=24):
        """Advance one control period: sweep effector to target, push blocks."""
        start = self._effector_xy
        end = self._effector_target_xy
        sweep = None
        if self._arm is not None:
            sweep = self._arm_sweep(end, n_substeps)
        for k in range(1, n_substeps + 1):
            if sweep is not None:
                self._effector_xy = sweep[k - 1]
            else:
                self._effector_xy = start + (end - start) * (k / n_substeps)
            self._resolve_contacts()
        # Eliminate residual drift so repeated zero-actions are stable.
        self._effector_xy = end.copy()
        self._resolve_contacts()
        # A successful sweep already left _arm_joints at IK(end); only the
        # (rare, out-of-workspace) straight-line fallback needs a re-sync.
        if self._arm is not None and sweep is None:
            self._sync_arm_to_effector()

    def _arm_sweep(self, target_xy, n_substeps):
        """FK trace of the joint-space interpolation toward IK(target).

        Falls back to None (straight-line sweep) when the target is outside
        the arm's reachable workspace — mirroring the reference, where an
        unreachable IK target leaves the arm at its best-effort pose.
        """
        q_target = self._arm.inverse(
            self._effector_pose(target_xy), initial_joints=self._arm_joints
        )
        if q_target is None:
            return None
        q0 = self._arm_joints
        trace = []
        for k in range(1, n_substeps + 1):
            q = q0 + (q_target - q0) * (k / n_substeps)
            trace.append(self._arm.forward(q).translation[:2])
        self._arm_joints = q_target
        return trace

    def stabilize(self, nsteps=100):
        """Quasi-static model has no residual dynamics; just settle contacts."""
        del nsteps
        self._resolve_contacts()

    def _resolve_contacts(self):
        xy = self._block_xy
        # Effector -> block pushout.
        delta = xy - self._effector_xy
        dist = np.linalg.norm(delta, axis=1)
        min_sep = EFFECTOR_RADIUS + BLOCK_RADIUS
        hit = dist < min_sep
        if hit.any():
            # Push along the contact normal to exactly touching; blocks
            # sitting exactly on the effector center get a fixed normal.
            normal = np.where(
                dist[:, None] > 1e-9, delta / np.maximum(dist, 1e-9)[:, None],
                np.array([1.0, 0.0]),
            )
            xy[hit] = self._effector_xy + normal[hit] * min_sep
            # Pushed blocks rotate slightly toward the push direction,
            # approximating the frictional spin of a real shove.
            spin = np.arctan2(normal[hit][:, 1], normal[hit][:, 0])
            self._block_yaw[hit] += 0.02 * np.sin(
                spin - self._block_yaw[hit]
            )
        # Block <-> block overlap relaxation.
        for _ in range(_RELAX_ITERS):
            moved = False
            for i in range(len(xy)):
                d = xy - xy[i]
                dd = np.linalg.norm(d, axis=1)
                close = (dd < 2 * BLOCK_RADIUS) & (dd > 0)
                for j in np.flatnonzero(close):
                    n = d[j] / max(dd[j], 1e-9)
                    push = (2 * BLOCK_RADIUS - dd[j]) / 2
                    xy[i] -= n * push
                    xy[j] += n * push
                    moved = True
            if not moved:
                break

    # -- state save/restore --------------------------------------------

    def get_state(self):
        """Deep-copied snapshot; `set_state` restores it bit-for-bit."""
        state = {
            "block_xy": self._block_xy.copy(),
            "block_yaw": self._block_yaw.copy(),
            "effector_xy": self._effector_xy.copy(),
            "effector_target_xy": self._effector_target_xy.copy(),
        }
        if self._arm_joints is not None:
            state["arm_joints"] = self._arm_joints.copy()
        return state

    def set_state(self, state):
        self._block_xy = np.array(state["block_xy"], dtype=np.float64)
        self._block_yaw = np.array(state["block_yaw"], dtype=np.float64)
        self._effector_xy = np.array(state["effector_xy"], dtype=np.float64)
        self._effector_target_xy = np.array(
            state["effector_target_xy"], dtype=np.float64
        )
        if self._arm is not None:
            if "arm_joints" in state:
                self._arm_joints = np.array(state["arm_joints"], np.float64)
            else:
                # Snapshot from an arm-less backend (cross-backend restore):
                # re-derive joints from the restored effector pose so the
                # next sweep doesn't interpolate from a stale configuration.
                self._sync_arm_to_effector()
