"""Pluggable physics backends for the Language-Table board.

The reference runs exclusively on PyBullet (`language_table.py:41-42`); we
abstract the physics behind a small backend contract (pose get/set,
deterministic stepping, bit-exact state snapshots — see
tests/test_backends.py) so the env runs hermetically on pure numpy.

**PyBullet backend: retired (round 3).** pybullet is not installable in
this image and its URDF assets are not bundled, so a PyBullet backend could
never execute here — an unverifiable backend is risk masquerading as
coverage (it was the test suite's only skips). The decision and the
re-introduction path (the backend contract any new physics engine must
satisfy) are recorded in docs/physics.md. `make_backend("auto")` is kept as
an alias for the default kinematic backend so reference-style call sites
keep working.
"""

from rt1_tpu.envs.backends.kinematic import KinematicBackend


def make_backend(name="auto", **kwargs):
    if name in ("kinematic", "auto"):
        return KinematicBackend(**kwargs)
    if name == "kinematic_arm":
        # xArm6 FK/IK in the control loop (reference arm-physics parity).
        return KinematicBackend(arm="kinematic", **kwargs)
    if name == "pybullet":
        raise ValueError(
            "The PyBullet backend was retired in round 3 (pybullet is not "
            "installable in this image; see docs/physics.md). Use "
            "backend='kinematic' or 'kinematic_arm'."
        )
    raise ValueError(f"Unknown physics backend: {name}")


__all__ = ["KinematicBackend", "make_backend"]
