"""Pluggable physics backends for the Language-Table board.

The reference runs exclusively on PyBullet (`language_table.py:41-42`); we
abstract the physics so the env also runs hermetically (pure numpy) where
PyBullet isn't installed. `make_backend("auto")` prefers PyBullet when
importable, else the kinematic backend.
"""

from rt1_tpu.envs.backends.kinematic import KinematicBackend


def make_backend(name="auto", **kwargs):
    if name == "kinematic":
        return KinematicBackend(**kwargs)
    if name == "kinematic_arm":
        # xArm6 FK/IK in the control loop (reference arm-physics parity).
        return KinematicBackend(arm="kinematic", **kwargs)
    if name in ("auto", "pybullet"):
        try:
            from rt1_tpu.envs.backends.pybullet_backend import PyBulletBackend

            return PyBulletBackend(**kwargs)
        except ImportError:
            if name == "pybullet":
                raise
            return KinematicBackend(**kwargs)
    raise ValueError(f"Unknown physics backend: {name}")


__all__ = ["KinematicBackend", "make_backend"]
