"""PyBullet physics backend (optional).

Exposes the same interface as `KinematicBackend` on top of a PyBullet DIRECT
session, mirroring the reference's simulation setup (`language_table.py:
546-736`: plane + workspace + xArm + cylinder effector + block URDFs,
240 Hz fixed timestep). Requires `pybullet` plus the Language-Table URDF
assets; both are absent from this image, so this module is import-gated and
the env defaults to the kinematic backend.
"""

import numpy as np

try:
    import pybullet
    import pybullet_utils.bullet_client as bullet_client
except ImportError as e:  # pragma: no cover - exercised only with pybullet
    raise ImportError(
        "PyBulletBackend requires the 'pybullet' package, which is not "
        "installed. Use backend='kinematic' (default) instead."
    ) from e

from rt1_tpu.envs import constants


class PyBulletBackend:  # pragma: no cover - requires pybullet + assets
    """Full-physics backend over PyBullet DIRECT."""

    name = "pybullet"

    def __init__(self, block_names=None, asset_root=None, shared_memory=False):
        if asset_root is None:
            raise ValueError(
                "PyBulletBackend needs asset_root pointing at the "
                "Language-Table URDF assets (blocks/, workspace, arm)."
            )
        from rt1_tpu.envs import blocks as blocks_module

        self._block_names = list(block_names or blocks_module.ALL_BLOCKS)
        self._asset_root = asset_root
        mode = (
            pybullet.SHARED_MEMORY if shared_memory else pybullet.DIRECT
        )
        self._client = bullet_client.BulletClient(mode)
        self._client.setGravity(0, 0, -9.8)
        self._client.setPhysicsEngineParameter(enableFileCaching=0)
        self._block_ids = {}
        for name in self._block_names:
            self._block_ids[name] = self._client.loadURDF(
                f"{asset_root}/blocks/{name}.urdf"
            )
        self._effector_xy = np.array(
            [constants.CENTER_X, constants.CENTER_Y]
        )
        self._effector_target_xy = self._effector_xy.copy()
        # Kinematic effector cylinder (no arm URDF needed): a zero-mass body
        # teleported along the sweep each substep; pybullet's contact
        # resolution shoves blocks out of penetration, approximating the
        # reference's position-controlled cylinder end effector.
        col = self._client.createCollisionShape(
            pybullet.GEOM_CYLINDER, radius=0.0125, height=0.08
        )
        self._effector_id = self._client.createMultiBody(
            baseMass=0,
            baseCollisionShapeIndex=col,
            basePosition=[self._effector_xy[0], self._effector_xy[1], 0.04],
        )

    @property
    def block_names(self):
        return list(self._block_names)

    def block_pose(self, name):
        pos, quat = self._client.getBasePositionAndOrientation(
            self._block_ids[name]
        )
        yaw = self._client.getEulerFromQuaternion(quat)[-1]
        return np.array(pos[:2]), float(yaw)

    def set_block_pose(self, name, xy, yaw=0.0):
        quat = self._client.getQuaternionFromEuler([np.pi / 2, 0, yaw])
        self._client.resetBasePositionAndOrientation(
            self._block_ids[name], [xy[0], xy[1], 0.0], quat
        )

    def park_block(self, name):
        self.set_block_pose(name, (5.0, 5.0), 0.0)

    def effector_xy(self):
        return self._effector_xy.copy()

    def effector_target_xy(self):
        return self._effector_target_xy.copy()

    def teleport_effector(self, xy):
        self._effector_xy = np.asarray(xy, dtype=np.float64).copy()
        self._effector_target_xy = self._effector_xy.copy()
        self._place_effector(self._effector_xy)

    def set_effector_target(self, xy):
        self._effector_target_xy = np.asarray(xy, dtype=np.float64).copy()

    def _place_effector(self, xy):
        self._client.resetBasePositionAndOrientation(
            self._effector_id, [xy[0], xy[1], 0.04], [0, 0, 0, 1]
        )

    def step(self, n_substeps=24):
        start = self._effector_xy
        end = self._effector_target_xy
        for k in range(1, n_substeps + 1):
            self._place_effector(start + (end - start) * (k / n_substeps))
            self._client.stepSimulation()
        self._effector_xy = self._effector_target_xy.copy()

    def stabilize(self, nsteps=100):
        for _ in range(nsteps):
            self._client.stepSimulation()

    def get_state(self):
        """Same stacked-array schema as KinematicBackend.get_state, so
        callers can switch backends without translating snapshots."""
        poses = [self.block_pose(name) for name in self._block_names]
        return {
            "block_xy": np.stack([xy for xy, _ in poses]),
            "block_yaw": np.array([yaw for _, yaw in poses]),
            "effector_xy": self._effector_xy.copy(),
            "effector_target_xy": self._effector_target_xy.copy(),
        }

    def set_state(self, state):
        for i, name in enumerate(self._block_names):
            self.set_block_pose(
                name, state["block_xy"][i], float(state["block_yaw"][i])
            )
        self._effector_xy = np.array(state["effector_xy"])
        self._effector_target_xy = np.array(state["effector_target_xy"])
        self._place_effector(self._effector_xy)
