"""Board geometry and observation constants for the Language-Table env.

Parity source: reference `language_table/environments/constants.py:25-65`.
These numbers define the physical workspace, camera, and observation shapes;
they are data, so they must match the reference exactly for train/eval parity.
"""

import math

import numpy as np

# Workspace bounds in robot/base frame (meters). X grows away from the arm
# base ("top" of the image is small x), Y spans left/right.
X_MIN = 0.15
X_MAX = 0.6
Y_MIN = -0.3048
Y_MAX = 0.3048
CENTER_X = (X_MAX - X_MIN) / 2.0 + X_MIN
CENTER_Y = (Y_MAX - Y_MIN) / 2.0 + Y_MIN
WORKSPACE_BOUNDS = np.array(((X_MIN, Y_MIN), (X_MAX, Y_MAX)))
WORKSPACE_BOUNDS_BUFFER = 0.08

# Height at which the cylindrical effector rides above the board, and its
# "pointing down" orientation as a rotation vector.
EFFECTOR_HEIGHT = 0.145
EFFECTOR_DOWN_ROTVEC = (0.0, math.pi, 0.0)

# Rejection-sampling thresholds for initial pose generation.
BLOCK_DISTANCE_THRESHOLD = 0.0175
ARM_DISTANCE_THRESHOLD = 0.06

# Max number of characters in the byte-encoded instruction observation.
INSTRUCTION_LENGTH = 512

# Rendered observation size (RealSense D415-like camera).
IMAGE_WIDTH = 320
IMAGE_HEIGHT = 180
CAMERA_POSE = (0.75, 0.0, 0.5)
CAMERA_ORIENTATION = (np.pi / 5, np.pi, -np.pi / 2)
CAMERA_INTRINSICS = (
    0.803 * IMAGE_WIDTH,  # fx
    0,
    IMAGE_WIDTH / 2.0,  # cx
    0,
    0.803 * IMAGE_WIDTH,  # fy
    IMAGE_HEIGHT / 2.0,  # cy
    0,
    0,
    1,
)

# Sparse-reward radius shared by the block-to-block style tasks
# (reference `rewards/constants.py:17`).
TARGET_BLOCK_DISTANCE = 0.05
