"""Block-set definitions for the Language-Table board.

Parity source: reference `language_table/environments/blocks.py:24-160`.
The N_CHOOSE_K train/test split must reproduce the reference's seeded shuffle
(`blocks.py:120-129`) bit-for-bit so dataset/eval splits line up.
"""

import collections
import enum
import itertools

import numpy as np


class BlockMode(enum.Enum):
    """Which set of blocks is on the table."""

    BLOCK_1 = "BLOCK_1"  # single green star (debug)
    BLOCK_4 = "BLOCK_4"  # the original 4-block board
    BLOCK_8 = "BLOCK_8"  # 2 of each color / 2 of each shape
    BLOCK_4_WPOLE = "BLOCK_4_WPOLE"  # 4 blocks + purple goal pole
    BLOCK_8_WPOLE = "BLOCK_8_WPOLE"  # 8 blocks + purple goal pole
    N_CHOOSE_K = "N_CHOOSE_K"  # combinatorial 4..10 of the 16 blocks


BLOCK_MODES = [m.value for m in BlockMode]

COLORS = ("red", "blue", "green", "yellow")
SHAPES = ("moon", "cube", "star", "pentagon")
ALL_BLOCKS = ["_".join(p) for p in itertools.product(COLORS, SHAPES)]

FIXED_1 = ["green_star"]
FIXED_4 = ("red_moon", "blue_cube", "green_star", "yellow_pentagon")
FIXED_8 = (
    "red_moon",
    "red_pentagon",
    "blue_moon",
    "blue_cube",
    "green_cube",
    "green_star",
    "yellow_star",
    "yellow_pentagon",
)
POLE = "purple_pole"
FIXED_4_WPOLE = FIXED_4 + (POLE,)
FIXED_8_WPOLE = FIXED_8 + (POLE,)


def _n_choose_k_combinations():
    """All 4..10-of-16 block subsets, seeded-shuffled then split 90/10.

    Mirrors the reference's module-level construction
    (`blocks.py:118-129`): numpy RandomState(0) in-place shuffle of the
    full combination list, first 90% train.
    """
    combos = []
    for k in range(4, 11):
        combos.extend(itertools.combinations(ALL_BLOCKS, k))
    rng = np.random.RandomState(seed=0)
    rng.shuffle(combos)
    split = int(len(combos) * 0.9)
    return combos[:split], combos[split:]


TRAIN_COMBINATIONS, TEST_COMBINATIONS = _n_choose_k_combinations()


def block_set(mode):
    """The unique block universe for a mode (used for obs-space keys)."""
    mode = BlockMode(mode)
    if mode == BlockMode.BLOCK_1:
        return FIXED_1
    if mode == BlockMode.BLOCK_4:
        return FIXED_4
    if mode == BlockMode.BLOCK_8:
        return FIXED_8
    if mode == BlockMode.N_CHOOSE_K:
        return ALL_BLOCKS
    if mode == BlockMode.BLOCK_4_WPOLE:
        return FIXED_4_WPOLE
    if mode == BlockMode.BLOCK_8_WPOLE:
        return FIXED_8_WPOLE
    raise ValueError(f"Unsupported block mode: {mode}")


def block_subsets(mode, training):
    """All block subsets the env may sample a board from."""
    mode = BlockMode(mode)
    if mode == BlockMode.N_CHOOSE_K:
        return TRAIN_COMBINATIONS if training else TEST_COMBINATIONS
    return [block_set(mode)]


def text_descriptions(mode):
    """Human-readable names, e.g. 'red_moon' -> 'red moon'."""
    return [b.replace("_", " ") for b in block_set(mode)]


def block_pairs(mode):
    """All ordered pairs of distinct blocks (for instruction enumeration)."""
    return itertools.permutations(block_set(mode), 2)


def synonym_groups(mode):
    """Per-block referring-expression variants, unioned over board states.

    `language.block_synonyms` admits a bare color ('red block') or bare
    shape ('star') only when unique on the current board; this returns, per
    block, every variant that is valid on SOME reachable board of `mode`.
    Fixed boards (BLOCK_4/8, ±pole) always show the full set, so a bare
    form is reachable iff the color/shape is unique in the set — which
    includes e.g. the pole on BLOCK_8_WPOLE. N_CHOOSE_K boards are
    subsets, so any bare form can become unique. Order matches
    block_synonyms (color, shape, canonical).
    """
    names = block_set(mode)
    color_counts = collections.Counter(color_shape(b)[0] for b in names)
    shape_counts = collections.Counter(color_shape(b)[1] for b in names)
    any_subset = mode == BlockMode.N_CHOOSE_K
    groups = []
    for b in names:
        color, shape = color_shape(b)
        variants = []
        if any_subset or color_counts[color] == 1:
            variants.append(f"{color} block")
        if any_subset or shape_counts[shape] == 1:
            variants.append(shape)
        variants.append(f"{color} {shape}")
        groups.append(variants)
    return groups


def color_shape(block):
    color, shape = block.split("_")
    return color, shape
