"""6-DOF pose container.

Parity source: reference `language_table/environments/utils/pose3d.py:40-67`
(scipy Rotation + translation, vec7, serialize/deserialize, shallow asdict).
"""

import dataclasses

import numpy as np
from scipy.spatial import transform


@dataclasses.dataclass
class Pose3d:
    """Rotation + translation."""

    rotation: transform.Rotation
    translation: np.ndarray

    @property
    def vec7(self):
        """[x, y, z, qx, qy, qz, qw]."""
        return np.concatenate([self.translation, self.rotation.as_quat()])

    @property
    def matrix(self):
        """4x4 homogeneous transform."""
        m = np.eye(4)
        m[:3, :3] = self.rotation.as_matrix()
        m[:3, 3] = np.asarray(self.translation)
        return m

    def multiply(self, other: "Pose3d") -> "Pose3d":
        return Pose3d.from_matrix(self.matrix @ other.matrix)

    def inverse(self) -> "Pose3d":
        inv_rot = self.rotation.inv()
        return Pose3d(
            rotation=inv_rot,
            translation=-inv_rot.apply(self.translation),
        )

    @staticmethod
    def from_matrix(m: np.ndarray) -> "Pose3d":
        return Pose3d(
            rotation=transform.Rotation.from_matrix(m[:3, :3]),
            translation=np.array(m[:3, 3]),
        )

    def asdict(self):
        # Shallow copy (tf.data chokes on deepcopy'd Rotations,
        # reference pose3d.py:27-37).
        return {
            f.name: getattr(self, f.name)
            for f in dataclasses.fields(self)
        }

    def serialize(self):
        return {
            "rotation": self.rotation.as_quat().tolist(),
            "translation": np.asarray(self.translation).tolist(),
        }

    @staticmethod
    def deserialize(data):
        return Pose3d(
            rotation=transform.Rotation.from_quat(data["rotation"]),
            translation=np.array(data["translation"]),
        )

    def __eq__(self, other):
        return np.array_equal(
            self.rotation.as_quat(), other.rotation.as_quat()
        ) and np.array_equal(self.translation, other.translation)

    def __ne__(self, other):
        return not self.__eq__(other)
