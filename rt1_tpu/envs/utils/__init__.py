"""Environment utilities: 6-DOF poses + xArm kinematics."""

from rt1_tpu.envs.utils.pose3d import Pose3d
from rt1_tpu.envs.utils.xarm import XArmKinematics

__all__ = ["Pose3d", "XArmKinematics"]
