"""UFactory xArm6 kinematics: analytic FK + damped-least-squares IK.

Parity source: reference `language_table/environments/utils/xarm_sim_robot.py:
40-220` — there, FK/IK are delegated to PyBullet's URDF model
(`calculateInverseKinematics`). This module gives the framework arm
kinematics without a physics engine: modified-DH forward kinematics from the
published xArm6 parameter table (UFactory developer manual) and an iterative
damped-least-squares IK with a numeric Jacobian.

Note (documented deviation): joint-space values match the real arm's DH
model; the reference's URDF-derived numbers may differ at the millimeter
level. The contract tested here mirrors the reference test intent
(`utils/xarm_sim_robot_test.py:41-78`): FK determinism and IK∘FK round-trip
to centimeter accuracy.
"""

import dataclasses
from typing import Optional, Sequence

import numpy as np
from scipy.spatial import transform

from rt1_tpu.envs.utils.pose3d import Pose3d

# Modified-DH rows (alpha_{i-1}, a_{i-1}, d_i, theta_offset_i) for xArm6.
_T2_OFFSET = -1.3849179
XARM6_MDH = (
    (0.0, 0.0, 0.267, 0.0),
    (-np.pi / 2, 0.0, 0.0, _T2_OFFSET),
    (0.0, 0.28948866, 0.0, -_T2_OFFSET),
    (-np.pi / 2, 0.0775, 0.3425, 0.0),
    (np.pi / 2, 0.0, 0.0, 0.0),
    (-np.pi / 2, 0.076, 0.097, 0.0),
)

HOME_JOINT_POSITIONS = np.deg2rad([0, -20, -80, 0, 100, -30])

# Per-joint limits (radians), from the xArm6 spec sheet.
JOINT_LIMITS = np.array(
    [
        (-2 * np.pi, 2 * np.pi),
        (-2.059, 2.0944),
        (-3.927, 0.19198),
        (-2 * np.pi, 2 * np.pi),
        (-1.69297, np.pi),
        (-2 * np.pi, 2 * np.pi),
    ]
)


def _mdh_transform(alpha, a, d, theta):
    ca, sa = np.cos(alpha), np.sin(alpha)
    ct, st = np.cos(theta), np.sin(theta)
    return np.array(
        [
            [ct, -st, 0.0, a],
            [st * ca, ct * ca, -sa, -d * sa],
            [st * sa, ct * sa, ca, d * ca],
            [0.0, 0.0, 0.0, 1.0],
        ]
    )


@dataclasses.dataclass
class XArmKinematics:
    """FK/IK over the xArm6 chain (tool frame = flange)."""

    mdh: Sequence = XARM6_MDH
    joint_limits: np.ndarray = dataclasses.field(
        default_factory=lambda: JOINT_LIMITS.copy()
    )

    def forward(self, joints: np.ndarray) -> Pose3d:
        """Joint angles (6,) -> flange pose in the base frame."""
        joints = np.asarray(joints, np.float64)
        m = np.eye(4)
        for (alpha, a, d, offset), q in zip(self.mdh, joints):
            m = m @ _mdh_transform(alpha, a, d, q + offset)
        return Pose3d.from_matrix(m)

    forward_kinematics = forward

    def _pose_error(self, joints, target: Pose3d):
        cur = self.forward(joints)
        pos_err = target.translation - cur.translation
        rot_err = (target.rotation * cur.rotation.inv()).as_rotvec()
        return np.concatenate([pos_err, rot_err])

    def inverse(
        self,
        target: Pose3d,
        initial_joints: Optional[np.ndarray] = None,
        max_iters: int = 200,
        tol: float = 1e-5,
        damping: float = 1e-3,
        step_scale: float = 1.0,
    ) -> Optional[np.ndarray]:
        """Damped-least-squares IK; None when it fails to converge.

        Equivalent role to PyBullet's `calculateInverseKinematics` in the
        reference (`xarm_sim_robot.py:154-187`), which also iterates from
        the current configuration.
        """
        q = np.array(
            initial_joints
            if initial_joints is not None
            else HOME_JOINT_POSITIONS,
            np.float64,
        )
        eps = 1e-6
        for _ in range(max_iters):
            err = self._pose_error(q, target)
            if np.linalg.norm(err) < tol:
                # q is already limit-clipped every iteration; no re-wrapping
                # (joint 3's range extends below -pi, so a naive [-pi, pi)
                # wrap would corrupt valid solutions).
                return q
            # Numeric Jacobian, central differences.
            jac = np.zeros((6, 6))
            for j in range(6):
                dq = np.zeros(6)
                dq[j] = eps
                jac[:, j] = (
                    self._pose_error(q + dq, target)
                    - self._pose_error(q - dq, target)
                ) / (2 * eps)
            # err(q+dq) ≈ err(q) + J dq → solve J dq = -(-err) ... the error
            # decreases along +J⁺·err since err is target-minus-current.
            jtj = jac.T @ jac + damping * np.eye(6)
            dq = np.linalg.solve(jtj, jac.T @ err)
            q = q - step_scale * dq
            q = np.clip(q, self.joint_limits[:, 0], self.joint_limits[:, 1])
        err = self._pose_error(q, target)
        if np.linalg.norm(err) < 1e-3:
            return q
        return None

    inverse_kinematics = inverse
