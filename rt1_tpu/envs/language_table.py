"""The Language-Table gym-style environment.

Parity source: reference `language_table/environments/language_table.py:45-199`
(reset/step/render/succeeded/encode/decode/state save-restore). Physics runs
on a pluggable backend (see `rt1_tpu/envs/backends/`); everything else —
board sampling, task/instruction sampling, observation layout, reward
plumbing — reproduces the reference semantics.

Observation dict (matching `language_table.py:407-416`):
  effector_translation          (2,) float32 actual effector xy
  effector_target_translation   (2,) float32 commanded effector xy
  instruction                   (512,) int32 utf-8 bytes, zero padded
  rgb                           (180, 320, 3) uint8 rendered board

Action: (2,) float32 delta xy in [-0.1, 0.1] per 0.1s control step.
"""

import collections
import copy

import numpy as np

from rt1_tpu.envs import blocks as blocks_module
from rt1_tpu.envs import constants, task_info
from rt1_tpu.envs.backends import make_backend
from rt1_tpu.envs.rendering import add_debug_info_to_image, render_board


class LanguageTable:
    """2-D tabletop block-pushing env driven by natural-language tasks."""

    def __init__(
        self,
        block_mode,
        training=True,
        reward_factory=None,
        control_frequency=10.0,
        seed=None,
        delay_reward_steps=0,
        render_text_in_image=True,
        backend="kinematic",
        backend_kwargs=None,
        step_frequency=240.0,
    ):
        self._block_mode = blocks_module.BlockMode(block_mode)
        self._training = training
        self._rng = np.random.RandomState(seed=seed)
        self._render_text_in_image = render_text_in_image

        self._instruction = self.encode_instruction(None)
        self._instruction_str = None
        self._task_info = None
        self._start_block = blocks_module.block_set(self._block_mode)[0]
        self._oracle_target_block = None
        self._oracle_target_translation = None
        self._target_absolute_location = None
        self._target_relative_location = None

        self._image_size = (constants.IMAGE_HEIGHT, constants.IMAGE_WIDTH)

        if step_frequency % control_frequency != 0:
            raise ValueError(
                "Control frequency must divide the simulation step frequency."
            )
        self._control_frequency = control_frequency
        self._sim_steps_per_step = int(step_frequency / control_frequency)

        backend_kwargs = dict(backend_kwargs or {})
        backend_kwargs.setdefault(
            "block_names", list(blocks_module.block_set(self._block_mode))
        )
        self._backend = make_backend(backend, **backend_kwargs)

        self._reward_calculator = None
        if reward_factory is not None:
            self._reward_calculator = reward_factory(
                goal_reward=100.0,
                rng=self._rng,
                delay_reward_steps=delay_reward_steps,
                block_mode=self._block_mode,
            )

        self._blocks_on_table = list(blocks_module.block_set(self._block_mode))
        self.reset()

    # -- spaces ---------------------------------------------------------

    @property
    def action_space_low(self):
        return np.array([-0.1, -0.1], np.float32)

    @property
    def action_space_high(self):
        return np.array([0.1, 0.1], np.float32)

    def observation_shapes(self):
        return collections.OrderedDict(
            effector_translation=(2,),
            effector_target_translation=(2,),
            instruction=(constants.INSTRUCTION_LENGTH,),
            rgb=(*self._image_size, 3),
        )

    # -- gym API --------------------------------------------------------

    def seed(self, seed=None):
        self._rng = np.random.RandomState(seed=seed)
        if self._reward_calculator is not None:
            self._reward_calculator.seed(self._rng)

    def reset(self, reset_poses=True):
        if reset_poses:
            combos = blocks_module.block_subsets(
                self._block_mode, self._training
            )
            combo_idx = self._rng.choice(range(len(combos)))
            blocks_on_table = list(combos[combo_idx])
            self._reset_poses_randomly(blocks_on_table)
        else:
            # State-restore path: keep the block subset that was restored
            # rather than drawing a fresh combo.
            blocks_on_table = list(self._blocks_on_table)

        self._blocks_on_table = blocks_on_table
        # On the state-restore path the task info was just restored from the
        # snapshot; asking the (unrestored) reward for a task update would
        # clobber it with the previous episode's task.
        state = self._compute_state(request_task_update=reset_poses)
        self._previous_state = state
        return self._compute_observation(state=state)

    def step(self, action):
        self._step_robot_and_sim(action)
        state = self._compute_state()
        if self._reward_calculator is None:
            reward, done = 0.0, False
        else:
            reward, done = self._reward_calculator.reward(state)
        observation = self._compute_observation(state=state)
        return observation, reward, done, {}

    def render(self, mode="rgb_array"):
        del mode
        image = self._render_image()
        if not self._render_text_in_image:
            return image
        debug_info = {}
        if self._instruction_str is not None:
            debug_info["instruction"] = self._instruction_str
        return add_debug_info_to_image(image, debug_info)

    @property
    def succeeded(self):
        if self._reward_calculator is None:
            return False
        state = self._compute_state()
        # Peeking must not advance the delayed-reward counter.
        saved_zone_steps = self._reward_calculator._in_reward_zone_steps
        reward, _ = self._reward_calculator.reward(state)
        self._reward_calculator._in_reward_zone_steps = saved_zone_steps
        return reward > 0.0

    @property
    def instruction_str(self):
        return self._instruction_str

    @property
    def blocks_on_table(self):
        return list(self._blocks_on_table)

    @property
    def backend(self):
        return self._backend

    # -- instruction byte codec (reference `language_table.py:208-232`) --

    @staticmethod
    def encode_instruction(instruction):
        if not instruction:
            return np.zeros(constants.INSTRUCTION_LENGTH, dtype=np.int32)
        raw = list(instruction.encode("utf-8"))
        if len(raw) > constants.INSTRUCTION_LENGTH:
            raise ValueError(
                "Instruction length too long %d > %d; %s"
                % (len(raw), constants.INSTRUCTION_LENGTH, instruction)
            )
        raw = raw + [0] * (constants.INSTRUCTION_LENGTH - len(raw))
        return np.array(raw, dtype=np.int32)

    @staticmethod
    def decode_instruction(bytes_list):
        non_zero = bytes_list[np.where(bytes_list != 0)]
        if non_zero.shape[0] == 0:
            return ""
        return bytes(non_zero.tolist()).decode("utf-8")

    # -- state save / restore (reference `:234-359`) ---------------------

    def get_board_state(self):
        """Serializable snapshot: physics + task metadata."""
        state = {
            "physics": self._backend.get_state(),
            "blocks_on_table": list(self._blocks_on_table),
        }
        text_fields = dict(
            start_block=self._start_block,
            oracle_target_block=self._oracle_target_block,
            target_absolute_location=self._target_absolute_location,
            target_relative_location=self._target_relative_location,
            instruction_str=self._instruction_str,
        )
        for key, value in text_fields.items():
            if value is not None:
                state[key] = self.encode_instruction(value).tolist()
        if self._oracle_target_translation is not None:
            state["oracle_target_translation"] = (
                np.asarray(self._oracle_target_translation).tolist()
            )
        if self._instruction is not None:
            state["instruction"] = self._instruction.tolist()
        # Snapshot the reward calculator's task internals (chosen blocks,
        # targets, zone counters) so post-restore step()/reward() score the
        # restored task, not whatever episode ran since.
        if self._reward_calculator is not None:
            state["reward_state"] = {
                k: copy.deepcopy(v)
                for k, v in self._reward_calculator.__dict__.items()
                if k != "_rng"
            }
        return state

    def set_board_state(self, state):
        self._backend.set_state(state["physics"])
        self._blocks_on_table = list(state["blocks_on_table"])
        for key in (
            "start_block",
            "oracle_target_block",
            "target_absolute_location",
            "target_relative_location",
            "instruction_str",
        ):
            if key in state:
                setattr(
                    self,
                    "_" + key,
                    self.decode_instruction(np.array(state[key])),
                )
            else:
                # Absent in the snapshot means it was None at save time;
                # clear any value left over from the current episode.
                setattr(self, "_" + key, None)
        self._oracle_target_translation = None
        if "oracle_target_translation" in state:
            self._oracle_target_translation = np.array(
                state["oracle_target_translation"]
            )
        if "instruction" in state:
            instruction = state["instruction"]
            if len(instruction) < constants.INSTRUCTION_LENGTH:
                instruction = np.pad(
                    instruction,
                    (0, constants.INSTRUCTION_LENGTH - len(instruction)),
                )
            self._instruction = np.array(instruction, dtype=np.int32)
        if "reward_state" in state and self._reward_calculator is not None:
            self._reward_calculator.__dict__.update(
                copy.deepcopy(state["reward_state"])
            )
        self.reset(reset_poses=False)

    # Aliases matching the reference method names.
    get_pybullet_state = get_board_state
    set_pybullet_state = set_board_state

    # -- internals ------------------------------------------------------

    def _render_image(self):
        poses = {
            b: self._backend.block_pose(b) for b in self._blocks_on_table
        }
        goal = None
        if self._reward_calculator is not None:
            goal = self._reward_calculator.get_goal_region()
        return render_board(
            poses,
            self._backend.effector_xy(),
            image_size=self._image_size,
            goal_region=goal,
        )

    def _step_robot_and_sim(self, action):
        """Clip the delta action into workspace bounds and advance physics."""
        target = self._backend.effector_target_xy() + np.asarray(action[:2])
        target = np.clip(
            target,
            constants.WORKSPACE_BOUNDS[0],
            constants.WORKSPACE_BOUNDS[1],
        )
        self._backend.set_effector_target(target)
        self._backend.step(self._sim_steps_per_step)

    def _compute_observation(self, state=None):
        if state is None:
            state = self._compute_state()
        return collections.OrderedDict(
            effector_translation=state["effector_translation"],
            effector_target_translation=state["effector_target_translation"],
            instruction=state["instruction"],
            rgb=state["rgb"],
        )

    def compute_state(self, request_task_update=True):
        return self._compute_state(request_task_update)

    def _compute_state(self, request_task_update=True):
        """Full state dict: block poses + masks + oracle features + rgb."""
        poses = {
            b: self._backend.block_pose(b) for b in self._backend.block_names
        }
        e_target = np.array(
            self._backend.effector_target_xy(), np.float32
        )

        obs = collections.OrderedDict(
            effector_target_to_start_block_translation=np.array(
                poses[self._start_block][0] - e_target, np.float32
            ),
            start_block_orientation=np.array(
                [poses[self._start_block][1]], np.float32
            ),
        )
        for name, (xy, yaw) in poses.items():
            obs[f"block_{name}_translation"] = np.array(xy, np.float32)
            obs[f"block_{name}_orientation"] = np.array([yaw], np.float32)
            mask = 1.0 if name in self._blocks_on_table else 0.0
            obs[f"block_{name}_mask"] = np.array([mask], np.float32)

        # Long-horizon tasks may switch which block is being pushed;
        # refresh the task info from the reward (reference `:453-466`).
        if (
            request_task_update
            and hasattr(self._reward_calculator, "get_current_task_info")
        ):
            updated = self._reward_calculator.get_current_task_info(obs)
            self._set_task_info(updated)

        self._add_oracle_features(obs, poses, e_target)
        obs["effector_translation"] = np.array(
            self._backend.effector_xy(), np.float32
        )
        obs["effector_target_translation"] = e_target
        obs["instruction"] = self._instruction
        obs["rgb"] = self._render_image()
        return obs

    def _add_oracle_features(self, obs, poses, e_target):
        obs["effector_target_to_start_block_translation"] = np.array(
            poses[self._start_block][0] - e_target, np.float32
        )
        obs["start_block_orientation"] = np.array(
            [poses[self._start_block][1]], np.float32
        )
        if self._oracle_target_translation is not None:
            obs["effector_target_to_task_target_translation"] = np.array(
                self._oracle_target_translation - e_target, np.float32
            )
            obs["task_target_orientation"] = np.array([0.0], np.float32)
        elif self._oracle_target_block is not None:
            obs["effector_target_to_task_target_translation"] = np.array(
                poses[self._oracle_target_block][0] - e_target, np.float32
            )
            obs["task_target_orientation"] = np.array(
                [poses[self._oracle_target_block][1]], np.float32
            )
        else:
            obs["effector_target_to_task_target_translation"] = np.array(
                [0.0, 0.0], np.float32
            )
            obs["task_target_orientation"] = np.array([0.0], np.float32)
        return obs

    def _set_task_info(self, info):
        """Unpack a TaskInfo into start-block / target fields + instruction."""
        self._task_info = info
        self._oracle_target_block = None
        self._oracle_target_translation = None
        self._target_absolute_location = None
        self._target_relative_location = None

        if isinstance(info, task_info.Block2BlockTaskInfo):
            self._start_block = info.block1
            self._oracle_target_block = info.block2
        elif isinstance(info, task_info.Block2LocationTaskInfo):
            self._start_block = info.block
            self._oracle_target_translation = info.target_translation
            self._target_absolute_location = info.location
        elif isinstance(info, task_info.Block2LineTaskInfo):
            self._start_block = info.block
            self._oracle_target_translation = info.target_translation
        elif isinstance(info, task_info.Block2RelativeLocationTaskInfo):
            self._start_block = info.block
            self._target_relative_location = info.location
            self._oracle_target_translation = info.target_translation
        elif isinstance(info, task_info.Block2BlockRelativeLocationTaskInfo):
            self._start_block = info.block
            self._oracle_target_block = info.target_block
            self._target_relative_location = info.direction
            self._oracle_target_translation = info.target_translation
        elif isinstance(info, task_info.SeparateBlocksTaskInfo):
            self._start_block = info.block
            self._oracle_target_translation = info.target_translation
        elif isinstance(info, task_info.Point2BlockTaskInfo):
            self._start_block = info.block_target
            self._oracle_target_block = info.block_target
        elif isinstance(info, task_info.Block2PoleTaskInfo):
            self._start_block = info.block1
            self._oracle_target_block = info.goal
        else:
            raise ValueError(f"Unknown task info: {info}")

        if (
            self._oracle_target_block is None
            and self._oracle_target_translation is None
        ):
            raise ValueError(
                "Reward must provide either a target block or a target "
                "translation for the oracle."
            )
        self._instruction_str = info.instruction
        self._instruction = self.encode_instruction(info.instruction)

    def _reset_poses_randomly(self, blocks_on_table):
        """Rejection-sample a valid board + task (reference `:822-931`)."""
        xmin = constants.X_MIN + constants.WORKSPACE_BOUNDS_BUFFER
        ymin = constants.Y_MIN + constants.WORKSPACE_BOUNDS_BUFFER
        xmax = constants.X_MAX - constants.WORKSPACE_BOUNDS_BUFFER
        ymax = constants.Y_MAX - constants.WORKSPACE_BOUNDS_BUFFER

        # Park every block off-board, then sample the effector start.
        for name in self._backend.block_names:
            self._backend.park_block(name)
        effector_xy = self._rng.uniform(
            low=[xmin, ymin, constants.EFFECTOR_HEIGHT],
            high=[xmax, ymax, constants.EFFECTOR_HEIGHT],
        )[:2]
        self._backend.teleport_effector(effector_xy)
        self._backend.stabilize()

        num_reward_attempts = 0
        max_num_reward_attempts = 20
        while True:
            placed = []
            for name in blocks_on_table:
                attempts = 0
                while True:
                    candidate = self._rng.uniform(
                        low=[xmin, ymin, 0.0], high=[xmax, ymax, 0.0]
                    )
                    yaw = self._rng.uniform(low=0.0, high=2 * np.pi)
                    far_from_blocks = (
                        not placed
                        or min(
                            np.linalg.norm(candidate - p) for p in placed
                        )
                        > constants.BLOCK_DISTANCE_THRESHOLD
                    )
                    far_from_arm = (
                        np.linalg.norm(candidate[:2] - effector_xy)
                        > constants.ARM_DISTANCE_THRESHOLD
                    )
                    if far_from_blocks and far_from_arm:
                        placed.append(candidate)
                        self._backend.set_block_pose(
                            name, candidate[:2], yaw
                        )
                        break
                    attempts += 1
                    if attempts > 20:
                        raise ValueError(
                            "Exceeded max attempts for generating block pose."
                        )
            self._backend.stabilize(nsteps=200)

            if self._reward_calculator is not None:
                self._blocks_on_table = list(blocks_on_table)
                info = self._reward_calculator.reset(
                    self._compute_state(request_task_update=False),
                    blocks_on_table=list(blocks_on_table),
                )
                num_reward_attempts += 1
                if info == task_info.FAILURE:
                    if num_reward_attempts >= max_num_reward_attempts:
                        raise ValueError(
                            "Cannot find a block config with valid reward."
                        )
                    continue
                self._set_task_info(info)
                if self._instruction_str is None:
                    if num_reward_attempts >= max_num_reward_attempts:
                        raise ValueError(
                            "Cannot find a block config with valid reward."
                        )
                    continue
            break
