"""Instruction-grammar vocabulary shared across reward families.

Parity source: reference `language_table/environments/rewards/synonyms.py`.
The string tables are data and must match the reference exactly — instruction
counts (tests/test_env_instructions.py) and any text-conditioned policy depend
on the literal strings.
"""

import collections

from rt1_tpu.envs import blocks as blocks_module

PUSH_VERBS = [
    "push the",
    "move the",
    "slide the",
    "put the",
]

PREPOSITIONS = [
    "to the",
    "towards the",
    "close to the",
    "next to the",
]

POINT_PREPOSITIONS = [
    "point next to the",
    "point close to the",
    "point to the",
    "point at the",
    "move the arm next to the",
    "move the arm close to the",
    "move the arm to the",
    "move your arm next to the",
    "move your arm close to the",
    "move your arm to the",
    "move next to the",
    "move close to the",
    "move to the",
]


def block_synonyms(block, blocks_on_table):
    """Ways to refer to `block` unambiguously given the current board.

    A bare color ('red block') or bare shape ('star') is only valid when it
    is unique on the table; 'color shape' is always valid
    (reference `synonyms.py:20-35`).
    """
    color, shape = blocks_module.color_shape(block)
    colors = collections.Counter(
        blocks_module.color_shape(b)[0] for b in blocks_on_table
    )
    shapes = collections.Counter(
        blocks_module.color_shape(b)[1] for b in blocks_on_table
    )
    names = []
    if colors[color] == 1:
        names.append(f"{color} block")
    if shapes[shape] == 1:
        names.append(shape)
    names.append(f"{color} {shape}")
    return names
