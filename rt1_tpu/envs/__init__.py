"""Language-Table board environment: geometry, blocks, rewards, simulator.

TPU-native rebuild of the reference's `language_table/environments/` package
(see SURVEY.md §2.5). The board/reward/instruction logic is pure numpy and has
no simulator dependency; the physics backend is pluggable (kinematic numpy
backend always available, PyBullet optional).
"""

from rt1_tpu.envs import blocks, constants, language, task_info
from rt1_tpu.envs.language_table import LanguageTable

__all__ = ["blocks", "constants", "language", "task_info", "LanguageTable"]
