"""Reward-family registry and combined instruction enumeration.

Parity source: reference `language_table/environments/rewards/instructions.py`
(aggregate generator + vocab) and the per-family modules.
"""

from rt1_tpu.envs.rewards.base import BoardReward, inside_bounds
from rt1_tpu.envs.rewards.block2block import BlockToBlockReward
from rt1_tpu.envs.rewards.block2block_relative import (
    BlockToBlockRelativeLocationReward,
)
from rt1_tpu.envs.rewards.block2location import BlockToAbsoluteLocationReward
from rt1_tpu.envs.rewards.block2relativelocation import (
    BlockToRelativeLocationReward,
)
from rt1_tpu.envs.rewards.corner import BlockToCornerReward
from rt1_tpu.envs.rewards.play import PlayReward
from rt1_tpu.envs.rewards.point2block import PointToBlockReward
from rt1_tpu.envs.rewards.separate_blocks import SeparateBlocksReward

CLIP_VOCAB_SIZE = 49408

REWARD_FAMILIES = {
    "block2block": BlockToBlockReward,
    "point2block": PointToBlockReward,
    "block2relativelocation": BlockToRelativeLocationReward,
    "block2absolutelocation": BlockToAbsoluteLocationReward,
    "block2block_relative_location": BlockToBlockRelativeLocationReward,
    "separate_blocks": SeparateBlocksReward,
    "block1_to_corner": BlockToCornerReward,
    "play": PlayReward,
}


def get_reward_factory(name):
    return REWARD_FAMILIES[name]


def generate_all_instructions(block_mode):
    """All instructions across the six enumerable families, reference order."""
    from rt1_tpu.envs.rewards import (
        block2block,
        block2block_relative,
        block2location,
        block2relativelocation,
        point2block,
        separate_blocks,
    )

    return (
        block2block.generate_all_instructions(block_mode)
        + point2block.generate_all_instructions(block_mode)
        + block2relativelocation.generate_all_instructions(block_mode)
        + block2location.generate_all_instructions(block_mode)
        + block2block_relative.generate_all_instructions(block_mode)
        + separate_blocks.generate_all_instructions(block_mode)
    )


def generate_runtime_instructions(block_mode):
    """Every instruction the reward SAMPLERS can emit at runtime.

    `generate_all_instructions` mirrors the reference's enumeration, which
    (faithfully) diverges from its own samplers in two ways: canonical
    block names only (samplers draw from the per-board synonym space —
    bare colors/shapes when unique), and 3-verb lists where samplers use
    the generic 4-verb push list (block2location, corner; corner isn't
    enumerated at all). Embedding tables built for closed-loop eval must
    cover the sampler space, so this unions each family's
    `runtime_instructions` (behaviorally pinned by
    `tests/test_env_instructions.py`). The play family's BLOCK_8 generator
    is open-ended and excluded; its fixed BLOCK_4 set is included.
    """
    from rt1_tpu.envs import blocks
    from rt1_tpu.envs.rewards import (
        block2block,
        block2block_relative,
        block2location,
        block2relativelocation,
        corner,
        play,
        point2block,
        separate_blocks,
    )

    out = list(generate_all_instructions(block_mode))
    seen = set(out)

    def extend(items):
        for s in items:
            if s not in seen:
                seen.add(s)
                out.append(s)

    for family in (
        block2block,
        point2block,
        block2relativelocation,
        block2location,
        block2block_relative,
        separate_blocks,
        corner,
    ):
        extend(family.runtime_instructions(block_mode))
    if block_mode == blocks.BlockMode.BLOCK_4:
        # Same split constant as PlayReward's sampler — never hardcode a
        # number here (a mismatch silently uncovers play instructions).
        extend(
            play.get_100_4block_instructions(
                num_train_per_family=play.NUM_TRAIN_PER_FAMILY
            )
        )
    return out


def vocab_size(block_mode):
    words = set()
    for instruction in generate_all_instructions(block_mode):
        words.update(instruction.split(" "))
    return len(words)


__all__ = [
    "BoardReward",
    "inside_bounds",
    "BlockToBlockReward",
    "PointToBlockReward",
    "BlockToRelativeLocationReward",
    "BlockToAbsoluteLocationReward",
    "BlockToBlockRelativeLocationReward",
    "SeparateBlocksReward",
    "BlockToCornerReward",
    "PlayReward",
    "REWARD_FAMILIES",
    "get_reward_factory",
    "generate_all_instructions",
    "vocab_size",
    "CLIP_VOCAB_SIZE",
]
