"""Reward-family base class for the Language-Table board.

Parity source: reference `language_table/environments/rewards/reward.py:24-74`.
A reward owns task sampling (`reset` → TaskInfo or FAILURE) and scoring
(`reward(state)` → (reward, done)). `state` is the flat dict the env exposes:
`block_<name>_translation` / `block_<name>_orientation` per block plus
effector keys.
"""

import numpy as np

from rt1_tpu.envs import constants, language


class BoardReward:
    """Base class for all board reward/task families."""

    def __init__(self, goal_reward, rng, delay_reward_steps, block_mode):
        self._block_mode = block_mode
        self._goal_reward = goal_reward
        self._rng = rng
        # Number of consecutive in-zone steps required before the sparse
        # reward fires (0 = immediate).
        self._delay_reward_steps = delay_reward_steps
        self._in_reward_zone_steps = None
        self._target_translation = None

    def seed(self, rng):
        self._rng = rng

    def get_goal_region(self):
        """(target translation, radius) for visualization, or (None, None)."""
        return None, None

    def reset(self, state, blocks_on_table):
        raise NotImplementedError

    def reward(self, state):
        raise NotImplementedError

    # -- shared helpers -------------------------------------------------

    def _block_pose(self, block, state):
        return (
            state[f"block_{block}_translation"],
            state[f"block_{block}_orientation"],
        )

    def _block_xy(self, block, state):
        return np.array(self._block_pose(block, state)[0])

    def _pick_block(self, blocks_on_table):
        return self._rng.choice(blocks_on_table)

    def _pick_two_blocks(self, blocks_on_table):
        return self._rng.choice(blocks_on_table, 2, replace=False)

    def _pick_synonym(self, block, blocks_on_table):
        return self._rng.choice(language.block_synonyms(block, blocks_on_table))

    def _maybe_goal(self, in_zone):
        """Sparse-reward gate with the delay-steps mechanism."""
        if in_zone:
            if self._in_reward_zone_steps >= self._delay_reward_steps:
                return self._goal_reward, True
            self._in_reward_zone_steps += 1
        return 0.0, False


def inside_bounds(target, buffer=constants.WORKSPACE_BOUNDS_BUFFER):
    """Is an (x, y) target inside the workspace, with a safety buffer?"""
    x, y = target
    return (
        constants.X_MIN + buffer < x < constants.X_MAX - buffer
        and constants.Y_MIN + buffer < y < constants.Y_MAX - buffer
    )
