"""Long-horizon 'play' instruction families (no scripted reward).

Parity source: reference `language_table/environments/rewards/play.py`.
Instruction text is data and matches the reference's grammar exactly; the
reward is always 0 (these tasks are scored by humans / learned models).
"""

import itertools
import random

import numpy as np

from rt1_tpu.envs import constants, task_info
from rt1_tpu.envs.rewards import base

BLOCKS4 = ["red moon", "blue cube", "green star", "yellow pentagon"]
BLOCKS8 = [
    "red moon", "red pentagon", "blue moon", "blue cube", "green cube",
    "green star", "yellow star", "yellow pentagon",
]
LOCATIONS = [
    "top left corner", "top center", "top right corner", "center left",
    "center", "center right", "bottom left corner", "bottom center",
    "bottom right corner",
]
COLORS = ["red", "blue", "green", "yellow"]
ORDERINGS = list(itertools.permutations(BLOCKS4))


def obj_in_place_then_remainder_in_other(blocks, locations):
    return [
        f"put the {b} in the {l0}, then put the rest of the blocks in the {l1}"
        for b in blocks
        for l0 in locations
        for l1 in locations
        if l0 != l1
    ]


def k_in_place_then_k_minus_1_in_other(blocks, locations):
    numbers = ["one", "two", "three", "four", "five", "six", "seven", "eight"]
    out = []
    for number in numbers[: len(blocks)][:-1]:
        noun = "block" if number == "one" else "blocks"
        for l0 in locations:
            for l1 in locations:
                if l0 != l1:
                    out.append(
                        f"put {number} {noun} in the {l0}, "
                        f"then put the rest in the {l1}"
                    )
    return out


def triangle_in_place_remainder_in_rest(locations):
    return [
        (
            "make a triangle out of three blocks and put it in the "
            f"{l0} of the board, then put the remainder in the {l1} of the board"
        )
        for l0 in locations
        for l1 in locations
        if l0 != l1
    ]


def order_by(orderings):
    return [
        "order the blocks from %s: %s" % (orientation, ", ".join(ordering))
        for orientation in ["top to bottom", "left to right"]
        for ordering in orderings
    ]


def _expand(seeded):
    out = []
    for seed, expansions in seeded:
        if expansions is None:
            out.append(seed)
        else:
            out.extend(seed % e for e in expansions)
    return out


# The train-split size per long-horizon family. Single source of truth for
# BOTH the sampler (PlayReward below) and the runtime embedding table
# (rewards.generate_runtime_instructions): if the two disagreed, a table
# embedder would silently miss play instructions at reset and the policy
# would get a stale/KeyError embedding mid-eval.
NUM_TRAIN_PER_FAMILY = 20


def get_100_4block_instructions(num_train_per_family=NUM_TRAIN_PER_FAMILY,
                                num_test_per_family=5,
                                return_train=True):
    """20 random train (+5 test) instructions per long-horizon family."""
    train_inst, test_inst = [], []
    # Local RNG seeded like the reference's `random.seed(0)` (play.py:110)
    # without the side effect of reseeding the process-global random module.
    rng = random.Random(0)

    def take(family):
        rng.shuffle(family)
        if num_train_per_family:
            train_inst.extend(family[:num_train_per_family])
            test_inst.extend(
                family[
                    num_train_per_family:
                    num_train_per_family + num_test_per_family
                ]
            )
        else:
            train_inst.extend(family)

    take(_expand([
        ("put all the blocks in a line", None),
        ("put all the blocks in a %s line", ["horizontal", "vertical"]),
        ("put all the blocks in a vertical line on the %s side of the board",
         ["left", "center", "right"]),
        ("put all the blocks in a horizontal line on the %s side of the board",
         ["bottom", "center", "top"]),
        ("put the blocks in a diagonal line from the %s",
         ["top left to bottom right", "top right to bottom left"]),
        ("surround the %s with the other blocks", BLOCKS4),
        ("put all the blocks in the %s", LOCATIONS),
        ("put blocks in all four corners", None),
        ("make a %s shape out of the blocks",
         ["rectangle", "square", "diamond", "parallelogram"]),
    ]))
    take(obj_in_place_then_remainder_in_other(BLOCKS4, LOCATIONS))
    take(k_in_place_then_k_minus_1_in_other(BLOCKS4, LOCATIONS))
    take(triangle_in_place_remainder_in_rest(LOCATIONS))
    take(order_by(ORDERINGS))
    return train_inst if return_train else test_inst


def unique_color_combos():
    combos = list(itertools.combinations(COLORS, 2))
    out = []
    for ci, cj in combos:
        complement = [
            (a, b) for a, b in combos if ci not in (a, b) and cj not in (a, b)
        ]
        out.append((ci, cj, complement[0][0], complement[0][1]))
    return out


def colors_in_locations():
    out = []
    for colors, locations in itertools.product(
        itertools.permutations(COLORS, 4), itertools.permutations(LOCATIONS, 4)
    ):
        inst = (
            f"put the {colors[0]} blocks in the {locations[0]}, "
            f"the {colors[1]} blocks in the {locations[1]}, "
            f"the {colors[2]} blocks in the {locations[2]}, "
            f"and the {colors[3]} blocks in the {locations[3]}."
        )
        if len(inst) > 256:
            raise ValueError(f"Instruction greater than max length: {inst}")
        out.append(inst)
    return out


def group_color_pairs():
    return [
        (
            f"put the {ci} and {cj} blocks together in a group, then put the "
            f"{ck} and {cl} blocks together in a group."
        )
        for ci, cj, ck, cl in itertools.permutations(COLORS, 4)
    ]


def group_color_pairs_in_locations():
    return [
        (
            f"put the {ci} and {cj} blocks together in the {li}, then put the "
            f"{ck} and {cl} blocks together in the {lj}."
        )
        for ci, cj, ck, cl in unique_color_combos()
        for li, lj in itertools.permutations(LOCATIONS, 2)
    ]


def get_colors_in_lines():
    return [
        (
            f"make one {mi} line out of the {ci} and {cj} blocks, then "
            f"make a {mj} line out of the {ck} and {cl} blocks"
        )
        for mi in ["horizontal", "vertical"]
        for mj in ["horizontal", "vertical"]
        for ci, cj, ck, cl in unique_color_combos()
    ]


def get_line_tasks():
    tasks = [
        "put the blocks in a line",
        "put all the blocks in a vertical line",
        "put all the blocks in a horizontal line",
    ]
    tasks += [
        f"put all the blocks in a vertical line on the {m} of the board"
        for m in ["left", "center", "right"]
    ]
    tasks += [
        f"put all the blocks in a horizontal line on the {m} of the board"
        for m in ["bottom", "center", "top"]
    ]
    tasks += [
        f"put the blocks in a diagonal line from the {m}"
        for m in ["top left to bottom right", "top right to bottom left"]
    ]
    return tasks


def get_surround_tasks():
    return [f"surround the {b} with the others" for b in BLOCKS8]


def blocks_in_order_outer_edge():
    outer = [
        "top left", "top center", "top right", "center left", "center right",
        "bottom left", "bottom center", "bottom right",
    ]
    out = []
    for ordering in itertools.permutations(BLOCKS8, len(BLOCKS8)):
        inst = "put the " + "".join(
            f"{b} to {l}, " for b, l in zip(ordering, outer)
        )
        if len(inst) > 256:
            raise ValueError(f"Instruction greater than max length: {inst}")
        out.append(inst)
    return out


def all_blocks_in_location():
    return [f"put all the blocks in the {l}" for l in LOCATIONS]


def k_blocks_in_location_i_rest_in_location_j():
    return [
        f"put {k} blocks in the {li}, then the rest in the {lj}"
        for k in range(1, 8)
        for li, lj in itertools.permutations(LOCATIONS, 2)
    ]


def get_shape_instructions():
    shapes = [
        "square", "triangle", "circle", "diamond", "parallelogram", "G", "O",
        "L", "E", "A", "T", "X", "V", "Y", "U", "S", "C", "Z", "N", "J",
    ]
    out = [f'make a "{shape}"" shape out of all the blocks' for shape in shapes]
    out.append("make a smiley face out of the blocks")
    out.append(
        "make a rainbow out of the blocks (red, yellow, green, "
        "blue in a semicircle)"
    )
    return out


def get_sort_tasks():
    return ["group the blocks by color"]


_FAMILY_CACHE = {}


def _cached_family(fn):
    """Families like colors_in_locations build 10k-70k strings; build once."""
    if fn not in _FAMILY_CACHE:
        _FAMILY_CACHE[fn] = fn()
    return _FAMILY_CACHE[fn]


def get_random_8block_instruction(rng):
    task_fns = [
        get_sort_tasks, colors_in_locations, group_color_pairs,
        get_colors_in_lines, group_color_pairs_in_locations, get_line_tasks,
        get_surround_tasks, blocks_in_order_outer_edge,
        all_blocks_in_location, k_blocks_in_location_i_rest_in_location_j,
        get_shape_instructions,
    ]
    return rng.choice(_cached_family(rng.choice(task_fns)))


class PlayReward(base.BoardReward):
    """Long-horizon instruction sampler; never emits reward."""

    def __init__(self, goal_reward, rng, delay_reward_steps, block_mode):
        super().__init__(goal_reward, rng, delay_reward_steps, block_mode)
        self.block_mode = block_mode.value
        if self.block_mode == "BLOCK_4":
            self._all_instructions = get_100_4block_instructions(
                num_train_per_family=NUM_TRAIN_PER_FAMILY
            )

    def _sample_instruction(self, start_block, target_block, blocks_on_table):
        if self.block_mode == "BLOCK_4":
            return self._rng.choice(self._all_instructions)
        if self.block_mode == "BLOCK_8":
            return get_random_8block_instruction(self._rng)
        raise ValueError(f"Unsupported block mode: {self.block_mode}")

    def reset(self, state, blocks_on_table):
        attempts = 0
        while True:
            start_block, target_block = self._pick_two_blocks(blocks_on_table)
            dist = np.linalg.norm(
                self._block_xy(start_block, state)
                - self._block_xy(target_block, state)
            )
            if dist < constants.TARGET_BLOCK_DISTANCE + 0.01:
                attempts += 1
                if attempts > 10:
                    return task_info.FAILURE
                continue
            break
        self._start_block = start_block
        self._target_block = target_block
        self._instruction = self._sample_instruction(
            start_block, target_block, blocks_on_table
        )
        self._in_reward_zone_steps = 0
        return task_info.Block2BlockTaskInfo(
            instruction=self._instruction,
            block1=self._start_block,
            block2=self._target_block,
        )

    def reward(self, state):
        return 0.0, False
