"""'Point/move the arm to block X' task.

Parity source: reference `language_table/environments/rewards/point2block.py`.
Scored on the *effector target* position, not any block motion.
"""

import numpy as np

from rt1_tpu.envs import blocks as blocks_module
from rt1_tpu.envs import constants, language, task_info
from rt1_tpu.envs.rewards import base


def generate_all_instructions(block_mode):
    out = []
    for block_text in blocks_module.text_descriptions(block_mode):
        for prep in language.POINT_PREPOSITIONS:
            out.append(f"{prep} {block_text}")
    return out


def runtime_instructions(block_mode):
    """Sampler-complete: all block synonym variants, not just canonical."""
    out = []
    for group in blocks_module.synonym_groups(block_mode):
        for block_text in group:
            for prep in language.POINT_PREPOSITIONS:
                out.append(f"{prep} {block_text}")
    return out


class PointToBlockReward(base.BoardReward):
    """Sparse reward when the effector reaches the chosen block."""

    def _sample_instruction(self, block, blocks_on_table):
        block_text = self._pick_synonym(block, blocks_on_table)
        prep = self._rng.choice(language.POINT_PREPOSITIONS)
        return f"{prep} {block_text}"

    def reset(self, state, blocks_on_table):
        attempts = 0
        while True:
            block = self._pick_block(blocks_on_table)
            dist = np.linalg.norm(
                self._block_xy(block, state)
                - np.array(state["effector_target_translation"])
            )
            if dist < constants.TARGET_BLOCK_DISTANCE + 0.01:
                attempts += 1
                if attempts > 10:
                    return task_info.FAILURE
                continue
            break
        self._block = block
        self._instruction = self._sample_instruction(block, blocks_on_table)
        self._in_reward_zone_steps = 0
        return task_info.Point2BlockTaskInfo(
            instruction=self._instruction, block_target=block
        )

    def reward(self, state):
        dist = np.linalg.norm(
            self._block_xy(self._block, state)
            - np.array(state["effector_target_translation"])
        )
        return self._maybe_goal(dist < constants.TARGET_BLOCK_DISTANCE)
