"""'Push block X <relative position> of block Y' task.

Parity source: reference
`language_table/environments/rewards/block2block_relative_location.py`.
"""

import itertools

import numpy as np

from rt1_tpu.envs import blocks as blocks_module
from rt1_tpu.envs import language, task_info
from rt1_tpu.envs.rewards import base

MAGNITUDE_X = 0.08
MAGNITUDE_Y = 0.08
MAGNITUDE_X_DIAG = 0.04
MAGNITUDE_Y_DIAG = 0.04

DRAGGED_THRESHOLD = 0.05
TARGET_DISTANCE = 0.04

UP, DOWN, LEFT, RIGHT = -1.0, 1.0, -1.0, 1.0

DIRECTIONS = {
    "up": [UP, 0.0],
    "down": [DOWN, 0.0],
    "left": [0.0, LEFT],
    "right": [0.0, RIGHT],
    "diagonal_up_left": [UP, LEFT],
    "diagonal_up_right": [UP, RIGHT],
    "diagonal_down_left": [DOWN, LEFT],
    "diagonal_down_right": [DOWN, RIGHT],
}

VERBS = [
    "move the",
    "push the",
    "put the",
    "bring the",
    "slide the",
]

DIRECTION_SYNONYMS = {
    "up": ["above the", "to the top side of the", "to the top of the"],
    "down": ["below the", "to the bottom side of the", "to the bottom of the"],
    "left": [
        "just left of the",
        "to the left of the",
        "left of the",
        "to the left side of the",
    ],
    "right": [
        "just right of the",
        "to the right of the",
        "right of the",
        "to the right side of the",
    ],
    "diagonal_up_left": [
        "to the top left side of the",
        "to the top left of the",
        "diagonally up and to the left of the",
    ],
    "diagonal_up_right": [
        "to the top right side of the",
        "to the top right of the",
        "diagonally up and to the right of the",
    ],
    "diagonal_down_left": [
        "to the bottom left side of the",
        "to the bottom left of the",
        "diagonally down and to the left of the",
    ],
    "diagonal_down_right": [
        "to the bottom right side of the",
        "to the bottom right of the",
        "diagonally down and to the right of the",
    ],
}


def task_id_table():
    """task string 'start-target-direction' -> stable numeric id."""
    strings = sorted(
        f"{start}-{target}-{direction}"
        for start in blocks_module.ALL_BLOCKS
        for target in blocks_module.ALL_BLOCKS
        for direction in DIRECTIONS
    )
    return {s: i for i, s in enumerate(strings)}


UNIQUE_TASK_STRINGS = task_id_table()
NUM_UNIQUE_TASKS = len(UNIQUE_TASK_STRINGS)


def direction_offset(direction, scale=1.0):
    mag_x = MAGNITUDE_X_DIAG if "diagonal" in direction else MAGNITUDE_X
    mag_y = MAGNITUDE_Y_DIAG if "diagonal" in direction else MAGNITUDE_Y
    return np.array(DIRECTIONS[direction]) * np.array(
        [mag_x * scale, mag_y * scale]
    )


def is_block2block_relative_pair(xy_block, xy_target):
    """Does xy_target sit at one of the canonical offsets from xy_block?"""
    for d in DIRECTIONS:
        target = np.array(xy_block) + direction_offset(d)
        if np.linalg.norm(target - xy_target) < 1e-6:
            return True
    return False


def generate_all_instructions(block_mode):
    out = []
    names = blocks_module.text_descriptions(block_mode)
    for block_syn, target_syn in itertools.permutations(names, 2):
        for verb in VERBS:
            for direction in DIRECTIONS:
                for direction_syn in DIRECTION_SYNONYMS[direction]:
                    out.append(
                        f"{verb} {block_syn} {direction_syn} {target_syn}"
                    )
    return out


def runtime_instructions(block_mode):
    """Sampler-complete: synonym pairs of distinct blocks (the sampler's
    PUSH_VERBS is a subset of the enumeration VERBS, so VERBS covers it)."""
    out = []
    for g1, g2 in itertools.permutations(
        blocks_module.synonym_groups(block_mode), 2
    ):
        for block_syn in g1:
            for target_syn in g2:
                for verb in VERBS:
                    for direction in DIRECTIONS:
                        for direction_syn in DIRECTION_SYNONYMS[direction]:
                            out.append(
                                f"{verb} {block_syn} {direction_syn} {target_syn}"
                            )
    return out


class BlockToBlockRelativeLocationReward(base.BoardReward):
    """Sparse reward when block sits on the offset ray from the target block."""

    def __init__(self, goal_reward, rng, delay_reward_steps, block_mode):
        super().__init__(goal_reward, rng, delay_reward_steps, block_mode)
        self._target_block = None
        self._block = None
        self._direction = None
        self._instruction = None
        self._target_translation = None

    def _sample_instruction(self, block, target_block, direction, blocks_on_table):
        # NOTE: samples from the generic 4-verb push list, matching the
        # reference (`block2block_relative_location.py:202`); the module's
        # 5-verb VERBS list (with 'bring the') is used only for enumeration,
        # exactly as in the reference.
        verb = self._rng.choice(language.PUSH_VERBS)
        block_syn = self._pick_synonym(block, blocks_on_table)
        target_syn = self._pick_synonym(target_block, blocks_on_table)
        direction_syn = self._rng.choice(DIRECTION_SYNONYMS[direction])
        return f"{verb} {block_syn} {direction_syn} {target_syn}"

    def target_translation_for(self, state, target_block, direction, scale=1.0):
        return np.array(
            self._block_pose(target_block, state)[0]
        ) + direction_offset(direction, scale)

    def get_current_task_info(self, state):
        if self._target_block is None:
            raise ValueError("must call .reset first")
        self._target_translation = self.target_translation_for(
            state, self._target_block, self._direction
        )
        return task_info.Block2BlockRelativeLocationTaskInfo(
            instruction=self._instruction,
            block=self._block,
            target_translation=self._target_translation,
            target_block=self._target_block,
            direction=self._direction,
        )

    def reset(self, state, blocks_on_table):
        tries = 0
        while True:
            block, target_block = self._pick_two_blocks(blocks_on_table)
            direction = self._rng.choice(list(DIRECTIONS.keys()))
            target = self.target_translation_for(state, target_block, direction)
            if base.inside_bounds(target):
                break
            tries += 1
            if tries > 100:
                return task_info.FAILURE
        info = self.reset_to(
            state, block, target_block, direction, blocks_on_table
        )
        self._in_reward_zone_steps = 0
        already_done = self.reward_for(
            state, self._block, self._target_block, self._direction,
            delay_reward_steps=0,
        )[1]
        if already_done:
            return task_info.FAILURE
        return info

    def reset_to(self, state, block, target_block, direction, blocks_on_table):
        self._block = block
        self._target_block = target_block
        # Remember where the target block started: dragging it too far
        # invalidates the task.
        self._target_block_reset_translation = np.copy(
            self._block_pose(target_block, state)[0]
        )
        self._direction = direction
        self._target_translation = self.target_translation_for(
            state, target_block, direction
        )
        self._instruction = self._sample_instruction(
            block, target_block, direction, blocks_on_table
        )
        return self.get_current_task_info(state)

    @property
    def target_translation(self):
        return self._target_translation

    def reward(self, state):
        return self.reward_for(
            state,
            self._block,
            self._target_block,
            self._direction,
            self._delay_reward_steps,
        )

    def reward_for(self, state, pushing_block, target_block, direction,
                   delay_reward_steps):
        pushing_xy = self._block_xy(pushing_block, state)
        target_xy = self._block_xy(target_block, state)
        offset_xy = self.target_translation_for(state, target_block, direction)

        # Accept any point on the ray from half the offset to 10% past it.
        diff = offset_xy - target_xy
        on_line = False
        for cand in np.linspace(diff * 0.5, diff * 1.1, 10):
            if np.linalg.norm(target_xy + cand - pushing_xy) < TARGET_DISTANCE:
                on_line = True
                break

        dragged = (
            np.linalg.norm(self._target_block_reset_translation - target_xy)
            > DRAGGED_THRESHOLD
        )

        if on_line and not dragged:
            if self._in_reward_zone_steps >= delay_reward_steps:
                return self._goal_reward, True
            self._in_reward_zone_steps += 1
        return 0.0, False

    def get_goal_region(self):
        return self._target_translation, TARGET_DISTANCE

    def reward_for_info(self, state, info):
        return self.reward_for(
            state,
            pushing_block=info.block,
            target_block=info.target_block,
            direction=info.direction,
            delay_reward_steps=self._delay_reward_steps,
        )

    def get_current_task_id(self):
        key = f"{self._block}-{self._target_block}-{self._direction}"
        return UNIQUE_TASK_STRINGS[key]

    def debug_info(self, state):
        return np.linalg.norm(
            self._block_xy(self._block, state)
            - self.target_translation_for(
                state, self._target_block, self._direction
            )
        )
