"""'Push block X <slightly> <direction>' task.

Parity source: reference
`language_table/environments/rewards/block2relativelocation.py`.
"""

import numpy as np

from rt1_tpu.envs import blocks as blocks_module
from rt1_tpu.envs import language, task_info
from rt1_tpu.envs.rewards import base

MAGNITUDES = {"near": 0.15, "far": 0.25}

# Board frame: top-left of the image is (0, 0), so "up" decreases x.
UP, DOWN, LEFT, RIGHT = -1.0, 1.0, -1.0, 1.0

DIRECTIONS = {
    "up": [UP, 0.0],
    "down": [DOWN, 0.0],
    "left": [0.0, LEFT],
    "right": [0.0, RIGHT],
    "diagonal_up_left": [UP, LEFT] / np.linalg.norm([UP, LEFT]),
    "diagonal_up_right": [UP, RIGHT] / np.linalg.norm([UP, RIGHT]),
    "diagonal_down_left": [DOWN, LEFT] / np.linalg.norm([DOWN, LEFT]),
    "diagonal_down_right": [DOWN, RIGHT] / np.linalg.norm([DOWN, RIGHT]),
}

VERBS = [
    "move the",
    "push the",
    "slide the",
]

SLIGHTLY_SYNONYMS = [
    "slightly",
    "a bit",
    "a little",
    "a little bit",
    "somewhat",
]

DIRECTION_SYNONYMS = {
    "up": ["up", "upwards"],
    "down": ["down", "downwards"],
    "left": ["to the left", "left"],
    "right": ["to the right", "right"],
}

DIAGONAL_PREPOSITIONS = [
    "%s and %s",
    "%s and then %s",
    "diagonally %s and %s",
    "%s and %s diagonally",
]

TARGET_DISTANCE = 0.1


def slightly_variants(verb, block, direction):
    """All 'slightly'-modified phrasings of a near push."""
    yield f"slightly {verb} {block} {direction}"
    for syn in SLIGHTLY_SYNONYMS:
        yield f"{verb} {block} {syn} {direction}"
        yield f"{verb} {block} {direction} {syn}"


def sample_slightly(rng, verb, block, direction):
    mode = rng.choice(["slightly_first", "prefix", "suffix"])
    if mode == "slightly_first":
        return f"slightly {verb} {block} {direction}"
    syn = rng.choice(SLIGHTLY_SYNONYMS)
    if mode == "prefix":
        return f"{verb} {block} {syn} {direction}"
    return f"{verb} {block} {direction} {syn}"


def diagonal_variants(direction):
    """All natural-language renderings of a canonical diagonal direction."""
    _, first, second = direction.split("_")
    for first_syn in DIRECTION_SYNONYMS[first]:
        for second_syn in DIRECTION_SYNONYMS[second]:
            for prep in DIAGONAL_PREPOSITIONS:
                yield prep % (first_syn, second_syn)


def sample_diagonal(rng, direction):
    _, first, second = direction.split("_")
    first_syn = rng.choice(DIRECTION_SYNONYMS[first])
    second_syn = rng.choice(DIRECTION_SYNONYMS[second])
    prep = rng.choice(DIAGONAL_PREPOSITIONS)
    return prep % (first_syn, second_syn)


def runtime_instructions(block_mode):
    """Sampler-complete: all block synonym variants (same verb list — this
    family samples from its own 3-verb VERBS, unlike block2location)."""
    flat = [
        v for g in blocks_module.synonym_groups(block_mode) for v in g
    ]
    return generate_all_instructions(block_mode, names=flat)


def generate_all_instructions(block_mode, names=None):
    out = []
    if names is None:
        names = blocks_module.text_descriptions(block_mode)
    for block_text in names:
        for verb in VERBS:
            for direction in DIRECTIONS:
                if "diagonal" in direction:
                    syns = diagonal_variants(direction)
                else:
                    syns = DIRECTION_SYNONYMS[direction]
                for direction_syn in syns:
                    out.extend(
                        slightly_variants(verb, block_text, direction_syn)
                    )
                    out.append(f"{verb} {block_text} {direction_syn}")
    return out


class BlockToRelativeLocationReward(base.BoardReward):
    """Sparse reward at an invisible offset target from the block's start."""

    def _sample_instruction(self, block, distance_mode, direction, blocks_on_table):
        verb = self._rng.choice(VERBS)
        block_syn = self._pick_synonym(block, blocks_on_table)
        if "diagonal" in direction:
            direction_text = sample_diagonal(self._rng, direction)
        else:
            direction_text = self._rng.choice(DIRECTION_SYNONYMS[direction])
        if distance_mode == "near":
            return sample_slightly(self._rng, verb, block_syn, direction_text)
        return f"{verb} {block_syn} {direction_text}"

    def reset(self, state, blocks_on_table):
        tries = 0
        while True:
            self._block = self._pick_block(blocks_on_table)
            block_xy = self._block_xy(self._block, state)
            direction = self._rng.choice(sorted(DIRECTIONS.keys()))
            distance_mode = self._rng.choice(sorted(MAGNITUDES.keys()))
            target = block_xy + (
                np.array(DIRECTIONS[direction]) * MAGNITUDES[distance_mode]
            )
            if base.inside_bounds(target):
                break
            tries += 1
            if tries > 100:
                return task_info.FAILURE
        self._instruction = self._sample_instruction(
            self._block, distance_mode, direction, blocks_on_table
        )
        self._target_translation = np.copy(target)
        self._in_reward_zone_steps = 0
        return task_info.Block2RelativeLocationTaskInfo(
            instruction=self._instruction,
            block=self._block,
            location=direction,
            target_translation=self._target_translation,
        )

    def get_goal_region(self):
        return self._target_translation, TARGET_DISTANCE

    def reward(self, state):
        dist = np.linalg.norm(
            self._block_xy(self._block, state)
            - np.array(self._target_translation)
        )
        return self._maybe_goal(dist < TARGET_DISTANCE)
