"""'Separate block X from the other blocks' task.

Parity source: reference
`language_table/environments/rewards/separate_blocks.py`.
"""

import numpy as np

from rt1_tpu.envs import blocks as blocks_module
from rt1_tpu.envs import task_info
from rt1_tpu.envs.rewards import base

# Blocks need "separating" when at least this close together.
CONSIDERED_JOINED_THRESHOLD = 0.08
# How far past the avoid-centroid to push.
MAGNITUDE = 0.1
# Solved when within this distance of the invisible target.
DISTANCE_TO_TARGET_THRESHOLD = 0.025

SEPARATE_TEMPLATES = [
    "pull the %s apart from the %s",
    "move the %s away from the %s",
    "separate the %s from the %s",
]

GROUP_SYNONYMS = ["group", "clump", "group of blocks"]
REST = "rest of the blocks"


def _avoid_phrase(avoid_syns, n_on_table, group_syn, rng=None):
    """Render the list of blocks to move away from as one phrase.

    Mirrors the reference's cascading-if rendering
    (`separate_blocks.py:52-69,113-127`) including its quirk that the REST
    ("rest of the blocks") assignment is always overridden by a later
    branch (len 1-3 or >= 4 cover every case), so REST never actually
    appears in generated instructions — behavioral parity over intent.
    """
    phrase = None
    if len(avoid_syns) == n_on_table - 1:
        phrase = REST
    if len(avoid_syns) == 1:
        phrase = avoid_syns[0]
    if len(avoid_syns) == 2:
        phrase = "%s and %s" % tuple(avoid_syns)
    if len(avoid_syns) == 3:
        specific = "%s, %s, and %s" % tuple(avoid_syns)
        if rng is None:
            phrase = specific
        else:
            phrase = rng.choice([specific, group_syn])
    if len(avoid_syns) >= 4:
        phrase = group_syn
    return phrase


def generate_all_instructions(block_mode):
    out = []
    names = blocks_module.text_descriptions(block_mode)
    for block_syn in names:
        for idx in range(1, len(names)):
            avoid_syns = names[:idx]
            for group_syn in GROUP_SYNONYMS:
                avoid_str = _avoid_phrase(avoid_syns, len(names), group_syn)
                for template in SEPARATE_TEMPLATES:
                    out.append(template % (block_syn, avoid_str))
    return out


def runtime_instructions(block_mode):
    """Sampler-complete: avoid-lists are ordered tuples of OTHER blocks'
    synonyms (sizes 1-3 rendered explicitly, 3 may also use a group
    synonym, >= 4 always does) — the parity enumeration's name-prefix
    orderings cover only a sliver of this. Quadratic-ish in board size;
    intended for the BLOCK_4/BLOCK_8 table configs (N_CHOOSE_K's space is
    astronomically large — use a string-level embedder there).
    """
    import itertools

    groups = blocks_module.synonym_groups(block_mode)
    out = []
    for i, g in enumerate(groups):
        others = [g2 for j, g2 in enumerate(groups) if j != i]
        avoid_strs = list(GROUP_SYNONYMS)  # len 3 group branch and >= 4
        for g2 in others:
            avoid_strs.extend(g2)  # len 1
        for ga, gb in itertools.permutations(others, 2):  # len 2, ordered
            avoid_strs.extend(
                f"{a} and {b}" for a in ga for b in gb
            )
        for ga, gb, gc in itertools.permutations(others, 3):  # len 3
            avoid_strs.extend(
                f"{a}, {b}, and {c}"
                for a in ga
                for b in gb
                for c in gc
            )
        for block_syn in g:
            for avoid_str in avoid_strs:
                for template in SEPARATE_TEMPLATES:
                    out.append(template % (block_syn, avoid_str))
    return out


class SeparateBlocksReward(base.BoardReward):
    """Push the most-crowded block away from its neighbors."""

    def __init__(self, goal_reward, rng, delay_reward_steps, block_mode):
        super().__init__(goal_reward, rng, delay_reward_steps, block_mode)
        self._instruction = None
        self._block = None
        self._avoid_blocks = None
        self._target_translation = None
        self._avoid_centroid_xy = None

    def get_current_task_info(self, state):
        if self._block is None:
            raise ValueError("must call .reset first")
        self._target_translation = self.target_translation_for(
            state, self._block, self._avoid_blocks
        )
        return task_info.SeparateBlocksTaskInfo(
            instruction=self._instruction,
            block=self._block,
            avoid_blocks=self._avoid_blocks,
            target_translation=self._target_translation,
        )

    def _sample_instruction(self, block, avoid_blocks, blocks_on_table):
        block_syn = self._pick_synonym(block, blocks_on_table)
        avoid_syns = [
            self._pick_synonym(b, blocks_on_table) for b in avoid_blocks
        ]
        group_syn = self._rng.choice(GROUP_SYNONYMS)
        avoid_str = _avoid_phrase(
            avoid_syns, len(blocks_on_table), group_syn, rng=self._rng
        )
        template = self._rng.choice(SEPARATE_TEMPLATES)
        return template % (block_syn, avoid_str)

    def _closest_blocks(self, block, block_xy, all_xy):
        dists = sorted(
            (
                (name, np.linalg.norm(block_xy - xy))
                for name, xy in all_xy
                if name != block
            ),
            key=lambda kv: kv[1],
        )
        joined = [kv for kv in dists if kv[1] < CONSIDERED_JOINED_THRESHOLD]
        if not joined:
            return [], np.inf
        return [kv[0] for kv in joined], float(
            np.mean([kv[1] for kv in joined])
        )

    def _blocks_to_separate(self, state, blocks_on_table):
        all_xy = [(b, self._block_xy(b, state)) for b in blocks_on_table]
        xy_of = dict(all_xy)
        candidates = sorted(
            (
                (b, self._closest_blocks(b, xy_of[b], all_xy))
                for b in xy_of
            ),
            key=lambda kv: kv[1][1],
        )
        push_block, (avoid_blocks, avg_dist) = candidates[0]
        return push_block, avoid_blocks, avg_dist

    def _avoid_direction(self, state, push_block, avoid_blocks):
        push_xy = self._block_xy(push_block, state)
        centroid = np.mean(
            [self._block_xy(b, state) for b in avoid_blocks], axis=0
        )
        self._avoid_centroid_xy = centroid
        to_centroid = centroid - push_xy
        to_centroid = to_centroid / (
            np.linalg.norm(to_centroid) + np.finfo(np.float32).eps
        )
        return -to_centroid

    def target_translation_for(self, state, block, avoid_blocks):
        direction = self._avoid_direction(state, block, avoid_blocks)
        return self._avoid_centroid_xy + direction * MAGNITUDE

    def reset(self, state, blocks_on_table):
        tries = 0
        while True:
            push_block, avoid_blocks, _ = self._blocks_to_separate(
                state, blocks_on_table
            )
            if not avoid_blocks:
                # Everything already far apart: no valid task on this board.
                return task_info.FAILURE
            target = self.target_translation_for(
                state, push_block, avoid_blocks
            )
            if base.inside_bounds(target):
                break
            tries += 1
            if tries > 100:
                return task_info.FAILURE
        return self.reset_to(state, push_block, avoid_blocks, blocks_on_table)

    def reset_to(self, state, block, avoid_blocks, blocks_on_table):
        self._block = block
        self._avoid_blocks = avoid_blocks
        self._target_translation = self.target_translation_for(
            state, block, avoid_blocks
        )
        self._instruction = self._sample_instruction(
            block, avoid_blocks, blocks_on_table
        )
        self._in_reward_zone_steps = 0
        return self.get_current_task_info(state)

    @property
    def target_translation(self):
        return self._target_translation

    def reward(self, state):
        return self.reward_for(state, self._block, self._target_translation)

    def reward_for(self, state, push_block, target_translation):
        dist = np.linalg.norm(
            self._block_xy(push_block, state) - target_translation
        )
        return self._maybe_goal(dist < DISTANCE_TO_TARGET_THRESHOLD)

    def reward_for_info(self, state, info):
        return self.reward_for(
            state, push_block=info.block,
            target_translation=info.target_translation,
        )

    def debug_info(self, state):
        return np.linalg.norm(
            self._block_xy(self._block, state) - self._target_translation
        )
