"""'Push the block to the bottom-left corner' single-corner task.

Parity source: reference
`language_table/environments/rewards/block1_to_corner.py`.
"""

import enum

import numpy as np

from rt1_tpu.envs import blocks as blocks_module
from rt1_tpu.envs import language, task_info
from rt1_tpu.envs.rewards import base

_BUFFER = 0.08
X_MAX = 0.6
Y_MIN = -0.3048

TARGET_DISTANCE = 0.08


class Locations(enum.Enum):
    BOTTOM_LEFT = "bottom_left"


ABSOLUTE_LOCATIONS = {
    "bottom_left": [X_MAX - _BUFFER, Y_MIN + _BUFFER],
}

LOCATION_SYNONYMS = {
    "bottom_left": [
        "bottom left of the board",
        "bottom left",
        "bottom left corner",
    ],
}

VERBS = [
    "move the",
    "push the",
    "slide the",
]


def generate_all_instructions(block_mode, verbs=None, names=None):
    """Pass `verbs=language.PUSH_VERBS` / synonym names for the sampler's
    actual spaces (see `rewards.generate_runtime_instructions`)."""
    out = []
    verbs = VERBS if verbs is None else verbs
    if names is None:
        names = blocks_module.text_descriptions(block_mode)
    for block_text in names:
        for location in ABSOLUTE_LOCATIONS:
            for location_syn in LOCATION_SYNONYMS[location]:
                for verb in verbs:
                    out.append(f"{verb} {block_text} to the {location_syn}")
    return out


def runtime_instructions(block_mode):
    """Sampler-complete: PUSH_VERBS (the sampler's list) x all synonyms."""
    flat = [v for g in blocks_module.synonym_groups(block_mode) for v in g]
    return generate_all_instructions(
        block_mode, verbs=language.PUSH_VERBS, names=flat
    )


class BlockToCornerReward(base.BoardReward):
    """Sparse reward when the chosen block reaches the corner region."""

    def __init__(self, goal_reward, rng, delay_reward_steps, block_mode):
        super().__init__(goal_reward, rng, delay_reward_steps, block_mode)
        self._block = None
        self._instruction = None
        self._location = None
        self._target_translation = None

    def _sample_instruction(self, block, blocks_on_table, location):
        verb = self._rng.choice(language.PUSH_VERBS)
        block_text = self._pick_synonym(block, blocks_on_table)
        location_syn = self._rng.choice(LOCATION_SYNONYMS[location])
        return f"{verb} {block_text} to the {location_syn}"

    def reset(self, state, blocks_on_table):
        block = self._pick_block(blocks_on_table)
        location = self._rng.choice(list(sorted(ABSOLUTE_LOCATIONS.keys())))
        info = self.reset_to(state, block, location, blocks_on_table)
        # Reject boards that already satisfy the task. A plain reward() call
        # would miss this under delay_reward_steps > 0 (and bump the zone
        # counter); check the goal region directly.
        dist = np.linalg.norm(
            self._block_xy(self._block, state)
            - np.array(self._target_translation)
        )
        if dist < TARGET_DISTANCE:
            return task_info.FAILURE
        return info

    def reset_to(self, state, block, location, blocks_on_table):
        self._block = block
        self._instruction = self._sample_instruction(
            block, blocks_on_table, location
        )
        self._target_translation = np.copy(ABSOLUTE_LOCATIONS[location])
        self._location = location
        info = self.get_current_task_info(state)
        self._in_reward_zone_steps = 0
        return info

    @property
    def target_translation(self):
        return self._target_translation

    def reward(self, state):
        return self.reward_for(state, self._block, self._target_translation)

    def reward_for(self, state, pushing_block, target_translation):
        dist = np.linalg.norm(
            self._block_xy(pushing_block, state)
            - np.array(target_translation)
        )
        return self._maybe_goal(dist < TARGET_DISTANCE)

    def reward_for_info(self, state, info):
        return self.reward_for(state, info.block, info.target_translation)

    def debug_info(self, state):
        return np.linalg.norm(
            self._block_xy(self._block, state)
            - np.array(self._target_translation)
        )

    def get_current_task_info(self, state):
        return task_info.Block2LocationTaskInfo(
            instruction=self._instruction,
            block=self._block,
            location=self._location,
            target_translation=self._target_translation,
        )
