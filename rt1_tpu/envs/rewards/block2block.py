"""'Push block X to block Y' task — the headline eval task.

Parity source: reference `language_table/environments/rewards/block2block.py`.
"""

import itertools

import numpy as np

from rt1_tpu.envs import blocks as blocks_module
from rt1_tpu.envs import constants, language, task_info
from rt1_tpu.envs.rewards import base


def generate_all_instructions(block_mode):
    """Every literal instruction this family can emit, canonical names only."""
    out = []
    names = blocks_module.text_descriptions(block_mode)
    for start_text, target_text in itertools.permutations(names, 2):
        for verb in language.PUSH_VERBS:
            for prep in language.PREPOSITIONS:
                out.append(f"{verb} {start_text} {prep} {target_text}")
    return out


def runtime_instructions(block_mode):
    """Sampler-complete: every synonym pairing `_sample_instruction` can
    emit (the parity enumeration above is canonical names only)."""
    out = []
    for g1, g2 in itertools.permutations(
        blocks_module.synonym_groups(block_mode), 2
    ):
        for start_text in g1:
            for target_text in g2:
                for verb in language.PUSH_VERBS:
                    for prep in language.PREPOSITIONS:
                        out.append(f"{verb} {start_text} {prep} {target_text}")
    return out


class BlockToBlockReward(base.BoardReward):
    """Sparse reward when the start block reaches the target block."""

    def _sample_instruction(self, start_block, target_block, blocks_on_table):
        verb = self._rng.choice(language.PUSH_VERBS)
        start_syn = self._pick_synonym(start_block, blocks_on_table)
        target_syn = self._pick_synonym(target_block, blocks_on_table)
        prep = self._rng.choice(language.PREPOSITIONS)
        return f"{verb} {start_syn} {prep} {target_syn}"

    def reset(self, state, blocks_on_table):
        """Pick two blocks far enough apart; FAILURE after 10 tries."""
        attempts = 0
        while True:
            start_block, target_block = self._pick_two_blocks(blocks_on_table)
            dist = np.linalg.norm(
                self._block_xy(start_block, state)
                - self._block_xy(target_block, state)
            )
            if dist < constants.TARGET_BLOCK_DISTANCE + 0.01:
                attempts += 1
                if attempts > 10:
                    return task_info.FAILURE
                continue
            break
        self._start_block = start_block
        self._target_block = target_block
        self._instruction = self._sample_instruction(
            start_block, target_block, blocks_on_table
        )
        self._in_reward_zone_steps = 0
        return task_info.Block2BlockTaskInfo(
            instruction=self._instruction,
            block1=start_block,
            block2=target_block,
        )

    def get_goal_region(self):
        return self._target_translation, constants.TARGET_BLOCK_DISTANCE

    def reward(self, state):
        start_xy = self._block_xy(self._start_block, state)
        target_xy = self._block_xy(self._target_block, state)
        self._target_translation = target_xy
        dist = np.linalg.norm(start_xy - target_xy)
        return self._maybe_goal(dist < constants.TARGET_BLOCK_DISTANCE)
