"""'Push block X to <absolute board location>' task.

Parity source: reference
`language_table/environments/rewards/block2absolutelocation.py`.
"""

import enum

import numpy as np

from rt1_tpu.envs import blocks as blocks_module
from rt1_tpu.envs import language, task_info
from rt1_tpu.envs.rewards import base

# The arm's reachable bounds are offset slightly from the board center in x;
# absolute named locations compensate (reference `block2absolutelocation.py:28-46`).
_X_BUFFER = 0.025
X_MIN = 0.15 - _X_BUFFER
X_MAX = 0.6 - _X_BUFFER
Y_MIN = -0.3048
Y_MAX = 0.3048
CENTER_X = (X_MAX - X_MIN) / 2.0 + X_MIN
CENTER_Y = (Y_MAX - Y_MIN) / 2.0 + Y_MIN

TARGET_DISTANCE = 0.115
CENTER_TARGET_DISTANCE = 0.1


class Locations(enum.Enum):
    TOP = "top"
    TOP_LEFT = "top_left"
    TOP_RIGHT = "top_right"
    CENTER = "center"
    CENTER_LEFT = "center_left"
    CENTER_RIGHT = "center_right"
    BOTTOM = "bottom"
    BOTTOM_LEFT = "bottom_left"
    BOTTOM_RIGHT = "bottom_right"


ABSOLUTE_LOCATIONS = {
    "top": [X_MIN, CENTER_Y],
    "top_left": [X_MIN, Y_MIN],
    "top_right": [X_MIN, Y_MAX],
    "center": [CENTER_X, CENTER_Y],
    "center_left": [CENTER_X, Y_MIN],
    "center_right": [CENTER_X, Y_MAX],
    "bottom": [X_MAX, CENTER_Y],
    "bottom_left": [X_MAX, Y_MIN],
    "bottom_right": [X_MAX, Y_MAX],
}

LOCATION_SYNONYMS = {
    "top": ["top side", "top", "towards your base"],
    "top_left": [
        "top left of the board",
        "top left",
        "upper left corner",
        "top left corner",
    ],
    "top_right": [
        "top right of the board",
        "top right",
        "upper right corner",
        "top right corner",
    ],
    "center": [
        "middle of the board",
        "center of the board",
        "center",
        "middle",
    ],
    "center_left": ["left side of the board", "center left", "left side"],
    "center_right": ["right side of the board", "center right", "right side"],
    "bottom": ["bottom side", "bottom"],
    "bottom_left": [
        "bottom left of the board",
        "bottom left",
        "lower left corner",
        "bottom left corner",
    ],
    "bottom_right": [
        "bottom right of the board",
        "bottom right",
        "lower right corner",
        "bottom right corner",
    ],
}

VERBS = [
    "move the",
    "push the",
    "slide the",
]


def generate_all_instructions(block_mode, verbs=None, names=None):
    """Enumeration mirrors the reference's 3-verb list and canonical names
    by default; `runtime_instructions` passes the sampler's actual spaces
    (see `rewards.generate_runtime_instructions`)."""
    out = []
    verbs = VERBS if verbs is None else verbs
    if names is None:
        names = blocks_module.text_descriptions(block_mode)
    for block_text in names:
        for location in ABSOLUTE_LOCATIONS:
            for location_syn in LOCATION_SYNONYMS[location]:
                for verb in verbs:
                    out.append(f"{verb} {block_text} to the {location_syn}")
    return out


def runtime_instructions(block_mode):
    """Sampler-complete: PUSH_VERBS (the sampler's list) x all synonyms."""
    flat = [v for g in blocks_module.synonym_groups(block_mode) for v in g]
    return generate_all_instructions(
        block_mode, verbs=language.PUSH_VERBS, names=flat
    )


class BlockToAbsoluteLocationReward(base.BoardReward):
    """Sparse reward when the block reaches a named board region."""

    def __init__(self, goal_reward, rng, delay_reward_steps, block_mode):
        super().__init__(goal_reward, rng, delay_reward_steps, block_mode)
        self._block = None
        self._instruction = None
        self._location = None
        self._target_translation = None

    def _sample_instruction(self, block, blocks_on_table, location):
        # NOTE: samples the verb from the generic push-verb list, matching the
        # reference (`block2absolutelocation.py:127-136`), which differs from
        # the 3-verb list used for enumeration.
        verb = self._rng.choice(language.PUSH_VERBS)
        block_text = self._pick_synonym(block, blocks_on_table)
        location_syn = self._rng.choice(LOCATION_SYNONYMS[location])
        return f"{verb} {block_text} to the {location_syn}"

    def reset(self, state, blocks_on_table):
        block = self._pick_block(blocks_on_table)
        location = self._rng.choice(list(sorted(ABSOLUTE_LOCATIONS.keys())))
        info = self.reset_to(state, block, location, blocks_on_table)
        if self._in_goal_region(state, self._block, self._target_translation):
            # Board already satisfies the task; ask the env to re-randomize.
            return task_info.FAILURE
        return info

    def reset_to(self, state, block, location, blocks_on_table):
        self._block = block
        self._instruction = self._sample_instruction(
            block, blocks_on_table, location
        )
        self._target_translation = np.copy(ABSOLUTE_LOCATIONS[location])
        self._location = location
        info = self.get_current_task_info(state)
        self._in_reward_zone_steps = 0
        return info

    @property
    def target_translation(self):
        return self._target_translation

    def _radius(self):
        if self._location == Locations.CENTER.value:
            return CENTER_TARGET_DISTANCE
        return TARGET_DISTANCE

    def get_goal_region(self):
        return self._target_translation, self._radius()

    def _in_goal_region(self, state, block, target_translation):
        dist = np.linalg.norm(
            self._block_xy(block, state) - np.array(target_translation)
        )
        return dist < self._radius()

    def reward(self, state):
        return self.reward_for(state, self._block, self._target_translation)

    def reward_for(self, state, pushing_block, target_translation):
        return self._maybe_goal(
            self._in_goal_region(state, pushing_block, target_translation)
        )

    def reward_for_info(self, state, info):
        return self.reward_for(state, info.block, info.target_translation)

    def debug_info(self, state):
        return np.linalg.norm(
            self._block_xy(self._block, state)
            - np.array(self._target_translation)
        )

    def get_current_task_info(self, state):
        return task_info.Block2LocationTaskInfo(
            instruction=self._instruction,
            block=self._block,
            location=self._location,
            target_translation=self._target_translation,
        )
