"""Top-down board renderer (PIL) + instruction overlay.

Replaces the reference's PyBullet TINY_RENDERER camera render
(`language_table.py:579-597`) and cv2 text overlay (`:1000-1029`) with a
dependency-light orthographic render of the board: colored block shapes,
effector, and workspace. The visual domain is consistent between data
collection and eval within this framework (pixel parity with PyBullet's
perspective render is impossible without PyBullet).
"""

import textwrap

import numpy as np
from PIL import Image, ImageDraw, ImageFont

from rt1_tpu.envs import constants

BOARD_COLOR = (90, 90, 95)
BORDER_COLOR = (50, 50, 55)
EFFECTOR_COLOR = (20, 20, 20)
EFFECTOR_RING = (230, 230, 230)

BLOCK_COLORS = {
    "red": (205, 60, 50),
    "blue": (60, 90, 205),
    "green": (60, 160, 70),
    "yellow": (230, 200, 50),
    "purple": (140, 60, 200),
}

# Margin of world space drawn around the workspace (meters).
_MARGIN = 0.02


def _world_to_px(xy, image_size):
    """Map board (x, y) to pixel (col, row). x spans image rows (top=X_MIN)."""
    h, w = image_size
    x, y = xy
    row = (x - (constants.X_MIN - _MARGIN)) / (
        (constants.X_MAX - constants.X_MIN) + 2 * _MARGIN
    ) * h
    col = (y - (constants.Y_MIN - _MARGIN)) / (
        (constants.Y_MAX - constants.Y_MIN) + 2 * _MARGIN
    ) * w
    return col, row


def _scale(image_size):
    """Pixels per meter (row axis)."""
    h, _ = image_size
    return h / ((constants.X_MAX - constants.X_MIN) + 2 * _MARGIN)


def _shape_points(shape, yaw, radius):
    """Unit outline for a block shape, rotated by yaw, scaled to radius."""
    if shape == "cube":
        angles = np.array([0.25, 0.75, 1.25, 1.75]) * np.pi
        pts = np.stack([np.cos(angles), np.sin(angles)], -1) * 1.25
    elif shape == "pentagon":
        angles = np.linspace(0, 2 * np.pi, 5, endpoint=False) - np.pi / 2
        pts = np.stack([np.cos(angles), np.sin(angles)], -1) * 1.2
    elif shape == "star":
        angles = np.linspace(0, 2 * np.pi, 10, endpoint=False) - np.pi / 2
        radii = np.where(np.arange(10) % 2 == 0, 1.45, 0.62)
        pts = np.stack([np.cos(angles), np.sin(angles)], -1) * radii[:, None]
    elif shape == "moon":
        # Crescent: approximated by an outer arc + offset inner arc.
        outer = np.linspace(-0.75 * np.pi, 0.75 * np.pi, 12)
        inner = np.linspace(0.6 * np.pi, -0.6 * np.pi, 12)
        pts = np.concatenate([
            np.stack([np.cos(outer), np.sin(outer)], -1) * 1.25,
            np.stack([np.cos(inner) * 0.85 + 0.45, np.sin(inner) * 0.85], -1),
        ])
    elif shape == "pole":
        pts = np.array(
            [[-0.5, -1.6], [0.5, -1.6], [0.5, 1.6], [-0.5, 1.6]]
        )
    else:
        angles = np.linspace(0, 2 * np.pi, 12, endpoint=False)
        pts = np.stack([np.cos(angles), np.sin(angles)], -1)
    c, s = np.cos(yaw), np.sin(yaw)
    rot = np.array([[c, -s], [s, c]])
    return pts @ rot.T * radius


def render_board(block_poses, effector_xy, image_size=None, goal_region=None):
    """Render the board state to an RGB uint8 array.

    Args:
      block_poses: {block_name: (xy, yaw)} for blocks on the table.
      effector_xy: (x, y) of the effector cylinder.
      image_size: (height, width); defaults to the reference camera size.
      goal_region: optional (target_xy, radius) drawn as a translucent ring.
    """
    if image_size is None:
        image_size = (constants.IMAGE_HEIGHT, constants.IMAGE_WIDTH)
    h, w = image_size
    img = Image.new("RGB", (w, h), BORDER_COLOR)
    draw = ImageDraw.Draw(img, "RGBA")

    # Workspace surface.
    x0, y0 = _world_to_px((constants.X_MIN, constants.Y_MIN), image_size)
    x1, y1 = _world_to_px((constants.X_MAX, constants.Y_MAX), image_size)
    draw.rectangle([x0, y0, x1, y1], fill=BOARD_COLOR)

    px_per_m = _scale(image_size)

    if goal_region is not None and goal_region[0] is not None:
        gx, gy = _world_to_px(goal_region[0], image_size)
        gr = goal_region[1] * px_per_m
        draw.ellipse([gx - gr, gy - gr, gx + gr, gy + gr],
                     outline=(0, 255, 0, 160), width=2)

    from rt1_tpu.envs.backends.kinematic import BLOCK_RADIUS, EFFECTOR_RADIUS

    for name, (xy, yaw) in block_poses.items():
        color_name, shape = name.split("_")
        color = BLOCK_COLORS.get(color_name, (128, 128, 128))
        cx, cy = _world_to_px(xy, image_size)
        pts = _shape_points(shape, yaw, BLOCK_RADIUS * px_per_m)
        # world (x -> row, y -> col): point offsets are (dy -> px, dx -> py).
        poly = [(cx + float(p[1]), cy + float(p[0])) for p in pts]
        draw.polygon(poly, fill=color, outline=tuple(int(c * 0.6) for c in color))

    ex, ey = _world_to_px(effector_xy, image_size)
    er = EFFECTOR_RADIUS * px_per_m * 1.4
    draw.ellipse([ex - er, ey - er, ex + er, ey + er], fill=EFFECTOR_COLOR)
    draw.ellipse([ex - er, ey - er, ex + er, ey + er],
                 outline=EFFECTOR_RING, width=1)

    return np.asarray(img, dtype=np.uint8)


def add_debug_info_to_image(image, info_dict):
    """Upscale to 640x360 and draw the wrapped instruction above the frame.

    Mirrors the reference overlay layout (`language_table.py:1000-1029`):
    resize to 640x360, prepend a white strip, wrap at 35 chars.
    """
    img = Image.fromarray(image).resize((640, 360), Image.BILINEAR)
    text = ""
    if "instruction" in info_dict:
        text = "instruction: %s" % info_dict["instruction"]
    wrapped = textwrap.wrap(text, width=35)
    strip_h = int(3 * int(360 * 0.08))
    canvas = Image.new("RGB", (640, 360 + strip_h), (255, 255, 255))
    canvas.paste(img, (0, strip_h))
    draw = ImageDraw.Draw(canvas)
    font = ImageFont.load_default()
    y = 2
    for line in wrapped:
        draw.text((2, y), line, fill=(0, 0, 0), font=font)
        y += 14
    return np.asarray(canvas, dtype=np.uint8)
