"""Mutual exclusion for the single attached axon TPU chip — as a mechanism.

Two full round sessions of TPU evidence were lost to *claim wedges*: an axon
client killed (or exiting) mid-claim leaves the chip grant held server-side,
and every later claim hangs ~25 min then fails UNAVAILABLE, for hours
(RESULTS.md round-2/round-3 timelines). The no-exceptions "prefix every
CPU-only python with PALLAS_AXON_POOL_IPS=" rule lived only in process
documentation (.claude/skills/verify/SKILL.md) and failed in round 3 — one
unprefixed one-liner cost a 10+ hour TPU window.

This module is the in-code guard (VERDICT r3 "next round" #2):

* a **claim lockfile** (default `<repo>/.chip_claim.lock`) records which
  process may talk to the chip.  `bench.py`, `scripts/tpu_validation.py` and
  `scripts/learn_proof.py` acquire it before any backend init.
* an **import-time guard** (`guard()`, called from `rt1_tpu/__init__`)
  auto-enrolls any axon-enabled process that imports the framework: it
  either takes the lock or — when a *different live* process holds it —
  refuses loudly with the holder's identity, long before the process can
  dial the relay and collide with the in-flight claim.
* a **token umbrella** (`RT1_CHIP_CLAIM_TOKEN`) lets an owner's
  subprocesses (claim probes, bench children) run under the parent's claim
  instead of dead-locking against it.

The prefix rule remains as a backstop for processes that never import
`rt1_tpu` (see `.claude/skills/verify/SKILL.md`), but the catastrophic case
— two framework processes claiming concurrently — is now refused by code.

Stdlib-only on purpose: it must be importable before (and without) jax.

The reference has no equivalent subsystem — its GPUs are process-local and
a crashed client releases them with the process.  A tunneled, leased TPU
chip makes claim lifetime a first-class failure domain, so the framework
gets a first-class mechanism for it.
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import time
import uuid

LOCK_ENV = "RT1_CHIP_CLAIM_LOCK"
TOKEN_ENV = "RT1_CHIP_CLAIM_TOKEN"
DISABLE_ENV = "RT1_CHIP_GUARD_DISABLE"
# Set by entrypoints that manage the claim lifecycle themselves (bench.py,
# scripts/tpu_validation.py, scripts/learn_proof.py) BEFORE importing
# rt1_tpu: the import-time guard then stays out of the way so their
# explicit acquire() owns the claim (patient waits, probe transfer,
# friendly exit codes). Without this, guard()'s import-time acquisition
# would preempt the explicit one into a powerless umbrella claim.
SELF_MANAGED_ENV = "RT1_CHIP_GUARD_SELF"

# Lock holders are always python processes (the lock is written by this
# module).  A recycled pid whose cmdline is not python is therefore stale.
_HOLDER_CMD_MARKERS = (b"python", b"pytest")


class ChipClaimHeld(RuntimeError):
    """Another live process holds the chip claim lock."""


def lock_path() -> str:
    path = os.environ.get(LOCK_ENV)
    if path:
        return path
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(repo, ".chip_claim.lock")


def axon_active() -> bool:
    """Whether this process would dial the axon relay on jax backend init.

    The CPU prefix (`PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu`) makes this
    False; the production env (`PALLAS_AXON_POOL_IPS=127.0.0.1`,
    `JAX_PLATFORMS=axon`) makes it True.
    """
    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return False
    platforms = os.environ.get("JAX_PLATFORMS", "").lower()
    if not platforms:
        # No explicit platform + a registered axon plugin: jax would pick
        # the accelerator backend, i.e. dial.
        return True
    return "axon" in platforms or "tpu" in platforms


def _pid_start(pid: int) -> int | None:
    """Kernel start time (clock ticks since boot) of `pid`, or None.

    /proc/<pid>/stat field 22; parsed from after the last ')' because the
    comm field may itself contain spaces or parens.
    """
    try:
        with open(f"/proc/{int(pid)}/stat", "rb") as f:
            stat = f.read()
        rest = stat[stat.rindex(b")") + 1:].split()
        # rest[0] is field 3 (state); starttime is field 22 -> rest[19].
        return int(rest[19])
    except (OSError, ValueError, IndexError):
        return None


def _pid_alive(pid: int, expected_start: int | None = None) -> bool:
    try:
        with open(f"/proc/{int(pid)}/cmdline", "rb") as f:
            cmdline = f.read()
    except (OSError, ValueError):
        return False
    if expected_start is not None:
        # Pid-recycling detector (ADVICE r4): the lock records the holder's
        # kernel start time; a same-pid process with a different start time
        # is a recycled pid, not the holder — without this, any long-lived
        # python process that reuses the pid makes a stale lock look held
        # forever (blocking all claims until a manual `clear`).
        actual = _pid_start(pid)
        if actual is not None and actual != expected_start:
            return False
    if not cmdline.strip(b"\0"):
        # Mid-exec (fork->exec window) or zombie: the pid exists but its
        # cmdline is momentarily empty. Err on the side of "alive" — a
        # false "dead" here green-lights the concurrent-claim collision
        # this module exists to prevent, while a false "alive" merely
        # waits/refuses until the state resolves.
        return True
    return any(m in cmdline for m in _HOLDER_CMD_MARKERS)


def _record_alive(record: dict) -> bool:
    """Liveness of a lock record's holder, start-time-verified when the
    record carries one (records from older code lack `pid_start`)."""
    return _pid_alive(record.get("pid", -1), record.get("pid_start"))


def holder(path: str | None = None) -> dict | None:
    """The current lock record, or None when unlocked/corrupt."""
    path = path or lock_path()
    try:
        with open(path) as f:
            record = json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return None
    return record if isinstance(record, dict) and "pid" in record else None


class Claim:
    """A held (or inherited) chip claim.  Context-manager; release() is
    idempotent and only ever deletes a lockfile this claim owns."""

    def __init__(self, path: str, token: str, owned: bool):
        self.path = path
        self.token = token
        self.owned = owned
        self._released = False

    def release(self) -> None:
        if self._released or not self.owned:
            self._released = True
            return
        self._released = True
        record = holder(self.path)
        if record and record.get("token") == self.token:
            try:
                os.unlink(self.path)
            except OSError:
                pass

    def transfer(self, pid: int, tag: str) -> None:
        """Hand the lock to `pid` (e.g. a dangling claim probe that must be
        left to its own ~25-min client-side give-up rather than killed).
        The lock then expires via the pid-liveness check when `pid` exits.
        """
        if not self.owned:
            return  # an umbrella claim has nothing to hand over
        _write_lock(self.path, pid=pid, tag=tag, token=self.token)
        self.owned = False  # the dangling child owns it now; never unlink

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


def _reap(path: str, observed: dict | None) -> bool:
    """Atomically remove a lock we observed as stale/corrupt.

    Blind `os.unlink(path)` is a TOCTOU: between our read and the unlink,
    another process may have reaped the same stale lock and linked a fresh
    valid one — the unlink would then destroy a live claim and let two
    owners dial the chip. Rename-to-private-name is atomic (exactly one
    reaper wins); the content check afterwards restores a lock that turned
    out to be someone's fresh one.
    """
    victim = f"{path}.{os.getpid()}.reap"
    try:
        os.rename(path, victim)
    except OSError:
        return False  # someone else reaped or replaced it first; re-examine
    try:
        with open(victim) as f:
            current = json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        current = None
    if current is None or current == observed:
        try:
            os.unlink(victim)
        except OSError:
            pass
        return True
    # Raced: we renamed a FRESH lock someone linked after our read. Put it
    # back (link fails only if yet another lock appeared meanwhile — then
    # nothing safe remains to do and the next acquire() sorts it out).
    try:
        os.link(victim, path)
    except OSError:
        pass
    try:
        os.unlink(victim)
    except OSError:
        pass
    return False


def _write_lock(path: str, *, pid: int, tag: str, token: str) -> None:
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(
            {"pid": pid, "tag": tag, "token": token,
             "pid_start": _pid_start(pid), "created": time.time()},
            f,
        )
    os.replace(tmp, path)


def _held_message(record: dict, path: str) -> str:
    age = time.time() - record.get("created", time.time())
    return (
        f"TPU chip claim is held by pid {record.get('pid')} "
        f"(tag={record.get('tag')!r}, {age / 60:.1f} min old, lock={path}). "
        f"Starting a second axon client now would collide with the "
        f"in-flight claim and can wedge the chip for hours "
        f"(RESULTS.md round-3 timeline). Wait for the holder to exit, run "
        f"CPU-only (PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu), or — if the "
        f"holder is provably not talking to the chip — remove the lock "
        f"with: PALLAS_AXON_POOL_IPS= python -m rt1_tpu.chip_claim clear "
        f"(the CPU prefix keeps the CLI itself outside the guard)"
    )


def acquire(tag: str, path: str | None = None, wait_s: float = 0.0,
            poll_s: float = 10.0) -> Claim:
    """Take the chip-claim lock (or join the parent's via the token env).

    Raises ChipClaimHeld when a different live process holds it and it does
    not free up within `wait_s`.  On success the claim token is exported to
    `RT1_CHIP_CLAIM_TOKEN` so subprocesses inherit the umbrella, and an
    atexit release is registered (SIGKILL'd owners are reaped by the
    pid-liveness check on the next acquire).
    """
    path = path or lock_path()
    my_token = os.environ.get(TOKEN_ENV)
    deadline = time.monotonic() + wait_s
    while True:
        token = my_token or uuid.uuid4().hex
        # Atomic create-with-content: write a private tmp, hard-link it into
        # place (link fails iff the lock exists). A bare O_EXCL-create-then-
        # write would expose an empty file that a concurrent acquirer reads
        # as corrupt and unlinks — both processes then "own" the chip.
        tmp = f"{path}.{os.getpid()}.acquire"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "pid": os.getpid(),
                    "tag": tag,
                    "token": token,
                    "pid_start": _pid_start(os.getpid()),
                    "created": time.time(),
                },
                f,
            )
        try:
            os.link(tmp, path)
        except FileExistsError:
            record = holder(path)
            if record is None:
                # Corrupt or vanished mid-read: reap (atomically) and retry.
                if os.path.exists(path):
                    _reap(path, None)
                continue
            if not _record_alive(record):
                # Stale: holder died (possibly SIGKILL'd — atexit skipped).
                # Checked BEFORE the token umbrella: a child inheriting the
                # token of a dead parent must not join a defunct umbrella
                # that a concurrent fresh acquirer is about to reap.
                _reap(path, record)
                continue
            if my_token and record.get("token") == my_token:
                # Live parent holds the lock; run under its umbrella.
                return Claim(path, my_token, owned=False)
            if time.monotonic() < deadline:
                time.sleep(poll_s)
                continue
            raise ChipClaimHeld(_held_message(record, path))
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        claim = Claim(path, token, owned=True)
        os.environ[TOKEN_ENV] = token
        atexit.register(claim.release)
        return claim


_GUARD_CLAIM: Claim | None = None


def guard() -> None:
    """Import-time enrollment, called from `rt1_tpu/__init__`.

    CPU-pinned processes pass through untouched.  An axon-enabled process
    either takes the claim lock (becoming the one allowed claimant) or —
    when a different live process holds it — gets a loud refusal *before*
    any backend init can dial the relay.  `RT1_CHIP_GUARD_DISABLE=1` is the
    escape hatch.
    """
    global _GUARD_CLAIM
    if os.environ.get(DISABLE_ENV) == "1":
        return
    if os.environ.get(SELF_MANAGED_ENV) == "1":
        # bench/tpu_validation/learn_proof manage the claim themselves;
        # an import-time acquisition here would demote their explicit
        # acquire() to a powerless umbrella (no transfer, no patience).
        return
    if not axon_active():
        return
    if _GUARD_CLAIM is not None:
        return
    prog = os.path.basename(sys.argv[0]) if sys.argv and sys.argv[0] else "python"
    _GUARD_CLAIM = acquire(f"import:{prog}:{os.getpid()}")


def main(argv=None) -> int:
    """`python -m rt1_tpu.chip_claim {status|clear}` operator CLI.

    Run it CPU-prefixed (`PALLAS_AXON_POOL_IPS= python -m ...`): unprefixed
    in the axon env, importing the package runs guard(), which would refuse
    against a live holder before this function is reached. Against a STALE
    lock the guard auto-acquires — released here so status/clear report the
    external state, not this CLI process itself.
    """
    global _GUARD_CLAIM
    if _GUARD_CLAIM is not None:
        _GUARD_CLAIM.release()
        _GUARD_CLAIM = None
    argv = sys.argv[1:] if argv is None else argv
    cmd = argv[0] if argv else "status"
    path = lock_path()
    record = holder(path)
    if cmd == "status":
        if record is None:
            print(json.dumps({"locked": False, "path": path}))
        else:
            print(
                json.dumps(
                    {
                        "locked": True,
                        "path": path,
                        "holder": record,
                        "holder_alive": _record_alive(record),
                    }
                )
            )
        return 0
    if cmd == "clear":
        if record is not None and _record_alive(record):
            print(
                f"refusing to clear: holder pid {record['pid']} is alive "
                f"({record.get('tag')!r}). Kill/stop it first (SIGINT, "
                f"never SIGKILL mid-claim), or pass its death to the "
                f"stale-reaper by just retrying your command.",
                file=sys.stderr,
            )
            return 1
        if record is not None:
            _reap(path, record)
        else:
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
        print(json.dumps({"cleared": True, "path": path}))
        return 0
    print(f"unknown command {cmd!r}; use: status | clear", file=sys.stderr)
    return 2


if __name__ == "__main__":
    # `python -m` executes this file as a distinct `__main__` module while
    # the package __init__'s guard() ran in the canonical
    # `rt1_tpu.chip_claim` instance — dispatch there so main() can see
    # (and release) the guard's claim.
    from rt1_tpu import chip_claim as _canonical

    raise SystemExit(_canonical.main())
