"""Checkpoint/config identity stamping for training workdirs.

A checkpoint silently restores into a *differently configured* model when
no parameter shape depends on the mismatched knob (e.g.
`time_sequence_length` — the positional embedding is fixed at
max(256, tokens)), and the resulting eval records garbage success rates
attributed to the wrong config. The reference has no guard for this
(`/root/reference/language_table/eval/main_rt1.py` trusts its flags);
here the training run stamps its identity into `train_meta.json` and every
consumer validates against it before restoring.

Extracted from `scripts/learn_proof.py` (VERDICT r4 weak #7).
"""

from __future__ import annotations

import json
import os

META_NAME = "train_meta.json"


def stamp_train_meta(train_dir: str, values: dict) -> None:
    """Record the training run's identity. Called on FRESH starts only —
    resuming runs treat the recorded file as ground truth and must never
    restamp it from current flags."""
    os.makedirs(train_dir, exist_ok=True)
    with open(os.path.join(train_dir, META_NAME), "w") as f:
        json.dump(values, f, indent=2)


def check_train_meta(train_dir: str, context: str, expected: dict,
                     log=print) -> None:
    """Raise ValueError when `expected` disagrees with the recorded meta.

    Only keys present in BOTH are compared: the recorded file is the
    authority for what was checked at training time, and a workdir predating
    the stamp (no file) passes with a notice rather than blocking eval of
    old checkpoints.
    """
    path = os.path.join(train_dir, META_NAME)
    if not os.path.exists(path):
        log(f"{context}: no {META_NAME} (pre-r3 workdir); skipping check")
        return
    with open(path) as f:
        recorded = json.load(f)
    mismatches = {
        k: (recorded[k], expected[k])
        for k in expected
        if k in recorded and recorded[k] != expected[k]
    }
    if mismatches:
        raise ValueError(
            f"{context}: flags disagree with the checkpoint's training config "
            f"{path}: {mismatches}. Pass the training-time flags (or retrain)."
        )
