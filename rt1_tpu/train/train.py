"""The full SPMD training loop + absl CLI.

Replaces `distribute_train.py:192-247` (Lightning Trainer.fit over DDP) and
`language_table/train/train.py:60-218` (pmap loop) with one mesh-wide jitted
step driven by a host loop: restore-or-initialize, per-step trace annotation,
periodic metrics/checkpoint/eval, throughput accounting, and — via
`config.resilience` (rt1_tpu/resilience/, docs/resilience.md) — NaN
guardrails with checkpoint rollback, preemption-safe save-and-exit, and
retried I/O.

Run:
  python -m rt1_tpu.train.train --config rt1_tpu/train/configs/tiny.py \
      --workdir /tmp/rt1
"""

from __future__ import annotations

import functools
import os
from typing import Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from rt1_tpu.specs import language_table_action_space, sample_space
from rt1_tpu.trainer import (
    create_train_state,
    make_optimizer,
    make_train_step_fns,
)
from rt1_tpu.trainer.checkpoints import CheckpointConfig, CheckpointManager
from rt1_tpu.trainer.metrics import (
    ThroughputMeter,
    create_writer,
    log_parameter_overview,
    scalars_from_metrics,
    step_trace,
    write_hparams,
)


def build_model(model_config, mesh=None):
    """Construct the RT-1 policy from `config.model`.

    `mesh` enables mesh-coupled features: a >1 "stage" axis pipelines the
    decoder (GPipe, parallel/pipeline.py), a >1 "seq" axis is required for
    attention_impl="ring". Eval/restore callers may omit it — parameter
    layout does not depend on the mesh.
    """
    from rt1_tpu.models.rt1 import RT1Policy

    tokenizer_def = None
    if model_config.image_tokenizer == "tiny":
        from rt1_tpu.models.tiny_tokenizer import TinyImageTokenizer

        tokenizer_def = TinyImageTokenizer(
            num_tokens=model_config.num_image_tokens,
            emb=model_config.token_embedding_size,
            dtype=jnp.bfloat16
            if model_config.dtype == "bfloat16"
            else jnp.float32,
        )
    elif model_config.image_tokenizer == "efficientnet_small":
        # Same FiLM-EfficientNet + TokenLearner family at ~0.35/0.35 scaling:
        # spatially faithful but CPU-trainable (the flagship B3 needs a TPU).
        from rt1_tpu.models.image_tokenizer import RT1ImageTokenizer

        tokenizer_def = RT1ImageTokenizer(
            embedding_output_dim=model_config.token_embedding_size,
            use_token_learner=model_config.use_token_learner,
            num_tokens=model_config.num_image_tokens,
            width_coefficient=0.35,
            depth_coefficient=0.35,
            dtype=jnp.bfloat16
            if model_config.dtype == "bfloat16"
            else jnp.float32,
        )
    return RT1Policy(
        action_space=language_table_action_space(),
        vocab_size=model_config.vocab_size,
        token_embedding_size=model_config.token_embedding_size,
        num_layers=model_config.num_layers,
        layer_size=model_config.layer_size,
        num_heads=model_config.num_heads,
        feed_forward_size=model_config.feed_forward_size,
        dropout_rate=model_config.dropout_rate,
        time_sequence_length=model_config.time_sequence_length,
        use_token_learner=model_config.use_token_learner,
        num_image_tokens=model_config.num_image_tokens,
        image_tokenizer_def=tokenizer_def,
        photometric_augmentation=model_config.get(
            "photometric_augmentation", False
        ),
        focal_gamma=model_config.get("focal_gamma", 0.0),
        aux_mse_weight=model_config.get("aux_mse_weight", 0.0),
        action_decode=model_config.get("action_decode", "argmax"),
        remat=model_config.get("remat", False),
        attention_impl=model_config.get("attention_impl", "dense"),
        mesh=mesh,
        pipeline_microbatches=model_config.get("pipeline_microbatches", 4),
        # Opt-in Switch MoE decoder FFN (models/moe.py); "dense" is
        # reference parity.
        ffn_impl=model_config.get("ffn_impl", "dense"),
        num_experts=model_config.get("num_experts", 4),
        moe_aux_weight=model_config.get("moe_aux_weight", 0.01),
        moe_capacity_factor=model_config.get("moe_capacity_factor", 2.0),
        moe_ff_dim=model_config.get("moe_ff_dim", None),
        dtype=jnp.bfloat16
        if model_config.dtype == "bfloat16"
        else jnp.float32,
    )


def build_family(model_config, mesh=None):
    """(model, init_fn, loss_fn) for config.model.family = "rt1" | "lava".

    The reference trains its two model families from separate stacks
    (Stack A `distribute_train.py` for RT-1, Stack B
    `language_table/train/train.py:105-116` for LAVA/BC); here one train
    loop serves both — the family only selects the model constructor, the
    init signature, and the loss closure plugged into the jitted SPMD step.
    """
    family = model_config.get("family", "rt1")
    if family == "rt1":
        return build_model(model_config, mesh=mesh), None, None
    if (
        mesh is not None
        and getattr(mesh, "shape", {}).get("stage", 1) > 1
    ):
        raise ValueError(
            f"mesh.stage > 1 (pipeline parallelism) is only supported for "
            f"the 'rt1' family; family={family!r} would silently replicate "
            f"all compute across the stage axis"
        )
    if family == "lava":
        from rt1_tpu.models.lava import SequenceLAVMSE
        from rt1_tpu.trainer.bc import adapt_obs_for_lava, make_bc_step_loss_fn

        lv = model_config.lava
        text_encoder_def = None
        if lv.lang_encoder == "clip":
            from rt1_tpu.models.lava.clip_text import CLIPTextEncoder

            text_encoder_def = CLIPTextEncoder(
                vocab_size=lv.get("text_vocab", 514),
                context_length=lv.get("text_context", 77),
                width=lv.get("text_width", 512),
                num_layers=lv.get("text_layers", 12),
                num_heads=lv.get("text_heads", 8),
                embed_dim=lv.get("text_embed_dim", 512),
            )
        model = SequenceLAVMSE(
            action_size=lv.action_size,
            dense_resnet_width=lv.dense_resnet_width,
            dense_resnet_num_blocks=lv.dense_resnet_num_blocks,
            lava_num_layers=lv.num_layers,
            lava_sequence_length=model_config.time_sequence_length,
            lava_temporal_transformer_num_layers=lv.temporal_num_layers,
            lava_d_model=lv.d_model,
            lava_num_heads=lv.num_heads,
            lava_pyramid_fuse_layers=tuple(lv.pyramid_fuse_layers),
            lava_image_encoder=lv.image_encoder,
            lava_lang_encoder=lv.lang_encoder,
            text_encoder_def=text_encoder_def,
        )

        def init_fn(model, rng, obs, actions):
            return model.init(
                {"params": rng}, adapt_obs_for_lava(obs), train=False
            )

        return model, init_fn, make_bc_step_loss_fn(model)
    raise ValueError(f"Unknown model family: {family!r}")


def _make_clip_tokenizer(config):
    """Tokenizer matching the text tower's config, validated at the seam.

    `data.clip_bpe_path` loads the real CLIP merges (vocab 49408);
    unset uses the byte-level fallback (vocab 514). Context length and
    vocab must agree with `model.lava.text_context` / `text_vocab`, or the
    Embed gather clamps out-of-range ids / the posemb slice shape-fails —
    deep inside the traced step instead of here.
    """
    from rt1_tpu.text.clip_bpe import ClipBPETokenizer, default_tokenizer

    lv = config.model.lava
    context = lv.get("text_context", 77)
    path = config.data.get("clip_bpe_path")
    if path:
        tokenizer = ClipBPETokenizer.from_bpe_file(path, context_length=context)
    else:
        tokenizer = default_tokenizer(context_length=context)
    vocab = len(tokenizer.encoder)
    if vocab != lv.get("text_vocab", 514):
        raise ValueError(
            f"model.lava.text_vocab={lv.get('text_vocab')} but the "
            f"tokenizer ({'merges file' if path else 'byte-level default'}) "
            f"has vocab {vocab}; set text_vocab={vocab}"
        )
    return tokenizer


def _check_clip_token_config(config):
    """Fail at the config seam, not steps later inside a traced forward:
    the LAVA "clip" encoder consumes `instruction_tokenized_clip`, which
    only `data.clip_tokens=True` produces — and producing it for any other
    encoder ships a dead (window, 77) tensor to the device every step."""
    clip_tokens = config.data.get("clip_tokens", False)
    lava_clip = (
        config.model.get("family", "rt1") == "lava"
        and config.model.lava.lang_encoder == "clip"
    )
    if lava_clip and not clip_tokens:
        raise ValueError(
            "model.lava.lang_encoder='clip' requires data.clip_tokens=True "
            "(the pipeline must emit instruction_tokenized_clip)"
        )
    if clip_tokens and not lava_clip:
        raise ValueError(
            "data.clip_tokens=True but no model consumes "
            "instruction_tokenized_clip (set model.lava.lang_encoder='clip')"
        )


def synthetic_batches(config, seed=0) -> Iterator:
    """Random fixed batches when no dataset is configured (smoke/bench)."""
    rng = np.random.default_rng(seed)
    b = config.per_host_batch_size
    t = config.model.time_sequence_length
    h, w = config.data.height, config.data.width
    while True:
        obs = {
            "image": rng.random((b, t, h, w, 3), dtype=np.float32),
            "natural_language_embedding": rng.standard_normal(
                (b, t, 512), dtype=np.float32
            ),
        }
        actions = {
            "terminate_episode": rng.integers(
                0, 2, (b, t), dtype=np.int32
            ),
            "action": rng.uniform(-0.1, 0.1, (b, t, 2)).astype(np.float32),
        }
        yield {"observations": obs, "actions": actions}


def _packed_batches(
    config, split, paths, clip_tokenizer, seed=None
) -> Optional[Iterator]:
    """Packed-cache feed for `split`, or None to fall back to tf.data.

    The cache must exist and be fresh (same episodes, same geometry —
    build it with scripts/pack_dataset.py); anything else logs a warning
    and returns None so training proceeds on the tf.data path rather than
    training on stale pixels or dying at startup.

    With `config.resilience.io_retry` the manifest/mmap open and the feeder
    construction are retried with backoff — a transient filesystem error on
    a network mount degrades to a warning instead of killing startup (or a
    guard rollback's feeder rebuild mid-run).
    """
    from absl import logging

    from rt1_tpu import resilience
    from rt1_tpu.data import pack as pack_lib

    pack_dir = config.data.get("packed_cache_dir") or pack_lib.default_pack_dir(
        config.data.data_dir, split
    )
    fresh, reason = pack_lib.pack_status(
        pack_dir,
        paths,
        config.data.height,
        config.data.width,
        config.data.crop_factor,
    )
    if not fresh:
        logging.warning(
            "data.packed_cache=True but %s is missing or stale (%s) — "
            "falling back to the '%s' loader. Build it with: python "
            "scripts/pack_dataset.py --data_dir %s --split %s --height %d "
            "--width %d --crop_factor %s",
            pack_dir,
            reason,
            config.data.loader,
            config.data.data_dir,
            split,
            config.data.height,
            config.data.width,
            config.data.crop_factor,
        )
        return None
    from rt1_tpu.data.feeder import SampleAheadFeeder

    retry_opts = resilience.ResilienceOptions.from_config(config).retry_options()

    def _build(fn, *args, name, **kwargs):
        if retry_opts is None:
            return fn(*args, **kwargs)
        return resilience.retry_call(
            fn, *args, options=retry_opts, name=name, **kwargs
        )

    cache = _build(
        pack_lib.PackedEpisodeCache,
        pack_dir,
        window=config.model.time_sequence_length,
        clip_tokenizer=clip_tokenizer,
        name="packed_cache_open",
    )
    logging.info(
        "packed cache: feeding %s from %s (%d windows, %dx%d packed frames)",
        split, pack_dir, len(cache), cache.packed_h, cache.packed_w,
    )
    # Task-mixture sampling + per-task telemetry (train split only — eval
    # streams stay the unweighted pinned corpus walk): weights come from
    # `config.data.task_weights` ("task:weight,..." string, docs/data.md);
    # task-id emission arms exactly when the step's health pack will
    # consume it (model_health on, RT-1 family), so health-off runs keep a
    # byte-identical batch stream.
    task_weights = None
    emit_task_ids = False
    if split == "train":
        from rt1_tpu import obs as obs_lib
        from rt1_tpu.data.feeder import parse_task_weights

        task_weights = parse_task_weights(config.data.get("task_weights"))
        emit_task_ids = (
            obs_lib.ObsOptions.from_config(config).model_health
            and config.model.get("family", "rt1") == "rt1"
        )
    return _build(
        SampleAheadFeeder,
        cache,
        config.per_host_batch_size,
        seed=config.seed if seed is None else seed,
        shuffle=split == "train",
        num_threads=config.data.get("feeder_threads", 2),
        depth=config.data.get("feeder_depth", 2),
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        stall_timeout_s=config.data.get("feeder_stall_timeout_s"),
        # Data flywheel: re-read the pack manifest at epoch boundaries and
        # pick up appended shards mid-run (train split only — eval streams
        # should stay pinned to one corpus). Single-process only: a
        # per-host refresh has no cross-host barrier, so multi-host runs
        # keep the corpus pinned for the whole run (the feeder raises on
        # the combination; restart to absorb appended shards).
        refresh_at_epoch=(
            split == "train"
            and config.data.get("packed_refresh", False)
            and jax.process_count() == 1
        ),
        task_weights=task_weights,
        emit_task_ids=emit_task_ids,
        name="feeder_construct",
    )


def dataset_batches(config, split="train", seed=None) -> Iterator:
    """Real data: windowed episode dataset, per-host sharded.

    `seed` overrides `config.seed` for the stream's shuffle/crop draws —
    the guard's rollback path rebuilds the iterator with a fresh seed so
    the restored run does not re-walk the exact batch sequence that
    produced the divergence.
    """
    import glob

    from rt1_tpu.data.pipeline import WindowedEpisodeDataset

    stream_seed = config.seed if seed is None else seed
    paths = sorted(
        glob.glob(os.path.join(config.data.data_dir, split, "episode_*.np*"))
    )
    if not paths:
        raise FileNotFoundError(
            f"No episodes under {config.data.data_dir}/{split}"
        )
    if config.data.get("clip_tokens", False) and config.data.loader == "rlds_tf":
        raise ValueError(
            "clip_tokens requires the windowed loaders ('tf' or 'numpy'); "
            "the rlds_tf graph pipeline does not tokenize instructions"
        )
    if config.data.loader == "rlds_tf":
        if config.data.get("packed_cache", False):
            raise ValueError(
                "data.packed_cache=True is incompatible with loader="
                "'rlds_tf' (the pure-TF graph cannot read the packed mmap "
                "store); use loader='tf' or 'numpy'"
            )
        # Pure-TF windowing pipeline: episodes stream lazily from the npz
        # store (one read per generator pull, bounded host memory) into the
        # same window/crop graph the direct-RLDS path uses
        # (rt1_tpu/data/rlds_pipeline.py). tf.data service with this loader
        # is limited to in-process/colocated workers (generator source);
        # use create_rlds_datasets + InGraphTableEmbedder for remote ones.
        from rt1_tpu.data.rlds_pipeline import (
            RldsPipelineConfig,
            make_episode_dataset_from_paths,
            windowed_rlds_dataset,
        )

        host_paths = paths[jax.process_index() :: jax.process_count()]
        cfg = RldsPipelineConfig(
            window=config.model.time_sequence_length,
            crop_factor=config.data.crop_factor,
            height=config.data.height,
            width=config.data.width,
            batch_size=config.per_host_batch_size,
            shuffle_buffer=config.data.shuffle_buffer,
            seed=stream_seed,
            data_service_address=config.data.get("data_service_address"),
        )
        tfds = windowed_rlds_dataset(
            make_episode_dataset_from_paths(host_paths), cfg,
            training=split == "train",
        )
        return iter(tfds.as_numpy_iterator())

    clip_tokenizer = None
    if config.data.get("clip_tokens", False):
        clip_tokenizer = _make_clip_tokenizer(config)

    if config.data.get("packed_cache", False):
        packed_iter = _packed_batches(
            config, split, paths, clip_tokenizer, seed=seed
        )
        if packed_iter is not None:
            return packed_iter
        # else: fall through to the tf.data/numpy path (warned inside).

    ds = WindowedEpisodeDataset(
        paths,
        window=config.model.time_sequence_length,
        crop_factor=config.data.crop_factor,
        height=config.data.height,
        width=config.data.width,
        clip_tokenizer=clip_tokenizer,
    )
    if config.data.loader == "tf":
        tfds = ds.as_tf_dataset(
            batch_size=config.per_host_batch_size,
            seed=stream_seed,
            shuffle_buffer=config.data.shuffle_buffer,
            process_index=jax.process_index(),
            process_count=jax.process_count(),
        )
        return iter(tfds.as_numpy_iterator())
    return ds.numpy_batches(
        batch_size=config.per_host_batch_size,
        seed=stream_seed,
        process_index=jax.process_index(),
        process_count=jax.process_count(),
    )


def _state_for_save(state):
    """The tree handed to Orbax at save time.

    Single process keeps the historical `jax.device_get` (host numpy —
    saves never hold device buffers while serializing). Multi-process
    hands over the sharded `jax.Array`s untouched: device_get of a
    dp/fsdp-sharded leaf would raise (this host cannot address the other
    hosts' shards), and Orbax's multihost path wants the global arrays —
    each host then writes exactly its own shard bytes.
    """
    if jax.process_count() == 1:
        return jax.device_get(state)
    return state


def train_and_evaluate(config, workdir: str):
    """Run the training loop; returns the final TrainState.

    Self-healing behavior (`config.resilience`, docs/resilience.md): with
    the guard on, non-finite updates are skipped on device and persistent
    divergence escalates to a checkpoint rollback with a fresh data-stream
    seed (bounded by a rollback budget, then GuardAbortError); with
    `preempt_save`, SIGTERM/SIGINT force-saves a checkpoint at the current
    step, drains the feeder, and returns normally (exit 0) so the next
    launch resumes exactly; with `io_retry`, checkpoint and packed-cache
    I/O retries with backoff before giving up. All of it is off by default
    for configs without a `resilience` block.
    """
    from rt1_tpu import obs, resilience

    # Multi-process rendezvous FIRST — before any device access (the plan
    # resolves against the global device set, and a post-backend-init
    # rendezvous is too late). No-op unless `config.parallel.distributed`
    # is enabled; idempotent across runs in one process.
    from rt1_tpu.parallel import initialize_from_config

    initialize_from_config(config)

    # Observability first: the tracer must be live before dataset_batches
    # spawns feeder workers, or their assembly spans are lost.
    obs_opts = obs.ObsOptions.from_config(config, workdir)
    if obs_opts.trace:
        obs.trace.enable(obs_opts.trace_path, obs_opts.trace_max_events)

    # Run-level goodput ledger (obs/goodput.py): everything from here to
    # the first loop step accrues to its "init" bucket (checkpoint restore
    # time is carved out into "ckpt_restore" via the manager's on_io hook).
    ledger = None
    if obs_opts.goodput:
        ledger = obs.GoodputLedger()
        ledger.open_phase("init")

    res_opts = resilience.ResilienceOptions.from_config(config)
    retry_opts = res_opts.retry_options()
    # Deterministic fault schedule (config string + RT1_FAULTS env) — the
    # chaos-run channel; None on production runs.
    fault_plan = resilience.faults.install_from(res_opts.faults)
    if fault_plan is not None:
        from absl import logging

        logging.warning(
            "resilience: fault plan armed: %s",
            sorted(fault_plan.fired_counts()),
        )
    step_guard = (
        resilience.StepGuard(res_opts.guard_options()) if res_opts.guard
        else None
    )

    writer = create_writer(workdir)

    _check_clip_token_config(config)
    # ONE plan resolution: mesh shape (dp × fsdp × tp × pp, or auto by
    # device count) + the declarative param layout, from `config.parallel`
    # (legacy `config.mesh` configs fall back transparently). The same
    # resolution runs in eval/restore.py and serve, so dense/fsdp/tp/pp are
    # config-only switches with no per-callsite spec plumbing.
    from rt1_tpu.parallel import ShardingPlan, mixed_precision_from_config

    sharding_plan = ShardingPlan.from_config(config)
    mesh = sharding_plan.mesh
    mixed_precision = mixed_precision_from_config(config)
    if mixed_precision and config.model.dtype != "bfloat16":
        from absl import logging

        # True mixed precision = bf16 compute against f32 masters; the
        # compute dtype must be bf16 for the step's cast to take effect
        # (masters, optimizer state, and checkpoints stay f32 regardless).
        logging.info(
            "parallel.mixed_precision: forcing model compute dtype "
            "bfloat16 (was %s); master params/opt state stay float32",
            config.model.dtype,
        )
        with config.unlocked():
            config.model.dtype = "bfloat16"
    # Recorded AFTER the mixed-precision dtype mutation so the hparams
    # describe the program that actually runs (model.dtype=bfloat16 under
    # parallel.mixed_precision, not the pre-mutation value).
    write_hparams(
        writer, dict(config.to_dict()) if hasattr(config, "to_dict") else {}
    )
    model, init_fn, loss_fn = build_family(config.model, mesh=mesh)
    data_size = sharding_plan.data_parallel_size
    # The batch the jitted step sees is GLOBAL: per-host rows × processes
    # (each host feeds its block, data/pipeline.py `put_global`). The
    # mesh's batch ways must divide it, and on a host-major mesh each
    # host's rows must map onto its own devices — per-host divisibility by
    # the per-host share of the batch axes.
    nproc = jax.process_count()
    global_batch = config.per_host_batch_size * nproc
    if nproc > 1 and data_size % nproc != 0:
        # Each host feeds only its own rows, so a batch shard must never
        # span hosts (and a batch-REPLICATED mesh, data_size < nproc,
        # cannot be fed per-host rows at all). Reject at the config seam
        # rather than deep inside the first prefetch's
        # make_array_from_process_local_data.
        raise ValueError(
            f"multi-process run: the mesh batch axes (data x fsdp = "
            f"{data_size} ways) must divide evenly across "
            f"{nproc} processes — give dp (or fsdp) a multiple of the "
            f"process count"
        )
    per_host_ways = data_size // nproc if nproc > 1 else data_size
    if config.per_host_batch_size % per_host_ways != 0:
        raise ValueError(
            f"per_host_batch_size={config.per_host_batch_size} must be "
            f"divisible by this host's share of the mesh batch axes "
            f"({per_host_ways} of data x fsdp = {data_size} ways)"
        )
    if mesh.shape["stage"] > 1:
        accum = max(int(config.get("accum_steps", 1)), 1)
        # Each accumulation microstep forwards batch/accum rows, sharded
        # over data — that is the batch pipeline_apply actually sees.
        shard_batch = global_batch // data_size // accum
        micro = config.model.get("pipeline_microbatches", 4)
        if shard_batch == 0 or shard_batch % micro != 0:
            raise ValueError(
                f"pipeline parallelism: per-data-shard per-accum-step batch "
                f"{shard_batch} (= global batch {global_batch} / "
                f"{data_size} data shards / {accum} accum steps) must be a "
                f"positive multiple of pipeline_microbatches={micro}"
            )

    if config.data.data_dir:
        train_iter = dataset_batches(config, "train")
        # Stamp the dataset's provenance (instruction embedder, env config)
        # next to the checkpoints, so eval can refuse a policy/embedder
        # mismatch (the embedding is the task specification).
        from rt1_tpu.data.collect import read_manifest

        manifest = read_manifest(config.data.data_dir)
        if manifest is not None and jax.process_index() == 0:
            import json

            os.makedirs(workdir, exist_ok=True)
            with open(os.path.join(workdir, "data_manifest.json"), "w") as f:
                json.dump(manifest, f, indent=2, sort_keys=True)
    else:
        train_iter = synthetic_batches(config, config.seed)

    first = next(train_iter)
    # Model init must not see the feeder's per-task telemetry member — the
    # observation contract is the model's; the task ids exist only for the
    # jitted step's one-hot reduction (stripped there before the forward).
    example = (
        {
            k: v
            for k, v in first["observations"].items()
            if k != obs.health.TASK_ID_KEY
        },
        first["actions"],
    )

    tx = make_optimizer(
        learning_rate=config.learning_rate,
        milestones=config.lr_milestones,
        gamma=config.lr_gamma,
        steps_per_epoch=config.steps_per_epoch,
        grad_clip_norm=config.grad_clip_norm or None,
    )
    rng = jax.random.PRNGKey(config.seed)
    state = create_train_state(model, rng, example, tx, init_fn=init_fn)
    pretrained_encoder = config.model.get("pretrained_encoder")
    if pretrained_encoder:
        from rt1_tpu.trainer.checkpoints import latest_step

        if latest_step(os.path.join(workdir, "checkpoints")) is not None:
            # Resumed runs (incl. every DAgger extension) restore their
            # checkpoint immediately below — grafting first would be wasted
            # work and, worse, a false "grafted" provenance line in the log.
            pretrained_encoder = None
    if pretrained_encoder:
        # Hermetic substitute for the reference's ImageNet-pretrained tower
        # (film_efficientnet_encoder.py:376-425): graft a state-regression-
        # pretrained encoder (train/pretrain_vision.py) into the tokenizer
        # BEFORE restore — a resumed run's checkpoint still wins.
        from absl import logging

        from rt1_tpu.train.pretrain_vision import (
            graft_encoder_into_policy,
            load_encoder,
        )

        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        grafted = graft_encoder_into_policy(
            variables, load_encoder(pretrained_encoder)
        )
        state = state.replace(
            params=grafted["params"],
            batch_stats=grafted.get("batch_stats", state.batch_stats),
        )
        logging.info("grafted pretrained encoder from %s", pretrained_encoder)
    if jax.process_index() == 0:
        log_parameter_overview(
            state.params, os.path.join(workdir, "parameters.txt")
        )

    ckpt = CheckpointManager(
        CheckpointConfig(
            directory=os.path.join(os.path.abspath(workdir), "checkpoints"),
            # `or None` coerces legacy 0-means-keep-all configs; the config
            # itself now uses a placeholder (None = keep all) explicitly.
            max_to_keep=config.max_to_keep or None,
            save_interval_steps=config.checkpoint_every_steps,
            keep_period=config.keep_period,
            retry=retry_opts,
            on_io=ledger.note_io if ledger is not None else None,
        )
    )
    # Plan-migrating restore (parallel/reshard.py): the template carries
    # the CURRENT plan's target shardings, so a checkpoint saved under a
    # different mesh/plan (a bigger slice, dense vs fsdp) resumes directly
    # in this run's layout instead of relying on a layout coincidence.
    state, initial_step = ckpt.restore_or_initialize(state, plan=sharding_plan)

    fns = make_train_step_fns(
        model, mesh, state, accum_steps=config.accum_steps, loss_fn=loss_fn,
        guard_nonfinite=res_opts.guard,
        guard_grad_norm_max=res_opts.guard_grad_norm_max,
        model_health=obs_opts.model_health,
        health_group_depth=obs_opts.health_group_depth,
        # Per-task telemetry: the feeder publishes its frozen task-id
        # table when it emits task ids (packed multi-task corpora with
        # model_health on); other sources leave the pack task-free.
        health_task_names=tuple(
            getattr(train_iter, "health_task_names", ()) or ()
        ),
        plan=sharding_plan,
        mixed_precision=mixed_precision,
        check_coverage=config.model.get("family", "rt1") == "rt1",
    )
    state = fns.shard_state(state)

    if ledger is not None and obs_opts.goodput_mfu:
        # Arm the live MFU gauge: FLOPs per step from XLA cost analysis of
        # the LOWERED step program — avals only, so no second compile and
        # no extra device transfer; a failed estimate just disarms the
        # gauge (obs/flops.py returns None).
        with obs.trace.span("goodput_flops_estimate"):
            batch_tpl = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                (first["observations"], first["actions"]),
            )
            rng_tpl = jax.ShapeDtypeStruct((2,), jnp.uint32)
            if fns.guarded:
                skips_tpl = jax.ShapeDtypeStruct((), jnp.int32)
                flops = obs.flops.train_step_flops(
                    fns.train_step, state, skips_tpl, batch_tpl, rng_tpl
                )
            else:
                flops = obs.flops.train_step_flops(
                    fns.train_step, state, batch_tpl, rng_tpl
                )
            ledger.set_flops_per_step(flops, n_chips=jax.device_count())

    eval_iter = None
    if config.eval_every_steps:
        if config.data.data_dir:
            try:
                eval_iter = dataset_batches(config, "val")
            except FileNotFoundError:
                eval_iter = None
        else:
            eval_iter = synthetic_batches(config, config.seed + 1)

    meter = ThroughputMeter(
        config.per_host_batch_size * jax.process_count(),
        initial_step=initial_step,
    )
    # Step wall-time attribution (wait_data/h2d/device_step/host + rolling
    # stall_pct) — always on: a handful of perf_counter reads per step.
    timeline = obs.StepTimeline(
        window=obs_opts.stall_window, sync=obs_opts.sync_timing
    )
    # Feeder-side gauges when the packed sample-ahead feeder is the source.
    feeder_stats = getattr(train_iter, "stats", None)
    # Flywheel corpus gauges (shards / freshness epoch / corpus size /
    # staleness): the feeder exposes them when it feeds from the packed
    # cache; rendered as rt1_flywheel_* on the scrape and flywheel/* in TB.
    flywheel_stats = getattr(train_iter, "flywheel_stats", None)

    recorder = None
    if obs_opts.flight_recorder:
        recorder = obs.FlightRecorder(
            obs_opts.flight_recorder_size, path=obs_opts.flight_recorder_path
        )
    coordinator = None
    if res_opts.preempt_save:
        # Preemption-safe shutdown: the first SIGTERM/SIGINT runs the dump
        # callbacks (the flight record survives preemption too) and sets a
        # flag the loop polls — the LOOP then force-saves, drains, and
        # returns (exit 0). The recorder's own die-with-dump handler is NOT
        # installed in this mode; a second signal restores the previous
        # handlers and re-raises, so a wedged drain still dies honestly.
        callbacks = []
        if recorder is not None:
            callbacks.append(lambda: recorder.dump(reason="preempt"))
        coordinator = resilience.PreemptionCoordinator(callbacks=callbacks)
        coordinator.install()
    elif recorder is not None:
        # SIGTERM chains to SIG_DFL (process dies there) — the host trace
        # must dump inside the handler or a terminated traced run loses it.
        recorder.install_sigterm(
            extra=obs.trace.dump if obs_opts.trace else None
        )

    # Opt-in Prometheus scrape target for the train process: renders the
    # latest written scalars + rolling timing/feeder gauges on demand —
    # scrape cost lands on the scraper's thread, not the step.
    latest_scalars: dict = {}
    metrics_server = None
    if obs_opts.prometheus_port >= 0 and jax.process_index() == 0:
        from absl import logging

        def _render_prometheus():
            scalars = dict(latest_scalars)
            scalars.update(timeline.scalars())
            if feeder_stats is not None:
                scalars.update(
                    {f"feeder/{k}": v for k, v in feeder_stats().items()}
                )
            # rt1_train_guard_* / rt1_train_retry_* / rt1_train_preempt_*:
            # live on every scrape, not only after a log step wrote them.
            if step_guard is not None:
                scalars.update(step_guard.counters())
            scalars.update(resilience.retry.counters())
            if coordinator is not None:
                scalars.update(coordinator.counters())
            if fault_plan is not None:
                scalars.update(fault_plan.counters())
            # rt1_train_goodput_*: live run-level wall-time partition +
            # MFU on every scrape (rt1_train_health_* ride in via
            # latest_scalars from the last log step).
            if ledger is not None:
                scalars.update(ledger.scalars())
            body = obs.prometheus.render_scalar_gauges(scalars)
            # rt1_flywheel_*: live corpus-growth gauges — a scrape during
            # an epoch shows the shard pickup the moment the feeder takes
            # it, independent of the log-step cadence.
            if flywheel_stats is not None:
                body += obs.prometheus.render_scalar_gauges(
                    flywheel_stats(), prefix="rt1_flywheel_"
                )
            return body

        metrics_server = obs.MetricsServer(
            _render_prometheus,
            host=obs_opts.prometheus_host,
            port=obs_opts.prometheus_port,
        )
        logging.info("obs: train metrics listener at %s", metrics_server.url)

    # Double-buffered device feed: H2D for step N+1 overlaps compute of
    # step N (uint8 images by default — 4x fewer bytes than float32).
    # `timeline.timed` charges time blocked on the host iterator to the
    # wait_data bucket; the rest of next(dev_iter) is the h2d bucket.
    import contextlib
    import itertools

    from rt1_tpu.data.pipeline import device_feeder

    def _host_stream(iterator, initial=()):
        """Wrap a host batch iterator for the device feed: fault injection
        (nan_batch site, indexed by batch ordinal within this stream) under
        the timeline's wait_data accounting. The model-init example batch
        is extracted BEFORE this wrapper, so a poisoned batch 0 can never
        leak NaNs into parameter initialization."""
        stream = itertools.chain(initial, iterator)
        plan = resilience.faults.active()
        if plan is not None:
            def _with_faults(inner):
                from absl import logging

                for i, b in enumerate(inner):
                    if plan.should_fire("nan_batch", index=i):
                        logging.warning(
                            "resilience: injected nan_batch at host batch "
                            "%d", i,
                        )
                        b = resilience.faults.poison_batch(b)
                    yield b

            stream = _with_faults(stream)
        return timeline.timed(stream)

    dev_iter = device_feeder(
        _host_stream(train_iter, initial=[first]),
        fns.batch_sharding,
        depth=2,
    )
    def _obs_teardown():
        # Runs on success AND on a loop exception (after the flight dump):
        # leaking any of these poisons the next run in this process — a
        # bound scrape port, a SIGTERM handler referencing a dead recorder,
        # a stale process-wide tracer swallowing the next enable().
        if metrics_server is not None:
            metrics_server.close()
        if coordinator is not None:
            coordinator.uninstall()
        if recorder is not None:
            recorder.uninstall_sigterm()
        if obs_opts.trace:
            from absl import logging

            # disable() dumps to obs_opts.trace_path and clears the
            # process-wide recorder, so back-to-back runs (tests, sweeps)
            # don't bleed spans into each other's traces.
            obs.trace.disable()
            logging.info(
                "obs: host trace written to %s", obs_opts.trace_path
            )

    crash_guard = (
        recorder.dump_on_exception()
        if recorder is not None
        else contextlib.nullcontext()
    )
    # The host iterator is rebound on rollback; close whichever is current
    # at exit (drains the sample-ahead feeder's worker threads).
    live_iter = {"host": train_iter}

    def _close_host_iter():
        closer = getattr(live_iter["host"], "close", None)
        if callable(closer):
            closer()

    def _write_goodput():
        # Success, crash, and preempt paths all leave a summary on disk —
        # run_report's post-mortem needs it most when the run died.
        if ledger is None or not obs_opts.goodput_summary_path:
            return
        if jax.process_index() != 0:
            return
        from absl import logging

        try:
            path = ledger.write_summary(obs_opts.goodput_summary_path)
            s = ledger.summary()
            logging.info(
                "obs: goodput summary at %s (goodput %.1f%%, badput %.1f%%"
                "%s)",
                path, s["goodput_pct"], s["badput_pct"],
                ", mfu %.2f%%" % s["mfu_pct"] if "mfu_pct" in s else "",
            )
        except Exception:  # noqa: BLE001 - accounting must not mask exits
            pass

    guard_skips = fns.init_guard_skips() if fns.guarded else None
    # Steps at or before this mark are post-rollback re-runs — badput the
    # ledger books as rollback_replay, not productive step time.
    replay_until = initial_step
    cleanup = contextlib.ExitStack()
    cleanup.callback(_obs_teardown)
    cleanup.callback(_close_host_iter)
    cleanup.callback(_write_goodput)
    if ledger is not None:
        ledger.close_phase()  # init ends where the step loop begins
    with cleanup, crash_guard:
        step = initial_step
        while step < config.num_steps:
            if fault_plan is not None:
                # Self-delivered SIGTERM ("sigterm@<step>"): the chaos-run
                # stand-in for a scheduler preemption, handled exactly like
                # the real one (coordinator flag -> save-and-exit below).
                resilience.faults.maybe_signal("sigterm", index=step)
            timeline.start_step(step)
            # The XPlane step annotation spans the batch pull + the step,
            # as before this loop was instrumented — the device profiler's
            # per-step view must keep including input wait/H2D.
            with step_trace("train", step):
                with timeline.phase("h2d", exclusive_of="wait_data"):
                    batch = next(dev_iter)
                with timeline.phase("device_step"):
                    step_rng = jax.random.fold_in(rng, step)
                    if fns.guarded:
                        state, guard_skips, metrics = fns.train_step(
                            state, guard_skips, batch, step_rng
                        )
                    else:
                        state, metrics = fns.train_step(
                            state, batch, step_rng
                        )
            step_record = timeline.end_step(sync_on=metrics.get("loss"))
            if ledger is not None:
                ledger.note_step(step_record, replay=step < replay_until)

            log_now = (step + 1) % config.log_every_steps == 0
            verdict = resilience.GuardVerdict.OK
            health_scalars = None
            if log_now:
                # The health pack is a vector — pop it before the per-key
                # scalar fetch (a mean over the pack is meaningless) and
                # unpack it against the step builder's name layout.
                health_vec = (
                    metrics.pop(obs.health.PACK_KEY, None)
                    if fns.health_names
                    else None
                )
                scalars = scalars_from_metrics(metrics)
                if health_vec is not None:
                    health_scalars = obs.health.unpack(
                        fns.health_names, health_vec
                    )
                    scalars.update(health_scalars)
                # The guard judges the scalars this loop already fetched —
                # its host-side cost at log steps is arithmetic on floats.
                if step_guard is not None:
                    verdict = step_guard.observe(step + 1, scalars)
                    scalars.update(step_guard.counters())
                scalars.update(meter.update(step + 1))
                scalars.update(timeline.scalars())
                if ledger is not None:
                    scalars.update(ledger.scalars())
                if feeder_stats is not None:
                    scalars.update(
                        {
                            f"feeder/{k}": v
                            for k, v in feeder_stats().items()
                        }
                    )
                if flywheel_stats is not None:
                    scalars.update(
                        {
                            f"flywheel/{k}": v
                            for k, v in flywheel_stats().items()
                        }
                    )
                scalars.update(resilience.retry.counters())
                if coordinator is not None:
                    scalars.update(coordinator.counters())
                if fault_plan is not None:
                    scalars.update(fault_plan.counters())
                writer.write_scalars(step + 1, scalars)
                latest_scalars.update(scalars)
                latest_scalars["step"] = step + 1

            if recorder is not None:
                rec = {
                    k: v for k, v in step_record.items() if k != "step"
                }
                if log_now:
                    rec["loss"] = scalars.get("loss")
                    if health_scalars is not None:
                        rec["health"] = health_scalars
                    if step_guard is not None:
                        rec["guard"] = step_guard.counters()
                    retry_counters = resilience.retry.counters()
                    if retry_counters:
                        rec["retry"] = retry_counters
                if feeder_stats is not None:
                    rec["feeder"] = feeder_stats()
                recorder.record(step + 1, **rec)

            if verdict is resilience.GuardVerdict.ABORT:
                raise resilience.GuardAbortError(
                    f"guard: rollback budget "
                    f"({res_opts.guard_rollback_budget}) exhausted and "
                    f"training is still unhealthy at step {step + 1}: "
                    f"{step_guard.last_reason}"
                )
            if verdict is resilience.GuardVerdict.ROLLBACK:
                from absl import logging

                ckpt.wait_until_finished()
                target = ckpt.latest_step()
                if target is None:
                    raise resilience.GuardAbortError(
                        f"guard: training unhealthy at step {step + 1} "
                        f"({step_guard.last_reason}) with no checkpoint to "
                        f"roll back to (first save at step "
                        f"{config.checkpoint_every_steps})"
                    )
                logging.warning(
                    "resilience: guard ROLLBACK at step %d (%s) — "
                    "restoring checkpoint step %d with a fresh data seed",
                    step + 1, step_guard.last_reason, target,
                )
                state = ckpt.restore(state, step=target, plan=sharding_plan)
                step_guard.notify_rollback(target)
                # Fresh stream offset: re-walking the exact batch sequence
                # would reproduce the divergence deterministically.
                fresh_seed = config.seed + 7919 * step_guard.rollbacks
                _close_host_iter()
                if config.data.data_dir:
                    train_iter = dataset_batches(
                        config, "train", seed=fresh_seed
                    )
                else:
                    train_iter = synthetic_batches(config, fresh_seed)
                live_iter["host"] = train_iter
                feeder_stats = getattr(train_iter, "stats", None)
                flywheel_stats = getattr(train_iter, "flywheel_stats", None)
                dev_iter = device_feeder(
                    _host_stream(train_iter), fns.batch_sharding, depth=2
                )
                obs.trace.counter("guard_rollbacks", step_guard.rollbacks)
                if ledger is not None:
                    ledger.mark_rollback()
                # Everything up to the step we just abandoned is now a
                # re-run — the ledger books it as rollback_replay badput.
                replay_until = max(replay_until, step + 1)
                step = target
                continue

            if (
                eval_iter is not None
                and (step + 1) % config.eval_every_steps == 0
            ):
                losses = []
                for _ in range(config.eval_batches):
                    ev = next(eval_iter)
                    ev_metrics = fns.eval_step(
                        state,
                        fns.shard_batch((ev["observations"], ev["actions"])),
                    )
                    losses.append(scalars_from_metrics(ev_metrics)["loss"])
                writer.write_scalars(
                    step + 1, {"eval_loss": float(np.mean(losses))}
                )

            last = step + 1 == config.num_steps
            saved = False
            if last or (step + 1) % config.checkpoint_every_steps == 0:
                # device_get only on save steps: the full-state D2H copy
                # would otherwise sync the host every step and kill the
                # prefetch overlap. Trace-span only, NOT a timeline bucket:
                # this runs between steps, and folding multi-second saves
                # into the next step's host bucket would make its buckets
                # exceed its total. Multi-process: NO device_get — a host
                # cannot materialize other hosts' fsdp/dp shards; Orbax
                # takes the sharded jax.Arrays and each host writes its own
                # shard files.
                with obs.trace.span("checkpoint_save", step=step + 1):
                    saved = ckpt.save(
                        step + 1, _state_for_save(state), force=last
                    )

            if coordinator is not None and coordinator.triggered:
                from absl import logging

                logging.warning(
                    "resilience: preemption signal %s — force-saving step "
                    "%d, draining the feeder, exiting 0",
                    coordinator.signum, step + 1,
                )
                if ledger is not None:
                    ledger.mark_preempted()
                drain_cm = (
                    ledger.phase("preempt_drain")
                    if ledger is not None
                    else contextlib.nullcontext()
                )
                # The force-save inside the drain is carved out into the
                # ckpt_save bucket by note_io's phase steal.
                with drain_cm:
                    if not saved:
                        with obs.trace.span("preempt_save", step=step + 1):
                            ckpt.save(
                                step + 1, _state_for_save(state), force=True
                            )
                    _close_host_iter()
                break

            step += 1

    ckpt.wait_until_finished()
    writer.flush()
    # Refresh the summary the cleanup stack already wrote: the async final
    # checkpoint's wait and the teardown itself belong in the totals.
    _write_goodput()
    return state


def apply_sweep_trial(config, config_module, trial: int):
    """Apply trial `trial` of the config module's `sweep()` (the open
    equivalent of the reference's `get_hyper` hook,
    `configs/language_table_sim_local.py:84-89`) onto `config` in place."""
    trials = config_module.sweep()
    if not 0 <= trial < len(trials):
        raise ValueError(f"--sweep_trial {trial} out of range [0, {len(trials)})")
    overrides = trials[trial]
    with config.unlocked():
        config.update_from_flattened_dict(overrides)
    return overrides


def main(argv):
    del argv
    import importlib.util

    from absl import flags, logging
    from ml_collections import config_flags

    FLAGS = flags.FLAGS
    config = FLAGS.config
    if FLAGS.sweep_trial >= 0:
        module_name = config_flags.get_config_filename(FLAGS["config"])
        spec = importlib.util.spec_from_file_location("sweep_cfg", module_name)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        if not hasattr(mod, "sweep"):
            raise ValueError(f"{module_name} defines no sweep()")
        overrides = apply_sweep_trial(config, mod, FLAGS.sweep_trial)
        logging.info("sweep trial %d: %s", FLAGS.sweep_trial, overrides)
    train_and_evaluate(config, FLAGS.workdir)


if __name__ == "__main__":
    from absl import app, flags
    from ml_collections import config_flags

    config_flags.DEFINE_config_file("config", None, "Config file.", lock_config=True)
    flags.DEFINE_string("workdir", "/tmp/rt1_tpu", "Work/output directory.")
    flags.DEFINE_integer(
        "sweep_trial", -1,
        "If >= 0, apply this trial of the config module's sweep() before "
        "training (one process per trial).")
    flags.mark_flags_as_required(["config"])
    app.run(main)
