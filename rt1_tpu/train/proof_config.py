"""Training-config assembly for the learning-proof arms.

Extracted from ``scripts/learn_proof.py`` (VERDICT r4 next #7) so the
LR-schedule placement logic is unit-testable without absl FLAGS.
"""

from __future__ import annotations


def proof_train_config(
    data_dir: str,
    num_steps: int,
    *,
    image_tokenizer: str = "efficientnet_b3",
    seq_len: int = 6,
    focal_gamma: float = 0.0,
    aux_mse_weight: float = 0.0,
    dtype: str = "bfloat16",
    pretrained_encoder: str = "",
    height: int = 128,
    width: int = 224,
    batch: int = 32,
    checkpoint_every: int = 2500,
    constant_lr: bool = False,
):
    """The flagship/CPU learning-proof config on top of the standard
    language-table config (reference schedule shape:
    ``/root/reference/distribute_train.py:283-287``).

    MultiStepLR milestones (50, 75, 90) "epochs" -> decay at 50/75/90% of
    the run. ``max(1, ...)``: ``steps_per_epoch=0`` would collapse every
    milestone to boundary 0 and train the whole run at the final decayed
    LR. ``constant_lr`` pushes every boundary past the horizon instead —
    the round-4 recipe for DART/DAgger arms whose data distribution
    shifts late in the run.
    """
    from rt1_tpu.train.configs import language_table

    config = language_table.get_config()
    config.model.image_tokenizer = image_tokenizer
    config.model.time_sequence_length = seq_len
    config.model.focal_gamma = focal_gamma
    config.model.aux_mse_weight = aux_mse_weight
    config.model.dtype = dtype
    if pretrained_encoder:
        config.model.pretrained_encoder = pretrained_encoder
    config.data.data_dir = data_dir
    config.data.height = height
    config.data.width = width
    config.per_host_batch_size = batch
    config.num_steps = num_steps
    config.steps_per_epoch = (
        num_steps * 100 if constant_lr else max(1, num_steps // 100)
    )
    config.checkpoint_every_steps = checkpoint_every
    config.keep_period = 10000
    config.log_every_steps = 50
    config.eval_every_steps = 1000
    config.eval_batches = 4
    return config
