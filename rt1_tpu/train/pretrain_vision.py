"""Hermetic vision pretraining: block-state regression from sim frames.

The reference initializes its image tower from ImageNet-pretrained
EfficientNet-B3 weights
(`/root/reference/pytorch_robotics_transformer/film_efficientnet/
film_efficientnet_encoder.py:376-425`); this image carries no pretrained
blobs and no network, so every arm so far trained vision from scratch —
and round 4 concluded the learning failure is perception-limited
(RESULTS.md). This module is the in-image substitute (VERDICT r4 next #3):
the simulator generates unlimited (frame, block/effector position) pairs
for free, so the encoder can be pretrained on *state regression* — exactly
the visual competence the policy needs — and then grafted into the RT-1
tokenizer as its initialization.

It doubles as a **perception-capacity probe**: the attainable position
error of a given (encoder, resolution) on this task is a direct measure of
what the policy's vision can resolve, independent of BC/DAgger dynamics —
the measured answer to round 4's "capacity, initialization, or both?"
confound (VERDICT r4 weak #4).

The encoder module tree is identical to the one inside
`RT1ImageTokenizer` (``EfficientNetEncoder`` under name ``"encoder"``), so
`graft_encoder_into_policy` is a pure subtree transplant with shape
validation — no porting, no renaming.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import flax
import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from rt1_tpu.models.encoder import EfficientNetEncoder


def generate_state_regression_dataset(
    num_frames: int,
    block_mode: str = "BLOCK_4",
    seed: int = 0,
    image_hw: tuple[int, int] = (64, 96),
    random_steps: int = 8,
    reward_name: str = "block2block",
):
    """Render `num_frames` frames with ground-truth block/effector targets.

    Each sample: reset to a randomized board, take `U[0, random_steps]`
    uniform random effector actions (diversifying effector pose and block
    contact states), then record (resized rgb, [effector_xy, block_xy...]).
    Labels are free — the sim knows its own state — which is what makes
    this pretraining hermetic.

    Returns (images uint8 (N,H,W,3), targets float32 (N,D), target_names).
    """
    import cv2

    from rt1_tpu.envs import blocks, rewards
    from rt1_tpu.envs.language_table import LanguageTable

    env = LanguageTable(
        block_mode=blocks.BlockMode(block_mode),
        reward_factory=rewards.get_reward_factory(reward_name),
        seed=seed,
    )
    rng = np.random.default_rng(seed)
    images, targets = [], []
    target_names: Optional[list[str]] = None
    while len(images) < num_frames:
        env.reset()
        for _ in range(int(rng.integers(0, random_steps + 1))):
            env.step(rng.uniform(-0.03, 0.03, size=2).astype(np.float32))
        state = env.compute_state(request_task_update=False)
        block_keys = sorted(
            k for k in state if k.startswith("block_")
            and k.endswith("_translation")
        )
        if target_names is None:
            target_names = ["effector_x", "effector_y"] + [
                f"{k}_{ax}" for k in block_keys for ax in ("x", "y")
            ]
        vec = np.concatenate(
            [np.asarray(state["effector_translation"], np.float32)]
            + [np.asarray(state[k], np.float32) for k in block_keys]
        )
        rgb = cv2.resize(
            np.asarray(state["rgb"]), (image_hw[1], image_hw[0]),
            interpolation=cv2.INTER_LINEAR,
        )
        images.append(rgb.astype(np.uint8))
        targets.append(vec)
    return np.stack(images), np.stack(targets), target_names


class VisionPretrainModel(nn.Module):
    """EfficientNetEncoder (the exact RT1ImageTokenizer submodule) + a
    regression head. FiLM context is zeros during pretraining — the FiLM
    projections are zero-initialized (models/film.py), so the grafted
    encoder behaves identically until language conditioning trains."""

    target_dim: int
    token_embedding_size: int = 512
    width_coefficient: float = 0.35
    depth_coefficient: float = 0.35
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, images: jnp.ndarray, train: bool = False):
        x = images.astype(jnp.float32) / 255.0  # ops/image.py convention
        context = jnp.zeros((x.shape[0], 512), self.dtype)
        feats = EfficientNetEncoder(
            token_embedding_size=self.token_embedding_size,
            early_film=True,
            pooling=True,
            dtype=self.dtype,
            width_coefficient=self.width_coefficient,
            depth_coefficient=self.depth_coefficient,
            name="encoder",
        )(x, context=context, train=train)
        return nn.Dense(self.target_dim, name="head")(feats)


def pretrain_encoder(
    images: np.ndarray,
    targets: np.ndarray,
    *,
    num_steps: int = 3000,
    batch_size: int = 32,
    learning_rate: float = 1e-3,
    val_fraction: float = 0.1,
    seed: int = 0,
    width_coefficient: float = 0.35,
    depth_coefficient: float = 0.35,
    token_embedding_size: int = 512,
    eval_every: int = 500,
    log=print,
):
    """Train the probe; return (variables, metrics).

    Targets are standardized per-dimension (mean/std recorded in metrics);
    the reported `val_rmse` is de-standardized — board units (meters for
    Language-Table translations), directly comparable across encoders and
    resolutions.
    """
    import optax

    n_val = max(1, int(len(images) * val_fraction))
    train_x, val_x = images[n_val:], images[:n_val]
    train_y, val_y = targets[n_val:], targets[:n_val]
    mu = train_y.mean(axis=0)
    sd = train_y.std(axis=0) + 1e-8
    train_yn = (train_y - mu) / sd
    val_yn = (val_y - mu) / sd

    model = VisionPretrainModel(
        target_dim=targets.shape[1],
        width_coefficient=width_coefficient,
        depth_coefficient=depth_coefficient,
        token_embedding_size=token_embedding_size,
    )
    rng = jax.random.PRNGKey(seed)
    variables = model.init(rng, jnp.asarray(train_x[:2]), train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    tx = optax.adam(learning_rate)
    opt_state = tx.init(params)

    @jax.jit
    def train_step(params, batch_stats, opt_state, bx, by, dropout_rng):
        def loss_fn(p):
            out, mut = model.apply(
                {"params": p, "batch_stats": batch_stats},
                bx, train=True,
                mutable=["batch_stats"],
                rngs={"dropout": dropout_rng},
            )
            return jnp.mean((out - by) ** 2), mut["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_stats, opt_state, loss

    @jax.jit
    def eval_err(params, batch_stats, bx):
        return model.apply(
            {"params": params, "batch_stats": batch_stats}, bx, train=False
        )

    def val_rmse(params, batch_stats):
        preds = []
        for i in range(0, len(val_x), batch_size):
            preds.append(np.asarray(eval_err(
                params, batch_stats, jnp.asarray(val_x[i:i + batch_size])
            )))
        preds = np.concatenate(preds) * sd + mu
        return float(np.sqrt(np.mean((preds - val_y) ** 2)))

    data_rng = np.random.default_rng(seed)
    history = []
    for step in range(num_steps):
        idx = data_rng.integers(0, len(train_x), batch_size)
        rng, dropout_rng = jax.random.split(rng)
        params, batch_stats, opt_state, loss = train_step(
            params, batch_stats, opt_state,
            jnp.asarray(train_x[idx]), jnp.asarray(train_yn[idx]),
            dropout_rng,
        )
        if step % eval_every == 0 or step == num_steps - 1:
            rmse = val_rmse(params, batch_stats)
            history.append({"step": step, "train_loss": float(loss),
                            "val_rmse": rmse})
            log(f"pretrain step {step}: loss {float(loss):.4f} "
                f"val_rmse {rmse * 1000:.2f} mm")
    variables = {"params": params, "batch_stats": batch_stats}
    metrics = {
        "val_rmse": history[-1]["val_rmse"],
        "val_rmse_mm": history[-1]["val_rmse"] * 1000.0,
        "history": history,
        "target_mean": mu.tolist(),
        "target_std": sd.tolist(),
        "num_train_frames": int(len(train_x)),
        "num_val_frames": int(len(val_x)),
    }
    return variables, metrics


def save_encoder(variables, metrics, path: str) -> None:
    """Serialize the ENCODER subtree (+ metrics sidecar JSON) to `path`."""
    enc = {
        "params": variables["params"]["encoder"],
        "batch_stats": variables.get("batch_stats", {}).get("encoder", {}),
    }
    with open(path, "wb") as f:
        f.write(flax.serialization.to_bytes(enc))
    with open(path + ".json", "w") as f:
        json.dump({k: v for k, v in metrics.items() if k != "history"}
                  | {"history": metrics.get("history", [])}, f, indent=2)


def load_encoder(path: str):
    """Inverse of `save_encoder` (structure restored from the bytes)."""
    with open(path, "rb") as f:
        return flax.serialization.msgpack_restore(f.read())


def graft_encoder_into_policy(policy_variables, encoder,
                              tokenizer_name: str | None = None):
    """Transplant pretrained encoder leaves into the policy's variables.

    Validates leaf-by-leaf shape equality (a resolution change is fine —
    the encoder is fully convolutional — but a width/depth-coefficient
    mismatch is a hard error, not a silent partial graft). Returns new
    variables; input unmodified.

    `tokenizer_name` defaults to auto-detection: the policy's tokenizer
    tree is named "image_tokenizer_def" when the module was passed into
    `RT1Policy` (Flax names passed-in submodules by field name — the
    `build_model` path) and "image_tokenizer" when constructed in setup.
    """
    if tokenizer_name is None:
        candidates = [
            k for k, v in policy_variables["params"].items()
            if isinstance(v, dict) and "encoder" in v
        ]
        if len(candidates) != 1:
            raise ValueError(
                f"could not locate the image-tokenizer subtree (top-level "
                f"keys with an 'encoder' child: {candidates}); pass "
                f"tokenizer_name explicitly"
            )
        tokenizer_name = candidates[0]
    def check_and_cast(dst_tree, src_tree, scope):
        dst_flat = flax.traverse_util.flatten_dict(dst_tree)
        src_flat = flax.traverse_util.flatten_dict(src_tree)
        if set(dst_flat) != set(src_flat):
            missing = set(dst_flat) ^ set(src_flat)
            raise ValueError(
                f"pretrained encoder {scope} tree mismatch "
                f"(differing keys: {sorted(missing)[:4]}...): was it trained "
                f"with the same width/depth coefficients?"
            )
        out = {}
        for k, dst in dst_flat.items():
            src = src_flat[k]
            if tuple(dst.shape) != tuple(np.shape(src)):
                raise ValueError(
                    f"pretrained encoder {scope} shape mismatch at "
                    f"{'/'.join(k)}: checkpoint {np.shape(src)} vs model "
                    f"{tuple(dst.shape)}"
                )
            out[k] = jnp.asarray(src, dst.dtype)
        return flax.traverse_util.unflatten_dict(out)

    params = flax.core.unfreeze(policy_variables["params"])
    params[tokenizer_name]["encoder"] = check_and_cast(
        params[tokenizer_name]["encoder"], encoder["params"], "params"
    )
    out = dict(policy_variables)
    out["params"] = params
    stats = flax.core.unfreeze(policy_variables.get("batch_stats", {}))
    if stats and encoder.get("batch_stats"):
        stats[tokenizer_name]["encoder"] = check_and_cast(
            stats[tokenizer_name]["encoder"], encoder["batch_stats"],
            "batch_stats",
        )
        out["batch_stats"] = stats
    return out
