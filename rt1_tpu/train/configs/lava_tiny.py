"""CPU smoke config for the LAVA family: tiny dims + synthetic data.

The reference trains LAVA from Stack B (`language_table/train/train.py:60-218`
with `configs/language_table_sim_local.py`); this config drives the same model
family through the unified train CLI:

  python -m rt1_tpu.train.train --config rt1_tpu/train/configs/lava_tiny.py \
      --workdir /tmp/lava
"""

from rt1_tpu.train.configs import tiny

sweep = tiny.sweep


def get_config():
    config = tiny.get_config()
    config.model.family = "lava"
    config.model.lava.d_model = 16
    config.model.lava.dense_resnet_width = 32
    config.model.lava.dense_resnet_num_blocks = 1
    config.model.lava.num_heads = 2
    config.model.lava.text_width = 16
    config.model.lava.text_layers = 2
    config.model.lava.text_heads = 2
    config.model.lava.text_embed_dim = 16
    # 64x64 divides cleanly through the 5-level conv-maxpool pyramid.
    config.data.height = 64
    config.data.width = 64
    return config
