"""Flagship config: RT-1 on Language-Table blocktoblock_sim.

Hyperparameters mirror the reference's implied throughput baseline
(`distribute_train.py:269-295` + SURVEY.md §2.1): batch 8/chip, seq_len 6,
256x456 images, lr 5e-4 with MultiStepLR [50, 75, 90] gamma 0.1, 100 epochs
over 7800 train episodes, vocab 256, 8 layers, TokenLearner with 8 tokens.
"""

import ml_collections


def get_config():
    config = ml_collections.ConfigDict()

    # Model (SURVEY.md §2.1 instantiation).
    config.model = ml_collections.ConfigDict()
    config.model.family = "rt1"  # "rt1" | "lava" (Stack A vs Stack B)
    config.model.vocab_size = 256
    config.model.token_embedding_size = 512
    config.model.num_layers = 8
    config.model.layer_size = 128
    config.model.num_heads = 8
    config.model.feed_forward_size = 512
    config.model.dropout_rate = 0.1
    config.model.time_sequence_length = 6
    config.model.use_token_learner = True
    config.model.num_image_tokens = 8
    config.model.image_tokenizer = "efficientnet_b3"
    config.model.dtype = "bfloat16"
    config.model.photometric_augmentation = False
    # Focal CE modulation (models/rt1.py): 0 = reference parity; > 0 fights
    # the BC marginal-collapse ("copycat") failure on smooth oracle demos.
    config.model.focal_gamma = 0.0
    # Soft-argmax MSE auxiliary (models/rt1.py): dense regression gradient
    # that bypasses the token-CE marginal plateau. 0 = reference parity.
    config.model.aux_mse_weight = 0.0
    # Inference decode: "argmax" (reference parity) | "expected" (soft E[a]).
    config.model.action_decode = "argmax"
    # jax.checkpoint the transformer + MBConv blocks: ~1/3 extra FLOPs for
    # O(1) activation memory — turn on when HBM, not compute, caps batch.
    config.model.remat = False
    # Attention implementation: "dense" (reference parity), "ring" (sequence-
    # parallel over the mesh's 'seq' axis), "pallas" (fused inference kernel).
    config.model.attention_impl = "dense"
    # GPipe microbatches per step when mesh.stage > 1 (parallel/pipeline.py).
    config.model.pipeline_microbatches = 4
    # Decoder FFN: "dense" (reference parity) or "moe" (Switch expert FFN,
    # expert-parallel over the mesh's 'model' axis — models/moe.py).
    config.model.ffn_impl = "dense"
    config.model.num_experts = 4
    config.model.moe_aux_weight = 0.01
    config.model.moe_capacity_factor = 2.0
    config.model.moe_ff_dim = ml_collections.config_dict.placeholder(int)
    # Path to a state-regression-pretrained encoder (train/pretrain_vision
    # .py::save_encoder) grafted into the tokenizer at initialization — the
    # hermetic stand-in for the reference's ImageNet-pretrained B3 tower
    # (film_efficientnet_encoder.py:376-425). None = train from scratch.
    config.model.pretrained_encoder = ml_collections.config_dict.placeholder(
        str
    )

    # LAVA family fields (used when family == "lava"; defaults mirror the
    # reference's SequenceLAVMSE config, `train/configs/
    # language_table_sim_local.py:27-49`).
    config.model.lava = ml_collections.ConfigDict()
    config.model.lava.action_size = 2
    config.model.lava.d_model = 128
    config.model.lava.num_layers = 2
    config.model.lava.temporal_num_layers = 2
    config.model.lava.num_heads = 2
    config.model.lava.pyramid_fuse_layers = (2, 3, 4)
    config.model.lava.image_encoder = "conv_maxpool"
    config.model.lava.lang_encoder = "embedding_in_obs"
    config.model.lava.dense_resnet_width = 256
    config.model.lava.dense_resnet_num_blocks = 8
    # In-graph CLIP text tower dims (lang_encoder == "clip"). Defaults match
    # the byte-level `clip_bpe.default_tokenizer` vocab (514); for public
    # OpenAI weights use vocab 49408 / width 512 / 12 layers / 8 heads and
    # the real merges file.
    config.model.lava.text_vocab = 514
    config.model.lava.text_context = 77
    config.model.lava.text_width = 512
    config.model.lava.text_layers = 12
    config.model.lava.text_heads = 8
    config.model.lava.text_embed_dim = 512

    # Data.
    config.data = ml_collections.ConfigDict()
    config.data.data_dir = ""  # empty -> synthetic random batches (smoke)
    config.data.height = 256
    config.data.width = 456
    config.data.crop_factor = 0.95
    # "tf": numpy_function-backed local pipeline; "rlds_tf": pure-TF graph
    # (tf.data-service-distributable); "numpy": dependency-free iterator.
    config.data.loader = "tf"
    config.data.shuffle_buffer = 2048
    # Emit "instruction_tokenized_clip" observations (CLIP BPE over the
    # stored instruction text) for the LAVA "clip" language encoder.
    config.data.clip_tokens = False
    # Path to CLIP's bpe_simple_vocab_16e6.txt(.gz) merges. None -> the
    # byte-level fallback tokenizer (model.lava.text_vocab must then be 514;
    # with the real merges use 49408).
    config.data.clip_bpe_path = ml_collections.config_dict.placeholder(str)
    # tf.data service endpoint for distributed preprocessing with the
    # "rlds_tf" loader (reference input_pipeline_rlds.py:307-317); None =
    # process batches locally.
    config.data.data_service_address = ml_collections.config_dict.placeholder(str)
    # Packed mmap frame cache (rt1_tpu/data/pack.py): feed training from
    # pre-decoded frames at augmentation-headroom resolution via the
    # sample-ahead feeder instead of the tf.data decode+crop path. Build
    # the cache offline with scripts/pack_dataset.py; a missing/stale cache
    # falls back to the tf.data path with a warning. Incompatible with
    # loader="rlds_tf".
    config.data.packed_cache = False
    # Override the cache location (default: <data_dir>/<split>_packed).
    config.data.packed_cache_dir = ml_collections.config_dict.placeholder(str)
    # Sample-ahead feeder shape: background assembly threads and the
    # per-thread ready-batch queue depth (total sample-ahead =
    # threads * depth batches).
    config.data.feeder_threads = 2
    config.data.feeder_depth = 2
    # Consumer-side stall diagnosis: if the train loop waits this long for
    # a feeder batch it raises FeederStalledError naming which workers are
    # alive and the queue depths, instead of blocking forever on a worker
    # that deadlocked without raising. None = wait indefinitely.
    config.data.feeder_stall_timeout_s = ml_collections.config_dict.placeholder(
        float
    )
    # Data flywheel (docs/data.md "Sharded pack format v2 & the
    # flywheel"): at every epoch boundary the train feeder re-reads the
    # pack manifest and picks up shards appended by
    # `scripts/pack_dataset.py --append` (serve-captured episodes) without
    # a restart; `flywheel/*` scalars + rt1_flywheel_* gauges track shard
    # count, corpus size, and staleness. Costs one manifest read per data
    # epoch when nothing changed.
    config.data.packed_refresh = True
    # Task-mixture sampling over the packed corpus (docs/data.md "Task
    # mixture & per-task telemetry"): "task:weight,..." per-task sampling
    # weights, e.g. "block2block:3,block1_to_corner:1,*:0.5" ("*" = every
    # task not named; "unknown" matches untagged legacy episodes). Empty =
    # off — the bit-identical pre-task uniform shuffle. Weighted epochs
    # sample windows with replacement (p ∝ weight of the window's task),
    # still a pure function of (seed, epoch, corpus, weights).
    config.data.task_weights = ""

    # Training schedule (reference: 100 epochs x 975 steps at batch 8).
    config.per_host_batch_size = 8
    config.num_steps = 97_500
    config.steps_per_epoch = 975
    config.learning_rate = 5e-4
    config.lr_milestones = (50, 75, 90)  # epochs
    config.lr_gamma = 0.1
    config.grad_clip_norm = 0.0  # 0 disables (reference has none)
    config.accum_steps = 1
    config.seed = 42

    # Parallelism plan (rt1_tpu/parallel/plan.py, docs/parallelism.md): the
    # dp × fsdp × tp × pp mesh shape plus the declarative param layout, all
    # config-only switches — train, eval, and serve resolve this block
    # identically. -1 dp = all remaining local devices. (Replaces the old
    # `config.mesh` block: data→dp, model→tp, seq→sp, stage→pp; legacy
    # configs with a `mesh` block still resolve via the same fallback.)
    config.parallel = ml_collections.ConfigDict()
    config.parallel.dp = -1
    # ZeRO-3 weight sharding: batch shards over dp×fsdp, weight matrices /
    # optimizer masters shard one dim over fsdp.
    config.parallel.fsdp = 1
    # Tensor parallelism (attention heads / FFN columns / MoE experts).
    config.parallel.tp = 1
    # Pipeline stages (GPipe over the decoder's layer stack); num_layers
    # must be divisible by this.
    config.parallel.pp = 1
    # Sequence/context parallelism (ring attention).
    config.parallel.sp = 1
    # Pick (dp, fsdp, tp) automatically from the device count
    # (plan.AUTO_MESH_SHAPES); pp/sp still honored as configured.
    config.parallel.auto = False
    # Plan-coverage strictness: True turns the "weight matrix matched no
    # rule" warning into a hard error at step-build time.
    config.parallel.strict = False
    # True mixed precision: f32 master params + optimizer state, one bf16
    # cast of params inside the jitted step for fwd/bwd (forces the model
    # compute dtype to bfloat16; f32 softmax/CE unchanged). Off = the
    # bit-identical pre-change f32 program.
    config.parallel.mixed_precision = False
    # Multi-process (multi-host) scale-out (rt1_tpu/parallel/distributed
    # .py, docs/parallelism.md "Multi-host"): with `enabled`, the train
    # entry runs `jax.distributed.initialize` BEFORE any device access, so
    # the plan resolves against the slice's global devices, per-host
    # feeders slice the global stream, and Orbax coordinates multihost
    # checkpoints. One config serves every host: leave process_id /
    # num_processes at -1 and set RT1_COORDINATOR / RT1_PROCESS_ID /
    # RT1_NUM_PROCESSES per host (or nothing at all on TPU pods — the
    # runtime reads the metadata server).
    config.parallel.distributed = ml_collections.ConfigDict()
    config.parallel.distributed.enabled = False
    config.parallel.distributed.coordinator_address = (
        ml_collections.config_dict.placeholder(str)
    )
    config.parallel.distributed.process_id = -1
    config.parallel.distributed.num_processes = -1

    # Observability (rt1_tpu/obs/, docs/observability.md). Defaults are
    # resolved by obs.ObsOptions.from_config, so configs without this block
    # (pinned proof configs) keep working.
    config.obs = ml_collections.ConfigDict()
    # Host-side Chrome-trace recording (train loop + feeder workers + H2D
    # in one Perfetto timeline); dumped to obs.trace_path at exit.
    config.obs.trace = False
    config.obs.trace_path = ml_collections.config_dict.placeholder(str)
    config.obs.trace_max_events = 200_000
    # Rolling window (steps) for the stall_pct gauge / timing buckets.
    config.obs.stall_window = 50
    # Block on each step's output for exact device_step attribution —
    # diagnosis mode; costs one host sync per step.
    config.obs.sync_timing = False
    # >= 0: serve Prometheus text on http://<host>:<port>/metrics from the
    # train process (0 = ephemeral port, logged at startup). < 0: off.
    config.obs.prometheus_port = -1
    config.obs.prometheus_host = "127.0.0.1"
    # Flight recorder: ring of the last N step records (timing buckets,
    # feeder queue depths, loss at log steps), dumped to JSONL on an
    # unhandled exception or SIGTERM.
    config.obs.flight_recorder = True
    config.obs.flight_recorder_size = 256
    config.obs.flight_recorder_path = ml_collections.config_dict.placeholder(
        str
    )
    # Model-health pack (obs/health.py): per-layer-group gradient norms,
    # post-optimizer update/param ratios, logit entropy, and per-dimension
    # token accuracy, computed inside the jitted step and fetched at log
    # steps (health/* scalars, rt1_train_health_* gauges). Measured
    # overhead on the packed tiny e2e bench is within the <=2% budget
    # (bench.py --health); off = bit-identical pre-health step program.
    config.obs.model_health = True
    # Param-tree path depth for health layer groups (2 = per decoder layer).
    config.obs.health_group_depth = 2
    # Run-level goodput ledger (obs/goodput.py): wall-time partition into
    # init/compile/step/data_stall/ckpt/rollback/preempt buckets, goodput/*
    # scalars + rt1_train_goodput_* gauges + <workdir>/goodput_summary.json
    # (merged into a post-mortem by scripts/run_report.py).
    config.obs.goodput = True
    config.obs.goodput_summary_path = ml_collections.config_dict.placeholder(
        str
    )
    # Live MFU gauge from XLA cost analysis of the lowered step (no second
    # compile; one extra trace of the step at startup).
    config.obs.goodput_mfu = True

    # Resilience (rt1_tpu/resilience/, docs/resilience.md). Defaults are
    # resolved by resilience.ResilienceOptions.from_config with everything
    # OFF, so configs without this block (pinned proof configs) keep the
    # exact pre-resilience loop; this flagship config turns the self-healing
    # paths on.
    config.resilience = ml_collections.ConfigDict()
    # Step guard: device-side non-finite update skip + host-side escalation
    # (skip -> checkpoint rollback with a fresh data seed -> abort).
    config.resilience.guard = True
    # > 0: also skip updates whose global grad-norm exceeds this (a
    # train-wrecking spike that is still finite). 0 = finiteness only.
    config.resilience.guard_grad_norm_max = 0.0
    # > 0: flag loss > factor * EMA(healthy losses) at log steps. 0 = off
    # (early-training loss cliffs make a universal default unsafe).
    config.resilience.guard_loss_spike_factor = 0.0
    config.resilience.guard_spike_ema_beta = 0.9
    config.resilience.guard_warmup_checks = 3
    # Consecutive bad log-step checks tolerated before rolling back.
    config.resilience.guard_skip_budget = 3
    # Rollbacks allowed before the run aborts (GuardAbortError).
    config.resilience.guard_rollback_budget = 2
    # Exponential-backoff retry on the I/O seams: checkpoint save/restore,
    # packed-cache open, feeder construction.
    config.resilience.io_retry = True
    config.resilience.retry_attempts = 3
    config.resilience.retry_backoff_s = 0.5
    config.resilience.retry_max_backoff_s = 8.0
    config.resilience.retry_deadline_s = 120.0
    # SIGTERM/SIGINT -> force-save at the current step, drain the feeder,
    # exit 0 (the preemption-resume path); a second signal escalates to the
    # previous handler (flight-recorder dump + die).
    config.resilience.preempt_save = True
    # Deterministic fault schedule for chaos runs/tests (resilience/faults
    # .py grammar, e.g. "nan_batch@7,ckpt_save@2"); RT1_FAULTS env appends.
    config.resilience.faults = ""

    # Checkpoint / logging cadence.
    config.checkpoint_every_steps = 975
    config.keep_period = 9750
    # None -> keep all checkpoints (reference save_top_k=-1). Set an int to
    # bound retention; keep_period still pins every Nth step.
    config.max_to_keep = ml_collections.config_dict.placeholder(int)
    config.log_every_steps = 50
    config.eval_every_steps = 975
    config.eval_batches = 6

    return config


def sweep():
    """Hyperparameter sweep hook (the open equivalent of the reference's
    `get_hyper` product-sweep, `configs/language_table_sim_local.py:84-89`):
    a list of {dotted-config-key: value} override dicts, one trial each.
    Apply with `config.update_from_flattened_dict(overrides)` or pass as
    `--config.<key>=<value>` CLI overrides per trial."""
    return [
        {"learning_rate": lr, "seed": seed}
        for lr in (1e-3, 5e-4, 1e-4)
        for seed in (42,)
    ]
