"""CPU smoke config: tiny model + synthetic data, seconds to run."""

from rt1_tpu.train.configs import language_table

# Sweep hook shared with the full config (--sweep_trial N applies one trial).
sweep = language_table.sweep


def get_config():
    config = language_table.get_config()
    config.model.token_embedding_size = 16
    config.model.num_layers = 2
    config.model.layer_size = 8
    config.model.num_heads = 2
    config.model.feed_forward_size = 16
    config.model.vocab_size = 32
    config.model.time_sequence_length = 3
    config.model.num_image_tokens = 2
    config.model.image_tokenizer = "tiny"
    config.model.dtype = "float32"

    config.data.height = 32
    config.data.width = 56
    # The flagship ships model_health + the MFU estimator on; the smoke
    # config keeps them off so its many tier-1 loop invocations don't each
    # pay the pack's extra compile + the lowering retrace. Tests and the
    # 25-step acceptance run enable them explicitly
    # (--config.obs.model_health=True --config.obs.goodput_mfu=True).
    config.obs.model_health = False
    config.obs.goodput_mfu = False
    # Divisible by the data axis on both 1-device and 8-device (virtual CPU
    # mesh) runs.
    config.per_host_batch_size = 8
    config.num_steps = 4
    config.steps_per_epoch = 2
    config.checkpoint_every_steps = 2
    config.log_every_steps = 1
    config.eval_every_steps = 2
    config.eval_batches = 1
    return config
