"""Resumable DAgger outer loop: the two-phase crash-safe state machine.

Extracted from `scripts/learn_proof.py::stage_dagger` (VERDICT r4 weak #7)
so the round-target derivation and crash-resume logic live under unit test
(`tests/test_dagger_loop.py`) instead of inside a CLI script that can only
be exercised by subprocess runs.

The loop alternates corrective collection with training extensions
(Ross et al. 2011; see `rt1_tpu/data/dagger.py` for why this attacks the
measured copycat-collapse failure mode). Host resets are routine in this
environment, so every transition is durable:

* **phase A** (`aggregated_round = k`, written BEFORE training) makes round
  `k`'s rollout+aggregation idempotent — a crash during the much-longer
  training extension must not re-append round `k`'s episodes on resume;
* **phase B** (`completed_rounds = k+1`, written after training) advances.

Round step targets derive from the base checkpoint recorded at FIRST entry
(`base + (k+1) * extra_steps`), so a mid-training crash cannot inflate a
round's step budget via the mid-extension checkpoint. The state file is
deleted once the loop finishes: it is crash-resume state, not run
provenance (callers archive the returned history for that).

The reference has no counterpart — its corpus is fixed pre-recorded teleop
(`/root/reference/rlds_np_convert.py`) and cannot be extended.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Callable


@dataclasses.dataclass(frozen=True)
class DaggerLoopConfig:
    """Outer-loop shape. `rounds` corrective iterations, each extending
    training by `extra_steps` beyond the base checkpoint."""

    rounds: int
    extra_steps: int


def _load_state(state_path: str, base_step: int) -> dict:
    if os.path.exists(state_path):
        with open(state_path) as f:
            return json.load(f)
    return {
        "completed_rounds": 0,
        "rounds": [],
        "aggregated_round": None,
        "base_step": base_step,
    }


def _checkpoint_state(state_path: str, state: dict) -> None:
    with open(state_path + ".tmp", "w") as f:
        json.dump(state, f, indent=2)
    os.replace(state_path + ".tmp", state_path)


def round_target_step(base_step: int, rnd: int, extra_steps: int) -> int:
    """Training target for round `rnd` (0-based): base + (rnd+1)*extra."""
    return base_step + (rnd + 1) * extra_steps


def run_dagger_loop(
    state_path: str,
    base_step: int,
    config: DaggerLoopConfig,
    collect_round: Callable[[int], dict],
    train_to: Callable[[int], None],
    log: Callable[[str], None] = print,
) -> list[dict]:
    """Run (or resume) the DAgger loop; returns the per-round history.

    `collect_round(rnd)` rolls out the CURRENT policy, aggregates the
    relabeled episodes into the corpus, and returns the history entry for
    round `rnd` — it runs exactly once per round across any number of
    crashes/resumes (phase-A durability). `train_to(target_step)` extends
    training to an absolute step target; it may run more than once for the
    same target after a mid-training crash and must therefore resume from
    the latest checkpoint (the standard `restore_or_initialize` contract).

    `base_step` is only used on FIRST entry; a resumed run keeps the
    recorded one so step targets never drift.

    The state file is NOT deleted here: callers archive the returned
    history first and then call `clear_state` — so a crash between loop
    completion and the archive write resumes into an already-complete
    state (returning the recorded history instantly) instead of silently
    re-running every round and double-appending episodes to the corpus.
    """
    state = _load_state(state_path, base_step)
    if state["rounds"] or state["completed_rounds"]:
        log(
            f"dagger: resuming at round {state['completed_rounds']} "
            f"(aggregated_round={state['aggregated_round']}, "
            f"base_step={state['base_step']})"
        )
    history = state["rounds"]
    for rnd in range(state["completed_rounds"], config.rounds):
        if state["aggregated_round"] == rnd:
            log(f"dagger round {rnd}: already aggregated; resuming training")
        else:
            entry = dict(collect_round(rnd))
            entry["round"] = rnd
            history.append(entry)
            state["aggregated_round"] = rnd
            # Phase A durable BEFORE the long training extension.
            _checkpoint_state(state_path, state)
            log(f"dagger round {rnd}: {entry}")
        train_to(round_target_step(state["base_step"], rnd,
                                   config.extra_steps))
        state["completed_rounds"] = rnd + 1
        state["aggregated_round"] = None
        _checkpoint_state(state_path, state)
    return history


def clear_state(state_path: str) -> None:
    """Delete the crash-resume state. Callers do this only AFTER the
    returned history is durably archived: the state is resume bookkeeping,
    not run provenance, and a leftover file would make a later fresh run in
    the same workdir silently skip its rounds."""
    try:
        os.unlink(state_path)
    except FileNotFoundError:
        pass
