"""Training entry point: config system + full train loop.

Replaces (SURVEY.md §2.2/§3.4):
* Stack A `distribute_train.py` (argparse CLI, Lightning Trainer.fit), and
* Stack B `train/main.py` + `train/train.py` (absl + ml_collections config
  files, pmap loop) — whose config-file pattern we adopt, as SURVEY §5
  recommends.
"""

from rt1_tpu.train.train import train_and_evaluate

__all__ = ["train_and_evaluate"]
