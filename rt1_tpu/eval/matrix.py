"""Standing task × checkpoint eval matrix — the quality observability plane.

The fleet's observability (request tracing, SLOs, health packs) can see
*how fast* and *how healthy* the system is, but nothing answered *which
tasks* a policy actually performs: the repo ships nine reward families
(`envs/rewards/`) while closed-loop eval historically exercised one. This
module runs the closed-loop protocol (`eval/evaluate.py`) across a grid of
reward families × checkpoints and reports it three ways:

* **live Prometheus gauges during the sweep** — ``rt1_eval_success{task=,
  checkpoint=}`` (cell success rate so far) and ``rt1_eval_episodes_total
  {task=,checkpoint=}``, rendered by :meth:`EvalMatrixState.render_prometheus`
  and served by the shared ``obs.MetricsServer`` when the CLI is given
  ``--prometheus_port`` — a long sweep is scrapeable, not a black box;
* **one BENCH-style JSON** (``BENCH_eval_matrix.json``) holding the full
  success matrix — the offline promotion-gate signal the ROADMAP's
  auto-deploy loop (eval gate → canary → rollback) consumes;
* **a run-report section** — ``scripts/run_report.py`` renders the matrix
  as a task × checkpoint table next to the goodput/health post-mortem.

Where the converted dataset is thin for a family, :func:`fill_pack`
generates per-task corpora with the scripted oracle (`envs/oracles/`,
episodes stamped with `data.collect.canonical_task_id` slugs) and feeds
them through the PR 10 ``append_shard`` path — the flywheel corpus grows
*multi-task*, and task-mixture training (`config.data.task_weights`) has
data to weight.

Import-light by contract: stdlib + `rt1_tpu.obs.prometheus` at module
scope; jax / envs / checkpoint machinery only inside functions (pinned by
tests/test_obs_imports.py — the sweep driver must stay clu/TF-free so it
can run in a serve-side promotion controller).

Run:
  python scripts/eval_matrix.py --config rt1_tpu/train/configs/tiny.py \
      --workdir /tmp/rt1 --episodes 3
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from rt1_tpu.obs import prometheus as obs_prometheus

#: BENCH artifact basename — written next to the checkpoints (run_report
#: picks it up) and wherever the CLI's --out points.
BENCH_BASENAME = "BENCH_eval_matrix.json"


def default_task_names() -> Tuple[str, ...]:
    """Every canonical reward family, sorted — the matrix's task axis."""
    from rt1_tpu.envs import rewards as rewards_module

    return tuple(sorted(rewards_module.REWARD_FAMILIES))


def checkpoint_steps(workdir: str, spec: str = "all") -> List[int]:
    """Checkpoint steps to evaluate, resolved from ``<workdir>/checkpoints``.

    `spec`: ``"all"`` — every retained step; ``"latest:N"`` — the newest N;
    or a comma-separated list of explicit steps (validated against disk).
    Plain integer-named non-empty directories count (the same defensive
    scan as `trainer.checkpoints.latest_step` — Orbax tmp dirs and torn
    mkdirs are skipped), so this needs no checkpoint machinery import.
    """
    ckpt_dir = os.path.join(workdir, "checkpoints")
    steps: List[int] = []
    if os.path.isdir(ckpt_dir):
        for d in os.listdir(ckpt_dir):
            if not d.isdigit():
                continue
            full = os.path.join(ckpt_dir, d)
            try:
                if not os.path.isdir(full) or not os.listdir(full):
                    continue
            except OSError:
                continue
            steps.append(int(d))
    steps.sort()
    spec = (spec or "all").strip()
    if spec == "all":
        return steps
    if spec.startswith("latest:"):
        n = int(spec.split(":", 1)[1])
        if n <= 0:
            raise ValueError(f"latest:N needs N >= 1, got {spec!r}")
        return steps[-n:]
    wanted = [int(s) for s in spec.split(",") if s.strip()]
    missing = sorted(set(wanted) - set(steps))
    if missing:
        raise ValueError(
            f"checkpoints {missing} not found under {ckpt_dir} "
            f"(on disk: {steps})"
        )
    return sorted(set(wanted))


class EvalMatrixState:
    """Thread-safe accumulator of matrix cells + the live gauge renderer.

    One cell per (task, checkpoint label); the sweep updates a cell after
    each `evaluate_policy` call, and a concurrent scraper reads a
    consistent snapshot — absence of a cell means "not reached yet", a
    cell with ``episodes == 0`` means "running now".
    """

    def __init__(self):
        self._lock = threading.Lock()
        # (task, ckpt label) -> {"successes", "episodes", "mean_episode_
        # length"}; insertion-ordered = sweep order.
        self._cells: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._started_unix = time.time()

    def note_cell_start(self, task: str, checkpoint: str) -> None:
        with self._lock:
            self._cells.setdefault(
                (task, checkpoint),
                {"successes": 0, "episodes": 0, "mean_episode_length": 0.0},
            )

    def note_cell(
        self,
        task: str,
        checkpoint: str,
        successes: int,
        episodes: int,
        mean_episode_length: float = 0.0,
    ) -> None:
        with self._lock:
            cell = self._cells.setdefault(
                (task, checkpoint),
                {"successes": 0, "episodes": 0, "mean_episode_length": 0.0},
            )
            total = cell["episodes"] + episodes
            if total > 0:
                cell["mean_episode_length"] = (
                    cell["mean_episode_length"] * cell["episodes"]
                    + mean_episode_length * episodes
                ) / total
            cell["successes"] += int(successes)
            cell["episodes"] = total

    # ---------------------------------------------------------- reporting

    def matrix(self) -> Dict[str, Dict[str, Dict[str, Any]]]:
        """{task: {checkpoint: cell}} with per-cell success_rate."""
        with self._lock:
            out: Dict[str, Dict[str, Dict[str, Any]]] = {}
            for (task, ckpt), cell in self._cells.items():
                row = out.setdefault(task, {})
                row[ckpt] = dict(
                    cell,
                    success_rate=(
                        cell["successes"] / cell["episodes"]
                        if cell["episodes"]
                        else 0.0
                    ),
                )
            return out

    def checkpoints(self) -> List[str]:
        """Checkpoint labels in sweep order (columns of the table)."""
        with self._lock:
            seen: List[str] = []
            for _, ckpt in self._cells:
                if ckpt not in seen:
                    seen.append(ckpt)
            return seen

    def render_prometheus(self) -> str:
        """The live-sweep scrape body: ``rt1_eval_*`` families.

        ``rt1_eval_success`` is the cell's success RATE so far (gauge,
        labeled {task, checkpoint}); ``rt1_eval_episodes_total`` counts
        completed episodes per cell. Task slugs ("unknown:<name>") ride
        the exposition label escaping like the serve-side task labels.
        """
        with self._lock:
            cells = {k: dict(v) for k, v in self._cells.items()}
            started = self._started_unix
        exp = obs_prometheus.TextExposition()
        exp.gauge(
            "rt1_eval_cells_total",
            len(cells),
            "Matrix cells started so far (tasks x checkpoints).",
        )
        exp.gauge(
            "rt1_eval_sweep_uptime_seconds",
            time.time() - started,
            "Wall seconds since the sweep started.",
        )
        if cells:
            exp.family(
                "rt1_eval_success",
                "gauge",
                [
                    (
                        {"task": task, "checkpoint": ckpt},
                        (
                            cell["successes"] / cell["episodes"]
                            if cell["episodes"]
                            else 0.0
                        ),
                    )
                    for (task, ckpt), cell in cells.items()
                ],
                "Closed-loop success rate per (task, checkpoint) cell.",
            )
            exp.family(
                "rt1_eval_episodes_total",
                "counter",
                [
                    ({"task": task, "checkpoint": ckpt}, cell["episodes"])
                    for (task, ckpt), cell in cells.items()
                ],
                "Episodes completed per (task, checkpoint) cell.",
            )
        return exp.render()


def policy_for_checkpoint(config, workdir: str, step: Optional[int]):
    """(policy, restored_step, history_keys) for one checkpoint step.

    The per-step twin of `eval/main.py:load_policy_from_workdir` (which is
    pinned to the newest checkpoint): same family dispatch, explicit step.
    """
    from rt1_tpu.eval.policy import LavaEvalPolicy, RT1EvalPolicy
    from rt1_tpu.eval.restore import restore_variables

    model, variables, restored, family, lava_clip = restore_variables(
        config, workdir, step=step
    )
    history_keys = None
    if lava_clip:
        history_keys = (
            "rgb_sequence", "natural_language_embedding", "instruction",
            "effector_translation", "effector_target_translation",
        )
    if family == "lava":
        clip_tokenizer = None
        if lava_clip:
            from rt1_tpu.train.train import _make_clip_tokenizer

            clip_tokenizer = _make_clip_tokenizer(config)
        policy = LavaEvalPolicy(
            model,
            variables,
            sequence_length=config.model.time_sequence_length,
            clip_tokenizer=clip_tokenizer,
        )
    else:
        policy = RT1EvalPolicy(model, variables)
    return policy, restored, history_keys


def run_matrix(
    policies: Sequence[Tuple[str, Any]],
    tasks: Sequence[str],
    *,
    episodes_per_cell: int = 3,
    max_episode_steps: int = 80,
    block_mode: str = "BLOCK_8",
    seed: int = 0,
    embedder: str = "hash",
    env_kwargs: Optional[Dict[str, Any]] = None,
    state: Optional[EvalMatrixState] = None,
    progress: Optional[Callable[[str, str, Dict[str, Any]], None]] = None,
) -> EvalMatrixState:
    """Sweep `policies` (label, policy-or-factory) × `tasks` through the
    closed-loop protocol, one `evaluate_policy` call per cell.

    Checkpoints are the OUTER loop so each policy is restored/walked once;
    an entry without an ``action`` attribute is treated as a zero-arg
    factory and called lazily here — so a long checkpoint list holds ONE
    restored parameter set in memory at a time, not all of them. The
    state updates after every cell, which is what makes the live gauges
    move during the sweep. `progress(task, label, cell)` fires per
    completed cell (the CLI logs it).
    """
    from rt1_tpu.envs import blocks
    from rt1_tpu.eval.evaluate import evaluate_policy

    state = state if state is not None else EvalMatrixState()
    mode = blocks.BlockMode(block_mode)
    for label, policy in policies:
        if not hasattr(policy, "action"):
            policy = policy()  # lazy restore: one checkpoint resident
        for task in tasks:
            state.note_cell_start(task, label)
            results = evaluate_policy(
                policy,
                workdir=None,
                reward_names=(task,),
                num_evals_per_reward=episodes_per_cell,
                max_episode_steps=max_episode_steps,
                block_mode=mode,
                seed=seed,
                embedder=embedder,
                env_kwargs=env_kwargs,
            )
            successes = int(results["successes"].get(task, 0))
            mean_len = float(
                results["mean_episode_length"].get(task, 0.0)
            )
            state.note_cell(
                task, label, successes, episodes_per_cell, mean_len
            )
            if progress is not None:
                progress(
                    task,
                    label,
                    {
                        "successes": successes,
                        "episodes": episodes_per_cell,
                        "mean_episode_length": mean_len,
                    },
                )
    return state


def run_gate(
    config,
    workdir: str,
    candidate_step: int,
    incumbent_step: Optional[int] = None,
    *,
    tasks: Optional[Sequence[str]] = None,
    episodes_per_cell: int = 2,
    max_episode_steps: int = 80,
    block_mode: str = "BLOCK_8",
    seed: int = 0,
    embedder: str = "hash",
    env_kwargs: Optional[Dict[str, Any]] = None,
    margin: float = 0.0,
    state: Optional[EvalMatrixState] = None,
    progress: Optional[Callable[[str, str, Dict[str, Any]], None]] = None,
) -> Dict[str, Any]:
    """The offline promotion gate as ONE library call: candidate vs.
    incumbent on the same task grid -> a verdict dict.

    Library entry for the deploy controller (the CLI keeps its own sweep
    loop): runs `run_matrix` over the two checkpoint columns with the
    same lazy policy factories the CLI builds — the incumbent column is
    restored, swept, and released before the candidate restores, so the
    caller never holds two parameter sets in memory.

    Pass criterion: candidate mean per-cell success must reach the
    incumbent's minus ``margin`` (>= incumbent - margin). With no
    incumbent (first deploy into an empty fleet) the candidate gates
    against 0.0 — any evaluable checkpoint passes, which is the honest
    floor when there is nothing to regress against. The verdict carries
    the full matrix so the signed artifact IS the evidence.
    """
    t0 = time.time()
    tasks = tuple(tasks) if tasks else default_task_names()
    columns: List[Tuple[str, Any]] = []
    if incumbent_step is not None:
        columns.append(
            (
                str(incumbent_step),
                lambda s=incumbent_step: policy_for_checkpoint(
                    config, workdir, s
                )[0],
            )
        )
    columns.append(
        (
            str(candidate_step),
            lambda s=candidate_step: policy_for_checkpoint(
                config, workdir, s
            )[0],
        )
    )
    state = run_matrix(
        columns,
        tasks,
        episodes_per_cell=episodes_per_cell,
        max_episode_steps=max_episode_steps,
        block_mode=block_mode,
        seed=seed,
        embedder=embedder,
        env_kwargs=env_kwargs,
        state=state,
        progress=progress,
    )
    matrix = state.matrix()

    def _mean(label: str) -> float:
        rates = [
            row[label]["success_rate"]
            for row in matrix.values()
            if label in row and row[label]["episodes"]
        ]
        return sum(rates) / len(rates) if rates else 0.0

    candidate_mean = _mean(str(candidate_step))
    incumbent_mean = (
        _mean(str(incumbent_step)) if incumbent_step is not None else 0.0
    )
    return {
        "gate": "eval_matrix",
        "candidate_step": int(candidate_step),
        "incumbent_step": (
            int(incumbent_step) if incumbent_step is not None else None
        ),
        "tasks": sorted(matrix),
        "episodes_per_cell": episodes_per_cell,
        "candidate_mean_success": round(candidate_mean, 4),
        "incumbent_mean_success": round(incumbent_mean, 4),
        "margin": margin,
        "passed": candidate_mean >= incumbent_mean - margin,
        "matrix": matrix,
        "wall_seconds": round(time.time() - t0, 1),
    }


def matrix_record(
    state: EvalMatrixState,
    *,
    episodes_per_cell: int,
    max_episode_steps: int,
    seed: int,
    embedder: str,
    backend: str,
    block_mode: str,
    wall_seconds: float,
    workdir: str = "",
    extra: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """The BENCH-style JSON record: full matrix + one headline number
    (mean per-cell success rate — comparable across sweeps of the same
    grid, NOT across different grids)."""
    matrix = state.matrix()
    rates = [
        cell["success_rate"]
        for row in matrix.values()
        for cell in row.values()
        if cell["episodes"]
    ]
    record = {
        "bench": "eval_matrix",
        "unit": "mean_cell_success_rate",
        "value": round(sum(rates) / len(rates), 4) if rates else 0.0,
        "tasks": sorted(matrix),
        "checkpoints": state.checkpoints(),
        "matrix": matrix,
        "episodes_per_cell": episodes_per_cell,
        "max_episode_steps": max_episode_steps,
        "seed": seed,
        "embedder": embedder,
        "backend": backend,
        "block_mode": block_mode,
        "workdir": workdir,
        "wall_seconds": round(wall_seconds, 1),
    }
    if extra:
        record.update(extra)
    return record


def write_record(record: Dict[str, Any], *paths: str) -> List[str]:
    """Atomically write the BENCH record to every given path."""
    written = []
    for path in paths:
        if not path:
            continue
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(record, f, indent=2, sort_keys=True)
        os.replace(tmp, path)
        written.append(path)
    return written


# ----------------------------------------------------- oracle corpus fill


def collect_task_corpus(
    episodes_dir: str,
    tasks: Sequence[str],
    episodes_per_task: int,
    *,
    block_mode: str = "BLOCK_8",
    seed: int = 0,
    max_steps: int = 80,
    embedder: str = "hash",
    image_hw: Optional[Tuple[int, int]] = None,
    max_attempts_factor: int = 8,
) -> Dict[str, List[str]]:
    """Oracle-generate `episodes_per_task` demos per reward family, each
    stamped with its canonical task id, into `episodes_dir`.

    Returns {task: [episode paths]}. A family the oracle cannot solve
    within ``episodes_per_task * max_attempts_factor`` attempts reports
    fewer (possibly zero) episodes instead of hanging — the matrix's
    corpus fill must degrade loudly, not block the sweep.
    """
    from rt1_tpu.data import collect as collect_lib
    from rt1_tpu.data.episodes import save_episode
    from rt1_tpu.envs import LanguageTable, blocks
    from rt1_tpu.envs import rewards as rewards_module
    from rt1_tpu.envs.oracles import RRTPushOracle
    from rt1_tpu.eval.embedding import get_embedder

    os.makedirs(episodes_dir, exist_ok=True)
    embed_fn = get_embedder(embedder)
    mode = blocks.BlockMode(block_mode)
    out: Dict[str, List[str]] = {}
    for t_i, task in enumerate(tasks):
        env = LanguageTable(
            block_mode=mode,
            reward_factory=rewards_module.get_reward_factory(task),
            seed=seed + t_i,
        )
        oracle = RRTPushOracle(env, use_ee_planner=True, seed=seed + t_i)
        slug = collect_lib.canonical_task_id(task)
        paths: List[str] = []
        attempts = 0
        while (
            len(paths) < episodes_per_task
            and attempts < episodes_per_task * max_attempts_factor
        ):
            attempts += 1
            ep = collect_lib.collect_episode(
                env,
                oracle,
                embed_fn,
                max_steps=max_steps,
                image_hw=image_hw,
                task=slug,
            )
            if ep is None:
                continue
            path = os.path.join(
                episodes_dir,
                f"episode_{slug.replace(':', '_')}_{len(paths)}.npz",
            )
            save_episode(path, ep)
            paths.append(path)
        out[task] = paths
    return out


def fill_pack(
    pack_dir: str,
    episodes_dir: str,
    tasks: Sequence[str],
    episodes_per_task: int,
    *,
    block_mode: str = "BLOCK_8",
    seed: int = 0,
    max_steps: int = 80,
    embedder: str = "hash",
) -> Dict[str, Any]:
    """Oracle corpora → the PR 10 append path: collect per-task episodes
    at the pack's source geometry and `append_shard` them, bumping the
    manifest's freshness epoch so a live train job's feeder absorbs the
    multi-task shard at its next epoch boundary.

    Returns a summary {task: episodes_collected, shards_after, ...}.
    """
    from rt1_tpu.data import pack as pack_lib

    manifest = pack_lib.load_manifest(pack_dir)
    image_hw = (
        int(manifest["source"]["height"]),
        int(manifest["source"]["width"]),
    )
    collected = collect_task_corpus(
        episodes_dir,
        tasks,
        episodes_per_task,
        block_mode=block_mode,
        seed=seed,
        max_steps=max_steps,
        embedder=embedder,
        image_hw=image_hw,
    )
    paths = [p for ps in collected.values() for p in ps]
    if paths:
        manifest = pack_lib.append_shard(pack_dir, paths)
    return {
        "episodes_per_task": {t: len(ps) for t, ps in collected.items()},
        "episodes_appended": len(paths),
        "shards_after": len(manifest["shards"]),
        "freshness_epoch": int(manifest.get("freshness_epoch", 0)),
        "corpus_tasks": sorted(
            {
                e.get("task") or pack_lib.UNKNOWN_TASK
                for e in manifest["episodes"]
            }
        ),
    }
