"""Restore a closed-loop eval policy from a training workdir.

The missing half of the reference's eval entry point
(`/root/reference/language_table/eval/main_rt1.py:52-76` builds the network
and loads a `.pth` by hand): given the training config and workdir, rebuild
the model, restore the newest (or a chosen) checkpoint, and wrap it in
`RT1EvalPolicy` ready for `evaluate_policy`.

Extracted from `scripts/learn_proof.py` (VERDICT r4 weak #7) so framework
users get checkpoint->policy as a library call, not script internals.
"""

from __future__ import annotations

import os


def restore_eval_policy(config, train_dir: str, step: int | None = None):
    """Build the model from `config.model`, restore `train_dir/checkpoints`
    (newest step unless `step` is given), and return an `RT1EvalPolicy`.

    A sample batch from the dataset described by `config.data` provides the
    shape/dtype example for parameter initialization; the val split is
    preferred, falling back to train for tiny smoke corpora with no val
    quota.
    """
    import jax

    from rt1_tpu.eval.policy import RT1EvalPolicy
    from rt1_tpu.train.train import build_model, dataset_batches
    from rt1_tpu.trainer import create_train_state, make_optimizer
    from rt1_tpu.trainer.checkpoints import CheckpointConfig, CheckpointManager

    model = build_model(config.model)
    try:
        batch = next(dataset_batches(config, "val"))
    except FileNotFoundError:  # tiny smoke datasets have no val quota
        batch = next(dataset_batches(config, "train"))
    example = (batch["observations"], batch["actions"])
    tx = make_optimizer(
        learning_rate=config.learning_rate,
        milestones=config.lr_milestones,
        gamma=config.lr_gamma,
        steps_per_epoch=config.steps_per_epoch,
    )
    state = create_train_state(model, jax.random.PRNGKey(0), example, tx)
    ckpt = CheckpointManager(
        CheckpointConfig(
            directory=os.path.join(os.path.abspath(train_dir), "checkpoints")
        )
    )
    state = ckpt.restore(jax.device_get(state), step=step)
    print(f"restored checkpoint at step {int(state.step)}")
    variables = {"params": state.params}
    if state.batch_stats:  # efficientnet_b3 tokenizer carries BatchNorm stats
        variables["batch_stats"] = state.batch_stats
    return RT1EvalPolicy(model, variables)
