"""Restore checkpoints into closed-loop policies and serving engines.

The missing half of the reference's eval entry point
(`/root/reference/language_table/eval/main_rt1.py:52-76` builds the network
and loads a `.pth` by hand): given the training config and workdir, rebuild
the model, restore the newest (or a chosen) checkpoint, and wrap it in
`RT1EvalPolicy` ready for `evaluate_policy` — or in a multi-session
`rt1_tpu.serve.PolicyEngine` for the batched inference service.

Extracted from `scripts/learn_proof.py` (VERDICT r4 weak #7) so framework
users get checkpoint->policy as a library call, not script internals.
`build_model_and_state` / `restore_variables` hold the dataset-free
synthetic-shape init shared by `eval/main.py` and `python -m rt1_tpu.serve`.
"""

from __future__ import annotations

import os


def build_model_and_state(config):
    """Model + randomly initialized train state from synthetic example
    shapes — no dataset on disk required (unlike `restore_eval_policy`).

    Returns (model, state, family, lava_clip); `lava_clip` flags the LAVA
    variant whose observation contract includes CLIP instruction tokens.
    """
    import jax
    import numpy as np

    from rt1_tpu.specs import language_table_action_space, sample_space
    from rt1_tpu.train.train import build_family
    from rt1_tpu.trainer import create_train_state, make_optimizer

    model, init_fn, _ = build_family(config.model)
    rng = jax.random.PRNGKey(0)
    t = config.model.time_sequence_length
    h, w = config.data.height, config.data.width
    obs = {
        "image": np.zeros((1, t, h, w, 3), np.float32),
        "natural_language_embedding": np.zeros((1, t, 512), np.float32),
    }
    family = config.model.get("family", "rt1")
    lava_clip = family == "lava" and config.model.lava.lang_encoder == "clip"
    if lava_clip:
        obs["instruction_tokenized_clip"] = np.zeros(
            (1, t, config.model.lava.get("text_context", 77)), np.int32
        )
    actions = sample_space(
        language_table_action_space(), jax.random.fold_in(rng, 1), (1, t)
    )
    state = create_train_state(
        model, rng, (obs, actions), make_optimizer(), init_fn=init_fn
    )
    return model, state, family, lava_clip


def _variables_from_state(state):
    variables = {"params": state.params}
    if state.batch_stats:  # efficientnet_b3 tokenizer carries BatchNorm stats
        variables["batch_stats"] = state.batch_stats
    return variables


def restore_variables(config, workdir, step=None):
    """Dataset-free build + checkpoint restore.

    Returns (model, variables, restored_step, family, lava_clip). Raises
    FileNotFoundError on an empty workdir — silently serving/evaluating
    randomly initialized weights would be worse than failing.

    The restore is a PLAN MIGRATION (parallel/reshard.py): the template
    carries this process's serving plan, so a checkpoint trained on a pod
    under fsdp/tp lands directly in the serve host's layout — for the
    default all-ones plan that is one device, i.e. a 1-device replica
    always loads a big-mesh checkpoint. A train config whose model axes
    exceed this host's devices falls back to plain single-host placement
    (the layout Orbax derives from the concrete template) with a warning,
    instead of refusing to serve.
    """
    from rt1_tpu.trainer.checkpoints import CheckpointConfig, CheckpointManager

    model, state, family, lava_clip = build_model_and_state(config)
    try:
        plan = serving_plan(config)
    except ValueError as exc:
        from absl import logging

        logging.warning(
            "eval/restore: serving plan unsatisfiable on this host (%s) — "
            "restoring with plain placement", exc,
        )
        plan = None
    ckpt = CheckpointManager(
        CheckpointConfig(
            directory=os.path.join(os.path.abspath(workdir), "checkpoints")
        )
    )
    state = ckpt.restore(state, step=step, plan=plan)
    restored_step = step if step is not None else ckpt.latest_step()
    return model, _variables_from_state(state), restored_step, family, lava_clip


def serving_plan(config):
    """The declarative sharding plan for a serving process, resolved from
    the SAME `config.parallel` block training uses (parallel/plan.py).

    Serving has no batch axis to shard (sessions are slots, not data
    shards), so `dp` collapses to 1 and the mesh covers exactly the
    fsdp × tp × pp × sp devices model parallelism needs — for the default
    all-ones config that is a 1-device mesh, byte-identical placement to
    the pre-plan engine. Returns None when jax has no initialized backend
    yet (callers treat that as plain placement).
    """
    import jax

    from rt1_tpu.parallel import ShardingPlan

    try:
        devices = jax.local_devices()
    except RuntimeError:  # no initialized backend — plain placement
        return None
    # One resolver with train (`auto` resolves against THIS host's devices,
    # the data axis collapses — sessions are slots, not shards); see
    # ShardingPlan.from_config(collapse_data=True).
    return ShardingPlan.from_config(
        config, devices=devices, collapse_data=True
    )


def _config_with_model_dtype(config, dtype: str):
    """A deep copy of `config` with `model.dtype` overridden — the bf16
    serving mode rebuilds the model at the bf16 COMPUTE dtype while the
    checkpoint (and therefore restore) stays at the f32 master dtype."""
    import copy

    cfg = copy.deepcopy(config)
    with cfg.unlocked():
        cfg.model.dtype = dtype
    return cfg


def build_serve_engine(
    config, workdir=None, step=None, inference_dtype="f32", **engine_kwargs
):
    """Feed a checkpoint (or random init when `workdir` is None) into a
    multi-session serving engine. Returns (engine, checkpoint_step);
    checkpoint_step is -1 for random init.

    Params are restored through the sharding plan (`serving_plan`): the
    engine places every leaf per the plan rule on the serve mesh, so a
    tensor-parallel or fsdp-sharded engine is the same config switch as in
    training — no per-callsite spec plumbing.

    ``inference_dtype`` selects the low-precision serving mode
    (rt1_tpu/models/quant.py; docs/serving.md "Low-precision serving"):

    * ``"f32"``  — today's path, byte-identical placement and compute.
    * ``"bf16"`` — the model is rebuilt at bf16 compute dtype and every
      float leaf is cast ONCE at restore (bit-identical to flax's own
      at-use cast, half the resident bytes).
    * ``"int8"`` — the quant plan's int8 group (parallel/plan.py
      `rt1_quant_rules`: FiLM-EfficientNet convs + transformer matmuls)
      quantizes per-output-channel on the host; norms, embeddings, the
      action head, and BN stats stay f32. Dequant `(w_int8 * scale) @ x`
      fuses into the matmuls.

    In bf16/int8 mode the engine keeps the master spec + the preparer, so
    `swap_variables` (POST /reload, fleet rolling reload) revalidates and
    requantizes every standby f32 checkpoint — compile_count stays 1.
    """
    from rt1_tpu.models.quant import (
        check_inference_dtype,
        serving_preparer,
    )
    from rt1_tpu.serve.engine import PolicyEngine

    check_inference_dtype(inference_dtype)
    if inference_dtype == "bf16":
        config = _config_with_model_dtype(config, "bfloat16")
    if workdir is None:
        model, state, family, _ = build_model_and_state(config)
        variables, restored_step = _variables_from_state(state), -1
    else:
        model, variables, restored_step, family, _ = restore_variables(
            config, workdir, step=step
        )
    if family != "rt1":
        raise ValueError(
            f"the serving engine batches RT-1 rolling network state; "
            f"family={family!r} is not servable (use the eval harness)"
        )
    prepare = serving_preparer(inference_dtype)
    master_variables = None
    if prepare is not None:
        import jax
        import numpy as np

        # Quantize/cast ON THE HOST from the f32 masters; the engine keeps
        # the master spec so reloads validate against the checkpoint
        # contract, not the serving dtypes.
        master_variables = jax.tree.map(lambda x: np.asarray(x), variables)
        variables = prepare(master_variables)
    if "plan" not in engine_kwargs:
        # Resolved lazily: an explicitly passed plan (or plan=None for
        # plain placement) must not trigger serving_plan's device-count
        # validation for a layout that will never be built.
        engine_kwargs["plan"] = serving_plan(config)
    engine = PolicyEngine(
        model,
        variables,
        inference_dtype=inference_dtype,
        prepare_variables=prepare,
        master_variables=master_variables,
        **engine_kwargs,
    )
    return engine, restored_step


def load_standby_variables(config, workdir=None, step=None):
    """Restore a checkpoint (or re-init when `workdir` is None) into HOST
    buffers for a zero-downtime engine hot-swap.

    Returns (variables, checkpoint_step) with every leaf a numpy array —
    the standby buffer `PolicyEngine.swap_variables` validates before any
    device memory is touched, so a corrupt checkpoint is rejected while
    the old params keep serving. Leaves keep the checkpoint's MASTER
    dtypes (f32 even for a bf16-compute engine) — swap_variables validates
    against the serving masters, and the engine re-places the buffer with
    each leaf's current plan sharding on swap. `workdir=None` rebuilds the same
    deterministic PRNGKey(0) random init as `build_serve_engine`'s
    random-init path (bit-identical params — the chaos harness uses this
    to prove reload parity without a trained checkpoint). checkpoint_step
    is -1 for random init.
    """
    import jax
    import numpy as np

    if workdir is None:
        _, state, _, _ = build_model_and_state(config)
        variables, restored_step = _variables_from_state(state), -1
    else:
        _, variables, restored_step, _, _ = restore_variables(
            config, workdir, step=step
        )
    host = jax.tree.map(lambda x: np.asarray(x), variables)
    return host, restored_step


def restore_eval_policy(config, train_dir: str, step: int | None = None):
    """Build the model from `config.model`, restore `train_dir/checkpoints`
    (newest step unless `step` is given), and return an `RT1EvalPolicy`.

    A sample batch from the dataset described by `config.data` provides the
    shape/dtype example for parameter initialization; the val split is
    preferred, falling back to train for tiny smoke corpora with no val
    quota.
    """
    import jax

    from rt1_tpu.eval.policy import RT1EvalPolicy
    from rt1_tpu.train.train import build_model, dataset_batches
    from rt1_tpu.trainer import create_train_state, make_optimizer
    from rt1_tpu.trainer.checkpoints import CheckpointConfig, CheckpointManager

    model = build_model(config.model)
    try:
        batch = next(dataset_batches(config, "val"))
    except FileNotFoundError:  # tiny smoke datasets have no val quota
        batch = next(dataset_batches(config, "train"))
    example = (batch["observations"], batch["actions"])
    tx = make_optimizer(
        learning_rate=config.learning_rate,
        milestones=config.lr_milestones,
        gamma=config.lr_gamma,
        steps_per_epoch=config.steps_per_epoch,
    )
    state = create_train_state(model, jax.random.PRNGKey(0), example, tx)
    ckpt = CheckpointManager(
        CheckpointConfig(
            directory=os.path.join(os.path.abspath(train_dir), "checkpoints")
        )
    )
    state = ckpt.restore(jax.device_get(state), step=step)
    print(f"restored checkpoint at step {int(state.step)}")
    return RT1EvalPolicy(model, _variables_from_state(state))
