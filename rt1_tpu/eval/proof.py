"""Learning-proof summary assembly: the eval stage's provenance record.

Extracted from ``scripts/learn_proof.py`` (VERDICT r4 next #7) so the
summary's decision logic — the pre-registered success criterion and
headline-powering rule — is unit-testable without subprocess runs.

The reference ships its learning evidence as a converged loss curve and an
eval checkpoint (``/root/reference/README.md:55-59``,
``/root/reference/language_table/eval/main_rt1.py:220``); this record is
the hermetic equivalent, with the decision rule written down before the
data exists.
"""

from __future__ import annotations

import json
import os
from typing import Any, Mapping, Sequence

# Pre-registered in round 5, BEFORE the flagship arm's eval ran
# (VERDICT r4 weak #3 / next #6): a 1/20 is within noise of 0/20, so no
# "success" headline may rest on fewer than this many formal-seed
# episodes; diagnostics-seed results are reported alongside, never as
# the headline.
MIN_EPISODES_FOR_SUCCESS_HEADLINE = 50
SUCCESS_CRITERION = "trained_successes >= max(1, oracle_successes // 2)"


def criterion_met(trained_successes: int, oracle_successes: int) -> bool:
    """The pre-registered bar: half the measured expert ceiling.

    Success is defined against the SAME protocol's oracle rate (VERDICT r3
    weak #7), not an absolute number: the RRT push oracle itself solves
    only about half of oracle-validated inits within the 80-step budget.
    """
    return trained_successes >= max(1, oracle_successes // 2)


def build_proof_summary(
    *,
    reward: str,
    block_mode: str,
    manifest: Mapping[str, Any] | None,
    flag_embedder: str,
    flag_exec_noise_std: float,
    episodes_collected: int,
    split_counts: Mapping[str, int],
    num_steps_requested: int,
    evaluated_checkpoint_step: int | None,
    seq_len: int,
    focal_gamma: float,
    aux_mse_weight: float,
    image_tokenizer: str,
    resolution: Sequence[int],
    eval_episodes: int,
    eval_seed: int,
    trained: Mapping[str, Any],
    random_results: Mapping[str, Any],
    oracle_results: Mapping[str, Any],
    curves: Mapping[str, Sequence],
) -> dict:
    """Assemble the ``learn_proof.json`` record.

    Provenance comes from reality, not flags, wherever the two can
    diverge (ADVICE r4): corpus noise/embedder from the manifest (the
    eval stage never collects, so the flag could silently mis-record),
    and the evaluated step from the checkpoint directory (after DAgger
    the checkpoint sits at base + rounds*extra, which the requested
    num_steps knows nothing about).
    """
    # A manifest that exists but lacks exec_noise_std is a PRE-DART clean
    # corpus (noise 0.0) — never the flag, which the eval stage could
    # silently mis-record. Flags are the fallback only with no manifest.
    if manifest is None:
        manifest = {}
        corpus_noise = flag_exec_noise_std
    else:
        corpus_noise = manifest.get("exec_noise_std", 0.0)
    summary = {
        "reward": reward,
        "block_mode": block_mode,
        "embedder": manifest.get("embedder", flag_embedder),
        "episodes_collected": episodes_collected,
        "episodes_by_split": dict(split_counts),
        "exec_noise_std": corpus_noise,
        "train_steps_requested": num_steps_requested,
        "evaluated_checkpoint_step": evaluated_checkpoint_step,
        "seq_len": seq_len,
        "focal_gamma": focal_gamma,
        "aux_mse_weight": aux_mse_weight,
        "image_tokenizer": image_tokenizer,
        "resolution": list(resolution),
        "eval_episodes": eval_episodes,
        "trained_successes": trained["successes"][reward],
        "random_successes": random_results["successes"][reward],
        "oracle_successes": oracle_results["successes"][reward],
        "trained_mean_episode_length":
            trained["mean_episode_length"][reward],
        "random_mean_episode_length":
            random_results["mean_episode_length"][reward],
        "oracle_mean_episode_length":
            oracle_results["mean_episode_length"][reward],
        "final_train_loss": curves["loss"][-1][1] if curves["loss"] else None,
        "final_eval_loss":
            curves["eval_loss"][-1][1] if curves["eval_loss"] else None,
    }
    summary["success_criterion"] = SUCCESS_CRITERION
    summary["criterion_met"] = bool(
        criterion_met(
            summary["trained_successes"], summary["oracle_successes"]
        )
    )
    summary["headline_protocol"] = {
        "criterion": SUCCESS_CRITERION + " on the formal eval seeds",
        "formal_eval_seed": eval_seed,
        "min_episodes_for_success_headline":
            MIN_EPISODES_FOR_SUCCESS_HEADLINE,
        "headline_eligible": bool(
            summary["criterion_met"]
            and eval_episodes >= MIN_EPISODES_FOR_SUCCESS_HEADLINE
        ),
        "registered": "round 5, before the flagship arm's eval",
    }
    return summary


def write_proof_json(workdir: str, summary: Mapping[str, Any]) -> str:
    """Durably write ``learn_proof.json`` (tmp+rename).

    A mid-write kill must not leave a truncated file that a pipeline's
    completeness check could mistake for a finished arm.
    """
    proof_path = os.path.join(workdir, "learn_proof.json")
    with open(proof_path + ".tmp", "w") as f:
        json.dump(summary, f, indent=2)
    os.replace(proof_path + ".tmp", proof_path)
    return proof_path
